"""Unified typed runtime configuration.

The reference configures its runtime through JVM system properties read
ad hoc all over the codebase (``utils/Engine.scala:113-154`` ``bigdl.*``
properties); the TPU-native equivalent is the ``BIGDL_*`` environment.
This module gives that surface ONE typed, documented object: every knob
the framework reads, its type, default, and consumer, resolved in a
single place.  Call sites keep reading through :func:`get_config` so a
test (or an embedding application) can inject overrides with
:func:`set_config` instead of mutating ``os.environ``.

| field                  | env var                     | consumer |
|------------------------|-----------------------------|----------|
| coordinator_address    | BIGDL_COORDINATOR_ADDRESS   | Engine (multi-host control plane) |
| num_processes          | BIGDL_NUM_PROCESSES         | Engine |
| process_id             | BIGDL_PROCESS_ID            | Engine |
| node_number            | BIGDL_NODE_NUMBER           | Engine (defaults to process count) |
| core_number            | BIGDL_CORE_NUMBER           | Engine (host cores for data pipeline) |
| default_pool_size      | BIGDL_DEFAULT_POOL_SIZE     | Engine.default thread pool |
| local_mode             | BIGDL_LOCAL_MODE            | Engine |
| failure_retry_times    | BIGDL_FAILURE_RETRY_TIMES   | Optimizer retry budget |
| failure_retry_interval | BIGDL_FAILURE_RETRY_INTERVAL| Optimizer retry window (s) |
| iteration_timeout      | BIGDL_ITERATION_TIMEOUT     | straggler guard ("", "0", float, "auto") |
| check_singleton_strict | BIGDL_CHECK_SINGLETON       | Engine.check_singleton raise-vs-warn |
| profile_dir            | BIGDL_PROFILE               | profiler hook |
| profile_iters          | BIGDL_PROFILE_ITERS         | profiler hook |
| telemetry_dir          | BIGDL_TELEMETRY             | telemetry run log dir (docs/observability.md) |
| telemetry_device       | BIGDL_TELEMETRY_DEVICE      | device-facts level: off / auto / full |
| module_scopes          | BIGDL_SCOPES                | jax.named_scope module paths in compiled HLO (default on; off disables attribution) |
| telemetry_attribution  | BIGDL_ATTRIBUTION           | emit per-module cost-attribution events (one re-lower + HLO parse per step object) |
| telemetry_comms        | BIGDL_COMMS                 | per-collective comms events (telemetry/comms.py): off / auto (sharded multi-device steps only) / on — one extra local XLA compile per step object |
| telemetry_memory       | BIGDL_MEMORY                | per-step memory events (telemetry/memory.py): off / auto (multi-device meshes only) / on — shares the comms compile, so on a sharded step the event is a text parse |
| fleet_interval         | BIGDL_FLEET_INTERVAL        | coordinator fleet-watcher poll seconds (telemetry/fleet.py; 0 = off; active only on multi-process runs) |
| flight_events          | BIGDL_FLIGHT                | crash flight-recorder ring capacity in events (0 = off) |
| profile_on_health      | BIGDL_PROFILE_ON_HEALTH     | arm a one-shot profiler capture (dir) when the health policy first escalates |
| metrics_port           | BIGDL_METRICS_PORT          | OpenMetrics/status HTTP endpoint port (0 = ephemeral; unset = off) |
| health_action          | BIGDL_HEALTH                | training-health policy: off / warn / skip / halt (default halt) |
| health_halt_after      | BIGDL_HEALTH_HALT_AFTER     | halt after N consecutive nonfinite steps (default 3) |
| no_native              | BIGDL_TPU_NO_NATIVE         | native kernel loader |
| log_disable            | BIGDL_LOGGER_DISABLE        | utils.logging redirect (disable) |
| log_file               | BIGDL_LOG_FILE              | utils.logging redirect target |
| log_thirdparty         | BIGDL_LOG_THIRDPARTY        | redirect third-party logs to file |
| prefetch_batches       | BIGDL_PREFETCH              | Optimizer input double-buffering depth (0 = sync) |
| async_checkpoint       | BIGDL_ASYNC_CHECKPOINT      | overlap checkpoint IO with training (default on) |
| retry_backoff          | BIGDL_RETRY_BACKOFF         | retry-loop backoff base seconds (exp + jitter, cap 30s; 0 = off) |
| resume                 | BIGDL_RESUME                | auto-resume from the checkpoint dir: auto / off (docs/fault_tolerance.md) |
| faults                 | BIGDL_FAULTS                | deterministic fault-injection plan (bigdl_tpu/faults.py) |
| faults_seed            | BIGDL_FAULTS_SEED           | seed for the plan's random choices (torn bytes) |
| cluster_dir            | BIGDL_CLUSTER_DIR           | shared dir for peer heartbeats + commit barrier (parallel/cluster.py; unset = cluster fault tolerance off) |
| cluster_deadline       | BIGDL_CLUSTER_DEADLINE      | peer-heartbeat deadline seconds (0 = derive from the straggler budget, else 120s) |
| heartbeat_interval     | BIGDL_HEARTBEAT_INTERVAL    | heartbeat publish/poll throttle seconds (default 1.0) |
| local_sync_h           | BIGDL_LOCAL_SYNC_H          | parameter_sync=local: local steps H between parameter averagings (parallel/local_sync.py; default 8) |
| local_sync_stale       | BIGDL_LOCAL_SYNC_STALE      | parameter_sync=local: staleness bound S — a peer S averaging rounds behind is shed (default 3) |
| local_sync_grace       | BIGDL_LOCAL_SYNC_GRACE      | parameter_sync=local: grace window seconds a peer AT the bound gets before the shed (0 = derive from the heartbeat interval) |
| scan_layers            | BIGDL_SCAN_LAYERS           | build registry models with repeated blocks stacked into ScanLayers (docs/compile.md; default off) |
| sparse_sync            | BIGDL_SPARSE                | sparse embedding-gradient sync (docs/sparse.md): off / auto (on when touched rows <= vocab/2) / on — numerics-exact row-sparse (indices, rows) sync instead of the dense table all-reduce |
| trace_requests         | BIGDL_TRACE                 | per-request serving traces (telemetry/request_trace.py): span timelines, /v1/trace/<id>, blame verdicts (default on; off disables recording) |
| trace_ring             | BIGDL_TRACE_RING            | recent-trace ring size per server (default 512) |
| trace_slowest          | BIGDL_TRACE_SLOWEST         | always-kept slowest-k traces per endpoint — the p99 exemplars eviction can never touch (default 8) |
| trace_spans            | BIGDL_TRACE_SPANS           | per-trace span cap; decode iterations past it are tallied in components, not recorded (default 512) |

Performance knobs read directly at their consumer (hardware-tuning
surface, not part of the typed object because they are read at trace
time inside jitted-program construction):

| env var               | consumer |
|-----------------------|----------|
| BIGDL_FLASH_BLOCK_Q/K | ops.attention flash block sizes (default 1024/512 — round-5 hardware sweep) |
| BIGDL_FLASH_MIN_SEQ   | ops.attention auto-backend threshold (default 512; dense below) |
| BIGDL_POOL_KERNEL     | ops.pooling_pallas argmax-index pool (off/auto/on/interpret; auto=off — see BASELINE.md postmortem) |
| BIGDL_COMPILE_CACHE   | Engine.enable_compile_cache persistent XLA executable cache dir |
| BIGDL_COMPILE_CACHE_MIN_S | Engine.enable_compile_cache min compile seconds for an entry to persist (default 0.1) |
| BIGDL_SINGLETON_WAIT  | Engine.check_singleton bounded wait (s) for a lock holder |
| BIGDL_COORDINATOR_TIMEOUT | Engine._init_distributed bounded jax.distributed join (s, default 300; 0 = unbounded) |
| BIGDL_PEAK_FLOPS      | telemetry.device MFU denominator override (FLOP/s per device) |
| BIGDL_PEAK_BW         | telemetry.device comms-bandwidth denominator override (interconnect bytes/s per device) |
| BIGDL_HBM_GB          | telemetry.memory per-device HBM budget override in GiB (fit estimator + OOM forensics; default: the per-chip table, else the live allocator limit) |
| JAX_PLATFORMS         | honored over externally-registered PJRT plugins via honor_platform_request |
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields
from typing import Optional

__all__ = ["BigDLConfig", "get_config", "set_config", "retry_backoff_s"]


def retry_backoff_s(attempt: int, base: Optional[float] = None) -> float:
    """The ONE restart/retry backoff policy: exponential from ``base``
    seconds (default: the ``BIGDL_RETRY_BACKOFF`` config) with
    multiplicative jitter, capped at 30 s; ``base <= 0`` disables.
    Shared by the Optimizer retry loop and the cluster Supervisor so
    the two cannot drift apart."""
    import random

    if base is None:
        base = get_config().retry_backoff
    if base <= 0:
        return 0.0
    return min(30.0, base * (2.0 ** max(attempt - 1, 0))) \
        * random.uniform(0.5, 1.0)


def _truthy(v: Optional[str]) -> bool:
    return (v or "").lower() in ("1", "true", "yes", "on")


@dataclass
class BigDLConfig:
    # multi-host control plane
    coordinator_address: Optional[str] = None
    num_processes: int = 1
    process_id: int = 0
    # host topology
    node_number: Optional[int] = None
    core_number: Optional[int] = None
    default_pool_size: Optional[int] = None
    local_mode: bool = False
    # failure handling
    failure_retry_times: int = 5
    failure_retry_interval: float = 120.0
    iteration_timeout: str = ""  # "", "0", "<seconds>", or "auto"
    check_singleton_strict: bool = False  # BIGDL_CHECK_SINGLETON: raise vs warn
    # profiling
    profile_dir: Optional[str] = None
    profile_iters: int = 5
    # telemetry (docs/observability.md): JSONL run logs + device facts
    telemetry_dir: Optional[str] = None
    telemetry_device: str = "auto"  # off | auto | full
    # module-path scopes in compiled HLO (cost attribution substrate)
    module_scopes: bool = True
    # emit per-module attribution events (re-lower + parse per step obj)
    telemetry_attribution: bool = False
    # per-collective comms events (telemetry/comms.py): off | auto | on.
    # auto = only for steps whose mesh spans >1 device — the one case
    # collectives exist.  Costs one extra LOCAL XLA compile per step
    # object (collectives only exist post-SPMD-partitioning, and jit's
    # executable cache is not reachable from the lowered program).
    telemetry_comms: str = "auto"
    # per-step memory events (telemetry/memory.py): off | auto | on.
    # auto = only for steps whose mesh spans >1 device (the case where
    # per-device HBM is the scaling question and where the comms event
    # already pays the post-SPMD compile the walker shares).
    telemetry_memory: str = "auto"
    # coordinator-side live fleet watcher poll seconds (0 disables)
    fleet_interval: float = 2.0
    # crash flight recorder: event-ring capacity (0 disables)
    flight_events: int = 2048
    # arm a one-shot profiler capture when health first escalates
    profile_on_health: Optional[str] = None
    # live metrics endpoint: None = off, 0 = ephemeral port
    metrics_port: Optional[int] = None
    # training health (telemetry/health.py): off | warn | skip | halt
    health_action: str = "halt"
    health_halt_after: int = 3
    # native layer
    no_native: bool = False
    # log management (LoggerFilter.scala property family)
    log_disable: bool = False
    log_file: Optional[str] = None
    log_thirdparty: bool = True
    # input pipeline: batches to transform+transfer ahead of the device
    prefetch_batches: int = 2
    # overlap checkpoint byte-writes with the next training iterations
    async_checkpoint: bool = True
    # failure-retry backoff base (seconds); exponential with jitter,
    # capped at 30s; 0 disables the sleep
    retry_backoff: float = 1.0
    # auto-resume from the configured checkpoint dir at optimize() start
    resume: str = "auto"  # auto | off
    # deterministic fault injection (bigdl_tpu/faults.py); "" = none
    faults: str = ""
    faults_seed: int = 0
    # cluster fault tolerance (bigdl_tpu/parallel/cluster.py): shared
    # heartbeat/commit dir (None = off), peer deadline (0 = derived),
    # heartbeat write/poll throttle
    cluster_dir: Optional[str] = None
    cluster_deadline: float = 0.0
    heartbeat_interval: float = 1.0
    # local-SGD (parallel/local_sync.py, docs/fault_tolerance.md
    # "Straggler tolerance"): H local steps between parameter
    # averagings; a peer whose averaging round falls S rounds behind
    # the fleet is shed.  Read by the Optimizer when
    # parameter_sync="local".
    local_sync_h: int = 8
    local_sync_stale: int = 3
    local_sync_grace: float = 0.0
    # scan-over-layers (nn/layers/scan.py, docs/compile.md): build the
    # registry models with repeated-block runs stacked into ScanLayers
    # so XLA compiles ONE block body instead of N
    scan_layers: bool = False
    # sparse embedding-gradient sync (nn/layers/embedding.py,
    # docs/sparse.md): off | auto | on.  auto (default) routes a
    # sparse-capable table through the row-sparse (indices, rows)
    # cotangent when the batch's worst-case touched rows are at most
    # half the vocab; on forces every capable table; off is the dense
    # A/B baseline.  Numerics-exact either way.
    sparse_sync: str = "auto"
    # request-level serving traces (telemetry/request_trace.py,
    # docs/observability.md "Tracing a request"): recording on/off,
    # recent-ring size, pinned slowest-k per endpoint, per-trace span cap
    trace_requests: bool = True
    trace_ring: int = 512
    trace_slowest: int = 8
    trace_spans: int = 512

    @classmethod
    def from_env(cls, env=os.environ) -> "BigDLConfig":
        def _int(name, default):
            v = env.get(name)
            return int(v) if v else default

        def _float(name, default):
            v = env.get(name)
            return float(v) if v else default

        return cls(
            coordinator_address=env.get("BIGDL_COORDINATOR_ADDRESS") or None,
            num_processes=_int("BIGDL_NUM_PROCESSES", 1),
            process_id=_int("BIGDL_PROCESS_ID", 0),
            node_number=_int("BIGDL_NODE_NUMBER", 0) or None,
            core_number=_int("BIGDL_CORE_NUMBER", 0) or None,
            default_pool_size=_int("BIGDL_DEFAULT_POOL_SIZE", 0) or None,
            local_mode=_truthy(env.get("BIGDL_LOCAL_MODE")),
            failure_retry_times=_int("BIGDL_FAILURE_RETRY_TIMES", 5),
            failure_retry_interval=_float("BIGDL_FAILURE_RETRY_INTERVAL", 120.0),
            iteration_timeout=(env.get("BIGDL_ITERATION_TIMEOUT") or "").strip(),
            check_singleton_strict=_truthy(env.get("BIGDL_CHECK_SINGLETON")),
            profile_dir=env.get("BIGDL_PROFILE") or None,
            profile_iters=_int("BIGDL_PROFILE_ITERS", 5),
            telemetry_dir=env.get("BIGDL_TELEMETRY") or None,
            telemetry_device=(env.get("BIGDL_TELEMETRY_DEVICE")
                              or "auto").strip().lower(),
            module_scopes=(env.get("BIGDL_SCOPES") or "on").strip().lower()
            not in ("0", "off", "false", "no"),
            telemetry_attribution=_truthy(env.get("BIGDL_ATTRIBUTION")),
            telemetry_comms=(env.get("BIGDL_COMMS")
                             or "auto").strip().lower(),
            telemetry_memory=(env.get("BIGDL_MEMORY")
                              or "auto").strip().lower(),
            fleet_interval=_float("BIGDL_FLEET_INTERVAL", 2.0),
            flight_events=_int("BIGDL_FLIGHT", 2048),
            profile_on_health=env.get("BIGDL_PROFILE_ON_HEALTH") or None,
            # NB: "0" is a VALID port request (ephemeral), so the usual
            # `_int(...) or None` falsiness shortcut would drop it
            metrics_port=(int(env["BIGDL_METRICS_PORT"])
                          if env.get("BIGDL_METRICS_PORT") not in
                          (None, "") else None),
            health_action=(env.get("BIGDL_HEALTH")
                           or "halt").strip().lower(),
            health_halt_after=_int("BIGDL_HEALTH_HALT_AFTER", 3),
            no_native=_truthy(env.get("BIGDL_TPU_NO_NATIVE")),
            log_disable=_truthy(env.get("BIGDL_LOGGER_DISABLE")),
            log_file=env.get("BIGDL_LOG_FILE") or None,
            log_thirdparty=_truthy(env.get("BIGDL_LOG_THIRDPARTY") or "true"),
            prefetch_batches=_int("BIGDL_PREFETCH", 2),
            async_checkpoint=_truthy(
                env.get("BIGDL_ASYNC_CHECKPOINT") or "true"),
            retry_backoff=_float("BIGDL_RETRY_BACKOFF", 1.0),
            resume=(env.get("BIGDL_RESUME") or "auto").strip().lower(),
            faults=(env.get("BIGDL_FAULTS") or "").strip(),
            faults_seed=_int("BIGDL_FAULTS_SEED", 0),
            cluster_dir=env.get("BIGDL_CLUSTER_DIR") or None,
            cluster_deadline=_float("BIGDL_CLUSTER_DEADLINE", 0.0),
            heartbeat_interval=_float("BIGDL_HEARTBEAT_INTERVAL", 1.0),
            local_sync_h=_int("BIGDL_LOCAL_SYNC_H", 8),
            local_sync_stale=_int("BIGDL_LOCAL_SYNC_STALE", 3),
            local_sync_grace=_float("BIGDL_LOCAL_SYNC_GRACE", 0.0),
            scan_layers=_truthy(env.get("BIGDL_SCAN_LAYERS")),
            sparse_sync=(env.get("BIGDL_SPARSE")
                         or "auto").strip().lower(),
            trace_requests=(env.get("BIGDL_TRACE") or "on").strip().lower()
            not in ("0", "off", "false", "no"),
            trace_ring=_int("BIGDL_TRACE_RING", 512),
            trace_slowest=_int("BIGDL_TRACE_SLOWEST", 8),
            trace_spans=_int("BIGDL_TRACE_SPANS", 512),
        )


_config: Optional[BigDLConfig] = None


def get_config() -> BigDLConfig:
    """The process-wide config.  An explicitly installed config
    (:func:`set_config`) wins; otherwise the environment is re-resolved
    on each call — call sites read it once per operation (not per
    iteration), so env mutations (e.g. in tests) take effect at the next
    operation boundary."""
    if _config is not None:
        return _config
    return BigDLConfig.from_env()


def set_config(cfg: Optional[BigDLConfig]) -> None:
    """Install an explicit config (tests / embedding apps); ``None``
    reverts to env resolution on next :func:`get_config`."""
    global _config
    _config = cfg
