"""The serving HTTP frontend — stdlib ``ThreadingHTTPServer`` on the
pattern ``telemetry/metrics_http.py`` proved (no new dependencies, a
daemon thread per connection, handler errors never kill the process).

Endpoints:

- ``POST /v1/predict`` — body ``{"inputs": <nested list>}``: one sample
  (the model's feature shape) or a ``[k, ...]`` micro-batch.  The
  request rides the bounded queue into the continuous batcher; the
  response carries outputs plus its own latency split
  (``{"outputs": ..., "ms": total, "queue_ms": wait}``).  ``429`` when
  the queue is full (backpressure), ``503`` while draining, ``400`` on
  shape/JSON errors, ``504`` when a dispatch exceeds the request
  timeout;
- ``POST /v1/generate`` (``generate=True`` servers) — body
  ``{"prompt": [token ids], "max_new_tokens": n, "temperature": t,
  "top_k": k, "seed": s, "stream": true}``.  Streaming (the default)
  answers with chunked transfer encoding, one JSON line per token
  (``{"token": id, "i": n}``) as each is sampled, closed by a
  ``{"done": true, ...stats}`` line — time-to-first-byte IS
  time-to-first-token.  ``stream: false`` returns one JSON object with
  the full token list.  Same 429/503/400 discipline as predict;
- ``GET /status``  — serving stats (qps, p50/p99 latency, queue depth,
  batch fill, padding waste, warm buckets, compile counts) merged with
  the same profiler/flight/cluster observer block ``/status`` carries
  on the metrics endpoint — one JSON shape for ``tools/tpu_watch.sh``
  and humans with curl;
- ``GET /healthz`` — 200 while serving, **503 once draining** (the
  load balancer's signal to stop routing here);
- ``GET /metrics`` — serving gauges in OpenMetrics text, scrape-ready.

Graceful shutdown: SIGTERM flips ``/healthz`` to 503, stops admissions,
finishes every queued request, then exits 0 (``serve/drain`` instant) —
a rolling restart drops zero accepted requests.
"""

from __future__ import annotations

import json
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from bigdl_tpu import telemetry as _telemetry
from bigdl_tpu.serving.batcher import (ContinuousBatcher, QueueFullError,
                                       Request)
from bigdl_tpu.serving.buckets import BucketPolicy
from bigdl_tpu.serving.executor import executor_for
from bigdl_tpu.telemetry import request_trace as _rt

__all__ = ["ModelServer", "serve_model", "get"]

_ACTIVE: Optional["ModelServer"] = None


def get() -> Optional["ModelServer"]:
    """The live server (None outside serving processes) — the accessor
    ``telemetry/metrics_http.py`` and ``tools/tpu_watch.sh`` read."""
    return _ACTIVE


class ModelServer:
    """One served model: executor + batcher + HTTP frontend.

    ``input_spec`` is the model's canonical batched input
    (``jax.ShapeDtypeStruct`` with a leading batch axis — what
    ``models/registry.input_spec`` returns); its trailing dims are the
    per-sample feature shape and its dtype gates request parsing.
    ``seq_buckets`` (token models) buckets the time axis too.

    ``generate=True`` (causal token models) adds the autoregressive
    path: the executor becomes a :class:`GenerateExecutor` (prefill +
    decode executables share the predict compile cache and ONE device
    copy of the weights), a :class:`GenerationBatcher` coalesces decode
    steps across requests, and ``POST /v1/generate`` streams tokens.
    ``decode_buckets`` / ``cache_buckets`` bound its executable key
    space; ``seq_buckets`` is required (prompts pad onto it).
    """

    def __init__(self, model, input_spec, name: str = "model",
                 host: str = "0.0.0.0", port: int = 0,
                 max_batch: int = 32, max_wait_ms: float = 5.0,
                 queue_limit: int = 256,
                 batch_buckets=None, seq_buckets=None,
                 mesh=None, compute_dtype=None,
                 request_timeout_s: float = 30.0,
                 generate: bool = False, decode_buckets=None,
                 cache_buckets=None, eos_token: Optional[int] = None,
                 max_new_tokens_limit: int = 1024,
                 slo_p99_ms: Optional[float] = None,
                 slo_ttft_ms: Optional[float] = None):
        from bigdl_tpu.utils.config import get_config

        self.model = model.evaluate()
        self.name = name
        self.sample_shape: Tuple[int, ...] = tuple(input_spec.shape[1:])
        self.dtype = np.dtype(input_spec.dtype)
        self.request_timeout_s = request_timeout_s
        self.max_new_tokens_limit = max_new_tokens_limit
        # request-level tracing (telemetry/request_trace.py): every
        # admitted request gets a trace id + span timeline; the store
        # keeps the recent ring AND the slowest-k per endpoint
        cfg = get_config()
        self.traces: Optional[_rt.TraceStore] = (
            _rt.TraceStore(ring=cfg.trace_ring,
                           slowest_k=cfg.trace_slowest)
            if cfg.trace_requests else None)
        self._trace_spans = cfg.trace_spans
        self.slo = _rt.SLOTracker(p99_ms=slo_p99_ms,
                                  ttft_ms=slo_ttft_ms)
        self._hist: Dict[str, _rt.LatencyHistogram] = {
            "predict": _rt.LatencyHistogram(),
            "generate": _rt.LatencyHistogram(),
            "ttft": _rt.LatencyHistogram()}
        self._baselines: Dict[str, _rt.ComponentBaseline] = {
            "predict": _rt.ComponentBaseline(),
            "generate": _rt.ComponentBaseline()}
        seq_axis = 1 if seq_buckets else None
        policy = BucketPolicy(max_batch=max_batch,
                              batch_buckets=batch_buckets,
                              seq_buckets=seq_buckets)
        self.gen_batcher = None
        if generate:
            from bigdl_tpu.serving.generate import (GenerateExecutor,
                                                    GenerationBatcher)

            if not seq_buckets:
                raise ValueError(
                    "generate=True needs seq_buckets (the prompt "
                    "padding shapes)")
            # a dedicated executor (not the shared executor_for cache):
            # its key space carries prefill/decode executables the
            # plain registry entry must never pay warmup for
            self.executor = GenerateExecutor(
                model, mesh=mesh, policy=policy,
                compute_dtype=compute_dtype,
                decode_buckets=decode_buckets,
                cache_buckets=cache_buckets)
            self.gen_batcher = GenerationBatcher(
                self.executor, max_wait_ms=max_wait_ms,
                queue_limit=queue_limit, eos_token=eos_token,
                on_retire=self._finish_generate_trace)
        else:
            self.executor = executor_for(model, mesh=mesh, policy=policy,
                                         compute_dtype=compute_dtype,
                                         seq_axis=seq_axis)
        self.batcher = ContinuousBatcher(
            self.executor.run, max_batch=max_batch,
            max_wait_ms=max_wait_ms, queue_limit=queue_limit,
            seq_pad=self._pad_seqs if seq_buckets else None,
            seq_trim=self._trim_seq if seq_buckets else None,
            bucket_rows=self._bucket_rows)
        self._started_at = time.time()
        self._term = threading.Event()
        self._stopped = False
        # open /v1/generate streams: the drain path waits for handlers
        # to flush their final chunks before tearing the HTTP server
        # down (the generations themselves finish via gen_batcher.stop)
        self._streams_lock = threading.Lock()
        self._open_streams = 0
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.model_server = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="bigdl-serve-http",
            kwargs={"poll_interval": 0.2}, daemon=True)
        self._thread.start()
        global _ACTIVE
        _ACTIVE = self
        _telemetry.instant("serve/started", port=self.port, model=name)

    # -- warmup ------------------------------------------------------------
    def warmup(self) -> float:
        """AOT-compile every bucket before taking traffic; returns the
        wall seconds spent (the cold-start cost paid ONCE, up front)."""
        return self.executor.warmup(self.sample_shape, self.dtype)

    # -- request path ------------------------------------------------------
    def _pad_seqs(self, xs):
        """Mixed-length token micro-batches: pad each to the batch's
        common seq bucket so they concatenate (axis-1 ragged -> one
        bucketed time axis).  Returns (padded list, common target) —
        the batcher keeps each request's ORIGINAL length and trims the
        outputs back via :meth:`_trim_seq`."""
        target = max(self.executor.policy.seq_bucket(x.shape[1])
                     for x in xs)
        return [self.executor.policy.pad(x, x.shape[0], target)
                for x in xs], target

    def _trim_seq(self, out, orig_len: int, target: int):
        """Slice one request's output time axis back to its submitted
        length.  Same guard as the executor's own slice-back: only
        leaves whose axis 1 equals the padded target are seq-shaped;
        time-reducing heads ([k, classes]) pass through untouched."""
        import jax

        def leaf(a):
            a = np.asarray(a)
            if a.ndim >= 2 and a.shape[1] == target:
                return a[:, :orig_len]
            return a

        return jax.tree.map(leaf, out)

    def _bucket_rows(self, rows: int, cap: int) -> int:
        try:
            return self.executor.policy.batch_bucket(min(rows, cap))
        except ValueError:
            return rows

    def parse_inputs(self, payload: Dict[str, Any]
                     ) -> Tuple[np.ndarray, bool]:
        """-> ([k, ...] rows, was_single_sample).  ONE list->ndarray
        conversion — the most expensive CPU step on the request path."""
        if not isinstance(payload, dict) or "inputs" not in payload:
            raise ValueError('body must be {"inputs": <nested list>}')
        arr = np.asarray(payload["inputs"], dtype=self.dtype)
        nd = len(self.sample_shape)
        single = arr.ndim == nd
        if single:
            arr = arr[None]
        elif arr.ndim != nd + 1:
            raise ValueError(
                f"inputs must have {nd} dims (one sample) or {nd + 1} "
                f"(a [k, ...] micro-batch); got {arr.ndim}")
        if arr.shape[0] > self.batcher.max_batch:
            raise ValueError(
                f"micro-batch of {arr.shape[0]} rows exceeds max_batch "
                f"{self.batcher.max_batch} — split the request")
        # fixed feature dims must match exactly; a seq-bucketed time
        # axis (axis 1) is the one allowed to vary
        fixed_from = 1 if self.executor.seq_axis is not None else 0
        if arr.shape[1 + fixed_from:] != self.sample_shape[fixed_from:]:
            raise ValueError(
                f"sample shape {arr.shape[1:]} incompatible with the "
                f"model's {self.sample_shape}")
        return arr, single

    def parse_generate(self, payload: Dict[str, Any]
                       ) -> Tuple[Dict[str, Any], bool]:
        """Validated kwargs for ``gen_batcher.submit`` + ``stream``;
        raises ValueError (the frontend's 400) on anything malformed."""
        if self.gen_batcher is None:
            raise ValueError(
                "this server was not started with generate=True")
        if not isinstance(payload, dict) or "prompt" not in payload:
            raise ValueError('body must be {"prompt": [token ids], ...}')
        prompt = np.asarray(payload["prompt"])
        if prompt.ndim != 1 or prompt.size < 1 \
                or not np.issubdtype(prompt.dtype, np.integer):
            raise ValueError("prompt must be a flat non-empty list of "
                             "integer token ids")
        n = int(payload.get("max_new_tokens", 32))
        if not 1 <= n <= self.max_new_tokens_limit:
            raise ValueError(f"max_new_tokens must be in "
                             f"[1, {self.max_new_tokens_limit}]")
        out = {"prompt": prompt.astype(np.int32),
               "max_new_tokens": n,
               "temperature": float(payload.get("temperature", 0.0)),
               "top_k": int(payload.get("top_k", 0)),
               "seed": int(payload.get("seed", 0))}
        if payload.get("eos_token") is not None:
            out["eos_token"] = int(payload["eos_token"])
        return out, bool(payload.get("stream", True))

    def predict(self, arr: np.ndarray, trace=None) -> Request:
        """Submit rows and wait for the carrying batch; returns the
        completed :class:`Request` (``output``/``queue_ms``/``dispatch``
        filled).  Raises QueueFullError / TimeoutError."""
        req = self.batcher.submit(arr, trace=trace)
        if not req.wait(self.request_timeout_s):
            # nobody will read the answer: tell the worker to DROP the
            # rows — under overload, timed-out work must not keep the
            # device busy amplifying the overload
            req.cancel()
            raise TimeoutError(
                f"no dispatch within {self.request_timeout_s}s")
        if req.error is not None:
            raise req.error
        return req

    # -- request tracing ---------------------------------------------------
    def start_trace(self, endpoint: str,
                    header_id: Optional[str] = None
                    ) -> Tuple[str, Optional["_rt.RequestTrace"]]:
        """Mint-or-propagate the trace id (the ``X-Request-Id``
        contract: a valid client id is kept and echoed, anything else
        replaced) and open a trace when recording is on.  The id is
        echoed even with tracing off — propagation is the contract,
        recording the observer."""
        tid = header_id if _rt.valid_id(header_id) else _rt.mint_id()
        trace = (_rt.RequestTrace(tid, endpoint,
                                  max_spans=self._trace_spans)
                 if self.traces is not None else None)
        return tid, trace

    def _emit_request(self, trace: "_rt.RequestTrace",
                      violated=None) -> None:
        tracer = _telemetry.get()
        if tracer is None:
            return
        doc = trace.to_dict()
        if violated:
            doc["slo_violated"] = list(violated)
        if self.slo.p99_ms is not None:
            doc["slo_p99_ms"] = self.slo.p99_ms
        if self.slo.ttft_ms is not None:
            doc["slo_ttft_ms"] = self.slo.ttft_ms
        tracer.emit("request", **doc)

    def finish_rejected(self, trace: Optional["_rt.RequestTrace"],
                        reason: str, endpoint: str = "predict",
                        trace_id: Optional[str] = None,
                        wall_ms: Optional[float] = None) -> None:
        """Terminal-span trace for a rejected/expired request (429
        queue_full, 503 draining, 504 dispatch_timeout) — rejection
        spikes stay diagnosable post-hoc, per reason.

        Budget accounting splits by reason: a 429/503 rejection is
        instant and deliberately stays OUT of the latency distribution
        (its ~0 ms wall would dilute the observed p99 DOWN and mask
        burn), but a 504 dispatch timeout is the opposite — the client
        waited the full ``wall_ms`` — so its wall enters the SLO burn
        and histograms; the requests that blew the budget are exactly
        the ones the gate must see.  Runs with tracing off too
        (``trace`` None): budgets burn regardless of recording."""
        violated = None
        if reason == "dispatch_timeout" and wall_ms is not None:
            violated = self._observe_budgets(
                endpoint, wall_ms,
                trace.trace_id if trace is not None
                else (trace_id or "untraced"))
        if trace is None:
            return
        trace.finish("rejected", reason)
        rem = max(0.0, (trace.total_ms or 0.0) - trace.span_sum_ms())
        trace.add_span("rejected", trace.finished_at - rem / 1000.0, rem,
                   reason=reason)
        if violated:
            trace.attrs["slo_violated"] = violated
        self.traces.add(trace)
        self._emit_request(trace)

    def finish_failed(self, trace: Optional["_rt.RequestTrace"],
                      message: str, endpoint: str = "predict",
                      trace_id: Optional[str] = None,
                      wall_ms: Optional[float] = None) -> None:
        """Terminal trace for a request whose dispatch raised (the 500
        path) — the requests most in need of post-hoc evidence are the
        ones that failed server-side.  Their walls are real waiting the
        client did, so they enter the SLO burn + histograms (matching
        the generate path, where errored requests land through the
        retire hook) — with or without a recorded trace."""
        violated = None
        if wall_ms is not None:
            violated = self._observe_budgets(
                endpoint, wall_ms,
                trace.trace_id if trace is not None
                else (trace_id or "untraced"))
        if trace is None:
            return
        trace.finish("error", message)
        self._close_books(trace)
        if violated:
            trace.attrs["slo_violated"] = violated
        self.traces.add(trace)
        self._emit_request(trace)

    def _observe_budgets(self, endpoint: str, ms: Optional[float],
                         trace_id: str,
                         ttft_ms: Optional[float] = None) -> list:
        """Latency histograms + SLO burn accounting for one completed
        request.  Deliberately independent of trace RECORDING: with
        ``BIGDL_TRACE=off`` the waterfalls go dark, but the declared
        budgets keep burning and the bench gate keeps gating."""
        hist = self._hist.get(endpoint)
        if hist is not None and ms is not None:
            hist.observe(ms)
        if ttft_ms is not None:
            self._hist["ttft"].observe(ttft_ms)
        violated = self.slo.observe(ms, trace_id, ttft_ms=ttft_ms)
        self.slo.maybe_gauges()
        return violated

    def _finish_predict_trace(self, trace: Optional["_rt.RequestTrace"],
                              req: Request, respond_ms: float,
                              wall_ms: Optional[float] = None) -> None:
        """Tile one predict request's wall time into owned spans off
        the worker's dispatch record, judge the blame verdict, and
        land the trace in the store + run log + SLO ledger."""
        if trace is None:
            self._observe_budgets("predict", wall_ms, "untraced")
            return
        d = req.dispatch or {}
        t0_ts = d.get("t0_ts", req.enqueued_ts)
        trace.add_span("queue_wait", req.enqueued_ts, req.queue_ms,
                   component="queue_wait", depth=d.get("co_requests"))
        _rt.stamp_dispatch_spans(
            trace, t0_ts, float(d.get("infer_ms", 0.0)), d, "infer",
            default_bucket=req.rows, rows=req.rows,
            co_requests=d.get("co_requests"),
            device_ms=d.get("device_ms"))
        trace.finish("ok")
        if respond_ms:
            trace.add_span("respond", trace.finished_at - respond_ms / 1000.0,
                       respond_ms, component="respond")
        self._close_books(trace)
        self._land(trace, "predict")

    def _finish_generate_trace(self, req) -> None:
        """GenerationBatcher retire hook: close out one generation's
        trace (components were tallied live by the worker), compute the
        co-batch-stall split, and land it."""
        trace = getattr(req, "trace", None)
        if trace is None:
            # enqueue-to-retire, NOT stats()["dur_s"]: dur_s is 0.0
            # for a request that never emitted a token (504 timeout,
            # prefill failure) and only partial for a timed-out one —
            # a budget must burn on the wall the client actually
            # waited, with recording off exactly like on
            wall_ms = (time.perf_counter() - req.enqueued_at) * 1000.0
            self._observe_budgets("generate", wall_ms, "untraced",
                                  ttft_ms=req.ttft_ms())
            return
        if trace.attrs.pop("timed_out", None):
            # the handler already told the client 504: land a terminal
            # dispatch_timeout REJECTION (per-reason counted) whose
            # full wall still burns the budgets — but keep its
            # components OUT of the healthy baseline, a 30s timeout
            # must not drag the medians the blame verdict judges by
            ttft = req.ttft_ms()
            if ttft is not None:
                trace.attrs["ttft_ms"] = round(ttft, 3)
            trace.attrs["n_tokens"] = len(req.tokens)
            trace.finish("rejected", "dispatch_timeout")
            self._close_books(trace)
            self._land(trace, "generate", ttft_ms=ttft,
                       observe_baseline=False)
            return
        status = {"error": "error",
                  "cancelled": "cancelled"}.get(req.finish_reason, "ok")
        baseline = self._baselines["generate"]
        # co_batch_stall: decode iterations that rode a LARGER co-batch
        # than the endpoint's typical one, judged against the typical
        # per-iteration cost — the time this request lost to riding a
        # crowded batch, split out of decode compute
        base_iter = baseline.median("decode_iter_ms")
        base_cb = baseline.median("decode_co_batch") or 1.0
        stall = 0.0
        for ms, cb in trace.iters:
            baseline.observe("decode_iter_ms", ms)
            baseline.observe("decode_co_batch", cb)
            if baseline.samples >= _rt.BASELINE_MIN_SAMPLES \
                    and cb > base_cb and base_iter:
                stall += max(0.0, ms - base_iter)
        if stall > 0:
            trace.add_component("co_batch_stall", stall)
            trace.add_component("compute", -stall)
        ttft = req.ttft_ms()
        if ttft is not None:
            trace.attrs["ttft_ms"] = round(ttft, 3)
        trace.attrs["n_tokens"] = len(req.tokens)
        trace.attrs["finish_reason"] = req.finish_reason
        trace.finish(status, req.error if status == "error" else None)
        self._close_books(trace)
        self._land(trace, "generate", ttft_ms=ttft)

    @staticmethod
    def _close_books(trace: "_rt.RequestTrace") -> None:
        """Every millisecond of wall time must be owned by exactly one
        span: whatever the instrumented crossings did not claim (host
        scheduling, sampling, queue hand-offs) becomes one explicit
        ``host`` residual span instead of a silent gap — the component
        sum equals the observed wall time by construction.  The
        residual is judged against the COMPONENT tally, not the span
        list: spans dropped past the per-trace cap already tallied
        their milliseconds there, and must not be counted again."""
        rem = (trace.total_ms or 0.0) - sum(trace.components.values())
        if rem > 0.05:
            trace.add_span("host", trace.finished_at - rem / 1000.0, rem,
                       component="host")

    def _land(self, trace: "_rt.RequestTrace", endpoint: str,
              ttft_ms: Optional[float] = None,
              observe_baseline: bool = True) -> None:
        """The one landing sequence: blame + baseline (healthy
        completions only — ``observe_baseline=False`` for rejected
        walls that must not drag the medians), budget observation,
        store, run-log emission."""
        if observe_baseline:
            baseline = self._baselines[endpoint]
            trace.blame = _rt.blame_verdict(trace.components, baseline,
                                            trace.total_ms)
            baseline.observe_components(trace.components)
        violated = self._observe_budgets(endpoint,
                                         trace.total_ms or 0.0,
                                         trace.trace_id,
                                         ttft_ms=ttft_ms)
        if violated:
            trace.attrs["slo_violated"] = violated
        self.traces.add(trace)
        self._emit_request(trace, violated=violated)

    # -- views -------------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        st = self.batcher.stats()
        st.update(
            model=self.name, port=self.port,
            uptime_s=round(time.time() - self._started_at, 3),
            sample_shape=list(self.sample_shape), dtype=str(self.dtype),
            batch_buckets=list(self.executor.policy.batch_buckets),
            seq_buckets=list(self.executor.policy.seq_buckets)
            if self.executor.policy.seq_buckets else None,
            warm_buckets=[list(k for k in key if k is not None)
                          for key in self.executor.warm_buckets()],
            compiles=self.executor.compile_count,
            warmup_s=round(self.executor.warmup_s, 3))
        if self.gen_batcher is not None:
            gen = self.gen_batcher.stats()
            gen["decode_buckets"] = list(self.executor.decode_buckets)
            gen["cache_buckets"] = list(self.executor.cache_buckets)
            st["generate"] = gen
        if self.traces is not None:
            # the tail-aware trace summary: counts, slowest-k ids per
            # endpoint (the p99 exemplars), rejection reasons — the
            # evidence index tpu_watch and humans-with-curl start from
            st["traces"] = self.traces.summary()
        if self.slo.active():
            st["slo"] = self.slo.status()
        try:
            # resident-executable HBM (weights + code + largest bucket
            # scratch): the number ROADMAP item 2's KV-cache budget
            # subtracts from the device before sizing caches
            st["memory"] = self.executor.memory_summary()
        except Exception:  # noqa: BLE001 - accounting is an observer
            pass
        return st

    def openmetrics(self) -> str:
        st = self.status()
        lines = []
        for key, mtype in (("qps", "gauge"), ("p50_ms", "gauge"),
                           ("p99_ms", "gauge"), ("queue_depth", "gauge"),
                           ("requests", "counter"),
                           ("rejected", "counter"),
                           ("rows", "counter"), ("batches", "counter"),
                           ("errors", "counter"),
                           ("compiles", "counter")):
            v = st.get(key)
            if v is None:
                continue
            name = f"bigdl_serve_{key}" + (
                "_total" if mtype == "counter" else "")
            lines.append(f"# TYPE {name} {mtype}")
            lines.append(f'{name}{{model="{self.name}"}} {float(v):g}')
        gen = st.get("generate") or {}
        for key, mtype in (("gen_tokens", "counter"),
                           ("tokens_s", "gauge"),
                           ("ttft_p50_ms", "gauge"),
                           ("ttft_p99_ms", "gauge"),
                           ("itl_p99_ms", "gauge"),
                           ("active_seqs", "gauge"),
                           ("cache_occupancy", "gauge")):
            v = gen.get(key)
            if v is None:
                continue
            name = "bigdl_gen_tokens_total" if key == "gen_tokens" \
                else f"bigdl_gen_{key}"
            lines.append(f"# TYPE {name} {mtype}")
            lines.append(f'{name}{{model="{self.name}"}} {float(v):g}')
        # real OpenMetrics histograms (fixed log-spaced buckets) beside
        # the ring-buffer gauges above: external scrapers compute
        # arbitrary quantiles from these; the gauges stay for
        # tpu_watch.sh (docs/observability.md)
        label = f'model="{self.name}"'
        lines.extend(self._hist["predict"].openmetrics(
            "bigdl_serve_latency_ms", f'{label},endpoint="predict"'))
        if self.gen_batcher is not None:
            lines.extend(self._hist["generate"].openmetrics(
                "bigdl_serve_latency_ms",
                f'{label},endpoint="generate"', type_line=False))
            lines.extend(self._hist["ttft"].openmetrics(
                "bigdl_serve_ttft_ms", label))
        if self.traces is not None:
            rej = self.traces.summary()["rejections"]
            if rej:
                lines.append("# TYPE bigdl_serve_rejected_by_reason"
                             "_total counter")
                for reason, n in sorted(rej.items()):
                    lines.append(
                        f"bigdl_serve_rejected_by_reason_total"
                        f'{{{label},reason="{reason}"}} {n}')
        if self.slo.active():
            burn = self.slo.burn()
            for which, metric in (("p99", "bigdl_slo_p99_burn_ratio"),
                                  ("ttft", "bigdl_slo_ttft_burn_ratio")):
                b = (burn.get(which) or {}).get("burn")
                if b is None:
                    continue
                lines.append(f"# TYPE {metric} gauge")
                lines.append(f"{metric}{{{label}}} {float(b):g}")
            lines.append("# TYPE bigdl_slo_violations_total counter")
            lines.append(f"bigdl_slo_violations_total{{{label}}} "
                         f"{self.slo.violations}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    # -- lifecycle ---------------------------------------------------------
    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> graceful drain (main thread only; the CLI
        entry calls this, library users drive ``stop()`` themselves)."""
        def _on_term(signum, frame):  # noqa: ARG001 - signal contract
            self._term.set()

        signal.signal(signal.SIGTERM, _on_term)
        signal.signal(signal.SIGINT, _on_term)

    def wait(self) -> None:
        """Block until SIGTERM/SIGINT (after install_signal_handlers)
        or ``stop()``."""
        while not self._term.is_set():
            self._term.wait(0.5)

    def draining(self) -> bool:
        return self._term.is_set() or self.batcher._draining \
            or self._stopped

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Drain (finish queued requests) then park everything; always
        announced as a ``serve/drain`` instant with the final stats."""
        if self._stopped:
            return
        self._stopped = True
        self._term.set()
        drained = self.batcher.stop(drain=drain, timeout=timeout)
        if self.gen_batcher is not None:
            # in-flight generations finish their remaining tokens
            # before the process exits — a rolling restart never
            # truncates a stream mid-generation
            drained = self.gen_batcher.stop(drain=drain,
                                            timeout=timeout) and drained
            if drain:
                deadline = time.time() + 5.0
                while time.time() < deadline:
                    with self._streams_lock:
                        if self._open_streams == 0:
                            break
                    time.sleep(0.02)
        _telemetry.instant("serve/drain", clean=bool(drained),
                           requests=self.batcher.requests,
                           rejected=self.batcher.rejected)
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
        global _ACTIVE
        if _ACTIVE is self:
            _ACTIVE = None


class _Handler(BaseHTTPRequestHandler):
    # chunked transfer encoding (the /v1/generate token stream) is
    # undefined for HTTP/1.0 — proxies and strict clients would pass
    # the raw chunk framing through to the user
    protocol_version = "HTTP/1.1"

    def _server(self) -> ModelServer:
        return self.server.model_server  # type: ignore[attr-defined]

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        try:
            path = self.path.split("?", 1)[0].rstrip("/")
            if path == "/v1/generate":
                self._generate()
                return
            if path != "/v1/predict":
                self.send_error(404)
                return
            srv = self._server()
            t0 = time.perf_counter()
            t0_ts = time.time()
            # accept/propagate a client X-Request-Id, mint otherwise —
            # echoed on EVERY response (success or rejection), so a
            # user ticket names the trace the operator pulls
            trace_id, trace = srv.start_trace(
                "predict", self.headers.get("X-Request-Id"))
            rid = {"X-Request-Id": trace_id}
            if srv.draining():
                srv.finish_rejected(trace, "draining")
                self._json(503, {"error": "draining"}, headers=rid)
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
                payload = json.loads(self.rfile.read(length) or b"{}")
                arr, single = srv.parse_inputs(payload)
            except (ValueError, TypeError) as e:
                self._json(400, {"error": str(e)}, headers=rid)
                return
            if trace is not None:
                trace.add_span("parse", t0_ts,
                           (time.perf_counter() - t0) * 1000.0,
                           component="host")
            try:
                req = srv.predict(arr, trace=trace)
            except QueueFullError as e:
                reason = "draining" if srv.draining() else "queue_full"
                srv.finish_rejected(trace, reason)
                self._json(503 if reason == "draining" else 429,
                           {"error": str(e)}, headers=rid)
                return
            except TimeoutError as e:
                srv.finish_rejected(
                    trace, "dispatch_timeout", trace_id=trace_id,
                    wall_ms=(time.perf_counter() - t0) * 1000.0)
                self._json(504, {"error": str(e)}, headers=rid)
                return
            except Exception as e:  # noqa: BLE001 - worker-relayed
                # a dispatch failure (req.error) still honours the id
                # contract: echo the header, land a terminal trace
                srv.finish_failed(
                    trace, f"{type(e).__name__}: {e}",
                    trace_id=trace_id,
                    wall_ms=(time.perf_counter() - t0) * 1000.0)
                self._json(500, {"error": f"{type(e).__name__}: {e}"},
                           headers=rid)
                return
            t_resp0 = time.perf_counter()
            outs = np.asarray(req.output)
            if single:
                outs = outs[0]  # one sample in -> one sample out
            body = {"outputs": outs.tolist(),
                    "ms": round((time.perf_counter() - t0) * 1000.0, 3),
                    "queue_ms": round(req.queue_ms, 3),
                    "trace_id": trace_id}
            srv._finish_predict_trace(
                trace, req, (time.perf_counter() - t_resp0) * 1000.0,
                wall_ms=body["ms"])
            self._json(200, body, headers=rid)
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001 - the server must survive
            try:
                self._json(500, {"error": f"{type(e).__name__}: {e}"})
            except Exception:  # noqa: BLE001 - client already gone
                pass

    def _generate(self) -> None:
        """``POST /v1/generate``: submit, then either stream one JSON
        line per token over chunked transfer encoding (time-to-first-
        byte IS time-to-first-token) or block for the whole answer."""
        srv = self._server()
        if srv.gen_batcher is None:
            self._json(404, {"error": "server not started with "
                                      "--generate"})
            return
        t0 = time.perf_counter()
        t0_ts = time.time()
        trace_id, trace = srv.start_trace(
            "generate", self.headers.get("X-Request-Id"))
        rid = {"X-Request-Id": trace_id}
        if srv.draining():
            srv.finish_rejected(trace, "draining", endpoint="generate")
            self._json(503, {"error": "draining"}, headers=rid)
            return
        try:
            length = int(self.headers.get("Content-Length") or 0)
            payload = json.loads(self.rfile.read(length) or b"{}")
            kwargs, stream = srv.parse_generate(payload)
        except (ValueError, TypeError) as e:
            self._json(400, {"error": str(e)}, headers=rid)
            return
        if trace is not None:
            trace.add_span("parse", t0_ts,
                       (time.perf_counter() - t0) * 1000.0,
                       component="host")
        try:
            req = srv.gen_batcher.submit(trace=trace, **kwargs)
        except QueueFullError as e:
            reason = "draining" if srv.draining() else "queue_full"
            srv.finish_rejected(trace, reason, endpoint="generate")
            self._json(503 if reason == "draining" else 429,
                       {"error": str(e)}, headers=rid)
            return
        except ValueError as e:  # prompt vs cache-bucket bounds
            self._json(400, {"error": str(e)}, headers=rid)
            return
        if not stream:
            if not req.wait(srv.request_timeout_s):
                # stamp BEFORE cancel: the retire hook reads it and
                # lands the trace as a dispatch_timeout REJECTION (the
                # per-reason counters must see generate 504s exactly
                # like predict ones), not a generic cancellation
                if trace is not None:
                    trace.attrs["timed_out"] = True
                req.cancel()
                self._json(504, {"error": "no completion within "
                                          f"{srv.request_timeout_s}s"},
                           headers=rid)
                return
            if req.error is not None:
                self._json(500, {"error": req.error}, headers=rid)
                return
            self._json(200, {"tokens": req.tokens,
                             "trace_id": trace_id, **req.stats()},
                       headers=rid)
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/jsonl")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("X-Request-Id", trace_id)
        self.end_headers()
        with srv._streams_lock:
            srv._open_streams += 1
        try:
            i = 0
            for ev in req.events(timeout=srv.request_timeout_s):
                if ev[0] == "token":
                    self._chunk({"token": ev[1], "i": i})
                    i += 1
                elif ev[0] == "done":
                    self._chunk({"done": True, "tokens": req.tokens,
                                 "trace_id": trace_id, **ev[1]})
                else:  # error sentinel
                    self._chunk({"error": ev[1]})
            self.wfile.write(b"0\r\n\r\n")
        except TimeoutError:
            # the decode stream stalled server-side past the request
            # timeout — a dispatch_timeout like the predict 504, and
            # recorded as one via the stamp
            if trace is not None:
                trace.attrs["timed_out"] = True
            req.cancel()
            self.close_connection = True
        except (BrokenPipeError, ConnectionResetError):
            # client gone: free the decode slot instead of generating
            # for nobody; the chunked body was never terminated, so
            # the connection cannot be reused
            req.cancel()
            self.close_connection = True
        finally:
            with srv._streams_lock:
                srv._open_streams -= 1

    def _chunk(self, obj: Dict[str, Any]) -> None:
        data = (json.dumps(obj, default=str) + "\n").encode("utf-8")
        self.wfile.write(f"{len(data):x}\r\n".encode("ascii")
                         + data + b"\r\n")
        self.wfile.flush()

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        try:
            srv = self._server()
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            if path in ("/", "/status"):
                status: Dict[str, Any] = {}
                try:
                    from bigdl_tpu.telemetry.metrics_http import \
                        _observer_status

                    status.update(_observer_status())
                except Exception:  # noqa: BLE001 - observers best-effort
                    pass
                # THIS frontend's own serving block, set LAST: the
                # observer block reads the process-global serving.get()
                # — with several live servers in one process it names
                # whichever registered last, and each port must report
                # itself
                status["serving"] = srv.status()
                self._json(200, status)
            elif path == "/metrics":
                body = srv.openmetrics().encode("utf-8")
                self._respond(200, body,
                              "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                if srv.draining():
                    self._json(503, {"ok": False, "draining": True})
                else:
                    self._json(200, {"ok": True})
            elif path.startswith("/v1/trace/"):
                # the evidence endpoint: "request abc123 was slow" ->
                # curl the id off the user's X-Request-Id echo and read
                # the waterfall + blame verdict
                tid = path[len("/v1/trace/"):]
                if srv.traces is None:
                    self._json(404, {"error": "tracing disabled "
                                              "(BIGDL_TRACE=off)"})
                elif not tid:
                    self._json(400, {"error": "GET /v1/trace/<id>"})
                else:
                    doc = srv.traces.get(tid)
                    if doc is None:
                        self._json(404, {
                            "error": f"trace {tid!r} not retained "
                                     f"(ring {srv.traces.ring} + "
                                     f"slowest-{srv.traces.slowest_k} "
                                     f"per endpoint)"})
                    else:
                        self._json(200, doc)
            else:
                self.send_error(404)
        except Exception:  # noqa: BLE001 - the server must survive
            try:
                self.send_error(500)
            except Exception:  # noqa: BLE001
                pass

    def _json(self, code: int, obj: Dict[str, Any],
              headers: Optional[Dict[str, str]] = None) -> None:
        self._respond(code, (json.dumps(obj, default=str) + "\n"
                             ).encode("utf-8"), "application/json",
                      headers=headers)

    def _respond(self, code: int, body: bytes, ctype: str,
                 headers: Optional[Dict[str, str]] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args) -> None:  # per-request stderr stays quiet
        pass


def serve_model(model, input_spec, warmup: bool = True,
                **kwargs) -> ModelServer:
    """Build a :class:`ModelServer` and (by default) AOT-warm every
    bucket before returning — the one-call serving entry point."""
    server = ModelServer(model, input_spec, **kwargs)
    if warmup:
        server.warmup()
    return server
