"""Production inference serving (docs/serving.md, ROADMAP item 2).

The batch :class:`~bigdl_tpu.optim.predictor.Predictor` scores datasets;
this package serves *traffic*: an HTTP frontend feeding a bounded
request queue, a continuous batcher that coalesces in-flight requests
under a max-latency + max-batch policy, bucketed padded shapes so
arrival-size variance never triggers an XLA recompile, and per-bucket
AOT executables (``jax.jit(...).lower().compile()``) warmed at startup
so first-request latency is a dispatch, not a compile.

Layering (each usable on its own):

- :mod:`bigdl_tpu.serving.buckets`  — the shape-bucket policy,
- :mod:`bigdl_tpu.serving.executor` — per-bucket AOT executables over a
  model's state (shared with the batch ``Predictor`` — one compile
  cache for offline and online inference),
- :mod:`bigdl_tpu.serving.batcher`  — bounded queue + continuous
  batcher with backpressure and graceful drain,
- :mod:`bigdl_tpu.serving.server`   — the stdlib-HTTP frontend
  (``POST /v1/predict``, ``/status``, ``/healthz``) on the proven
  ``telemetry/metrics_http.py`` pattern,
- :mod:`bigdl_tpu.serving.generate` — the LLM decode subsystem: KV
  cache, prefill/decode executables, continuous generation batching,
  and ``POST /v1/generate`` token streaming (docs/serving.md
  "Autoregressive generation").

Entry points: ``python -m bigdl_tpu.models.cli serve --model lenet``
and ``python bench_serving.py`` (the diff-gateable load harness).
"""

from __future__ import annotations

from bigdl_tpu.serving.batcher import ContinuousBatcher, QueueFullError
from bigdl_tpu.serving.buckets import BucketPolicy
from bigdl_tpu.serving.executor import BucketedExecutor, executor_for
from bigdl_tpu.serving.generate import (GenerateExecutor,
                                        GenerationBatcher,
                                        GenerationRequest)
from bigdl_tpu.serving.server import ModelServer, get, serve_model

__all__ = ["BucketPolicy", "BucketedExecutor", "executor_for",
           "ContinuousBatcher", "QueueFullError", "ModelServer",
           "serve_model", "get", "GenerateExecutor", "GenerationBatcher",
           "GenerationRequest"]
