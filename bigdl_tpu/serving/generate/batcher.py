"""Continuous batching for autoregressive generation.

The one-shot :class:`~bigdl_tpu.serving.batcher.ContinuousBatcher`
coalesces whole requests into one dispatch each; generation instead
runs ONE dispatch per emitted token, so the unit that coalesces is the
*decode step*: every iteration the worker runs a single ``[B, 1]``
decode over ALL active sequences, samples one token per row on the
host, and streams it to that row's client.  Prefill (the prompt's one
big forward) and decode are split the way *Parallax* splits sparse from
dense work — different shapes, different executables, one scheduler:

- arrivals wait in a bounded queue (429 past ``queue_limit``, the PR-8
  backpressure discipline) until a decode slot frees up;
- admissions are prefilled together (mixed prompt lengths pad onto the
  PR-8 seq buckets) and their first token — the TTFT token — is sampled
  straight off the prefill logits;
- a finished request's cache row is reusable at the very next
  iteration: membership changes rebuild the stacked KV cache by
  gathering surviving rows (``StackedKVCache.stack``), and a sequence
  crossing its cache-length bucket pads the whole stack up to the next
  bucket — every (decode batch, cache length) the scheduler can ask for
  is in the executor's closed, AOT-warmed key space.

Sampling is host-side with the persistent per-request RNG discipline:
each request owns a ``numpy`` Philox generator seeded on (seed,
request), so a sampled generation is reproducible from its seed alone
— independent of batch composition, admission order, or server uptime.

Telemetry: one ``generate`` event per COMPLETED request (tokens, dur,
ttft_ms, itl_p99_ms), the ``serve/generate`` token counter per decode
iteration, and the ``serve/active_seqs`` / ``serve/cache_occupancy``
gauges — the raw material for ``/status.serving.generate``,
``bigdl_gen_*`` metrics, and the fleet view's decode-replica columns.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu import telemetry as _telemetry
from bigdl_tpu.serving.batcher import QueueFullError, _pct
from bigdl_tpu.serving.generate.kv_cache import StackedKVCache

__all__ = ["GenerationBatcher", "GenerationRequest", "sample_token"]


def sample_token(logits: np.ndarray, temperature: float = 0.0,
                 top_k: int = 0,
                 rng: Optional[np.random.Generator] = None) -> int:
    """One next-token draw from a ``[V]`` log-prob row.

    ``temperature <= 0`` is greedy (argmax — no RNG consumed, so greedy
    requests are deterministic with no seed at all).  Otherwise the
    log-probs are divided by ``temperature``, optionally truncated to
    the ``top_k`` most likely ids, renormalized, and sampled from
    ``rng`` — the caller's PERSISTENT per-request generator."""
    logits = np.asarray(logits, np.float64)
    if temperature <= 0.0:
        return int(np.argmax(logits))
    if rng is None:
        raise ValueError("sampled decoding needs the request's rng")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    # shift BEFORE scaling: softmax is shift-invariant, and the shifted
    # max is exactly 0, so a tiny temperature drives the others to -inf
    # (-> greedy) instead of the unshifted inf - inf -> NaN
    scaled = (logits - np.max(logits)) / float(temperature)
    if top_k and top_k < scaled.shape[-1]:
        cutoff = np.partition(scaled, -top_k)[-top_k]
        scaled = np.where(scaled >= cutoff, scaled, -np.inf)
    probs = np.exp(scaled)
    probs /= probs.sum()
    return int(rng.choice(probs.shape[-1], p=probs))


class GenerationRequest:
    """One streaming generation: prompt in, a queue of token events out.

    The worker pushes ``("token", id, t_wall)`` tuples and finally one
    ``("done", stats)`` / ``("error", message)`` sentinel; the HTTP
    handler drains them via :meth:`events`.  ``cancel()`` (client gone)
    tells the scheduler to free the row at the next iteration instead
    of decoding for nobody.

    ``trace`` (optional, telemetry/request_trace.py): the server's
    RequestTrace riding along.  The worker stamps it live — queue wait,
    the prefill span, every decode iteration it rode (with that
    iteration's co-batch size) and per-token emit times — and the
    server's retire hook closes it out.
    """

    __slots__ = ("prompt", "max_new_tokens", "temperature", "top_k",
                 "seed", "eos_token", "rng", "stream", "done", "error",
                 "tokens", "enqueued_at", "enqueued_ts",
                 "first_token_at", "last_token_at", "itl_ms",
                 "cancelled", "finish_reason", "queue_ms", "trace")

    def __init__(self, prompt: np.ndarray, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0, eos_token: Optional[int] = None,
                 trace=None):
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must carry at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.prompt = prompt
        self.trace = trace
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.seed = int(seed)
        self.eos_token = eos_token
        # the persistent per-request stream: every draw this request
        # ever makes comes from here, keyed on (seed,) alone — the
        # reproducibility contract is independent of batching
        self.rng = np.random.Generator(np.random.Philox(self.seed))
        self.stream: "queue.Queue" = queue.Queue()
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.tokens: List[int] = []
        self.enqueued_at = time.perf_counter()
        self.enqueued_ts = time.time()  # epoch twin (span timestamps)
        self.first_token_at: Optional[float] = None
        self.last_token_at: Optional[float] = None
        self.itl_ms: List[float] = []
        self.cancelled = False
        self.finish_reason: Optional[str] = None
        self.queue_ms = 0.0

    # -- worker side -------------------------------------------------------
    def emit(self, token: int) -> None:
        now = time.perf_counter()
        if self.first_token_at is None:
            self.first_token_at = now
        else:
            self.itl_ms.append((now - self.last_token_at) * 1000.0)
        self.last_token_at = now
        self.tokens.append(int(token))
        if self.trace is not None:
            self.trace.note_token(time.time())
        self.stream.put(("token", int(token), now))

    def ttft_ms(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return (self.first_token_at - self.enqueued_at) * 1000.0

    def finish(self, reason: str) -> None:
        self.finish_reason = reason
        self.stream.put(("done", self.stats()))
        self.done.set()

    def fail(self, message: str) -> None:
        self.error = message
        self.finish_reason = "error"
        self.stream.put(("error", message))
        self.done.set()

    def stats(self) -> Dict[str, Any]:
        itl = sorted(self.itl_ms)
        dur = (self.last_token_at - self.enqueued_at) \
            if self.last_token_at else 0.0
        return {"n_tokens": len(self.tokens),
                "finish_reason": self.finish_reason,
                "ttft_ms": round(self.ttft_ms() or 0.0, 3),
                "itl_p99_ms": round(_pct(itl, 99.0), 3) if itl else 0.0,
                "dur_s": round(dur, 4),
                "tok_s": round(len(self.tokens) / dur, 2) if dur > 0
                else None,
                "queue_ms": round(self.queue_ms, 3)}

    # -- client side -------------------------------------------------------
    def cancel(self) -> None:
        self.cancelled = True

    def events(self, timeout: Optional[float] = None):
        """Yield ``("token", id, t)`` tuples then the terminal
        ``("done", stats)`` / ``("error", msg)``; raises TimeoutError
        when the stream stalls past ``timeout`` between events."""
        while True:
            try:
                ev = self.stream.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"no token within {timeout}s") from None
            yield ev
            if ev[0] in ("done", "error"):
                return

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)


class _Row:
    """One active sequence: its request + scheduler-side position."""

    __slots__ = ("req", "length", "last_token", "n_new")

    def __init__(self, req: GenerationRequest, length: int,
                 first_token: int):
        self.req = req
        self.length = length        # tokens IN the cache (prompt so far)
        self.last_token = first_token
        self.n_new = 1              # the prefill (TTFT) token counts


class GenerationBatcher:
    """Single worker thread interleaving prefill and coalesced decode.

    ``executor`` is a warm :class:`GenerateExecutor`; ``max_active`` is
    its largest decode bucket.  Admission control mirrors the predict
    batcher: a bounded waiting queue, :class:`QueueFullError` past
    capacity or once draining, and ``stop(drain=True)`` finishes every
    in-flight generation before parking (the SIGTERM path).

    ``on_retire`` (request tracing): called with every request exactly
    once at its terminal transition — finished, cancelled, or failed —
    so the server can close out its trace; exceptions in the hook never
    kill the worker.
    """

    def __init__(self, executor, max_wait_ms: float = 2.0,
                 queue_limit: int = 64,
                 eos_token: Optional[int] = None, on_retire=None):
        self.executor = executor
        self._on_retire = on_retire
        self.max_active = executor.max_active
        self.max_wait_s = max(0.0, max_wait_ms) / 1000.0
        self.queue_limit = queue_limit
        self.eos_token = eos_token
        self._q: "queue.Queue[GenerationRequest]" = queue.Queue(
            maxsize=queue_limit)
        self._active: List[_Row] = []
        self._stack: Optional[StackedKVCache] = None
        self._stats_lock = threading.Lock()
        self.requests = 0
        self.rejected = 0
        self.completed = 0
        self.errors = 0
        self.gen_tokens = 0
        self._ttft_ms: collections.deque = collections.deque(maxlen=2048)
        self._itl_ms: collections.deque = collections.deque(maxlen=8192)
        # (wall ts, tokens emitted) per decode iteration — tokens/s
        self._token_times: collections.deque = collections.deque(
            maxlen=8192)
        self._draining = False
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="bigdl-generate-batcher",
                                        daemon=True)
        self._thread.start()

    # -- admission ---------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0,
               seed: int = 0, eos_token: Optional[int] = None,
               trace=None) -> GenerationRequest:
        """Enqueue one generation; raises :class:`QueueFullError` at
        capacity or once draining."""
        if self._draining or self._stopped.is_set():
            raise QueueFullError("server is draining")
        if top_k < 0:
            # reject up front (the frontend's 400) — sample_token would
            # only raise mid-stream, after the 200 already went out
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        if not np.isfinite(temperature) or temperature < 0.0:
            # json.loads happily parses NaN/Infinity — reject here, not
            # in the worker where one poisoned distribution would fail
            # mid-stream
            raise ValueError("temperature must be a finite float >= 0, "
                             f"got {temperature}")
        req = GenerationRequest(prompt, max_new_tokens=max_new_tokens,
                                temperature=temperature, top_k=top_k,
                                seed=seed,
                                eos_token=eos_token if eos_token
                                is not None else self.eos_token,
                                trace=trace)
        largest = self.executor.cache_buckets[-1]
        if req.prompt.size >= largest:
            raise ValueError(
                f"prompt of {req.prompt.size} tokens leaves no room to "
                f"generate in the largest cache bucket {largest}")
        smax = self.executor.policy.seq_buckets[-1]
        if req.prompt.size > smax:
            # the prefill shape set is closed; padding truncates, so an
            # over-long prompt would silently lose its tail — reject
            raise ValueError(
                f"prompt of {req.prompt.size} tokens exceeds the "
                f"largest seq bucket {smax}")
        try:
            self._q.put_nowait(req)
        except queue.Full:
            with self._stats_lock:
                self.rejected += 1
            _telemetry.counter("serve/rejected", 1)
            raise QueueFullError(
                f"generation queue at capacity ({self.queue_limit})"
            ) from None
        with self._stats_lock:
            self.requests += 1
        _telemetry.counter("serve/requests", 1)
        return req

    def depth(self) -> int:
        return self._q.qsize()

    def active(self) -> int:
        return len(self._active)

    # -- the worker --------------------------------------------------------
    def _loop(self) -> None:
        while True:
            if self._stopped.is_set():
                self._fail_all("server stopped")
                return
            try:
                self._admit()
                if not self._active:
                    if self._draining and self._q.empty():
                        self._stopped.set()
                        return
                    time.sleep(0.005)
                    continue
                self._step()
            except BaseException as e:  # noqa: BLE001 - relayed per row
                self._fail_active(f"{type(e).__name__}: {e}")

    def _take_waiting(self, room: int) -> List[GenerationRequest]:
        """Pop up to ``room`` live requests; waits out ``max_wait_ms``
        only when NOTHING is active (an idle device coalesces arrivals
        for a fuller prefill; a busy one admits whatever is there)."""
        out: List[GenerationRequest] = []
        deadline = None
        while len(out) < room:
            block = not self._active and not out and not self._draining
            try:
                req = self._q.get(timeout=0.02 if block else 0.0)
            except queue.Empty:
                # active rows must not stall behind the coalescing
                # window — only an otherwise-idle worker waits it out
                if not out or self._active or deadline is None \
                        or time.perf_counter() >= deadline:
                    break
                time.sleep(0.001)
                continue
            if req.cancelled:
                req.finish("cancelled")
                self._notify_retire(req)
                continue
            if deadline is None:
                deadline = req.enqueued_at + self.max_wait_s
            out.append(req)
        return out

    def _admit(self) -> None:
        # one prefill dispatch per admission round: room is bounded by
        # the free decode slots AND the prefill batch-bucket ceiling —
        # a burst larger than max_batch admits over successive rounds
        # (decode for the already-running rows interleaves)
        room = min(self.max_active - len(self._active),
                   self.executor.policy.max_batch)
        if room <= 0:
            return
        newcomers = self._take_waiting(room)
        if not newcomers:
            return
        t0 = time.perf_counter()
        t0_ts = time.time()
        lengths = [r.prompt.size for r in newcomers]
        smax = max(lengths)
        tokens = np.zeros((len(newcomers), smax), np.int32)
        for i, r in enumerate(newcomers):
            tokens[i, :lengths[i]] = r.prompt
            r.queue_ms = (t0 - r.enqueued_at) * 1000.0
            if r.trace is not None:
                r.trace.add_span("queue_wait", r.enqueued_ts, r.queue_ms,
                             component="queue_wait",
                             co_admitted=len(newcomers))
        rec: Dict[str, Any] = {}
        try:
            logits, caches = self.executor.prefill(tokens, lengths,
                                                   record=rec)
        except BaseException as e:  # noqa: BLE001 - relayed per request
            with self._stats_lock:
                self.errors += len(newcomers)
            for req in newcomers:
                req.fail(f"{type(e).__name__}: {e}")
                self._notify_retire(req)
            return
        prefill_ms = (time.perf_counter() - t0) * 1000.0
        self._stamp_prefill(newcomers, t0_ts, prefill_ms, rec)
        rows: List[_Row] = []
        kept: List[int] = []
        for i, req in enumerate(newcomers):
            try:
                tok = sample_token(logits[i], req.temperature,
                                   req.top_k, req.rng)
            except Exception as e:  # noqa: BLE001 - one bad request
                # must not take down its co-admitted batch (or hang
                # later newcomers in neither queue nor active)
                with self._stats_lock:
                    self.errors += 1
                req.fail(f"{type(e).__name__}: {e}")
                self._notify_retire(req)
                continue
            req.emit(tok)  # the TTFT token, straight off the prefill
            rows.append(_Row(req, lengths[i], tok))
            kept.append(i)
        with self._stats_lock:
            self.gen_tokens += len(rows)
            now = time.time()
            self._token_times.append((now, len(rows)))
            for row in rows:
                ttft = row.req.ttft_ms()
                if ttft is not None:
                    self._ttft_ms.append(ttft)
        _telemetry.counter("serve/generate", len(rows))
        new_sources = [(caches, i, lengths[i]) for i in kept]
        survivors = self._stack.row_sources(
            list(range(len(self._active)))) if self._active else []
        self._active.extend(rows)
        self._rebuild(survivors + new_sources)
        # a prompt already at its cache ceiling finishes on the TTFT
        # token alone (nowhere to write the next k/v row)
        self._retire(self._finished_rows())

    def _stamp_prefill(self, newcomers: List[GenerationRequest],
                       t0_ts: float, prefill_ms: float, rec: dict
                       ) -> None:
        """Tile one prefill dispatch onto the traces that paid for it:
        each newcomer owns a (compile, prefill-compute, padding) split
        of the wall, and every ALREADY-ACTIVE row lost the whole
        dispatch to somebody else's prefill — the blame component
        ``prefill_interference`` (decode stalls while the worker
        prefills; a prefill flood shows up HERE, not as compute)."""
        from bigdl_tpu.telemetry import request_trace as _rt

        for r in newcomers:
            if r.trace is None:
                continue
            _rt.stamp_dispatch_spans(
                r.trace, t0_ts, prefill_ms, rec, "prefill",
                default_bucket=len(newcomers),
                co_prefill=len(newcomers),
                seq_bucket=rec.get("seq_bucket"))
        for row in self._active:
            tr = row.req.trace
            if tr is not None:
                tr.add_span("prefill_interference", t0_ts, prefill_ms,
                        component="prefill_interference",
                        newcomers=len(newcomers))

    def _notify_retire(self, req: GenerationRequest) -> None:
        """Terminal-transition hook (the server's trace close-out);
        an observer must never kill the worker."""
        if self._on_retire is None:
            return
        try:
            self._on_retire(req)
        except Exception:  # noqa: BLE001 - observers stay observers
            pass

    def _rebuild(self, sources) -> None:
        if not self._active:
            self._stack = None
            self._publish_gauges()  # idle must read 0, not last-busy
            return
        assert len(sources) == len(self._active)
        max_len = max(r.length for r in self._active)
        bucket = self.executor.cache_bucket(max_len + 1)
        batch = self.executor.decode_batch_bucket(len(self._active))
        self._stack = StackedKVCache.stack(sources, bucket, batch)
        self._publish_gauges()

    def _finished_rows(self) -> List[int]:
        largest = self.executor.cache_buckets[-1]
        out = []
        for i, row in enumerate(self._active):
            req = row.req
            if req.cancelled:
                if req.error is None:  # keep "error" for failed rows
                    req.finish_reason = "cancelled"
                out.append(i)
            elif row.n_new >= req.max_new_tokens:
                req.finish_reason = "length"
                out.append(i)
            elif req.eos_token is not None \
                    and row.last_token == req.eos_token:
                req.finish_reason = "stop"
                out.append(i)
            elif row.length >= largest:
                # the next decode would write at index ``length``,
                # which no longer exists — the last valid cell is
                # ``largest - 1``, so a bucket of C buys exactly C
                # positions of context
                req.finish_reason = "cache_full"
                out.append(i)
        return out

    def _retire(self, finished: Sequence[int]) -> None:
        if not finished:
            return
        done = [self._active[i] for i in finished]
        keep = [i for i in range(len(self._active))
                if i not in set(finished)]
        survivors = self._stack.row_sources(keep) if keep else []
        self._active = [self._active[i] for i in keep]
        self._rebuild(survivors)
        tracer = _telemetry.get()
        for row in done:
            req = row.req
            st = req.stats()
            with self._stats_lock:
                if req.error is None:
                    self.completed += 1
                self._itl_ms.extend(req.itl_ms)
            if req.error is None:
                # a failed row's terminal "error" event already went
                # out via fail(); retiring it only frees the slot
                req.finish(req.finish_reason or "stop")
            self._notify_retire(req)
            if tracer is not None:
                tracer.emit("generate", tokens=st["n_tokens"],
                            dur=st["dur_s"], ttft_ms=st["ttft_ms"],
                            itl_p99_ms=st["itl_p99_ms"],
                            finish=req.finish_reason,
                            queue_ms=st["queue_ms"])

    def _step(self) -> None:
        """One coalesced decode iteration over every active row."""
        stack = self._stack
        tokens = [row.last_token for row in self._active]
        t0 = time.perf_counter()
        t0_ts = time.time()
        rec: Dict[str, Any] = {}
        logits = self.executor.decode(stack, tokens, record=rec)
        decode_ms = (time.perf_counter() - t0) * 1000.0
        compile_ms = float(rec.get("compile_ms", 0.0) or 0.0)
        co_batch = len(self._active)
        iter_ms = max(0.0, decode_ms - compile_ms)
        for row in self._active:
            tr = row.req.trace
            if tr is None:
                continue
            # every rider pays this iteration's wall; the co-batch size
            # travels with it so the retire hook can split out
            # co_batch_stall against the endpoint's typical iteration
            if compile_ms:
                tr.add_span("compile", t0_ts, compile_ms,
                        component="compile")
            tr.add_span("decode", t0_ts + compile_ms / 1000.0, iter_ms,
                    component="compute", co_batch=co_batch)
            tr.note_iter(iter_ms, co_batch)
        emitted = 0
        for i, row in enumerate(self._active):
            # the executor scattered row i's token at position length;
            # the scheduler owns advancing the row past it
            row.length += 1
            stack.lengths[i] += 1
            try:
                tok = sample_token(logits[i], row.req.temperature,
                                   row.req.top_k, row.req.rng)
            except Exception as e:  # noqa: BLE001 - one bad request
                # must not take down the whole coalesced batch
                with self._stats_lock:
                    self.errors += 1
                row.req.fail(f"{type(e).__name__}: {e}")
                row.req.cancelled = True  # retired on the sweep below
                continue
            row.req.emit(tok)
            row.last_token = tok
            row.n_new += 1
            emitted += 1
        with self._stats_lock:
            self.gen_tokens += emitted
            self._token_times.append((time.time(), emitted))
        _telemetry.counter("serve/generate", emitted)
        finished = self._finished_rows()
        if finished:
            self._retire(finished)
        elif max(r.length for r in self._active) + 1 > stack.bucket:
            # a row crossed its cache bucket: pad the whole stack up
            self._rebuild(stack.row_sources(
                list(range(len(self._active)))))
        else:
            self._publish_gauges()

    def _publish_gauges(self) -> None:
        _telemetry.gauge("serve/active_seqs", len(self._active))
        _telemetry.gauge("serve/cache_occupancy",
                         self._stack.occupancy() if self._stack else 0.0)

    def _fail_active(self, message: str) -> None:
        with self._stats_lock:
            self.errors += len(self._active)
        for row in self._active:
            row.req.fail(message)
            self._notify_retire(row.req)
        self._active = []
        self._stack = None
        self._publish_gauges()

    def _fail_all(self, message: str) -> None:
        self._fail_active(message)
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                return
            req.fail(message)
            self._notify_retire(req)

    # -- stats / lifecycle -------------------------------------------------
    def stats(self, window_s: float = 60.0) -> Dict[str, Any]:
        now = time.time()
        # snapshot once: the worker swaps/nulls _stack without taking
        # _stats_lock, so a second read could see a different object
        stack = self._stack
        with self._stats_lock:
            recent = [(at, n) for (at, n) in self._token_times
                      if now - at <= window_s]
            ttft = sorted(self._ttft_ms)
            itl = sorted(self._itl_ms)
            out = {"requests": self.requests, "rejected": self.rejected,
                   "completed": self.completed, "errors": self.errors,
                   "gen_tokens": self.gen_tokens,
                   "active_seqs": len(self._active),
                   "waiting": self._q.qsize(),
                   "queue_limit": self.queue_limit,
                   "max_active": self.max_active,
                   "cache_occupancy": stack.occupancy()
                   if stack is not None else 0.0,
                   "cache_bucket": stack.bucket
                   if stack is not None else None,
                   "draining": self._draining}
        if recent:
            span = min(window_s,
                       max(0.25, now - min(at for at, _ in recent)))
            out["tokens_s"] = round(sum(n for _, n in recent) / span, 2)
        if ttft:
            out["ttft_p50_ms"] = round(_pct(ttft, 50.0), 3)
            out["ttft_p99_ms"] = round(_pct(ttft, 99.0), 3)
        if itl:
            out["itl_p50_ms"] = round(_pct(itl, 50.0), 3)
            out["itl_p99_ms"] = round(_pct(itl, 99.0), 3)
        return out

    def stop(self, drain: bool = True, timeout: float = 30.0) -> bool:
        """Stop admissions; with ``drain`` finish every queued AND
        in-flight generation first.  Returns True when the worker
        parked in time."""
        self._draining = True
        if not drain:
            self._stopped.set()
        self._thread.join(timeout)
        self._stopped.set()
        parked = not self._thread.is_alive()
        # TOCTOU sweep (the ContinuousBatcher.stop discipline): a
        # submit that raced the drain check still owes its client an
        # answer — the worker is dead here, so failing them is race-free
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            req.fail("server stopped")
            self._notify_retire(req)
        return parked
