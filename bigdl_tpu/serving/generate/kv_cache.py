"""The KV cache: trace-time plumbing + the stacked per-request store.

Two halves:

- :class:`CacheContext` — a thread-local ambient context bound around a
  traced forward (the same pattern as ``utils/rng.rng_context``).  When
  bound, every ``MultiHeadAttention`` routes its freshly projected k/v
  through :meth:`CacheContext.attend`: **prefill** records them (the
  layer's normal attention still runs — long prompts ride the flash
  kernel), **decode** scatters the single new k/v row into the cache at
  each request's own position and computes q-against-cache dense
  attention under a per-row length mask.  Layers are matched purely by
  TRACE ORDER (a counter), so the context needs no registry of module
  identities — the same model traces its attentions in the same order
  every time, and the executable's cache operand order is defined by
  that trace (``GenerateExecutor`` derives it via ``jax.eval_shape``).

- :class:`StackedKVCache` — the host-side container the scheduler owns:
  one ``[B, H, C, D]`` (k, v) pair per attention layer, row i belonging
  to active request i, ``C`` drawn from a fixed closed set of
  **cache-length buckets** (:func:`cache_buckets` — the PR-8 bucket
  discipline extended to the time axis, so decode executables are
  AOT-warmable).  Membership changes (a request finished — its row is
  immediately reusable — or a new prefill joined) rebuild the stack by
  gathering surviving rows; a request crossing its cache bucket pads the
  whole stack up to the next bucket.  Between rebuilds the stack flows
  through the decode executable untouched by the host.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["CacheContext", "StackedKVCache", "cache_buckets", "current",
           "bind"]


def cache_buckets(max_len: int, smallest: int = 64) -> Tuple[int, ...]:
    """The closed set of cache-length buckets: ``smallest``, doubling,
    capped at (and including) ``max_len``.  Every generated sequence
    lives at the smallest bucket that holds it, so the decode executable
    set is ``|decode batch buckets| x |cache buckets|`` — all AOT-warmed."""
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1, got {max_len}")
    out, b = [], min(smallest, max_len)
    while b < max_len:
        out.append(b)
        b *= 2
    out.append(max_len)
    return tuple(out)


class CacheContext:
    """Ambient trace-time KV plumbing; see the module docstring.

    ``mode``: ``"prefill"`` (record k/v, let the layer attend normally)
    or ``"decode"`` (scatter into + attend against the bound caches).
    ``lengths`` is the per-row token count already in the cache — in
    decode mode it is also the position the new token is written to and
    the index ``PositionalEmbedding`` looks up.  Rows padded onto the
    batch bucket carry length 0 and produce garbage nobody reads.
    """

    def __init__(self, mode: str, lengths=None,
                 caches: Optional[List[Tuple[Any, Any]]] = None):
        if mode not in ("prefill", "decode"):
            raise ValueError(f"mode must be prefill|decode, got {mode!r}")
        if mode == "decode" and (lengths is None or caches is None):
            raise ValueError("decode mode needs lengths and caches")
        self.mode = mode
        self.lengths = lengths
        self.caches = caches or []
        self.collected: List[Tuple[Any, Any]] = []
        self._idx = 0

    # -- the MultiHeadAttention hook ---------------------------------------
    def attend(self, q, k, v, causal: bool = True,
               scale: Optional[float] = None):
        """Called by ``MultiHeadAttention`` with the projected
        ``[B, H, S, D]`` q/k/v.  Returns the attention output in decode
        mode, or None in prefill mode (record-only — the layer's normal
        backend selection still runs the actual attention)."""
        import jax.numpy as jnp

        if self.mode == "prefill":
            self.collected.append((k, v))
            return None
        if self._idx >= len(self.caches):
            raise RuntimeError(
                f"decode trace touched attention layer {self._idx} but "
                f"only {len(self.caches)} caches were bound — the model "
                f"changed shape since the cache specs were derived")
        if q.shape[2] != 1:
            raise ValueError(
                f"decode expects q_len=1, got {q.shape[2]} — prefill "
                f"longer inputs instead")
        kc, vc = self.caches[self._idx]
        self._idx += 1
        rows = jnp.arange(kc.shape[0])
        kc = kc.at[rows, :, self.lengths, :].set(
            k[:, :, 0, :].astype(kc.dtype))
        vc = vc.at[rows, :, self.lengths, :].set(
            v[:, :, 0, :].astype(vc.dtype))
        self.collected.append((kc, vc))
        from bigdl_tpu.ops.attention import (dot_product_attention,
                                             select_attention_backend)
        from bigdl_tpu.ops.dispatch import note

        # q_len=1: the routing table hard-routes decode to dense (a
        # flash q block would be 127/128 padding) — recorded so
        # attribution can see the decode path chose XLA on purpose
        backend, reason = select_attention_backend(1, kc.shape[2],
                                                   masked=True)
        note("attention", "pallas" if backend == "flash" else "xla",
             reason)
        # row b attends cache positions 0..lengths[b] inclusive (the
        # slot its own new token was just written to)
        mask = (jnp.arange(kc.shape[2])[None, :]
                <= self.lengths[:, None])[:, None, None, :]
        return dot_product_attention(q, kc, vc, mask=mask, scale=scale)

    def positions(self):
        """Per-row absolute position of the current token (decode mode:
        the write index) — what ``PositionalEmbedding`` adds."""
        return self.lengths


# -- ambient binding ---------------------------------------------------------
class _Ambient(threading.local):
    def __init__(self):
        self.ctx: Optional[CacheContext] = None


_ambient = _Ambient()


def current() -> Optional[CacheContext]:
    """The bound :class:`CacheContext` (None outside generation traces)."""
    return _ambient.ctx


@contextmanager
def bind(mode: str, lengths=None, caches=None):
    """Bind a fresh :class:`CacheContext` for the dynamic extent of one
    traced forward; yields it so the caller can read ``collected``."""
    prev = _ambient.ctx
    ctx = CacheContext(mode, lengths=lengths, caches=caches)
    _ambient.ctx = ctx
    try:
        yield ctx
    finally:
        _ambient.ctx = prev


# -- the scheduler-owned stacked store ---------------------------------------
class StackedKVCache:
    """``[B, H, C, D]`` (k, v) per layer + host-side row lengths.

    ``B`` is a decode batch bucket, ``C`` a cache-length bucket; row i
    belongs to active request i (rows past ``n_rows`` are padding).  The
    arrays live on device and flow through the decode executable; the
    host only touches them on membership rebuilds.
    """

    def __init__(self, layers: List[Tuple[Any, Any]],
                 lengths: Sequence[int], bucket: int, batch: int):
        self.layers = layers          # [(k, v)] per attention layer
        self.lengths = list(lengths)  # live rows only (len = n_rows)
        self.bucket = int(bucket)     # C
        self.batch = int(batch)       # B (>= n_rows)

    @property
    def n_rows(self) -> int:
        return len(self.lengths)

    def occupancy(self) -> float:
        """Used cache cells / allocated cells — the ``/status`` and
        ``serve/cache_occupancy`` gauge number."""
        total = self.batch * self.bucket
        return round(sum(self.lengths) / total, 4) if total else 0.0

    def lengths_padded(self) -> np.ndarray:
        out = np.zeros((self.batch,), np.int32)
        out[:self.n_rows] = self.lengths
        return out

    @classmethod
    def stack(cls, rows: List[Tuple[List[Tuple[Any, Any]], int, int]],
              bucket: int, batch: int) -> "StackedKVCache":
        """Build a stack from per-request rows.  Each row is
        ``(layers, row_index, length)`` where ``layers`` is a stacked
        source (``[B', H, C', D]`` per layer) and ``row_index`` picks the
        request's row in it — so surviving rows of an old stack and the
        rows of a fresh prefill batch gather with ONE slice each."""
        import jax.numpy as jnp

        if not rows:
            raise ValueError("cannot stack zero rows")
        if batch < len(rows):
            raise ValueError(f"{len(rows)} rows > batch bucket {batch}")
        n_layers = len(rows[0][0])
        layers = []
        for li in range(n_layers):
            ks, vs = [], []
            for src, ri, _length in rows:
                k, v = src[li]
                ks.append(cls._fit(k[ri], bucket))
                vs.append(cls._fit(v[ri], bucket))
            k = jnp.stack(ks)
            v = jnp.stack(vs)
            if batch > k.shape[0]:
                pad = [(0, batch - k.shape[0])] + [(0, 0)] * (k.ndim - 1)
                k = jnp.pad(k, pad)
                v = jnp.pad(v, pad)
            layers.append((k, v))
        return cls(layers, [length for _, _, length in rows],
                   bucket, batch)

    @staticmethod
    def _fit(arr, bucket: int):
        """Pad or slice one ``[H, C', D]`` row onto cache length
        ``bucket`` (slicing only ever drops cells past the row's length
        — the scheduler never shrinks below a live sequence)."""
        import jax.numpy as jnp

        c = arr.shape[1]
        if c == bucket:
            return arr
        if c > bucket:
            return arr[:, :bucket, :]
        return jnp.pad(arr, [(0, 0), (0, bucket - c), (0, 0)])

    def row_sources(self, keep: Sequence[int]):
        """Rebuild inputs for the surviving ``keep`` row indices —
        feed straight back into :meth:`stack`."""
        return [(self.layers, i, self.lengths[i]) for i in keep]
