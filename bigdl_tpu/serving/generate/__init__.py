"""LLM decode subsystem: KV cache + cached decode + continuous batching.

The one-shot serving stack (docs/serving.md) answers a request with a
single forward; autoregressive generation instead runs ONE forward per
emitted token over an ever-growing context.  Re-reading the whole
context every step is the transformer_lm_long MFU cliff (0.40 -> 0.19,
BENCH_banked_r5.json) — so generation gets its own data path, split the
way *Parallax* (arXiv 1808.02621) splits sparse from dense work:

- **prefill** — the prompt's one big forward.  Rides the existing shape
  buckets and the flash-attention auto backend, and WRITES the per-layer
  k/v projections into a cache (``kv_cache.CacheContext``);
- **decode** — one token per step, q_len=1 against the cache.  Dense
  attention is the right shape there (a 128-row flash q block would be
  127/128 padding — ``select_attention_backend`` hard-routes q_len=1 to
  dense), and steps COALESCE across every active request
  (``GenerationBatcher``) so the device sees one ``[B, 1]`` dispatch per
  iteration instead of B tiny ones.

Cache lengths live on a fixed closed set of buckets
(``kv_cache.cache_buckets`` — the PR-8 bucket discipline extended to the
time axis), so every decode executable is AOT-warmed at startup and the
retrace detector stays clean over any traffic mix.
"""

from bigdl_tpu.serving.generate.batcher import (GenerationBatcher,
                                                GenerationRequest,
                                                sample_token)
from bigdl_tpu.serving.generate.decode import GenerateExecutor
from bigdl_tpu.serving.generate.kv_cache import (CacheContext, StackedKVCache,
                                                 cache_buckets, current)

__all__ = [
    "CacheContext",
    "StackedKVCache",
    "cache_buckets",
    "current",
    "GenerateExecutor",
    "GenerationBatcher",
    "GenerationRequest",
    "default_seq_buckets",
    "generation_model",
    "sample_token",
]


def generation_model(name: str, num_classes: int = 0):
    """Build registry model ``name`` for generation serving — the ONE
    place the front-ends (``cli serve --generate``, ``bench_serving.py
    --generate``) share the rule: trace-order cache plumbing cannot
    address a ScanLayers stack (one traced body for N layers), so
    models whose registry build may scan are built unrolled here."""
    from bigdl_tpu.models import registry

    if name == "transformer":
        from bigdl_tpu.models import build_transformer_lm

        return build_transformer_lm(vocab_size=num_classes or 256,
                                    scan=False)
    if name not in registry.MODELS:
        raise ValueError(f"unknown model {name!r}; choose from "
                         f"{registry.model_names()}")
    return registry.build_model(name, num_classes)


def default_seq_buckets(spec):
    """Default prompt buckets when the operator gives none: halving
    steps down from the model's canonical length, so short prompts do
    not pay full-context prefill (the closed-set discipline holds —
    every bucket is AOT-warmed)."""
    s = int(spec.shape[1])
    return sorted({max(16, s // 4), max(16, s // 2), s})
