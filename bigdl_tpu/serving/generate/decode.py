"""``GenerateExecutor``: AOT-warmed prefill + cached-decode executables.

A :class:`~bigdl_tpu.serving.executor.BucketedExecutor` subclass, so a
generation server keeps exactly ONE device copy of the weights and one
``refresh_state()`` contract across predict, prefill and decode: a
same-shape weight rollout keeps every warm executable (prefill, decode,
plain predict buckets) AND the live KV caches — the state is an
executable *argument*, so in-flight generations simply see the new
weights on their next step.  A shape/dtype change drops all executables
by design, exactly like the base class.

Executable key space (all AOT-warmed by :meth:`warmup`):

- ``("prefill", B, S)`` — B a policy batch bucket, S a policy seq
  bucket.  ``(state, tokens[B, S], lengths[B]) -> (last-position logits
  [B, V], per-layer k/v caches [B, H, S, D])``.  Runs the model's normal
  attention path (long prompts ride the flash kernel) under a recording
  :class:`~bigdl_tpu.serving.generate.kv_cache.CacheContext`.
- ``("decode", B, C)`` — B a decode batch bucket, C a cache-length
  bucket.  ``(state, tokens[B, 1], lengths[B], caches) -> (logits
  [B, V], updated caches)``.  One token per row, scattered into each
  row's own cache position, dense q-against-cache attention under a
  per-row length mask.

Both signatures are constant per key, so the retrace detector sees a
constant dispatch signature per kind (``GenerateExecutor.decode[b4c128]``)
and "zero steady-state compiles" stays a testable contract.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu import telemetry as _telemetry
from bigdl_tpu.analysis import hooks as _hooks
from bigdl_tpu.serving.executor import BucketedExecutor
from bigdl_tpu.serving.generate import kv_cache as _kv

__all__ = ["GenerateExecutor"]


def _pick_bucket(buckets: Sequence[int], n: int, what: str) -> int:
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(f"{what} of {n} exceeds the largest bucket "
                     f"{buckets[-1]} — the bucket set is closed")


class GenerateExecutor(BucketedExecutor):
    """Prefill/decode executables over one causal token model.

    ``cache_buckets``: closed ascending set of cache lengths (default
    :func:`kv_cache.cache_buckets` up to the model's positional
    ``max_len``).  ``decode_buckets``: closed ascending set of decode
    batch sizes — ``decode_buckets[-1]`` is the scheduler's max
    concurrent sequences.  The policy MUST carry seq buckets (prompts
    pad onto them) and the largest cache bucket must hold the largest
    seq bucket (a prompt must fit the cache it starts in).
    """

    def __init__(self, model, mesh=None, policy=None, compute_dtype=None,
                 decode_buckets: Optional[Sequence[int]] = None,
                 cache_buckets: Optional[Sequence[int]] = None,
                 token_dtype=np.int32):
        super().__init__(model, mesh=mesh, policy=policy,
                         compute_dtype=compute_dtype, seq_axis=1)
        if not self.policy.seq_buckets:
            raise ValueError(
                "generation needs seq buckets (the prompt padding "
                "shapes) — pass a BucketPolicy with seq_buckets")
        self._check_model(model)
        max_len = self._model_max_len(model)
        if cache_buckets is None:
            if max_len is None:
                raise ValueError(
                    "cache_buckets not given and the model declares no "
                    "positional max_len to derive them from")
            cache_buckets = _kv.cache_buckets(
                max_len, smallest=self.policy.seq_buckets[0])
        self.cache_buckets = tuple(sorted(set(int(c)
                                              for c in cache_buckets)))
        if max_len is not None and self.cache_buckets[-1] > max_len:
            raise ValueError(
                f"largest cache bucket {self.cache_buckets[-1]} exceeds "
                f"the model's positional max_len {max_len}")
        if self.policy.seq_buckets[-1] > self.cache_buckets[-1]:
            raise ValueError(
                f"largest seq bucket {self.policy.seq_buckets[-1]} "
                f"does not fit the largest cache bucket "
                f"{self.cache_buckets[-1]}")
        self.decode_buckets = tuple(sorted(set(
            int(b) for b in (decode_buckets or (1, 2, 4, 8)))))
        self.max_active = self.decode_buckets[-1]
        self.token_dtype = np.dtype(token_dtype)
        self._prefill_jit = None
        self._decode_jit = None
        self._cache_tmpl = None   # [(H, D, dtype)] per attention layer

    # -- model contract ----------------------------------------------------
    @staticmethod
    def _check_model(model) -> None:
        from bigdl_tpu.nn.layers.attention import MultiHeadAttention
        from bigdl_tpu.nn.layers.scan import ScanLayers

        mhas = [m for m in model.modules()
                if isinstance(m, MultiHeadAttention)]
        if not mhas:
            raise ValueError(
                "generation needs attention layers to cache — "
                f"{type(model).__name__} has none")
        bad = [m for m in mhas if not m.causal]
        if bad:
            raise ValueError(
                "generation requires causal attention everywhere (the "
                f"KV-cache contract); {len(bad)} layer(s) are not")
        if any(isinstance(m, ScanLayers) for m in model.modules()):
            raise ValueError(
                "ScanLayers stacks trace the block body ONCE, so the "
                "trace-order cache plumbing cannot address per-layer "
                "caches — build the model with scan=False for serving")

    @staticmethod
    def _model_max_len(model) -> Optional[int]:
        best = None
        for m in model.modules():
            n = getattr(m, "max_len", None)
            if isinstance(n, int) and n > 0:
                best = n if best is None else min(best, n)
        return best

    # -- traced functions --------------------------------------------------
    def _cast_state(self, state):
        import jax.numpy as jnp

        cdt = self.compute_dtype
        if cdt is None:
            return state
        return {k: (v.astype(cdt)
                    if jnp.issubdtype(v.dtype, jnp.floating) else v)
                for k, v in state.items()}

    def _make_prefill(self):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.nn.module import functional_call

        model = self.model

        def fwd(state, tokens, lengths):
            state = self._cast_state(state)
            with _kv.bind("prefill") as ctx:
                out, _ = functional_call(model, state, tokens,
                                         training=False)
            rows = jnp.arange(tokens.shape[0])
            logits = out[rows, jnp.clip(lengths - 1, 0), :]
            return logits.astype(jnp.float32), ctx.collected

        return jax.jit(fwd)

    def _make_decode(self):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.nn.module import functional_call

        model = self.model

        def fwd(state, tokens, lengths, caches):
            state = self._cast_state(state)
            with _kv.bind("decode", lengths=lengths,
                          caches=caches) as ctx:
                out, _ = functional_call(model, state, tokens,
                                         training=False)
            return out[:, -1, :].astype(jnp.float32), ctx.collected

        # the caches operand is DONATED: the per-row scatter updates in
        # place instead of materializing a full copy of every layer's
        # [B, H, C, D] k/v per emitted token (decode() reassigns
        # stack.layers to the outputs, so the stale operands are never
        # touched again)
        return jax.jit(fwd, donate_argnums=(3,))

    def _gen_fns(self):
        if self._prefill_jit is None:
            self._prefill_jit = self._make_prefill()
            self._decode_jit = self._make_decode()
        return self._prefill_jit, self._decode_jit

    def _cache_template(self) -> List[Tuple[int, int, Any]]:
        """Per-attention-layer ``(heads, head_dim, dtype)`` in TRACE
        order — derived from an abstract prefill (``jax.eval_shape``),
        so the decode operand order is the trace's own, not a guess
        from module introspection."""
        if self._cache_tmpl is not None:
            return self._cache_tmpl
        import jax

        self.refresh_state()
        prefill_fn, _ = self._gen_fns()
        s0 = self.policy.seq_buckets[0]
        tok = jax.ShapeDtypeStruct((1, s0), self.token_dtype)
        lens = jax.ShapeDtypeStruct((1,), np.int32)
        _, caches = jax.eval_shape(prefill_fn, self._state, tok, lens)
        tmpl = []
        for k, _v in caches:
            b, h, s, d = k.shape
            assert (b, s) == (1, s0), (b, s, s0)
            tmpl.append((h, d, k.dtype))
        self._cache_tmpl = tmpl
        return tmpl

    def _decode_cache_specs(self, batch: int, cache_len: int):
        import jax

        return [(jax.ShapeDtypeStruct((batch, h, cache_len, d), dt),
                 jax.ShapeDtypeStruct((batch, h, cache_len, d), dt))
                for h, d, dt in self._cache_template()]

    # -- compiling ---------------------------------------------------------
    def _compile_gen(self, key, name: str):
        """AOT-lower one prefill/decode executable (caller holds the
        lock) — the generation sibling of the base ``_compile``, same
        bookkeeping: compile event, per-bucket memory facts, OOM
        forensics on the compile path."""
        import jax

        prefill_fn, decode_fn = self._gen_fns()
        t0 = time.perf_counter()
        stage, b, x = key
        if stage == "prefill":
            args = (self._state,
                    jax.ShapeDtypeStruct((b, x), self.token_dtype),
                    jax.ShapeDtypeStruct((b,), np.int32))
            fn = prefill_fn
        else:
            args = (self._state,
                    jax.ShapeDtypeStruct((b, 1), self.token_dtype),
                    jax.ShapeDtypeStruct((b,), np.int32),
                    self._decode_cache_specs(b, x))
            fn = decode_fn
        try:
            compiled = fn.lower(*args).compile()
        except Exception as e:  # noqa: BLE001 - OOM forensics only
            self._maybe_raise_oom(e, f"GenerateExecutor.compile{list(key)}")
            raise
        self._exec[key] = compiled
        self.compile_count += 1
        try:
            from bigdl_tpu.telemetry.device import memory_facts

            mf = memory_facts(compiled)
            if mf:
                self.bucket_memory[key] = mf
        except Exception:  # noqa: BLE001 - accounting is an observer
            pass
        tracer = _telemetry.get()
        if tracer is not None:
            tracer.emit("compile", name=name,
                        dur=time.perf_counter() - t0, bucket=list(key),
                        cache_size=len(self._exec))
        return compiled

    def warmup(self, sample_shape: Tuple[int, ...], dtype) -> float:
        """Base warmup (the plain predict buckets) + every prefill and
        decode executable — after this, any generation traffic mix runs
        with zero compiles."""
        super().warmup(sample_shape, dtype)
        t0 = time.perf_counter()
        self._cache_template()
        keys = [("prefill", b, s) for b in self.policy.batch_buckets
                for s in self.policy.seq_buckets]
        keys += [("decode", b, c) for b in self.decode_buckets
                 for c in self.cache_buckets]
        with self._lock, _telemetry.span("serve/warmup",
                                         buckets=len(keys),
                                         stage="generate"):
            for key in keys:
                if key not in self._exec:
                    self._compile_gen(key, "GenerateExecutor.warmup")
        self.warmup_s += time.perf_counter() - t0
        return self.warmup_s

    # -- dispatch ----------------------------------------------------------
    def _run_key(self, key, kind: str, args: tuple,
                 record: Optional[Dict[str, Any]] = None):
        if _hooks.hooks_active():
            _hooks.dispatch_event(self, kind,
                                  {"tokens": args[1], "lengths": args[2]})
        compile_ms = 0.0
        with self._lock:
            if self._state is None:
                self.refresh_state()
            compiled = self._exec.get(key)
            if compiled is None:
                t_c0 = time.perf_counter()
                compiled = self._compile_gen(key,
                                             "GenerateExecutor.compile")
                compile_ms = (time.perf_counter() - t_c0) * 1000.0
        try:
            out = compiled(self._state, *args[1:])
        except Exception as e:  # noqa: BLE001 - OOM forensics only
            self._maybe_raise_oom(e, kind)
            raise
        if _hooks.hooks_active():
            _hooks.cache_event(self, kind, 1)
        if record is not None:
            # request tracing (telemetry/request_trace.py): an in-path
            # compile here is exactly the blame component "compile" —
            # a healthy warm server never fills this
            record["compile_ms"] = round(compile_ms, 3)
        return out

    def prefill_buckets(self, n_rows: int, seq_len: int) -> Tuple[int, int]:
        b = self.policy.batch_bucket(min(n_rows, self.policy.max_batch))
        s = self.policy.seq_bucket(seq_len)
        return b, s

    def prefill(self, tokens: np.ndarray, lengths: Sequence[int],
                record: Optional[Dict[str, Any]] = None):
        """``[n, s]`` prompt rows (ragged tails padded by the caller's
        bucket choice) -> ``(last-position logits [n, V] numpy,
        per-layer caches [B, H, S, D] on device)``."""
        import jax.numpy as jnp

        tokens = np.asarray(tokens, self.token_dtype)
        n = tokens.shape[0]
        b, s = self.prefill_buckets(n, tokens.shape[1])
        padded = self.policy.pad(tokens, b, s)
        lens = np.zeros((b,), np.int32)
        lens[:n] = np.asarray(lengths, np.int32)
        key = ("prefill", b, s)
        kind = f"GenerateExecutor.prefill[b{b}s{s}]"
        logits, caches = self._run_key(
            key, kind, (self._state, jnp.asarray(padded),
                        jnp.asarray(lens)), record=record)
        if record is not None:
            record.update(bucket=b, seq_bucket=s, rows=n,
                          padded_rows=b - n)
        return np.asarray(logits)[:n], caches

    def decode(self, stack: "_kv.StackedKVCache", tokens: np.ndarray,
               record: Optional[Dict[str, Any]] = None):
        """One coalesced decode step over ``stack``'s live rows.
        ``tokens``: ``[n_rows]`` last emitted token per row.  Updates
        ``stack.layers`` in place (the scatter-written caches) and
        returns ``[n_rows, V]`` logits; the CALLER advances lengths."""
        import jax.numpy as jnp

        if stack.batch not in self.decode_buckets:
            raise ValueError(f"stack batch {stack.batch} is not a "
                             f"decode bucket {self.decode_buckets}")
        if stack.bucket not in self.cache_buckets:
            raise ValueError(f"stack cache {stack.bucket} is not a "
                             f"cache bucket {self.cache_buckets}")
        if max(stack.lengths) >= stack.bucket:
            raise ValueError("a row is at cache capacity — grow the "
                             "stack before decoding")
        tok = np.zeros((stack.batch, 1), self.token_dtype)
        tok[:stack.n_rows, 0] = np.asarray(tokens, self.token_dtype)
        key = ("decode", stack.batch, stack.bucket)
        kind = f"GenerateExecutor.decode[b{stack.batch}c{stack.bucket}]"
        logits, new_caches = self._run_key(
            key, kind, (self._state, jnp.asarray(tok),
                        jnp.asarray(stack.lengths_padded()),
                        stack.layers), record=record)
        stack.layers = new_caches
        return np.asarray(logits)[:stack.n_rows]

    def decode_batch_bucket(self, n: int) -> int:
        return _pick_bucket(self.decode_buckets, n, "decode batch")

    def cache_bucket(self, length: int) -> int:
        return _pick_bucket(self.cache_buckets, length, "cache length")

    # -- views -------------------------------------------------------------
    def warm_buckets(self):
        """Key space mixes the base ``(batch, seq)`` predict tuples
        with ``("prefill"|"decode", b, x)`` — sort on stringified
        elements so the two families interleave stably."""
        with self._lock:
            return sorted(self._exec,
                          key=lambda k: tuple(map(str, k)))

    def memory_summary(self) -> Dict[str, Any]:
        """Base accounting with generation-aware bucket labels
        (``decode:b4c128`` instead of the predict ``b4`` form)."""
        from bigdl_tpu.telemetry.memory import _leaf_device_bytes

        with self._lock:
            state_bytes = sum(_leaf_device_bytes(v) for v in
                              (self._state or {}).values())
            buckets = {}
            peak_temp = code = 0
            for key, mf in sorted(self.bucket_memory.items(),
                                  key=lambda kv: tuple(map(str, kv[0]))):
                if isinstance(key[0], str):
                    stage, b, x = key
                    axis = "s" if stage == "prefill" else "c"
                    label = f"{stage}:b{b}{axis}{x}"
                else:
                    label = f"b{key[0]}" + (f"s{key[1]}"
                                            if key[1] is not None else "")
                buckets[label] = dict(mf)
                peak_temp = max(peak_temp, mf.get("temp_bytes", 0))
                code += mf.get("code_bytes", 0)
        return {"state_bytes": int(state_bytes),
                "code_bytes": int(code),
                "peak_temp_bytes": int(peak_temp),
                "resident_bytes": int(state_bytes + code + peak_temp),
                "buckets": buckets}
