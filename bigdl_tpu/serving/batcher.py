"""Continuous batcher: bounded queue -> coalesced bucketed dispatches.

The policy is adaptive max-latency / max-batch:

- a dispatch fires as soon as ``max_batch`` rows are assembled, OR
  ``max_wait_ms`` has passed since the OLDEST waiting request arrived —
  the deadline is anchored to the first request, so no request's queue
  wait exceeds ``max_wait_ms`` plus one in-flight batch;
- while a batch is on the device, arrivals keep queueing; the worker
  drains whatever is waiting the moment the previous dispatch returns
  (continuous batching — an idle accelerator never waits out a timer
  when work is queued, and a busy one coalesces for free);
- the queue is bounded: past ``queue_limit`` requests, ``submit``
  raises :class:`QueueFullError` and the HTTP frontend turns it into a
  429 — backpressure instead of unbounded latency.

Each executed batch emits one ``serve`` telemetry event (rows, bucket,
queue wait, infer time, padding waste) plus the ``serve/queue_depth``
gauge and ``serve/requests``/``serve/rejected`` counters — the raw
material for ``/status`` percentiles and ``telemetry diff``'s serving
metrics.  Graceful drain: ``stop(drain=True)`` stops admissions,
finishes every queued request, then parks the worker — the SIGTERM
path of ``models/cli.py serve``.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Callable, Deque, List, Optional, Tuple

import numpy as np

from bigdl_tpu import telemetry as _telemetry

__all__ = ["ContinuousBatcher", "QueueFullError", "Request"]


class QueueFullError(RuntimeError):
    """The bounded request queue is at capacity (HTTP 429)."""


class Request:
    """One enqueued inference request: ``x`` is ``[k, ...feature]``
    rows (k >= 1).  ``wait()`` blocks until the batch that carried it
    lands; ``output``/``error`` hold the result.  ``cancel()`` (the
    frontend's timeout path) tells the worker to DROP the rows instead
    of computing results nobody will read — under overload, timed-out
    work must not amplify the overload.

    ``trace`` (optional, telemetry/request_trace.py): the server's
    RequestTrace riding along; ``dispatch`` is filled by the worker with
    the carrying batch's split (epoch start, infer ms, bucket, padded
    rows, co-batched requests, in-path compile ms) so the server can
    tile the request's wall time into owned spans after ``wait()``."""

    __slots__ = ("x", "rows", "enqueued_at", "enqueued_ts", "done",
                 "output", "error", "queue_ms", "cancelled", "trace",
                 "dispatch")

    def __init__(self, x: np.ndarray, trace=None):
        self.x = x
        self.rows = int(x.shape[0])
        self.enqueued_at = time.perf_counter()
        self.enqueued_ts = time.time()  # epoch twin (span timestamps)
        self.done = threading.Event()
        self.output: Any = None
        self.error: Optional[BaseException] = None
        self.queue_ms: float = 0.0
        self.cancelled = False
        self.trace = trace
        self.dispatch: Optional[dict] = None

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    def cancel(self) -> None:
        self.cancelled = True


class ContinuousBatcher:
    """Single worker thread coalescing requests into bucketed
    executor dispatches.  ``runner(batch_x) -> batch_out`` is the
    executor's ``run`` (already bucket-padding); the batcher only
    decides WHEN to dispatch and HOW MANY rows ride along."""

    def __init__(self, runner: Callable[[np.ndarray], Any],
                 max_batch: int = 32, max_wait_ms: float = 5.0,
                 queue_limit: int = 256,
                 seq_pad: Optional[Callable[[List[np.ndarray]],
                                            Tuple[List[np.ndarray],
                                                  Optional[int]]]] = None,
                 seq_trim: Optional[Callable[[Any, int, int],
                                             Any]] = None,
                 bucket_rows: Optional[Callable[[int, int], int]] = None):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.runner = runner
        self.max_batch = max_batch
        self.max_wait_s = max(0.0, max_wait_ms) / 1000.0
        self.queue_limit = queue_limit
        # seq bucketing hooks (token models; the server injects both):
        # seq_pad([x...]) -> (padded [x...], common seq target) before
        # concatenation; seq_trim(rows_out, orig_len, target) slices a
        # request's output back to ITS submitted length afterwards
        self._seq_pad = seq_pad
        self._seq_trim = seq_trim
        # (rows, max_batch) -> padded bucket rows, for the padding-waste
        # stat; the server injects the executor policy's real buckets
        self._bucket_rows = bucket_rows
        self._q: "queue.Queue[Request]" = queue.Queue(maxsize=queue_limit)
        self._stats_lock = threading.Lock()
        self._lat_ms: Deque[Tuple[float, float]] = collections.deque(
            maxlen=4096)  # (wall finish time, e2e latency ms)
        self._queue_ms: Deque[float] = collections.deque(maxlen=4096)
        self.requests = 0
        self.rejected = 0
        self.rows = 0
        self.batches = 0
        self.padded_rows = 0
        self.errors = 0
        self._draining = False
        self._stopped = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._thread = threading.Thread(target=self._loop,
                                        name="bigdl-serve-batcher",
                                        daemon=True)
        self._thread.start()

    @property
    def runner(self):
        return self._runner

    @runner.setter
    def runner(self, fn) -> None:
        # executors expose their dispatch split (bucket, padded rows,
        # in-path compile, device ms) through a `record` kwarg — detect
        # on every assignment (tests and wrappers swap `.runner` live)
        # so plain callables keep working
        self._runner = fn
        try:
            import inspect

            self._runner_records = "record" in \
                inspect.signature(fn).parameters
        except (TypeError, ValueError):
            self._runner_records = False

    # -- admission ---------------------------------------------------------
    def submit(self, x: np.ndarray, trace=None) -> Request:
        """Enqueue ``[k, ...]`` rows; raises :class:`QueueFullError` at
        capacity or once draining."""
        if self._draining or self._stopped.is_set():
            raise QueueFullError("server is draining")
        req = Request(np.asarray(x), trace=trace)
        try:
            self._q.put_nowait(req)
        except queue.Full:
            with self._stats_lock:
                self.rejected += 1
            _telemetry.counter("serve/rejected", 1)
            raise QueueFullError(
                f"request queue at capacity ({self.queue_limit})") from None
        with self._stats_lock:
            self.requests += 1
        _telemetry.counter("serve/requests", 1)
        _telemetry.gauge("serve/queue_depth", self._q.qsize())
        return req

    def depth(self) -> int:
        return self._q.qsize()

    # -- the worker --------------------------------------------------------
    def _gather(self) -> List[Request]:
        """Block for the first request, then coalesce until the batch
        is full or the oldest request's ``max_wait_ms`` deadline
        passes.  Requests too big to ride along are left queued for
        the next batch (FIFO preserved: Queue pops in order and we
        only peek-ahead by popping, so an oversized pop is carried
        into the next gather via ``_carry``)."""
        batch: List[Request] = []
        rows = 0
        carry = getattr(self, "_carry", None)
        if carry is not None:
            self._carry = None
            if carry.cancelled:
                carry.done.set()
            else:
                batch.append(carry)
                rows = carry.rows
        while not batch:
            if self._stopped.is_set():
                return []
            if self._draining and self._q.empty():
                return []
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            if first.cancelled:  # timed-out client: drop, don't compute
                first.done.set()
                continue
            batch.append(first)
            rows = first.rows
        deadline = batch[0].enqueued_at + self.max_wait_s
        while rows < self.max_batch:
            remaining = deadline - time.perf_counter()
            try:
                if self._q.empty() and remaining <= 0:
                    break
                nxt = self._q.get(timeout=max(0.0, remaining)
                                  if self._q.empty() else 0.0)
            except queue.Empty:
                break
            if nxt.cancelled:
                nxt.done.set()
                continue
            if rows + nxt.rows > self.max_batch:
                self._carry = nxt  # rides the next batch, order intact
                break
            batch.append(nxt)
            rows += nxt.rows
        return batch

    def _loop(self) -> None:
        self._carry = None
        while True:
            batch = self._gather()
            if not batch:
                if self._stopped.is_set() or \
                        (self._draining and self._q.empty()
                         and getattr(self, "_carry", None) is None):
                    self._stopped.set()
                    self._idle.set()
                    return
                continue
            self._idle.clear()
            try:
                self._execute(batch)
            finally:
                self._idle.set()

    def _execute(self, batch: List[Request]) -> None:
        batch = [r for r in batch if not r.cancelled]
        if not batch:
            return
        t0 = time.perf_counter()
        t0_ts = time.time()
        rows = sum(r.rows for r in batch)
        for r in batch:
            r.queue_ms = (t0 - r.enqueued_at) * 1000.0
        rec: dict = {}
        try:
            xs = [r.x for r in batch]
            lens = [x.shape[1] if np.ndim(x) >= 2 else None for x in xs]
            target = None
            if self._seq_pad is not None:
                xs, target = self._seq_pad(xs)
            x = xs[0] if len(xs) == 1 else np.concatenate(xs, axis=0)
            if self._runner_records:
                out = self.runner(x, record=rec)
            else:
                out = self.runner(x)
            infer_ms = (time.perf_counter() - t0) * 1000.0
            for r in batch:
                # the carrying batch's split, per rider — the server
                # tiles each request's wall time from this after wait()
                r.dispatch = dict(rec, t0_ts=t0_ts,
                                  infer_ms=round(infer_ms, 3),
                                  co_requests=len(batch),
                                  batch_rows=rows)
            offset = 0
            for i, r in enumerate(batch):
                sliced = _slice_rows(out, offset, offset + r.rows)
                if target is not None and self._seq_trim is not None \
                        and lens[i] is not None and target > lens[i]:
                    # the executor saw only the batch-common padded
                    # length; slice THIS request's output back to the
                    # length it actually submitted
                    sliced = self._seq_trim(sliced, lens[i], target)
                r.output = sliced
                offset += r.rows
        except BaseException as e:  # noqa: BLE001 - relayed per request
            infer_ms = (time.perf_counter() - t0) * 1000.0
            with self._stats_lock:
                self.errors += 1
            for r in batch:
                r.error = e
        finally:
            done_at = time.time()
            with self._stats_lock:
                self.batches += 1
                self.rows += rows
                bucket = (self._bucket_rows or _next_bucket)(
                    rows, self.max_batch)
                self.padded_rows += max(0, bucket - rows)
                for r in batch:
                    e2e = (time.perf_counter() - r.enqueued_at) * 1000.0
                    self._lat_ms.append((done_at, e2e))
                    self._queue_ms.append(r.queue_ms)
            for r in batch:
                r.done.set()
        tracer = _telemetry.get()
        if tracer is not None:
            # queue_ms is anchored at the OLDEST rider (the worst case
            # the deadline contract bounds); min/mean travel beside it
            # so aggregate readers no longer overstate the typical wait
            waits = [r.queue_ms for r in batch]
            tracer.emit("serve", size=rows, requests=len(batch),
                        dur=(time.perf_counter() - t0),
                        queue_ms=round(max(waits), 3),
                        queue_ms_min=round(min(waits), 3),
                        queue_ms_mean=round(sum(waits) / len(waits), 3),
                        infer_ms=round(infer_ms, 3),
                        fill=round(rows / self.max_batch, 4))
            _telemetry.gauge("serve/queue_depth", self._q.qsize())

    # -- stats / lifecycle -------------------------------------------------
    def stats(self, window_s: float = 60.0) -> dict:
        now = time.time()
        with self._stats_lock:
            recent = [lat for (at, lat) in self._lat_ms
                      if now - at <= window_s]
            lat = sorted(recent)
            qms = list(self._queue_ms)[-len(lat):] if lat else []
            out = {"requests": self.requests, "rejected": self.rejected,
                   "rows": self.rows, "batches": self.batches,
                   "errors": self.errors,
                   "queue_depth": self._q.qsize(),
                   "queue_limit": self.queue_limit,
                   "max_batch": self.max_batch,
                   "max_wait_ms": self.max_wait_s * 1000.0,
                   "batch_fill": round(
                       self.rows / (self.batches * self.max_batch), 4)
                   if self.batches else None,
                   "padding_waste": round(
                       self.padded_rows / max(1, self.rows + self.padded_rows), 4),
                   "window_s": window_s,
                   "draining": self._draining}
        if lat:
            # rate over the span actually covered by the recent window
            # (a 3s-old server must not divide 300 requests by 60s)
            span = min(window_s,
                       max(0.25, now - min(at for (at, _) in self._lat_ms
                                           if now - at <= window_s)))
            out["qps"] = round(len(lat) / span, 2)
            out["p50_ms"] = round(_pct(lat, 50.0), 3)
            out["p99_ms"] = round(_pct(lat, 99.0), 3)
            out["queue_p50_ms"] = round(_pct(sorted(qms), 50.0), 3) \
                if qms else 0.0
        return out

    def stop(self, drain: bool = True, timeout: float = 30.0) -> bool:
        """Stop admissions; with ``drain`` finish everything queued
        first.  Returns True when the worker parked in time."""
        self._draining = True
        if not drain:
            self._stopped.set()
        self._thread.join(timeout)
        self._stopped.set()
        parked = not self._thread.is_alive()
        # TOCTOU sweep: a submit() that passed the draining check may
        # have enqueued AFTER the worker saw an empty queue and parked —
        # those requests were accepted, so the drain contract owes them
        # an answer.  The worker is dead here, so executing (or failing)
        # them inline is race-free.
        leftovers: List[Request] = []
        carry = getattr(self, "_carry", None)
        self._carry = None
        if carry is not None:
            leftovers.append(carry)
        while True:
            try:
                leftovers.append(self._q.get_nowait())
            except queue.Empty:
                break
        if drain and parked:
            chunk: List[Request] = []
            rows = 0
            for r in leftovers:
                if rows + r.rows > self.max_batch and chunk:
                    self._execute(chunk)
                    chunk, rows = [], 0
                chunk.append(r)
                rows += r.rows
            if chunk:
                self._execute(chunk)
        else:  # hard stop: fail fast instead of a silent client timeout
            for r in leftovers:
                r.error = QueueFullError("server stopped")
                r.done.set()
        return parked


def _slice_rows(out, lo: int, hi: int):
    import jax

    return jax.tree.map(lambda a: a[lo:hi], out)


def _next_bucket(n: int, cap: int) -> int:
    b = 1
    while b < n and b < cap:
        b *= 2
    return max(b, n)


def _pct(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(p / 100.0 * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]
