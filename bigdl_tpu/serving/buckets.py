"""Shape buckets: the fixed set of padded shapes serving compiles for.

Arrival-size variance is the production recompile hazard: every distinct
``[n, ...]`` batch shape is its own jit cache entry, and a compile in
the request path is a multi-second p99 spike (BENCH_banked_r5.json
``stages_s``: 32-445s cold compiles).  The policy here quantizes every
arrival onto a small, closed set of shapes:

- **batch buckets** — powers of two up to ``max_batch`` (overridable),
  so any batch of 1..max_batch rows pads to the next bucket and the
  worst-case padding waste is bounded at 50%;
- **sequence buckets** — for token models, the padded time axis also
  snaps to a bucket.  The default is the model's canonical sequence
  length (ONE bucket — numerics identical to the batch ``Predictor``);
  explicit buckets trade that equivalence for less padding compute on
  short requests (see docs/serving.md for the numerics caveat on
  non-causal models).

The bucket set is closed under ``warmup()``: the executor AOT-compiles
every (batch, seq) combination at startup, so steady-state traffic can
never meet a cold executable.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["BucketPolicy", "pow2_buckets"]


def pow2_buckets(max_batch: int) -> Tuple[int, ...]:
    """1, 2, 4, ... up to (and including) ``max_batch``."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)


class BucketPolicy:
    """The closed set of padded shapes one served model compiles for.

    ``batch_buckets``: ascending row-count buckets (default: powers of
    two up to ``max_batch``).  ``seq_buckets``: ascending time-axis
    buckets for token inputs (None = the feature shape is fixed and no
    axis is padded beyond batch).  ``pad_value`` fills padded cells —
    0 matches the text pipeline's reserved padding id and is inert for
    image rows (padded ROWS are sliced off the output either way).
    """

    def __init__(self, max_batch: int = 32,
                 batch_buckets: Optional[Sequence[int]] = None,
                 seq_buckets: Optional[Sequence[int]] = None,
                 pad_value: float = 0.0):
        buckets = tuple(sorted(set(batch_buckets or
                                   pow2_buckets(max_batch))))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"bad batch buckets {buckets}")
        self.batch_buckets = buckets
        self.max_batch = buckets[-1]
        self.seq_buckets = tuple(sorted(set(seq_buckets))) \
            if seq_buckets else None
        if self.seq_buckets and self.seq_buckets[0] < 1:
            raise ValueError(f"bad seq buckets {self.seq_buckets}")
        self.pad_value = pad_value

    # -- selection ---------------------------------------------------------
    def batch_bucket(self, n: int) -> int:
        """Smallest bucket >= n (n > max_batch is a caller bug — the
        batcher never assembles past ``max_batch``)."""
        if n < 1:
            raise ValueError(f"batch of {n} rows")
        for b in self.batch_buckets:
            if b >= n:
                return b
        raise ValueError(f"batch of {n} rows exceeds the largest bucket "
                         f"{self.max_batch}")

    def seq_bucket(self, t: int) -> Optional[int]:
        """Smallest sequence bucket >= t; None when no seq bucketing.
        A sequence longer than every bucket clamps to the largest (the
        executor truncates — the bucket set is closed by construction)."""
        if self.seq_buckets is None:
            return None
        for s in self.seq_buckets:
            if s >= t:
                return s
        return self.seq_buckets[-1]

    def bucket_keys(self):
        """Every (batch, seq) combination — the warmup compile set."""
        seqs = self.seq_buckets or (None,)
        return [(b, s) for b in self.batch_buckets for s in seqs]

    # -- padding -----------------------------------------------------------
    def pad(self, x: np.ndarray, batch_bucket: int,
            seq_bucket: Optional[int] = None) -> np.ndarray:
        """Pad ``[n, ...]`` rows up to ``[batch_bucket, ...]`` (and the
        time axis 1 up to ``seq_bucket``); over-long sequences truncate
        to the largest bucket."""
        x = np.asarray(x)
        n = x.shape[0]
        if n > batch_bucket:
            raise ValueError(f"{n} rows > bucket {batch_bucket}")
        if seq_bucket is not None and x.ndim >= 2 \
                and x.shape[1] > seq_bucket:
            x = x[:, :seq_bucket]
        target = (batch_bucket,) + x.shape[1:]
        if seq_bucket is not None and x.ndim >= 2:
            target = (batch_bucket, seq_bucket) + x.shape[2:]
        if target == x.shape:
            return x
        out = np.full(target, self.pad_value, dtype=x.dtype)
        out[tuple(slice(0, d) for d in x.shape)] = x
        return out

    def __repr__(self):
        return (f"BucketPolicy(batch={list(self.batch_buckets)}, "
                f"seq={list(self.seq_buckets) if self.seq_buckets else None})")
