"""Per-bucket AOT executables: the serving compile cache.

One :class:`BucketedExecutor` owns a model's inference executables —
one ``jax.jit(fwd).lower(state, spec).compile()`` per (batch-bucket,
seq-bucket) shape.  ``warmup()`` compiles the whole bucket set at
startup (``serve/warmup`` span, one ``compile`` event per bucket named
``ServeExecutor.warmup``), so first-request latency is a dispatch;
a compile that happens INSIDE the request path instead is emitted as
``ServeExecutor.compile`` — in a healthy server that name never appears
after startup, and ``telemetry diff`` gates on the compile count.

The executor is also the batch ``Predictor``'s compiled step
(``optim/predictor.py``): :func:`executor_for` keeps one executor per
live (model, mesh) pair, so offline scoring and online serving share
one compile cache — the fix for ``LocalPredictor.predict`` rebuilding
(and re-jitting) a fresh ``EvalStep`` on every call.

Retrace-detector integration mirrors TrainStep/EvalStep: every dispatch
reports through ``analysis.hooks`` under a per-bucket kind
(``ServeExecutor.run[b8]``), so within a bucket the signature is
constant by construction and ``trace_retraces`` stays clean over any
arrival-size mix — the test contract for "zero steady-state recompiles".
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from bigdl_tpu import telemetry as _telemetry
from bigdl_tpu.analysis import hooks as _hooks
from bigdl_tpu.serving.buckets import BucketPolicy

__all__ = ["BucketedExecutor", "executor_for", "default_policy"]


def _mesh_batch_div(mesh) -> int:
    """Rows every bucket must divide into on this mesh (1 off-mesh)."""
    if mesh is None:
        return 1
    from bigdl_tpu.parallel.mesh import DATA_AXIS

    return max(1, mesh.shape.get(DATA_AXIS, 1))


def default_policy(max_batch: int = 32, mesh=None) -> BucketPolicy:
    """The default bucket set, ALIGNED to the mesh batch axis: plain
    pow2 buckets off-mesh; on an N-way data mesh, multiples N, 2N, 4N
    ... (a bucket of 1 cannot shard over 2 devices)."""
    n = _mesh_batch_div(mesh)
    if n <= 1:
        return BucketPolicy(max_batch=max_batch)
    buckets, b = [], n
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max(max_batch, n))
    if buckets[-1] % n:
        buckets[-1] += n - buckets[-1] % n  # round up onto the mesh
    return BucketPolicy(max_batch=buckets[-1], batch_buckets=buckets)


class BucketedExecutor:
    """AOT-compiled, shape-bucketed inference over one model.

    ``seq_axis`` (models whose axis 1 is a padded time axis) enables
    sequence bucketing via ``policy.seq_buckets``; inputs longer than
    the largest bucket truncate.  ``compute_dtype`` mirrors EvalStep
    (e.g. ``jnp.bfloat16`` fwd with f32 params); quantized models pass
    None — the int8 path owns its dtypes.
    """

    def __init__(self, model, mesh=None, policy: Optional[BucketPolicy] = None,
                 compute_dtype=None, seq_axis: Optional[int] = None):
        from bigdl_tpu.nn.module import stamp_scope_names
        from bigdl_tpu.utils.config import get_config

        stamp_scope_names(model, enabled=get_config().module_scopes)
        self.model = model
        self.mesh = mesh
        self.policy = policy or default_policy(mesh=mesh)
        self.compute_dtype = compute_dtype
        self.seq_axis = seq_axis
        self.compile_count = 0
        self.warmup_s = 0.0
        self._fwd = self._make_fwd()
        self._exec: Dict[Tuple[int, Optional[int]], Any] = {}
        # per-bucket executable memory_analysis (recorded at compile
        # time): the resident-executable HBM the KV-cache budgeting
        # work (ROADMAP item 2) subtracts from the device budget
        self.bucket_memory: Dict[Tuple[int, Optional[int]],
                                 Dict[str, int]] = {}
        self._state = None        # device-placed {path: array}
        self._state_src = None    # host-side identity snapshot
        self._state_sig = None    # {path: (shape, dtype)} of the trace
        self._lock = threading.RLock()
        if mesh is not None:
            bad = [b for b in self.policy.batch_buckets
                   if not self._divisible(b)]
            if bad:
                raise ValueError(
                    f"batch buckets {bad} not divisible by the mesh "
                    f"batch axis — pick buckets that shard evenly")

    def _divisible(self, b: int) -> bool:
        from bigdl_tpu.parallel.mesh import DATA_AXIS

        n = self.mesh.shape.get(DATA_AXIS, 1)
        return b % n == 0

    def _make_fwd(self):
        import jax
        import jax.numpy as jnp

        from bigdl_tpu.nn.module import functional_call

        model, cdt = self.model, self.compute_dtype

        def fwd(state, x):
            if cdt is not None:
                state = {k: (v.astype(cdt)
                             if jnp.issubdtype(v.dtype, jnp.floating) else v)
                         for k, v in state.items()}
            out, _ = functional_call(model, state, x, training=False)
            if cdt is not None:
                out = jax.tree.map(
                    lambda a: a.astype(jnp.float32)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a, out)
            return out

        return fwd

    # -- state -------------------------------------------------------------
    def refresh_state(self) -> None:
        """Re-read the module tree's params/buffers onto the device.
        Identity-checked: unchanged arrays cost a dict walk, not a
        transfer.  A shape/dtype change (e.g. the model was re-built)
        drops the compiled executables — same-shape weight updates
        (training between predicts) keep every warm executable."""
        from bigdl_tpu.nn.module import state_dict

        host = state_dict(self.model)
        with self._lock:
            if self._state_src is not None \
                    and len(host) == len(self._state_src) \
                    and all(self._state_src.get(k) is v
                            for k, v in host.items()):
                return
            self._place_state(host)

    def _place_state(self, host) -> None:
        import jax
        import jax.numpy as jnp

        sig = {k: (tuple(np.shape(v)), str(getattr(v, "dtype", "?")))
               for k, v in host.items()}
        if self.mesh is not None:
            from bigdl_tpu.parallel.mesh import replicated

            state = {k: jax.device_put(jnp.asarray(v),
                                       replicated(self.mesh))
                     for k, v in host.items()}
        else:
            state = {k: jnp.asarray(v) for k, v in host.items()}
        if self._state_sig is not None and sig != self._state_sig:
            self._exec.clear()  # stale traces: the avals changed
        self._state_src = dict(host)
        self._state_sig = sig
        self._state = state

    # -- compiling ---------------------------------------------------------
    def _input_spec(self, key, sample_shape: Tuple[int, ...], dtype):
        import jax

        bb, sb = key
        shape = (bb,) + tuple(sample_shape)
        if sb is not None and len(shape) >= 2:
            shape = (bb, sb) + tuple(shape[2:])
        return jax.ShapeDtypeStruct(shape, dtype)

    def _compile(self, key, spec, name: str):
        import jax

        t0 = time.perf_counter()
        fn = jax.jit(self._fwd)
        if self.mesh is not None:
            from bigdl_tpu.parallel.mesh import data_sharding

            sharding = data_sharding(self.mesh, len(spec.shape))
            spec = jax.ShapeDtypeStruct(spec.shape, spec.dtype,
                                        sharding=sharding)
        try:
            compiled = fn.lower(self._state, spec).compile()
        except Exception as e:  # noqa: BLE001 - OOM forensics only
            self._maybe_raise_oom(e, f"ServeExecutor.compile{list(key)}")
            raise
        self._exec[key] = compiled
        self.compile_count += 1
        try:
            from bigdl_tpu.telemetry.device import memory_facts

            mf = memory_facts(compiled)
            if mf:
                self.bucket_memory[key] = mf
        except Exception:  # noqa: BLE001 - accounting is an observer
            pass
        dur = time.perf_counter() - t0
        tracer = _telemetry.get()
        if tracer is not None:
            tracer.emit("compile", name=name, dur=dur,
                        bucket=list(k for k in key if k is not None),
                        cache_size=len(self._exec))
        return compiled

    def _maybe_raise_oom(self, exc: Exception, context: str) -> None:
        """RESOURCE_EXHAUSTED from a serving compile or dispatch gets
        the same enriched postmortem the train path raises
        (telemetry/memory.py): largest resident buffers, categories,
        live-vs-limit, flight-dumped before the re-raise."""
        from bigdl_tpu.telemetry import memory as _tmem

        if not _tmem.is_oom(exc):
            return
        trees = {"state": self._state if self._state is not None else {}}
        summary = self.memory_summary()
        context = (f"{context} (resident executables: "
                   f"{len(self.bucket_memory)} buckets, "
                   f"{summary['resident_bytes']} bytes incl. state)")
        _tmem.raise_oom(exc, trees, context=context)

    def memory_summary(self) -> Dict[str, Any]:
        """Resident-executable HBM: per-device state (weights) bytes +
        the per-bucket executable breakdown.  ``resident_bytes`` =
        state + generated code + the LARGEST bucket temp (buckets run
        one at a time — their scratch is not additive; code is)."""
        from bigdl_tpu.telemetry.memory import _leaf_device_bytes

        with self._lock:
            state_bytes = sum(_leaf_device_bytes(v) for v in
                              (self._state or {}).values())
            buckets = {}
            peak_temp = code = 0
            for key, mf in sorted(self.bucket_memory.items(),
                                  key=lambda kv: (kv[0][0],
                                                  kv[0][1] or -1)):
                label = f"b{key[0]}" + (f"s{key[1]}"
                                        if key[1] is not None else "")
                buckets[label] = dict(mf)
                peak_temp = max(peak_temp, mf.get("temp_bytes", 0))
                code += mf.get("code_bytes", 0)
        return {"state_bytes": int(state_bytes),
                "code_bytes": int(code),
                "peak_temp_bytes": int(peak_temp),
                "resident_bytes": int(state_bytes + code + peak_temp),
                "buckets": buckets}

    def warmup(self, sample_shape: Tuple[int, ...], dtype) -> float:
        """AOT-compile every bucket in the policy for samples of
        ``sample_shape`` (feature shape, no batch axis).  Returns the
        wall seconds spent; idempotent per bucket."""
        # a warm RESTART'S warmup should load every bucket executable
        # from the persistent cache instead of recompiling the whole
        # set before the ready line (docs/compile.md; implicit:
        # accelerator-only unless BIGDL_COMPILE_CACHE opts plain CPU
        # in, =0 opts out) — the same managed cache aot_scan uses
        from bigdl_tpu.utils.engine import enable_compile_cache

        enable_compile_cache(implicit=True)
        t0 = time.perf_counter()
        self.refresh_state()
        with self._lock, _telemetry.span(
                "serve/warmup", buckets=len(self.policy.bucket_keys())):
            for key in self.policy.bucket_keys():
                if key not in self._exec:
                    spec = self._input_spec(key, sample_shape, dtype)
                    self._compile(key, spec, "ServeExecutor.warmup")
        self.warmup_s += time.perf_counter() - t0
        return self.warmup_s

    def warm_buckets(self):
        with self._lock:
            return sorted(self._exec,
                          key=lambda k: (k[0], k[1] if k[1] is not None
                                         else -1))

    def adopt_policy(self, policy: BucketPolicy,
                     seq_axis: Optional[int] = None) -> None:
        """Merge a caller's bucket requirements into the shared
        executor (the batch Predictor and a ModelServer over the same
        model keep ONE compile cache): batch buckets union, seq
        buckets/axis adopted when this executor had none.  Warm
        executables survive — the key set only grows."""
        with self._lock:
            self.policy.batch_buckets = tuple(sorted(
                set(self.policy.batch_buckets)
                | set(policy.batch_buckets)))
            self.policy.max_batch = self.policy.batch_buckets[-1]
            if policy.seq_buckets and not self.policy.seq_buckets:
                self.policy.seq_buckets = policy.seq_buckets
            if seq_axis is not None and self.seq_axis is None:
                self.seq_axis = seq_axis

    # -- dispatch ----------------------------------------------------------
    def bucket_of(self, x: np.ndarray) -> Tuple[int, Optional[int]]:
        x = np.asarray(x)
        n = x.shape[0]
        with self._lock:
            if n > self.policy.max_batch:
                # offline callers (Predictor at a larger batch_size)
                # grow the bucket set with the exact size — pow2 rounding
                # a steady full batch would waste real compute.  On a
                # mesh, round up onto the batch axis so the new bucket
                # still shards
                div = _mesh_batch_div(self.mesh)
                grown = n + (div - n % div) % div
                self.policy.batch_buckets = tuple(sorted(
                    set(self.policy.batch_buckets) | {grown}))
                self.policy.max_batch = grown
            bb = self.policy.batch_bucket(n)
        sb = None
        if self.seq_axis is not None and x.ndim >= 2:
            sb = self.policy.seq_bucket(x.shape[1])
        return bb, sb

    def run(self, x, record: Optional[Dict[str, Any]] = None) -> Any:
        """Pad ``[n, ...]`` onto its bucket, dispatch the warm
        executable (compiling it first if cold — emitted as the
        in-request-path ``ServeExecutor.compile``), slice the padding
        back off.  Returns the output pytree as numpy.

        ``record`` (request tracing, telemetry/request_trace.py): a dict
        the dispatch fills with its own split — bucket, padded rows,
        in-path ``compile_ms`` (zero on a warm bucket) and ``device_ms``
        — so the batcher can attribute each rider's wall time without
        re-deriving bucket selection."""
        import jax.numpy as jnp

        x = np.asarray(x)
        n = x.shape[0]
        key = self.bucket_of(x)
        padded = self.policy.pad(x, key[0], key[1])
        kind = f"ServeExecutor.run[b{key[0]}" \
               + (f"s{key[1]}]" if key[1] is not None else "]")
        if _hooks.hooks_active():
            _hooks.dispatch_event(self, kind, {"x": padded})
        compile_ms = 0.0
        with self._lock:
            if self._state is None:
                self.refresh_state()
            compiled = self._exec.get(key)
            if compiled is None:
                import jax

                t_c0 = time.perf_counter()
                spec = jax.ShapeDtypeStruct(padded.shape, padded.dtype)
                compiled = self._compile(key, spec, "ServeExecutor.compile")
                compile_ms = (time.perf_counter() - t_c0) * 1000.0
        xj = self._place_input(jnp.asarray(padded))
        t_d0 = time.perf_counter()
        try:
            out = compiled(self._state, xj)
        except Exception as e:  # noqa: BLE001 - OOM forensics only
            self._maybe_raise_oom(e, kind)
            raise
        if record is not None:
            import jax

            # dispatch is async: block before stamping device_ms so the
            # number is the compute, not the enqueue (the host-side
            # np.asarray conversion below would have blocked anyway)
            jax.block_until_ready(out)
            record.update(
                bucket=key[0], seq_bucket=key[1], rows=n,
                padded_rows=key[0] - n, compile_ms=round(compile_ms, 3),
                device_ms=round(
                    (time.perf_counter() - t_d0) * 1000.0, 3))
        if _hooks.hooks_active():
            # one executable per kind, forever — the detector sees a
            # constant signature AND a constant cache size per bucket
            _hooks.cache_event(self, kind, 1)
        import jax

        seq_in = x.shape[1] if (self.seq_axis is not None
                                and x.ndim >= 2) else None

        def host_rows(a):
            a = np.asarray(a)
            if key[0] == 1 and (a.ndim == 0 or a.shape[0] != 1):
                # Torch-legacy batch-1 ambiguity: Reshape's auto-detect
                # (Reshape.scala:61-63 semantics) treats a [1, ...]
                # input as UNBATCHED, so the bucket-1 executable's
                # output lost its batch axis — restore it so callers
                # always see [rows, ...]
                a = a[None]
            a = a[:n]
            if seq_in is not None and key[1] is not None \
                    and key[1] > seq_in and a.ndim >= 2 \
                    and a.shape[1] == key[1]:
                # seq-to-seq outputs carry the padded time axis: slice
                # back to the request's length.  Time-reducing heads
                # ([n, classes]) pass through untouched — their axis 1
                # doesn't match the bucket
                a = a[:, :seq_in]
            return a

        return jax.tree.map(host_rows, out)

    def _place_input(self, xj):
        if self.mesh is None:
            return xj
        import jax

        from bigdl_tpu.parallel.mesh import data_sharding

        return jax.device_put(xj, data_sharding(self.mesh, xj.ndim))


# -- the shared (model, mesh) -> executor cache ------------------------------
# LRU-capped: an executor strongly references its model (the fwd
# closure) and its compiled executables, so an UNBOUNDED registry would
# leak every model ever predicted for process lifetime (Module.predict
# routes through here).  The cap covers the real pattern — one or a few
# live served/scored models — and eviction merely costs the next
# predict of an evicted model a re-compile.
_REGISTRY_CAP = 8
_REGISTRY: "collections.OrderedDict[Tuple[int, Optional[int]], " \
           "Tuple[Any, BucketedExecutor]]" = collections.OrderedDict()
_REGISTRY_LOCK = threading.Lock()


def executor_for(model, mesh=None, max_batch: int = 32,
                 compute_dtype=None, seq_axis: Optional[int] = None,
                 policy: Optional[BucketPolicy] = None) -> BucketedExecutor:
    """One executor per live (model, mesh) pair — the process-wide
    compile cache shared by ``LocalPredictor`` and the serving layer.
    ``id()`` keys are revalidated against a weakref (CPython reuses
    addresses of collected objects); least-recently-used entries are
    evicted past the cap."""
    import weakref

    key = (id(model), id(mesh) if mesh is not None else None)
    with _REGISTRY_LOCK:
        hit = _REGISTRY.get(key)
        if hit is not None and hit[0]() is model:
            _REGISTRY.move_to_end(key)
            ex = hit[1]
            if policy is not None:
                ex.adopt_policy(policy, seq_axis=seq_axis)
            return ex
        if hit is not None:  # stale id reuse
            del _REGISTRY[key]
        ex = BucketedExecutor(
            model, mesh=mesh,
            policy=policy or default_policy(max_batch, mesh),
            compute_dtype=compute_dtype, seq_axis=seq_axis)
        try:
            ref = weakref.ref(model)
        except TypeError:  # unweakrefable model: no caching, still works
            return ex
        _REGISTRY[key] = (ref, ex)
        while len(_REGISTRY) > _REGISTRY_CAP:
            _REGISTRY.popitem(last=False)
        return ex
