"""bigdl_tpu — a TPU-native distributed deep-learning framework.

A ground-up JAX/XLA re-design with the capabilities of the reference
BigDL-on-Spark library (see SURVEY.md): Torch-style modules and criterions,
composable data pipelines, synchronous data-parallel training with sharded
parameter updates (ZeRO-1-style reduce-scatter/all-gather over ICI),
optimizers/schedules/triggers/validation, checkpoint-resume-retry,
TensorBoard event writing, and a model zoo — all built TPU-first on
``jax.sharding`` meshes and ``jit``-compiled train steps.
"""

__version__ = "0.1.0"

from bigdl_tpu.utils.engine import Engine  # noqa: F401
from bigdl_tpu.utils.rng import RNG  # noqa: F401
