"""Deterministic, seeded fault injection (docs/fault_tolerance.md).

The recovery machinery this framework ports from the reference — the
checkpoint retry loop (``DistriOptimizer.scala:790-856``), the straggler
watchdog, the health halt/skip policy, the flight recorder — is only
trustworthy if something actually exercises it.  This module is that
something: a :class:`FaultPlan` parsed from ``BIGDL_FAULTS`` (an env var
so the plan reaches every multihost subprocess worker unchanged)
describes *which* failure fires *where* and *when*, and thin injection
hooks wired into the hot paths make it happen — each fault exactly once,
each announced with a ``fault/injected`` telemetry instant so the run
log and the flight-recorder ring carry the ground truth a test (or a
postmortem) asserts against.

Plan syntax — comma-separated ``kind[@step][:pP][:ms]`` specs::

    BIGDL_FAULTS="crash@12,nan_grads@30,wedge@45,kill_worker@20:p1,torn_ckpt,data_err@7,straggle@4:p1:250"

- ``kind`` — one of :data:`KINDS` (below);
- ``@step`` — the 1-based training iteration (for ``data_err``: the
  1-based batch fetch; for ``torn_ckpt``: the first checkpoint written
  at ``neval >= step``; for ``straggle``: the first slowed fetch — the
  slowdown then persists for the rest of the run).  Omitted = the first
  opportunity;
- ``:pP`` — restrict to process index ``P`` (multihost); omitted = the
  fault fires on every process (SPMD-consistent, which is what a
  slice-wide event like preemption looks like);
- ``:ms`` — ``straggle`` only (and required for it): the per-batch
  delay in milliseconds.  Unlike every other kind, ``straggle`` is not
  exactly-once — a slow host stays slow, so every data fetch from
  ``@step`` on is delayed; only the ``fault/injected`` announcement
  fires once.

| kind          | injection point                  | exercises            |
|---------------|----------------------------------|----------------------|
| ``crash``     | Optimizer iteration loop         | retry + restore      |
| ``wedge``     | inside the guarded iteration     | straggler watchdog   |
| ``kill_worker``| Optimizer loop (SIGKILL self)   | cluster restart/resume|
| ``preempt``   | Optimizer loop (SIGTERM self)    | graceful preemption  |
| ``nan_grads`` | TrainStep gradient path (in-graph)| health halt/skip    |
| ``data_err``  | dataset fetch (prefetch relay)   | retry on data errors |
| ``torn_ckpt`` | checkpoint write (post-commit)   | digest verify + quarantine |
| ``peer_kill`` | Optimizer loop (SIGKILL self)    | collective watchdog + supervised restart |
| ``peer_wedge``| inside the iteration (no straggler rescue needed) | peer-heartbeat deadline |
| ``commit_crash``| cluster commit barrier (post-write, pre-ack) | manifest-capped restore (no mixed steps) |
| ``straggle``  | dataset fetch (persistent delay) | fleet blame + bounded-staleness shed (parallel/local_sync.py) |

Permanent capacity loss is modeled by KEEPING the plan across supervised
restarts (``supervise --keep-faults``): a ``peer_kill@step:pP`` then
fires in every incarnation — the host "never comes back" — which is the
signature the capacity-aware supervisor (``supervise --min-n``,
``parallel/cluster.py``) degrades the cluster width on.  A ``:pP``
selector for a process index outside the degraded width simply never
matches again — an absent host cannot fault.

Determinism: the spec is positional (step numbers, not probabilities)
and the only random choices (which bytes ``torn_ckpt`` flips) come from
a Philox generator seeded by ``BIGDL_FAULTS_SEED`` — the same plan +
seed reproduces the same failure byte-for-byte.
"""

from __future__ import annotations

import logging
import os
import re
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, List, Optional

import numpy as np

__all__ = ["KINDS", "FaultSpec", "FaultPlan", "InjectedFault",
           "get_plan", "reset"]

log = logging.getLogger("bigdl_tpu.faults")

#: every fault class the plan understands (docs/fault_tolerance.md);
#: the ``peer_*``/``commit_crash`` kinds are the DISTRIBUTED matrix —
#: aimed at the cluster watchdog + commit barrier (parallel/cluster.py)
KINDS = ("crash", "wedge", "kill_worker", "preempt", "nan_grads",
         "data_err", "torn_ckpt", "peer_kill", "peer_wedge",
         "commit_crash", "straggle")

#: kinds polled by the Optimizer iteration loop
_ITERATION_KINDS = ("crash", "wedge", "kill_worker", "preempt",
                    "peer_kill", "peer_wedge")

#: how long a wedged iteration sleeps — far past any sane straggler
#: budget; only the watchdog (or the harness timeout) ends it
WEDGE_SLEEP_S = 3600.0

_SPEC_RE = re.compile(r"^(?P<kind>[a-z_]+)(?:@(?P<step>\d+))?"
                      r"(?::p(?P<proc>\d+))?(?::(?P<ms>\d+))?$")


class InjectedFault(RuntimeError):
    """A crash/data fault planted by the FaultPlan — indistinguishable
    from a real failure to the retry loop (that is the point), but
    greppable in logs and flight dumps."""


@dataclass
class FaultSpec:
    kind: str
    step: Optional[int] = None     # None = first opportunity
    process: Optional[int] = None  # None = every process
    ms: Optional[int] = None       # straggle only: per-fetch delay
    fired: bool = False
    spec: str = ""                 # original text, for logs

    def matches(self, step: int, process_index: int) -> bool:
        if self.fired:
            return False
        if self.process is not None and self.process != process_index:
            return False
        if self.step is None:
            return True
        if self.kind in ("torn_ckpt", "commit_crash"):
            # checkpoints land on trigger steps only; fire on the first
            # write/commit at-or-after the requested step
            return step >= self.step
        return step == self.step


class FaultPlan:
    """The parsed plan plus the exactly-once firing bookkeeping.

    Thread-safe: the data fault fires on the prefetch thread and the
    checkpoint fault can fire on the async-checkpoint writer thread.
    """

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self.specs = specs
        self.seed = int(seed)
        self._rng = np.random.Generator(
            np.random.Philox(key=np.uint64(self.seed & (2 ** 64 - 1))))
        self._lock = threading.Lock()
        self._data_fetches = 0

    # -- construction --------------------------------------------------------
    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        specs: List[FaultSpec] = []
        for raw in (text or "").split(","):
            raw = raw.strip()
            if not raw:
                continue
            m = _SPEC_RE.match(raw)
            if m is None or m.group("kind") not in KINDS:
                raise ValueError(
                    f"bad fault spec {raw!r} (want kind[@step][:pP][:ms] "
                    f"with kind in {KINDS})")
            kind = m.group("kind")
            ms = int(m.group("ms")) if m.group("ms") else None
            if kind == "straggle" and ms is None:
                raise ValueError(
                    f"bad fault spec {raw!r}: straggle needs a delay — "
                    f"straggle[@step][:pP]:ms (e.g. straggle@4:p1:250)")
            if kind != "straggle" and ms is not None:
                raise ValueError(
                    f"bad fault spec {raw!r}: only straggle takes a "
                    f":ms delay")
            specs.append(FaultSpec(
                kind=kind,
                step=int(m.group("step")) if m.group("step") else None,
                process=int(m.group("proc")) if m.group("proc") else None,
                ms=ms,
                spec=raw))
        return cls(specs, seed=seed)

    def has(self, kind: str) -> bool:
        return any(s.kind == kind for s in self.specs)

    def __bool__(self) -> bool:
        return bool(self.specs)

    # -- firing --------------------------------------------------------------
    def _process_index(self) -> int:
        try:
            from bigdl_tpu.utils.engine import Engine

            return Engine.process_index()
        except Exception:  # noqa: BLE001 - engine not initialized
            return 0

    def _claim(self, kinds, step: int) -> Optional[FaultSpec]:
        """Atomically claim the first unfired matching spec."""
        pidx = self._process_index()
        with self._lock:
            for s in self.specs:
                if s.kind in kinds and s.matches(step, pidx):
                    s.fired = True
                    return s
        return None

    def _announce(self, spec: FaultSpec, step: int, point: str) -> None:
        from bigdl_tpu import telemetry

        log.warning(f"[Faults] injecting {spec.spec or spec.kind} "
                    f"at step {step} ({point})")
        telemetry.instant("fault/injected", fault=spec.kind, step=step,
                          point=point, spec=spec.spec)

    def poll_iteration(self, step: int) -> Optional[str]:
        """Called by the Optimizer at the top of iteration ``step``.
        ``crash`` raises, ``kill_worker``/``preempt`` signal this
        process; ``wedge`` is returned to the caller, which must stall
        INSIDE the straggler-guarded region (the watchdog is the
        mechanism under test)."""
        spec = self._claim(_ITERATION_KINDS, step)
        if spec is None:
            return None
        self._announce(spec, step, "iteration")
        if spec.kind == "crash":
            raise InjectedFault(f"injected crash at step {step}")
        if spec.kind in ("kill_worker", "peer_kill"):
            # the ungraceful death: no handler runs, no checkpoint
            # commits — recovery is the NEXT process's resume path
            # (peer_kill: the same SIGKILL aimed at the CLUSTER matrix —
            # the surviving hosts' collective watchdog is what's under
            # test, parallel/cluster.py)
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(60)  # SIGKILL delivery is asynchronous
        if spec.kind == "preempt":
            # deliver a REAL signal so the grace-window handler path is
            # exercised, not simulated
            os.kill(os.getpid(), signal.SIGTERM)
            return None
        # wedge: stall under the HOST straggler guard; peer_wedge: the
        # same stall, but the mechanism under test is the CLUSTER
        # watchdog — with no BIGDL_ITERATION_TIMEOUT set, only the
        # peer-heartbeat deadline (or the harness timeout) ends it
        return "wedge"

    def wedge_stall(self) -> None:
        """The stall body for a claimed ``wedge`` — runs inside the
        straggler-guarded iteration thread."""
        time.sleep(WEDGE_SLEEP_S)

    def grad_scale(self, step: int) -> float:
        """Multiplier folded into the gradients of iteration ``step`` by
        the compiled train step: 1.0 normally, NaN when a ``nan_grads``
        fault fires — the poison enters through the GRAD path, so the
        in-graph health probe sees nonfinite grads exactly as a real
        divergence would produce them."""
        spec = self._claim(("nan_grads",), step)
        if spec is None:
            return 1.0
        self._announce(spec, step, "grads")
        return float("nan")

    def straggle_sleep(self, fetch: int) -> float:
        """Seconds the ``fetch``-th batch fetch (1-based) must stall on
        this process, per the plan's ``straggle`` specs.  NOT
        exactly-once: a slow host stays slow, so every fetch at-or-after
        the spec's step is delayed (max over matching specs); ``fired``
        gates only the one-time ``fault/injected`` announcement."""
        pidx = self._process_index()
        delay = 0.0
        announce: List[FaultSpec] = []
        with self._lock:
            for s in self.specs:
                if s.kind != "straggle":
                    continue
                if s.process is not None and s.process != pidx:
                    continue
                if s.step is not None and fetch < s.step:
                    continue
                delay = max(delay, (s.ms or 0) / 1000.0)
                if not s.fired:
                    s.fired = True
                    announce.append(s)
        for s in announce:
            self._announce(s, fetch, "data")
        return delay

    def wrap_data_iter(self, it: Iterator) -> Iterator:
        """Wrap the dataset batch iterator: the Nth fetch (1-based,
        process-wide across run attempts) raises :class:`InjectedFault`
        on whatever thread performs it — under prefetch, the producer
        thread, exercising the error relay into the retry loop.  A
        ``straggle`` spec instead SLEEPS on that thread from its step
        on, so the delay lands inside the ``data_wait`` span the fleet
        blame attributes (telemetry/fleet.py)."""
        if not (self.has("data_err") or self.has("straggle")):
            return it

        def gen():
            for batch in it:
                with self._lock:
                    self._data_fetches += 1
                    n = self._data_fetches
                spec = self._claim(("data_err",), n)
                if spec is not None:
                    self._announce(spec, n, "data")
                    raise InjectedFault(f"injected data error at fetch {n}")
                delay = self.straggle_sleep(n)
                if delay > 0:
                    time.sleep(delay)
                yield batch

        return gen()

    def poll_commit(self, step: int) -> None:
        """Called by the cluster commit barrier AFTER this host's local
        checkpoint write is durable and BEFORE its barrier ack lands
        (``parallel/cluster.py``): a ``commit_crash`` fault SIGKILLs
        this process in exactly that window — the checkpoint exists
        locally, the cluster never certified it, and the manifest (not
        the newest file on disk) must decide what restores."""
        spec = self._claim(("commit_crash",), step)
        if spec is None:
            return
        self._announce(spec, step, "commit")
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(60)  # SIGKILL delivery is asynchronous

    def poll_checkpoint(self, path: str, step: int) -> None:
        """Called after a checkpoint write COMMITS (meta marker on
        disk): a ``torn_ckpt`` fault then corrupts one payload file
        under ``path`` while the complete-marker stays valid — the exact
        tear the marker cannot catch and the content digests must."""
        spec = self._claim(("torn_ckpt",), step)
        if spec is None:
            return
        torn = self._corrupt_one_file(path)
        self._announce(spec, step, f"checkpoint:{torn or 'none'}")

    def _corrupt_one_file(self, path: str) -> Optional[str]:
        """Flip bytes in the middle of the largest payload file under
        ``path`` (meta markers excluded — the tear must be silent).
        Returns the corrupted file's path."""
        candidates = []
        if os.path.isfile(path):
            candidates = [path]
        else:
            for root, _dirs, files in os.walk(path):
                for f in files:
                    if f.endswith(".json"):  # meta/commit markers stay valid
                        continue
                    p = os.path.join(root, f)
                    candidates.append(p)
        candidates = [p for p in candidates if os.path.getsize(p) > 0]
        if not candidates:
            return None
        # largest file = a real shard payload, deterministically chosen
        target = max(candidates, key=lambda p: (os.path.getsize(p), p))
        size = os.path.getsize(target)
        span = max(1, min(64, size // 2))
        offset = int(self._rng.integers(0, max(1, size - span)))
        junk = self._rng.integers(0, 256, size=span, dtype=np.uint8)
        with open(target, "r+b") as fh:
            fh.seek(offset)
            original = fh.read(span)
            flipped = bytes(b ^ 0xA5 for b in original) or bytes(junk)
            fh.seek(offset)
            fh.write(flipped)
        log.warning(f"[Faults] tore {target} ({span} bytes at {offset})")
        return target


# -- process-wide plan -------------------------------------------------------
_plan: Optional[FaultPlan] = None
_plan_lock = threading.Lock()


def get_plan() -> FaultPlan:
    """The process-wide plan, parsed once from ``BIGDL_FAULTS`` /
    ``BIGDL_FAULTS_SEED`` (empty plan when unset).  Cached so the
    exactly-once bookkeeping survives config re-resolution; tests use
    :func:`reset` between scenarios."""
    global _plan
    with _plan_lock:
        if _plan is None:
            from bigdl_tpu.utils.config import get_config

            cfg = get_config()
            _plan = FaultPlan.parse(cfg.faults, seed=cfg.faults_seed)
        return _plan


def reset() -> None:
    """Drop the cached plan (tests; a fresh plan re-reads the env)."""
    global _plan
    with _plan_lock:
        _plan = None
