"""bigdl_tpu.optim — optimization layer (SURVEY §2.8)."""

from bigdl_tpu.optim.optim_method import *  # noqa: F401,F403
from bigdl_tpu.optim.trigger import Trigger  # noqa: F401
from bigdl_tpu.optim.validation import *  # noqa: F401,F403
from bigdl_tpu.optim.regularizer import *  # noqa: F401,F403
from bigdl_tpu.optim.metrics import Metrics  # noqa: F401
from bigdl_tpu.optim.optimizer import (Optimizer, LocalOptimizer,  # noqa: F401
                                       DistriOptimizer, HealthError,
                                       HealthPolicy)
from bigdl_tpu.optim.evaluator import Evaluator  # noqa: F401
from bigdl_tpu.optim.predictor import LocalPredictor, Predictor  # noqa: F401
