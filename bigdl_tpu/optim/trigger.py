"""Triggers — composable fire/stop predicates over driver state
(``optim/Trigger.scala:26-127``: everyEpoch, severalIteration, maxEpoch,
maxIteration, maxScore, minLoss; plus and/or combinators)."""

from __future__ import annotations

from typing import Callable, Dict

__all__ = ["Trigger"]


class Trigger:
    def __init__(self, fn: Callable[[Dict], bool]):
        self._fn = fn

    def __call__(self, state: Dict) -> bool:
        return self._fn(state)

    # -- factories ---------------------------------------------------------
    @staticmethod
    def every_epoch() -> "Trigger":
        """Fires when the training loop crosses an epoch boundary."""
        holder = {"last": -1}

        def fn(state):
            ep = state.get("epoch", 1)
            if state.get("_epoch_boundary", False) and ep != holder["last"]:
                holder["last"] = ep
                return True
            return False

        return Trigger(fn)

    @staticmethod
    def several_iteration(interval: int) -> "Trigger":
        return Trigger(lambda s: s.get("neval", 0) % interval == 0 and s.get("neval", 0) > 0)

    @staticmethod
    def max_epoch(max_: int) -> "Trigger":
        return Trigger(lambda s: s.get("epoch", 1) > max_)

    @staticmethod
    def max_iteration(max_: int) -> "Trigger":
        return Trigger(lambda s: s.get("neval", 0) >= max_)

    @staticmethod
    def max_score(max_: float) -> "Trigger":
        return Trigger(lambda s: s.get("score", float("-inf")) > max_)

    @staticmethod
    def min_loss(min_: float) -> "Trigger":
        return Trigger(lambda s: s.get("loss", float("inf")) < min_)

    @staticmethod
    def and_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: all(t(s) for t in triggers))

    @staticmethod
    def or_(*triggers: "Trigger") -> "Trigger":
        return Trigger(lambda s: any(t(s) for t in triggers))
