"""Evaluator (``optim/Evaluator.scala:37`` + Local/DistriValidator):
run validation methods over a dataset with a compiled forward."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from bigdl_tpu.dataset.dataset import AbstractDataSet, DataSet
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.optim.validation import ValidationMethod, ValidationResult
from bigdl_tpu.parallel.train_step import EvalStep

__all__ = ["Evaluator"]


class Evaluator:
    def __init__(self, model, batch_size: int = 32, mesh=None):
        self.model = model
        self.batch_size = batch_size
        self.mesh = mesh

    def evaluate(self, dataset, methods: Sequence[ValidationMethod]
                 ) -> List[Tuple[ValidationResult, ValidationMethod]]:
        if isinstance(dataset, (list, tuple)):
            dataset = DataSet.array(list(dataset)).transform(
                SampleToMiniBatch(self.batch_size))
        step = EvalStep(self.model, mesh=self.mesh)
        was_training = self.model.is_training()
        self.model.evaluate()
        try:
            results: Optional[List[ValidationResult]] = None
            for batch in dataset.data(train=False):
                out = step.run(batch.get_input())
                rs = [m(out, batch.get_target()) for m in methods]
                results = rs if results is None else [a + b for a, b in zip(results, rs)]
        finally:
            if was_training:
                self.model.train()
        return list(zip(results or [], methods))
