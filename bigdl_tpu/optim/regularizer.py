"""Regularizers (``optim/Regularizer.scala:30-178``: L1L2Regularizer,
L1Regularizer, L2Regularizer).

The reference applies regularization inside each layer's
``accGradParameters``; here the training step applies it when assembling
gradients — per-parameter, honoring each layer's ``w_regularizer`` /
``b_regularizer`` configuration."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["Regularizer", "L1L2Regularizer", "L1Regularizer", "L2Regularizer"]


class Regularizer:
    def __init__(self):
        self.is_enabled = True

    def enable(self):
        self.is_enabled = True
        return self

    def disable(self):
        self.is_enabled = False
        return self

    def grad(self, param):
        """Gradient contribution d(penalty)/d(param)."""
        raise NotImplementedError

    def loss(self, param):
        raise NotImplementedError


class L1L2Regularizer(Regularizer):
    def __init__(self, l1: float = 0.0, l2: float = 0.0):
        super().__init__()
        self.l1, self.l2 = l1, l2

    def grad(self, param):
        g = 0.0
        if self.l1 != 0:
            g = g + self.l1 * jnp.sign(param)
        if self.l2 != 0:
            g = g + self.l2 * param
        return g

    def loss(self, param):
        total = 0.0
        if self.l1 != 0:
            total = total + self.l1 * jnp.sum(jnp.abs(param))
        if self.l2 != 0:
            total = total + 0.5 * self.l2 * jnp.sum(param * param)
        return total


class L1Regularizer(L1L2Regularizer):
    def __init__(self, l1: float):
        super().__init__(l1=l1, l2=0.0)


class L2Regularizer(L1L2Regularizer):
    def __init__(self, l2: float):
        super().__init__(l1=0.0, l2=l2)
