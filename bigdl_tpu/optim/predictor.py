"""Predictor (``optim/Predictor.scala:35``, ``optim/LocalPredictor.scala:37``):
batched inference over datasets/arrays with a compiled forward.

Since the serving PR, the compiled step comes from the **bucketed
executor** (``bigdl_tpu/serving/executor.py``): one process-wide
compile cache per (model, mesh), shared with the online serving layer.
This fixes the old behavior of building a fresh ``EvalStep`` — and
paying a full XLA compile — on every ``predict()`` call: repeated
predicts, and a Predictor running next to a ``ModelServer`` over the
same model, all hit the same warm per-shape executables; ragged final
batches pad onto a batch bucket instead of compiling their own shape.

Multi-input (pytree) models fall back to a per-Predictor cached
``EvalStep`` — still one compile per shape, never one per call.
"""

from __future__ import annotations

from typing import List

import numpy as np

from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import SampleToMiniBatch

__all__ = ["LocalPredictor", "Predictor"]


class LocalPredictor:
    def __init__(self, model, batch_size: int = 32, mesh=None):
        self.model = model
        self.batch_size = batch_size
        self.mesh = mesh
        self._eval_step = None  # pytree-input fallback, cached

    def _batches(self, data):
        from bigdl_tpu.dataset.dataset import AbstractDataSet

        if isinstance(data, (list, tuple)) and data and isinstance(data[0], Sample):
            ds = DataSet.array(list(data)).transform(SampleToMiniBatch(self.batch_size))
            yield from ds.data(train=False)
        elif isinstance(data, AbstractDataSet):
            yield from data.data(train=False)
        else:  # raw array: batch it
            arr = np.asarray(data)
            for i in range(0, len(arr), self.batch_size):
                from bigdl_tpu.dataset.minibatch import MiniBatch

                yield MiniBatch([arr[i:i + self.batch_size]])

    def _executor(self):
        from bigdl_tpu.serving.executor import executor_for

        return executor_for(self.model, mesh=self.mesh,
                            max_batch=self.batch_size)

    def _fallback_step(self):
        """Pytree inputs (multi-input graphs) don't bucket; keep ONE
        EvalStep per predictor so repeated predicts reuse its jit."""
        if self._eval_step is None:
            from bigdl_tpu.parallel.train_step import EvalStep

            self._eval_step = EvalStep(self.model, mesh=self.mesh)
        return self._eval_step

    def predict(self, data) -> np.ndarray:
        executor = self._executor()
        # the model may have trained since the last predict: re-read
        # params/buffers (identity-checked — unchanged state is free,
        # and same-shape updates keep every compiled executable)
        executor.refresh_state()
        was_training = self.model.is_training()
        self.model.evaluate()
        try:
            outs: List[np.ndarray] = []
            for batch in self._batches(data):
                x = batch.get_input()
                if isinstance(x, (list, tuple)):
                    outs.append(np.asarray(self._fallback_step().run(x)))
                else:
                    outs.append(np.asarray(executor.run(x)))
        finally:
            if was_training:
                self.model.train()
        return np.concatenate(outs) if outs else np.zeros((0,))

    def predict_class(self, data, one_based: bool = False) -> np.ndarray:
        out = self.predict(data)
        pred = out.argmax(axis=-1)
        return pred + 1 if one_based else pred


Predictor = LocalPredictor
