"""Predictor (``optim/Predictor.scala:35``, ``optim/LocalPredictor.scala:37``):
batched inference over datasets/arrays with a compiled forward."""

from __future__ import annotations

from typing import Iterable, List

import numpy as np

from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.parallel.train_step import EvalStep

__all__ = ["LocalPredictor", "Predictor"]


class LocalPredictor:
    def __init__(self, model, batch_size: int = 32, mesh=None):
        self.model = model
        self.batch_size = batch_size
        self.mesh = mesh

    def _batches(self, data):
        from bigdl_tpu.dataset.dataset import AbstractDataSet

        if isinstance(data, (list, tuple)) and data and isinstance(data[0], Sample):
            ds = DataSet.array(list(data)).transform(SampleToMiniBatch(self.batch_size))
            yield from ds.data(train=False)
        elif isinstance(data, AbstractDataSet):
            yield from data.data(train=False)
        else:  # raw array: batch it
            arr = np.asarray(data)
            for i in range(0, len(arr), self.batch_size):
                from bigdl_tpu.dataset.minibatch import MiniBatch

                yield MiniBatch([arr[i:i + self.batch_size]])

    def predict(self, data) -> np.ndarray:
        step = EvalStep(self.model, mesh=self.mesh)
        was_training = self.model.is_training()
        self.model.evaluate()
        try:
            outs: List[np.ndarray] = []
            for batch in self._batches(data):
                outs.append(np.asarray(step.run(batch.get_input())))
        finally:
            if was_training:
                self.model.train()
        return np.concatenate(outs) if outs else np.zeros((0,))

    def predict_class(self, data, one_based: bool = False) -> np.ndarray:
        out = self.predict(data)
        pred = out.argmax(axis=-1)
        return pred + 1 if one_based else pred


Predictor = LocalPredictor
