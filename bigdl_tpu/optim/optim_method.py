"""Optimization methods (SURVEY §2.8: SGD + LR-schedule family, Adam,
Adamax, Adagrad, Adadelta, RMSprop, LBFGS; base ``optim/OptimMethod.scala``).

Each method has a **pure functional core** — ``init_state(params)`` and
``update(grads, params, state) -> (new_params, new_state)`` over pytrees —
which the training step jits/pjits (state shards with the parameters for
the ZeRO-1 layout).  The reference's imperative
``optimize(feval, parameter)`` API is kept as a thin host-side shell for
parity (used by LBFGS-style workflows and tests).

Hyper-state the reference keeps in the mutable ``state`` Table
(evalCounter, epoch, ...) lives in the state pytree as scalars so schedules
compile into the step.
"""

from __future__ import annotations

import copy
import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "OptimMethod", "SGD", "Adam", "Adamax", "Adagrad", "Adadelta", "RMSprop",
    "LBFGS", "Default", "Poly", "Step", "MultiStep", "EpochDecay", "EpochStep",
    "NaturalExp", "Exponential", "Plateau", "Warmup", "SequentialSchedule",
    "EpochSchedule", "Regime",
]

Pytree = Any


def _tree_map(f, *trees):
    return jax.tree.map(f, *trees)


class OptimMethod:
    """Base (``optim/OptimMethod.scala:38``)."""

    def __init__(self):
        self.state: Dict[str, Any] = {}

    # -- functional core ---------------------------------------------------
    def init_state(self, params: Pytree) -> Pytree:
        return {"neval": jnp.zeros((), jnp.int32), "epoch": jnp.ones((), jnp.int32)}

    def update(self, grads: Pytree, params: Pytree, state: Pytree) -> Tuple[Pytree, Pytree]:
        raise NotImplementedError

    # -- sparse (row-sparse embedding-gradient) leg ------------------------
    # docs/sparse.md: a sparse-marked table's gradient arrives as
    # unique-coalesced ``(indices [C], rows [C, dim])`` pairs instead of
    # the dense ``[vocab, dim]`` scatter.  ``update_mixed`` merges them
    # into one update: methods with an exact lazy row-wise apply
    # (_apply_sparse) touch only the synced rows of the table and its
    # moments; everything else scatter-adds the rows into a dense
    # gradient LOCALLY (zero collectives — the sync already happened on
    # the rows) and defers to the method's own update().  Both legs are
    # numerics-exact vs the dense path.
    def _apply_sparse(self, idx, rows, param, state: Pytree, path: str,
                      scatter=None):
        """Exact lazy row-wise update of one table; returns
        ``(new_param, {state_key: new_moment_array})`` or None when this
        method has no exact lazy form (the caller densifies locally).
        ``state`` is the PRE-update state (counters not yet advanced);
        ``idx`` is unique-coalesced with out-of-range fill slots whose
        ``rows`` are zero — every scatter uses ``mode='drop'``.
        ``scatter`` (mesh runs) is the caller's partitioning-pinned row
        scatter (``TrainStep._row_scatter``): GSPMD left alone re-tiles
        the coalesced updates along the slots axis and lowers the row
        scatter as partial-scatter + a dense ``[vocab, dim]``
        all-reduce — exactly the collective this path exists to avoid."""
        return None

    @staticmethod
    def _scatter(scatter, target, idx, updates, op: str, kind: str,
                 path: str):
        """Row scatter through the caller's pinned implementation when
        given (``kind`` = 'param' | 'moment' names whose layout rules
        the target follows), else the plain XLA one."""
        if scatter is not None:
            return scatter(target, idx, updates, op, kind, path)
        if op == "set":
            return target.at[idx].set(updates, mode="drop")
        return target.at[idx].add(updates, mode="drop")

    @staticmethod
    def densify_rows(idx, rows, param):
        """The exact local fallback: scatter the coalesced rows into a
        zero table.  A gather's dense cotangent built once, locally —
        no collective rides it."""
        return jnp.zeros_like(param).at[idx].add(
            rows.astype(param.dtype), mode="drop")

    @staticmethod
    def _state_view(state: Pytree, keys) -> Pytree:
        """State with per-param moment dicts filtered to ``keys``
        (scalars pass through untouched)."""
        keys = set(keys)
        return {k: ({p: a for p, a in v.items() if p in keys}
                    if isinstance(v, dict) else v)
                for k, v in state.items()}

    def update_mixed(self, grads: Pytree, sparse, params: Pytree,
                     state: Pytree, scatter=None) -> Tuple[Pytree, Pytree]:
        """One optimizer step over dense grads (``grads``: path -> array,
        sparse paths absent) plus row-sparse grads (``sparse``: path ->
        ``(indices, rows)``).  Counters (neval/epoch) advance exactly
        once.  ``scatter`` see :meth:`_apply_sparse`."""
        if not sparse:
            return self.update(grads, params, state)
        lazy: Dict[str, Tuple[Any, Dict[str, Any]]] = {}
        densified: Dict[str, Any] = {}
        for path, (idx, rows) in sparse.items():
            res = self._apply_sparse(idx, rows, params[path], state, path,
                                     scatter=scatter)
            if res is None:
                densified[path] = self._scatter(
                    scatter, jnp.zeros_like(params[path]), idx,
                    rows.astype(params[path].dtype), "add", "param", path)
            else:
                lazy[path] = res
        dense_grads = {**grads, **densified}
        dparams = {k: params[k] for k in dense_grads}
        new_dp, new_state = self.update(dense_grads, dparams,
                                        self._state_view(state, dense_grads))
        new_params = dict(new_dp)
        for path, (new_p, moments) in lazy.items():
            new_params[path] = new_p
            for skey, arr in moments.items():
                merged = dict(new_state.get(skey) or {})
                merged[path] = arr
                new_state[skey] = merged
        return new_params, new_state

    # -- imperative parity shell ------------------------------------------
    def optimize(self, feval: Callable, parameter):
        """feval(x) -> (loss, grad); updates ``parameter`` in the reference
        API style and returns (new_parameter, [loss])."""
        if "func_state" not in self.state:
            self.state["func_state"] = self.init_state(parameter)
        loss, grad = feval(parameter)
        new_p, self.state["func_state"] = self.update(grad, parameter, self.state["func_state"])
        return new_p, [loss]

    def get_learning_rate(self) -> float:
        return float(getattr(self, "learning_rate", 0.0))

    def clear_history(self):
        self.state = {}

    def get_hyper_parameter(self) -> str:
        return f"Current learning rate is {self.get_learning_rate()}."

    def clone(self) -> "OptimMethod":
        return copy.deepcopy(self)

    def save(self, path: str, overwrite: bool = False):
        from bigdl_tpu.utils.serializer import save_optim_method

        save_optim_method(self, path, overwrite)
        return self

    @staticmethod
    def load(path: str) -> "OptimMethod":
        from bigdl_tpu.utils.serializer import load_optim_method

        return load_optim_method(path)


# --------------------------------------------------------------------------
# Learning-rate schedules (optim/SGD.scala:198-534)
# --------------------------------------------------------------------------

class LearningRateSchedule:
    """Maps (base_lr, state) -> lr.  Pure; compiles into the train step."""

    def rate(self, base_lr, state) -> jnp.ndarray:
        raise NotImplementedError


class Default(LearningRateSchedule):
    """Torch default: lr / (1 + neval * lrd) (``SGD.scala`` Default)."""

    def __init__(self, learning_rate_decay: float = 0.0):
        self.learning_rate_decay = learning_rate_decay

    def rate(self, base_lr, state):
        return base_lr / (1.0 + state["neval"].astype(jnp.float32) * self.learning_rate_decay)


class Poly(LearningRateSchedule):
    """lr * (1 - iter/max_iter)^power; 0 beyond max_iteration."""

    def __init__(self, power: float, max_iteration: int):
        self.power, self.max_iteration = power, max_iteration

    def rate(self, base_lr, state):
        it = state["neval"].astype(jnp.float32)
        frac = jnp.clip(1.0 - it / self.max_iteration, 0.0, 1.0)
        return base_lr * jnp.power(frac, self.power)


class Step(LearningRateSchedule):
    def __init__(self, step_size: int, gamma: float):
        self.step_size, self.gamma = step_size, gamma

    def rate(self, base_lr, state):
        k = jnp.floor_divide(state["neval"], self.step_size).astype(jnp.float32)
        return base_lr * jnp.power(self.gamma, k)


class MultiStep(LearningRateSchedule):
    def __init__(self, step_sizes, gamma: float):
        self.step_sizes = tuple(step_sizes)
        self.gamma = gamma

    def rate(self, base_lr, state):
        it = state["neval"]
        k = jnp.zeros((), jnp.float32)
        for s in self.step_sizes:
            k = k + (it >= s).astype(jnp.float32)
        return base_lr * jnp.power(self.gamma, k)


class EpochDecay(LearningRateSchedule):
    """lr * 0.1^decay_fn(epoch); decay_fn is host-side (static per epoch)."""

    def __init__(self, decay_fn: Callable[[int], float]):
        self.decay_fn = decay_fn

    def rate(self, base_lr, state):
        # epoch is a traced scalar; the decay function is arbitrary Python,
        # so we evaluate it via a small pure_callback-free table is not
        # possible generally — instead treat epoch as slowly-varying and
        # compute host-side when concrete, else via lax.stop_gradient trick.
        ep = state["epoch"]
        if isinstance(ep, jax.core.Tracer):
            # fall back: schedules using arbitrary python decay recompile per
            # epoch via the static_epoch mechanism in the train step
            ep_val = int(state.get("static_epoch", 1))
        else:
            ep_val = int(ep)
        return base_lr * (0.1 ** self.decay_fn(ep_val))


class EpochStep(LearningRateSchedule):
    def __init__(self, step_size: int, gamma: float):
        self.step_size, self.gamma = step_size, gamma

    def rate(self, base_lr, state):
        k = jnp.floor_divide(state["epoch"] - 1, self.step_size).astype(jnp.float32)
        return base_lr * jnp.power(self.gamma, k)


class NaturalExp(LearningRateSchedule):
    def __init__(self, decay_step: int, gamma: float):
        self.decay_step, self.gamma = decay_step, gamma

    def rate(self, base_lr, state):
        p = jnp.floor_divide(state["neval"], self.decay_step).astype(jnp.float32)
        return base_lr * jnp.exp(-self.gamma * p)


class Exponential(LearningRateSchedule):
    def __init__(self, decay_step: int, decay_rate: float, staircase: bool = False):
        self.decay_step, self.decay_rate, self.staircase = decay_step, decay_rate, staircase

    def rate(self, base_lr, state):
        p = state["neval"].astype(jnp.float32) / self.decay_step
        if self.staircase:
            p = jnp.floor(p)
        return base_lr * jnp.power(self.decay_rate, p)


class Warmup(LearningRateSchedule):
    """Linear ramp over delta for warmup_iteration steps, then the chained
    schedule (SGD.scala Warmup/SequentialSchedule)."""

    def __init__(self, delta: float, warmup_iteration: int,
                 after: Optional[LearningRateSchedule] = None):
        self.delta, self.warmup_iteration, self.after = delta, warmup_iteration, after

    def rate(self, base_lr, state):
        it = state["neval"].astype(jnp.float32)
        warm = base_lr + self.delta * it
        after = self.after.rate(base_lr + self.delta * self.warmup_iteration, state) \
            if self.after else base_lr + self.delta * self.warmup_iteration
        return jnp.where(it < self.warmup_iteration, warm, after)


class SequentialSchedule(LearningRateSchedule):
    """Chain schedules, each active for a number of iterations."""

    def __init__(self):
        self.schedules = []  # (schedule, duration)

    def add(self, schedule: LearningRateSchedule, max_iteration: int):
        self.schedules.append((schedule, max_iteration))
        return self

    def rate(self, base_lr, state):
        it = state["neval"]
        offset = 0
        out = None
        for i, (sched, dur) in enumerate(self.schedules):
            shifted = dict(state)
            shifted["neval"] = jnp.maximum(it - offset, 0)
            r = sched.rate(base_lr, shifted)
            last = i == len(self.schedules) - 1
            # the last schedule also covers iterations past the total budget
            sel = (it >= offset) if last else (it >= offset) & (it < offset + dur)
            out = r if out is None else jnp.where(sel, r, out)
            offset += dur
        return out


class Regime:
    def __init__(self, start_epoch: int, end_epoch: int, config: Dict[str, Any]):
        self.start_epoch, self.end_epoch, self.config = start_epoch, end_epoch, config


class EpochSchedule(LearningRateSchedule):
    """Per-epoch-range hyper config (``SGD.scala`` EpochSchedule)."""

    def __init__(self, regimes):
        self.regimes = list(regimes)

    def rate(self, base_lr, state):
        ep = state["epoch"]
        out = jnp.asarray(base_lr, jnp.float32)
        for r in self.regimes:
            lr = jnp.asarray(r.config.get("learning_rate", base_lr), jnp.float32)
            sel = (ep >= r.start_epoch) & (ep <= r.end_epoch)
            out = jnp.where(sel, lr, out)
        return out


class Plateau(LearningRateSchedule):
    """Reduce-on-plateau; driven host-side from validation scores
    (``SGD.scala`` Plateau).  The factor lives in state['plateau_factor']."""

    def __init__(self, monitor: str = "score", factor: float = 0.1, patience: int = 10,
                 mode: str = "min", epsilon: float = 1e-4, cooldown: int = 0,
                 min_lr: float = 0.0):
        self.monitor, self.factor, self.patience = monitor, factor, patience
        self.mode, self.epsilon, self.cooldown, self.min_lr = mode, epsilon, cooldown, min_lr
        self._best = None
        self._wait = 0
        self._cool = 0
        self.current_factor = 1.0

    def on_metric(self, value: float):
        """Host-side hook called by the Optimizer after validation."""
        better = (self._best is None
                  or (self.mode == "min" and value < self._best - self.epsilon)
                  or (self.mode == "max" and value > self._best + self.epsilon))
        if better:
            self._best = value
            self._wait = 0
        elif self._cool > 0:
            self._cool -= 1
        else:
            self._wait += 1
            if self._wait >= self.patience:
                self.current_factor *= self.factor
                self._wait = 0
                self._cool = self.cooldown

    def rate(self, base_lr, state):
        return jnp.maximum(base_lr * state.get("plateau_factor", self.current_factor), self.min_lr)


# --------------------------------------------------------------------------
# Methods
# --------------------------------------------------------------------------

class SGD(OptimMethod):
    """SGD with momentum/nesterov/dampening/weightDecay and the schedule
    family (``optim/SGD.scala``)."""

    def __init__(self, learning_rate: float = 1e-3, learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0, momentum: float = 0.0, dampening: Optional[float] = None,
                 nesterov: bool = False, learning_rate_schedule: Optional[LearningRateSchedule] = None):
        super().__init__()
        self.learning_rate = learning_rate
        self.weight_decay = weight_decay
        self.momentum = momentum
        self.dampening = dampening if dampening is not None else (0.0 if nesterov else 0.0)
        self.nesterov = nesterov
        if nesterov and (self.momentum <= 0 or self.dampening != 0):
            raise ValueError("Nesterov momentum requires momentum > 0 and dampening = 0")
        self.schedule = learning_rate_schedule or Default(learning_rate_decay)

    def init_state(self, params):
        st = super().init_state(params)
        if self.momentum > 0:
            st["velocity"] = _tree_map(jnp.zeros_like, params)
        return st

    def update(self, grads, params, state):
        lr = self.schedule.rate(self.learning_rate, state)
        wd = self.weight_decay
        if wd != 0:
            grads = _tree_map(lambda g, p: g + wd * p, grads, params)
        new_state = dict(state)
        if self.momentum > 0:
            # first step COPIES the raw gradient into the buffer —
            # dampening applies only from step 2 (``SGD.scala:95``:
            # ``copy(dfdx)`` on the None branch; torch matches).  With
            # dampening 0 the formulas coincide, so this only matters
            # for damp > 0 — which the hyperparameter fuzz caught.
            first = state["neval"] == 0
            vel = _tree_map(
                lambda v, g: jnp.where(
                    first, g,
                    self.momentum * v + (1.0 - self.dampening) * g),
                state["velocity"], grads)
            new_state["velocity"] = vel
            if self.nesterov:
                step = _tree_map(lambda g, v: g + self.momentum * v, grads, vel)
            else:
                step = vel
        else:
            step = grads
        new_p = _tree_map(lambda p, s: p - lr * s, params, step)
        new_state["neval"] = state["neval"] + 1
        return new_p, new_state

    def _apply_sparse(self, idx, rows, param, state, path, scatter=None):
        """Exact lazy SGD for a row-sparse table gradient.

        momentum = 0: pure row-wise ``p[u] -= lr * g`` — untouched rows
        are bit-identical to the dense path's ``p - lr * 0``.
        momentum > 0: the velocity decay ``mu * v`` is a LOCAL dense
        elementwise pass (every row's velocity decays, exactly as the
        dense path does — memory traffic, zero collectives) and the
        gradient lands row-wise on top, so multi-step numerics match the
        dense path exactly, including the first-step copy-the-raw-
        gradient semantic.  Weight decay densifies the gradient
        semantically (every row moves), so it falls back to the local
        densify path (return None)."""
        if self.weight_decay != 0:
            return None
        lr = self.schedule.rate(self.learning_rate, state)
        rows = rows.astype(param.dtype)
        moments = {}
        if self.momentum > 0:
            vel = state["velocity"][path]
            first = state["neval"] == 0
            decay = jnp.where(first, 0.0, self.momentum).astype(vel.dtype)
            damp = jnp.where(first, 0.0, self.dampening)
            vel = decay * vel
            vel = self._scatter(scatter, vel, idx,
                                (1.0 - damp).astype(vel.dtype) * rows,
                                "add", "moment", path)
            moments["velocity"] = vel
            if self.nesterov:
                step = self.momentum * vel
                step = self._scatter(scatter, step, idx, rows, "add",
                                     "moment", path)
            else:
                step = vel
            new_p = param - lr * step
        else:
            new_p = self._scatter(scatter, param, idx, -(lr * rows),
                                  "add", "param", path)
        return new_p, moments


class Adam(OptimMethod):
    """(``optim/Adam.scala``)."""

    def __init__(self, learning_rate: float = 1e-3, learning_rate_decay: float = 0.0,
                 beta1: float = 0.9, beta2: float = 0.999, epsilon: float = 1e-8):
        super().__init__()
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, params):
        st = super().init_state(params)
        st["m"] = _tree_map(jnp.zeros_like, params)
        st["v"] = _tree_map(jnp.zeros_like, params)
        return st

    def update(self, grads, params, state):
        t = state["neval"].astype(jnp.float32) + 1.0
        lr = self.learning_rate / (1.0 + state["neval"].astype(jnp.float32) * self.learning_rate_decay)
        b1, b2 = self.beta1, self.beta2
        m = _tree_map(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        v = _tree_map(lambda v, g: b2 * v + (1 - b2) * g * g, state["v"], grads)
        bc1 = 1.0 - jnp.power(b1, t)
        bc2 = 1.0 - jnp.power(b2, t)
        new_p = _tree_map(
            lambda p, m_, v_: p - lr * (m_ / bc1) / (jnp.sqrt(v_ / bc2) + self.epsilon),
            params, m, v)
        return new_p, {**state, "m": m, "v": v, "neval": state["neval"] + 1}


class Adamax(OptimMethod):
    """(``optim/Adamax.scala``)."""

    def __init__(self, learning_rate: float = 2e-3, beta1: float = 0.9,
                 beta2: float = 0.999, epsilon: float = 1e-38):
        super().__init__()
        self.learning_rate = learning_rate
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def init_state(self, params):
        st = super().init_state(params)
        st["m"] = _tree_map(jnp.zeros_like, params)
        st["u"] = _tree_map(jnp.zeros_like, params)
        return st

    def update(self, grads, params, state):
        t = state["neval"].astype(jnp.float32) + 1.0
        b1 = self.beta1
        m = _tree_map(lambda m, g: b1 * m + (1 - b1) * g, state["m"], grads)
        u = _tree_map(lambda u, g: jnp.maximum(self.beta2 * u, jnp.abs(g) + self.epsilon),
                      state["u"], grads)
        lr_t = self.learning_rate / (1.0 - jnp.power(b1, t))
        new_p = _tree_map(lambda p, m_, u_: p - lr_t * m_ / u_, params, m, u)
        return new_p, {**state, "m": m, "u": u, "neval": state["neval"] + 1}


class Adagrad(OptimMethod):
    """(``optim/Adagrad.scala``)."""

    def __init__(self, learning_rate: float = 1e-3, learning_rate_decay: float = 0.0,
                 weight_decay: float = 0.0):
        super().__init__()
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.weight_decay = weight_decay

    def init_state(self, params):
        st = super().init_state(params)
        st["accum"] = _tree_map(jnp.zeros_like, params)
        return st

    def update(self, grads, params, state):
        lr = self.learning_rate / (1.0 + state["neval"].astype(jnp.float32) * self.learning_rate_decay)
        if self.weight_decay != 0:
            grads = _tree_map(lambda g, p: g + self.weight_decay * p, grads, params)
        accum = _tree_map(lambda a, g: a + g * g, state["accum"], grads)
        new_p = _tree_map(lambda p, g, a: p - lr * g / (jnp.sqrt(a) + 1e-10),
                          params, grads, accum)
        return new_p, {**state, "accum": accum, "neval": state["neval"] + 1}

    def _apply_sparse(self, idx, rows, param, state, path, scatter=None):
        """Exact lazy Adagrad: an untouched row's dense update is
        ``accum += 0`` and ``p -= lr * 0 / ...`` — the identity — so
        touching only the synced rows IS the dense semantics.  The
        coalesce matters here: duplicate indices arrive pre-summed, so
        ``accum[r] += (sum of duplicates)^2`` exactly as the dense
        scatter-then-square would compute it.  Weight decay adds
        ``wd * p`` to every row's gradient, so it densifies (locally)
        instead."""
        if self.weight_decay != 0:
            return None
        lr = self.learning_rate / (
            1.0 + state["neval"].astype(jnp.float32)
            * self.learning_rate_decay)
        rows = rows.astype(param.dtype)
        acc = state["accum"][path]
        safe = jnp.clip(idx, 0, param.shape[0] - 1)
        a_rows = acc[safe] + rows * rows  # fill slots: rows == 0 -> no-op
        new_acc = self._scatter(scatter, acc, idx, a_rows, "set",
                                "moment", path)
        new_p = self._scatter(
            scatter, param, idx,
            -(lr * rows / (jnp.sqrt(a_rows) + 1e-10)), "add", "param",
            path)
        return new_p, {"accum": new_acc}


class Adadelta(OptimMethod):
    """(``optim/Adadelta.scala``)."""

    def __init__(self, decay_rate: float = 0.9, epsilon: float = 1e-10):
        super().__init__()
        self.decay_rate, self.epsilon = decay_rate, epsilon
        self.learning_rate = 1.0

    def init_state(self, params):
        st = super().init_state(params)
        st["accum"] = _tree_map(jnp.zeros_like, params)
        st["delta_accum"] = _tree_map(jnp.zeros_like, params)
        return st

    def update(self, grads, params, state):
        rho, eps = self.decay_rate, self.epsilon
        accum = _tree_map(lambda a, g: rho * a + (1 - rho) * g * g, state["accum"], grads)
        delta = _tree_map(lambda d, a, g: jnp.sqrt(d + eps) / jnp.sqrt(a + eps) * g,
                          state["delta_accum"], accum, grads)
        d_accum = _tree_map(lambda d, dl: rho * d + (1 - rho) * dl * dl,
                            state["delta_accum"], delta)
        new_p = _tree_map(lambda p, dl: p - dl, params, delta)
        return new_p, {**state, "accum": accum, "delta_accum": d_accum,
                       "neval": state["neval"] + 1}


class RMSprop(OptimMethod):
    """(``optim/RMSprop.scala``)."""

    def __init__(self, learning_rate: float = 1e-2, learning_rate_decay: float = 0.0,
                 decay_rate: float = 0.99, epsilon: float = 1e-8):
        super().__init__()
        self.learning_rate = learning_rate
        self.learning_rate_decay = learning_rate_decay
        self.decay_rate, self.epsilon = decay_rate, epsilon

    def init_state(self, params):
        st = super().init_state(params)
        st["rms"] = _tree_map(jnp.zeros_like, params)
        return st

    def update(self, grads, params, state):
        lr = self.learning_rate / (1.0 + state["neval"].astype(jnp.float32) * self.learning_rate_decay)
        rho = self.decay_rate
        rms = _tree_map(lambda r, g: rho * r + (1 - rho) * g * g, state["rms"], grads)
        new_p = _tree_map(lambda p, g, r: p - lr * g / (jnp.sqrt(r) + self.epsilon),
                          params, grads, rms)
        return new_p, {**state, "rms": rms, "neval": state["neval"] + 1}


class LBFGS(OptimMethod):
    """Limited-memory BFGS with optional line search
    (``optim/LBFGS.scala``, ``optim/LineSearch.scala``).  Host-side eager
    over a flat parameter vector — the reference uses it for full-batch
    problems, never in the distributed hot loop."""

    def __init__(self, max_iter: int = 20, max_eval: Optional[float] = None,
                 tol_fun: float = 1e-5, tol_x: float = 1e-9, n_correction: int = 100,
                 learning_rate: float = 1.0, line_search: bool = False):
        super().__init__()
        self.max_iter, self.tol_fun, self.tol_x = max_iter, tol_fun, tol_x
        self.max_eval = max_eval if max_eval is not None else max_iter * 1.25
        self.n_correction = n_correction
        self.learning_rate = learning_rate
        self.line_search = line_search

    def optimize(self, feval, x):
        x = jnp.asarray(x)
        old_dirs, old_steps = [], []
        loss, g = feval(x)
        losses = [float(loss)]
        d = -g
        g_old, f_old = g, loss
        H_diag = 1.0
        n_eval = 1
        for _ in range(self.max_iter):
            if jnp.max(jnp.abs(g)) <= self.tol_fun:
                break
            # two-loop recursion
            if old_dirs:
                q = -g
                al = []
                ro = [1.0 / jnp.dot(y, s) for y, s in zip(old_dirs, old_steps)]
                for i in range(len(old_dirs) - 1, -1, -1):
                    a = ro[i] * jnp.dot(old_steps[i], q)
                    al.append(a)
                    q = q - a * old_dirs[i]
                al.reverse()
                r = q * H_diag
                for i in range(len(old_dirs)):
                    b = ro[i] * jnp.dot(old_dirs[i], r)
                    r = r + (al[i] - b) * old_steps[i]
                d = r
            t = self.learning_rate if old_dirs else min(1.0, 1.0 / float(jnp.sum(jnp.abs(g)))) * self.learning_rate
            gtd = jnp.dot(g, d)
            if float(gtd) > -self.tol_x:
                break
            # step (optionally with backtracking line search)
            if self.line_search:
                f_new, g_new, t, ls_evals = _backtrack(feval, x, t, d, loss, gtd)
                n_eval += ls_evals
                x = x + t * d
            else:
                x = x + t * d
                f_new, g_new = feval(x)
                n_eval += 1
            y = g_new - g
            s = t * d
            ys = float(jnp.dot(y, s))
            if ys > 1e-10:
                if len(old_dirs) == self.n_correction:
                    old_dirs.pop(0)
                    old_steps.pop(0)
                old_dirs.append(y)
                old_steps.append(s)
                H_diag = ys / float(jnp.dot(y, y))
            f_old, g_old = loss, g
            loss, g = f_new, g_new
            losses.append(float(loss))
            if n_eval >= self.max_eval:
                break
            if float(jnp.max(jnp.abs(t * d))) <= self.tol_x:
                break
            if abs(float(loss - f_old)) < self.tol_fun:
                break
        return x, losses


def _backtrack(feval, x, t, d, f0, gtd, c1: float = 1e-4, max_ls: int = 25):
    evals = 0
    for _ in range(max_ls):
        f_new, g_new = feval(x + t * d)
        evals += 1
        if float(f_new) <= float(f0) + c1 * t * float(gtd):
            return f_new, g_new, t, evals
        t = t * 0.5
    return f_new, g_new, t, evals
