"""The Optimizer — host-side training driver (SURVEY §2.8 / §3.1-3.2).

Reproduces the reference ``Optimizer`` capabilities (``optim/Optimizer.scala:42``,
``optim/DistriOptimizer.scala``, ``optim/LocalOptimizer.scala``):
fluent configuration (optim method, validation, checkpoint, summaries, end
trigger), epoch/iteration accounting with throughput logging, trigger-driven
validation + checkpointing + TensorBoard summaries, checkpoint-resume, and
the failure-retry loop (``DistriOptimizer.scala:790-856``).

The compute core is ONE compiled :class:`~bigdl_tpu.parallel.train_step.TrainStep`
per run — the reference's two-Spark-jobs-per-iteration collapse into it
(see that module's docstring).  ``LocalOptimizer`` = single-device mesh;
``DistriOptimizer`` = the full Engine mesh; both drive the same loop, as the
reference's two classes drive the same semantics.
"""

from __future__ import annotations

import atexit
import logging
import os
import re
import signal
import threading
import time
import weakref
from datetime import datetime
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from bigdl_tpu import faults as _faults
from bigdl_tpu import telemetry
from bigdl_tpu.parallel import cluster as _cluster
from bigdl_tpu.dataset.dataset import AbstractDataSet, DataSet
from bigdl_tpu.dataset.minibatch import MiniBatch
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.optim_method import OptimMethod, SGD
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.optim.validation import ValidationMethod
from bigdl_tpu.parallel.train_step import EvalStep, TrainStep
from bigdl_tpu.telemetry.memory import MemoryExhaustedError
from bigdl_tpu.telemetry.health import (HealthError, HealthPolicy,
                                        probe_stats)
from bigdl_tpu.utils.ckpt_topology import TopologyMismatchError
from bigdl_tpu.utils import file as File
from bigdl_tpu.utils.config import get_config
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.rng import RNG

__all__ = ["Optimizer", "LocalOptimizer", "DistriOptimizer",
           "StragglerTimeout", "HealthError", "HealthPolicy"]


class StragglerTimeout(RuntimeError):
    """A training iteration exceeded the host-level straggler budget
    (see docs/straggler.md).  Raised into the retry loop, which restores
    the latest checkpoint — the SPMD analogue of the reference's
    drop-gradients-and-continue (``DistriOptimizer.scala:415-420``)."""


#: BIGDL_RESUME spellings — every other boolean knob accepts 0/false/no,
#: so auto-resume must too (a knob meant to DISABLE resuming that
#: silently resumed would be the worst possible failure mode)
_RESUME_ON = frozenset({"auto", "on", "1", "true", "yes"})
_RESUME_OFF = frozenset({"off", "0", "false", "no"})

#: optimizers with an async checkpoint write possibly in flight — a
#: clean interpreter exit right after the last step must JOIN them, or
#: the tail of the write (meta commit included) is silently abandoned
#: and the newest checkpoint never becomes discoverable
_LIVE_CKPT_WRITERS: "weakref.WeakSet" = weakref.WeakSet()


@atexit.register
def _drain_ckpt_writes_at_exit():
    for o in list(_LIVE_CKPT_WRITERS):
        try:
            o._join_checkpoint_write()
        except Exception:  # noqa: BLE001 - exit path must not raise
            pass


class _PreemptGuard:
    """Grace-window SIGTERM/SIGINT handling (docs/fault_tolerance.md).

    The first signal only sets a flag: the training loop finishes the
    in-flight step, commits a final checkpoint carrying the dataset /
    epoch position and host-RNG state, emits ``run/preempted``, and
    returns normally (the process exits 0) — the shape of a TPU-slice
    preemption notice honored.  A second signal means "now": the
    original disposition is restored and re-raised, so a stuck grace
    window can still be killed.

    Installable only on the main thread (CPython restricts
    ``signal.signal``); elsewhere it degrades to a no-op and SIGTERM
    keeps its default (kill) semantics.
    """

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self):
        self.requested = threading.Event()
        self.signum: Optional[int] = None
        self._old = {}
        self._installed = False

    def _handler(self, signum, frame):
        if self.requested.is_set():
            # second signal: restore + re-deliver — immediate semantics
            self.uninstall()
            os.kill(os.getpid(), signum)
            return
        self.signum = signum
        self.requested.set()
        log.warning(f"[Preempt] received signal {signum}: finishing the "
                    f"in-flight step, then committing a final checkpoint "
                    f"(send again to stop immediately)")

    def install(self) -> "_PreemptGuard":
        if threading.current_thread() is not threading.main_thread():
            return self
        try:
            for sig in self.SIGNALS:
                self._old[sig] = signal.signal(sig, self._handler)
            self._installed = True
        except (ValueError, OSError):  # non-main interpreter contexts
            self._old.clear()
        return self

    def uninstall(self):
        if not self._installed:
            return
        for sig, old in self._old.items():
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):
                pass
        self._old.clear()
        self._installed = False

log = logging.getLogger("bigdl_tpu.optim")
if not log.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s"))
    log.addHandler(_h)
    log.setLevel(logging.INFO)


class _BatchPrefetcher:
    """Double-buffered input pipeline: a host thread pulls batches from
    the dataset iterator (running the whole host transform chain) and
    places them on the mesh (h2d) while the device crunches the previous
    step.  The reference overlaps input the same way with its dedicated
    multithreaded transform+batch pipeline
    (``dataset/image/MTLabeledBGRImgToBatch.scala:31``); under JAX the
    device dispatch is already async, so pulling transform+h2d off the
    driver thread is the missing half of the overlap — with it, the
    Metrics ``data time`` stage collapses to queue-pop time (~0 when the
    pipeline keeps up).

    ``depth`` bounds the batches in flight (2 = classic double buffering,
    also bounding device memory for staged inputs)."""

    class _Error:
        def __init__(self, exc):
            self.exc = exc

    def __init__(self, data_iter, place_fn, depth: int, metrics: Metrics):
        import queue
        import threading

        self._it = data_iter
        self._place = place_fn
        self._metrics = metrics
        self._q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, name="bigdl-prefetch", daemon=True)
        self._thread.start()

    def _worker(self):
        try:
            for batch in self._it:
                if self._stop.is_set():
                    return
                t0 = time.perf_counter()
                placed = self._place(batch.get_input(), batch.get_target())
                # recorded under an explicitly-overlapped stage name: the
                # worker places batches AHEAD of consumption, so this is
                # producer-side busy time, NOT driver stall — folding it
                # into the driver's "host to device time" undercounted
                # data-wait exactly when the pipeline was the bottleneck
                # (VERDICT r4 Weak #7); the driver-stall instrument is
                # "data time" (queue-pop wait)
                self._metrics.add("host to device time (overlapped)",
                                  time.perf_counter() - t0)
                self._put_stop_aware((batch.size(), placed))
            else:
                self._put_stop_aware(None)  # iterator exhausted
        except BaseException as e:  # noqa: BLE001 — surfaced on next()
            # the same stop-aware retry as the item path: dropping the
            # error sentinel would leave the driver blocked in next()
            self._put_stop_aware(self._Error(e))

    def _put_stop_aware(self, item):
        import queue

        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.5)
                # producer-side fill level: a queue pinned at 0 means the
                # input pipeline is the bottleneck; pinned at depth means
                # the device is (docs/observability.md)
                telemetry.gauge("prefetch/queue_depth", self._q.qsize())
                return
            except queue.Full:
                continue

    def next(self):
        """(global_batch_size, placed_arrays) or None when exhausted;
        re-raises any producer-side failure on the driver thread (so the
        retry loop sees data errors exactly like compute errors)."""
        item = self._q.get()
        if isinstance(item, self._Error):
            raise item.exc
        return item

    def close(self):
        self._stop.set()
        # unblock a producer stuck on a full queue
        try:
            while True:
                self._q.get_nowait()
        except Exception:
            pass
        self._thread.join(timeout=5.0)


class Optimizer:
    """Factory + base driver.  ``Optimizer(model=..., dataset=...,
    criterion=...)`` picks Local vs Distri by Engine topology, mirroring
    ``Optimizer.apply`` (``optim/Optimizer.scala:411-430``)."""

    def __new__(cls, *args, **kwargs):
        if cls is Optimizer:
            target = DistriOptimizer if Engine.device_count() > 1 else LocalOptimizer
            obj = object.__new__(target)
            return obj
        return object.__new__(cls)

    def __init__(self, model, dataset, criterion, batch_size: Optional[int] = None,
                 end_trigger: Optional[Trigger] = None, *,
                 optim_method: Optional[OptimMethod] = None):
        if isinstance(dataset, (list, tuple)):
            if batch_size is None:
                raise ValueError("batch_size required when passing raw samples")
            # multi-host: each process keeps 1/N of the records and batches
            # its LOCAL share of the global batch (the reference's
            # one-cached-partition-per-node layout, DataSet.scala:164-240)
            nproc, pidx = Engine.process_count(), Engine.process_index()
            if nproc > 1:
                if batch_size % nproc != 0:
                    raise ValueError(
                        f"global batch_size {batch_size} must divide by the "
                        f"{nproc} host processes")
                dataset = DataSet.array(
                    list(dataset), num_shards=nproc, shard_index=pidx
                ).transform(SampleToMiniBatch(batch_size // nproc))
            else:
                dataset = DataSet.array(list(dataset)).transform(
                    SampleToMiniBatch(batch_size))
        self.model = model
        self.dataset: AbstractDataSet = dataset
        self.criterion = criterion
        # constructor kwarg for parity with the reference Python API
        # (optimizer.py Optimizer(..., optim_method=...)); set_optim_method
        # remains the fluent route
        self.optim_method: OptimMethod = optim_method or SGD()
        self.end_when: Trigger = end_trigger or Trigger.max_iteration(2**62)
        self.state: Dict = {"epoch": 1, "neval": 0}
        self.metrics = Metrics()
        from collections import deque

        self._iteration_times = deque(maxlen=20)  # straggler auto budget
        # validation
        self._val_trigger = None
        self._val_dataset = None
        self._val_methods: Sequence[ValidationMethod] = ()
        # checkpoint
        self._ckpt_path = None
        self._ckpt_trigger = None
        self._ckpt_overwrite = False
        self._ckpt_backend = "btpu"
        self._ckpt_keep = None
        self._pending_sharded_restore = None
        # summaries
        self._train_summary = None
        self._val_summary = None
        # step config
        self.parameter_sync = "allreduce"
        self.gradient_compression: Optional[str] = None
        self.compute_dtype = None
        self._grad_clip = None
        self._grad_clip_norm = None
        self._mesh = None  # set by subclass
        # training health (docs/observability.md): None = resolve from
        # BIGDL_HEALTH at optimize() time
        self._health_policy: Optional[HealthPolicy] = None

    # -- fluent config (Optimizer.scala:42-265) ----------------------------
    def set_optim_method(self, method: OptimMethod) -> "Optimizer":
        self.optim_method = method
        return self

    def set_end_when(self, trigger: Trigger) -> "Optimizer":
        self.end_when = trigger
        return self

    def set_validation(self, trigger: Trigger, dataset, methods: Sequence[ValidationMethod],
                       batch_size: Optional[int] = None) -> "Optimizer":
        if isinstance(dataset, (list, tuple)):
            dataset = DataSet.array(list(dataset)).transform(
                SampleToMiniBatch(batch_size or 32))
        self._val_trigger = trigger
        self._val_dataset = dataset
        self._val_methods = list(methods)
        return self

    def set_checkpoint(self, path: str, trigger: Trigger,
                       backend: str = "btpu",
                       keep: Optional[int] = None) -> "Optimizer":
        """``backend="btpu"`` (default): gather to the coordinator and
        write whole-model BTPU files — the reference's driver-side
        saveModel (``Optimizer.scala:284-322``).  ``backend="sharded"``:
        every host writes only its own array shards via orbax
        (``utils/sharded_ckpt.py``) — the pod-scale layout where the
        model may not fit one host.  ``keep=N`` retains only the newest N
        checkpoints (retention the reference lacks — its ``model.n``
        files accumulate forever); ``None`` keeps everything."""
        if backend not in ("btpu", "sharded"):
            raise ValueError(f"unknown checkpoint backend {backend!r}")
        if keep is not None and keep < 1:
            raise ValueError("keep must be >= 1")
        self._ckpt_path = path
        self._ckpt_trigger = trigger
        self._ckpt_backend = backend
        self._ckpt_keep = keep
        return self

    def overwrite_checkpoint(self) -> "Optimizer":
        self._ckpt_overwrite = True
        return self

    def set_train_summary(self, summary) -> "Optimizer":
        self._train_summary = summary
        return self

    def set_validation_summary(self, summary) -> "Optimizer":
        self._val_summary = summary
        return self

    def set_model(self, model) -> "Optimizer":
        self.model = model
        return self

    def set_state(self, state: Dict) -> "Optimizer":
        self.state.update(state)
        return self

    def set_parameter_sync(self, mode: str) -> "Optimizer":
        """'allreduce', 'sharded' (ZeRO-1: optimizer state over the data
        axis), 'fsdp' (ZeRO-3: parameters too — no whole replica per
        device), or 'local' (local SGD: every data-axis device trains
        its own island, parameters average every ``BIGDL_LOCAL_SYNC_H``
        steps under a bounded-staleness barrier —
        parallel/local_sync.py, docs/fault_tolerance.md "Straggler
        tolerance")."""
        if mode not in ("allreduce", "sharded", "fsdp", "local"):
            raise ValueError(f"unknown parameter_sync mode {mode!r}")
        self.parameter_sync = mode
        return self

    def set_gradient_compression(self, mode: Optional[str]) -> "Optimizer":
        """'bf16' reproduces the reference FP16CompressedTensor truncation."""
        self.gradient_compression = mode
        return self

    def set_compute_dtype(self, dtype) -> "Optimizer":
        self.compute_dtype = dtype
        return self

    def set_constant_gradient_clipping(self, lo: float, hi: float) -> "Optimizer":
        self._grad_clip = (lo, hi)
        return self

    def set_gradient_clipping_by_l2_norm(self, max_norm: float) -> "Optimizer":
        self._grad_clip_norm = max_norm
        return self

    def set_health_policy(self, policy: Optional[HealthPolicy]) -> "Optimizer":
        """Install a training-health policy (``telemetry/health.py``):
        numeric-health probes in the compiled step, loss-spike/plateau
        EWMA detection, and warn / skip-step / halt actions.  When never
        called, the policy comes from ``BIGDL_HEALTH`` /
        ``BIGDL_HEALTH_HALT_AFTER`` (default: halt after 3 consecutive
        nonfinite steps).  Pass a policy with ``on_nonfinite="off"`` (or
        set ``BIGDL_HEALTH=off``) to disable the probes entirely."""
        self._health_policy = policy
        return self

    # -- checkpointing -----------------------------------------------------
    def _checkpoint_dir(self) -> Optional[str]:
        return getattr(self, "_ckpt_dir", None)

    def _init_checkpoint_dir(self):
        if self._ckpt_path is None:
            return
        if self._ckpt_overwrite:
            self._ckpt_dir = self._ckpt_path
        else:
            stamp = datetime.now().strftime("%Y%m%d_%H%M%S")
            self._ckpt_dir = File.join(self._ckpt_path, stamp)
        File.makedirs(self._ckpt_dir)

    def _join_checkpoint_write(self):
        """Block until the in-flight async checkpoint write (if any) has
        landed — called before restores, before the next checkpoint, and
        at run end, so a reader can never observe a half-written file
        set."""
        fut = getattr(self, "_ckpt_future", None)
        if fut is not None:
            with self.metrics.timer("checkpoint wait time"):
                fut.result()
            self._ckpt_future = None

    def _driver_state_snapshot(self) -> Dict:
        """The driver state a checkpoint carries: epoch/iteration/record
        position PLUS the host-RNG state and the run's step-key seed —
        everything a fresh process needs to resume mid-epoch on the
        exact batch and random stream the interrupted run would have
        used next (docs/fault_tolerance.md)."""
        snap = dict(self.state)
        snap["rng_state"] = RNG.get_state()
        return snap

    def _save_checkpoint(self, step: TrainStep):
        if self._checkpoint_dir() is None:
            return
        if self._ckpt_backend == "sharded":
            # per-host shard writes — no gather, no single writer.  The
            # device-side dispatch happens NOW (orbax snapshots the
            # arrays); under BIGDL_ASYNC_CHECKPOINT the durable-write +
            # meta-commit tail overlaps the next training steps behind
            # the same _join_checkpoint_write barrier as the BTPU path.
            from bigdl_tpu.utils import sharded_ckpt

            self._join_checkpoint_write()  # meta commits stay ordered
            n = self.state["neval"]
            dest = File.join(self._ckpt_dir, f"sharded.{n}")
            use_async = get_config().async_checkpoint
            finish = sharded_ckpt.save_train_step(
                step, dest,
                extra={"driver_state": self._driver_state_snapshot()},
                wait=not use_async)

            def tail():
                if finish is not None:
                    finish()
                svc = _cluster.get()
                if svc is not None:
                    # two-phase cluster commit (parallel/cluster.py):
                    # THIS host's shards are durable — ack; the
                    # coordinator rolls all acks into the cluster
                    # manifest that gates restore eligibility
                    svc.commit_step(self._ckpt_dir, n)
                if self._ckpt_keep and Engine.is_coordinator():
                    # the manifest step is pinned: cluster restores CAP
                    # at it, so pruning it (because newer, possibly
                    # uncertified checkpoints fill the keep window)
                    # would strand the whole cluster
                    cap = (svc.restore_cap(self._ckpt_dir)
                           if svc is not None else None)
                    for p in sharded_ckpt.prune_old(
                            self._ckpt_dir, self._ckpt_keep,
                            trusted=dest, keep_step=cap,
                            # mixed-topology dirs: never delete the last
                            # checkpoint restorable onto the CURRENT
                            # width (docs/fault_tolerance.md "Elastic
                            # recovery")
                            restorable_fn=sharded_ckpt.restorable_onto_fn(
                                self._mesh)):
                        log.info(f"[Checkpoint] pruned {p}")
                log.info(f"[Checkpoint] saved sharded.{n} "
                         f"to {self._ckpt_dir}")
                telemetry.instant("checkpoint/saved", step=n,
                                  backend="sharded")

            if use_async:
                self._ckpt_future = self._ckpt_pool_submit(tail)
            else:
                tail()
            return
        from bigdl_tpu.utils.module_format import dumps

        # every process participates in the gathers (collectives on a
        # multi-host mesh); only the coordinator writes files —
        # single-writer-safe checkpointing
        step.sync_to_model()
        n = self.state["neval"]
        self.optim_method.state["driver_state"] = self._driver_state_snapshot()
        self.optim_method.state["func_state"] = jax.tree.map(
            np.asarray, step.gather_replicated(step.opt_state))
        if not Engine.is_coordinator():
            svc = _cluster.get()
            if svc is not None:
                # BTPU writes are coordinator-only, but the commit
                # barrier still needs every host's ack: "I reached the
                # step-n commit point with consistent driver state"
                svc.commit_step(self._ckpt_dir, n)
            return
        # snapshot to bytes NOW (consistent state); the IO can overlap
        # with the next training iterations (BIGDL_ASYNC_CHECKPOINT)
        self._join_checkpoint_write()
        from bigdl_tpu.utils import ckpt_digest, ckpt_topology

        blobs = [(dumps(self.model, kind="module"),
                  os.path.join(self._ckpt_dir, f"model.{n}")),
                 (dumps(self.optim_method, kind="optim"),
                  os.path.join(self._ckpt_dir, f"optimMethod.{n}"))]
        # content digests of the exact bytes being written, committed in
        # a meta marker AFTER the payload lands — restore verifies them
        # before loading, so a torn/bit-rotted pair is quarantined, not
        # silently deserialized.  The topology record rides along (own
        # digest): BTPU state is gathered whole-model — portable by
        # construction — but a restore onto a different width still
        # announces the reshard and the resume hint still names the
        # widths the sharded layout would accept.
        topo = ckpt_topology.topology_of(step)
        meta = {"neval": n,
                "digests": {os.path.basename(p): ckpt_digest.digest_bytes(b)
                            for b, p in blobs},
                "topology": topo,
                "topology_digest": ckpt_topology.digest(topo)}
        meta_path = os.path.join(self._ckpt_dir, f"ckptmeta.{n}.json")

        def write():
            import json as _json

            for blob, path in blobs:
                File.save(blob, path, overwrite=True)
            File.save(_json.dumps(meta).encode(), meta_path, overwrite=True)
            try:  # fault injection: tear the committed model payload
                _faults.get_plan().poll_checkpoint(blobs[0][1], n)
            except Exception:  # noqa: BLE001 - injection never fails a save
                pass
            svc = _cluster.get()
            if svc is not None:
                # coordinator ack + manifest roll-up: the per-host
                # digests recorded in the meta marker travel with the
                # ack into the cluster manifest
                svc.commit_step(self._ckpt_dir, n,
                                digests=meta["digests"])
            if self._ckpt_keep:
                self._prune_btpu(trusted=n)
            log.info(f"[Checkpoint] saved model.{n} / optimMethod.{n} "
                     f"to {self._ckpt_dir}")
            telemetry.instant("checkpoint/saved", step=n, backend="btpu")

        if get_config().async_checkpoint:
            self._ckpt_future = self._ckpt_pool_submit(write)
        else:
            write()

    def _ckpt_pool_submit(self, fn):
        from concurrent.futures import ThreadPoolExecutor

        if getattr(self, "_ckpt_pool", None) is None:
            self._ckpt_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="bigdl-ckpt")
        # interpreter exit joins this write (atexit drain): a fast exit
        # right after the last step must not abandon the meta commit
        _LIVE_CKPT_WRITERS.add(self)
        return self._ckpt_pool.submit(fn)

    def _prune_btpu(self, trusted: Optional[int] = None):
        """Keep only the newest ``keep`` model/optimMethod pairs (meta
        markers pruned with them) — coordinator-only (the btpu write
        path already is).  The newest VERIFIED pair is never deleted:
        if every newer checkpoint turns out torn, it is the only state
        a restore can still fall back to.  ``trusted`` marks the step
        number this very write just produced and digested, sparing a
        re-read+hash per save."""
        d = self._ckpt_dir
        nums = sorted(int(m.group(1))
                      for f in File.listdir(d)
                      if (m := re.match(r"model\.(\d+)$", f)))
        victims = nums[:-self._ckpt_keep]
        svc = _cluster.get()
        if svc is not None:
            # never prune the cluster-manifest step: cluster restores
            # cap at it, and newer (uncertified) pairs can't replace it
            cap = svc.restore_cap(d)
            victims = [n for n in victims if n != cap]
        if victims and not any(n == trusted or self._btpu_verify(d, n)[0]
                               for n in
                               reversed(nums[-self._ckpt_keep:])):
            for n in reversed(victims):
                if self._btpu_verify(d, n)[0]:
                    victims = [v for v in victims if v != n]
                    log.warning(f"[Checkpoint] retaining checkpoint {n} "
                                f"beyond keep={self._ckpt_keep}: it is "
                                f"the last verified-good one")
                    break
        for n in victims:
            for name in (f"model.{n}", f"optimMethod.{n}",
                         f"ckptmeta.{n}.json"):
                p = File.join(d, name)
                if File.exists(p):
                    File.remove(p)
            log.info(f"[Checkpoint] pruned model.{n} / optimMethod.{n}")

    @staticmethod
    def get_latest_file(path: str, prefix: str) -> Optional[str]:
        """(``DistriOptimizer.scala:868-885``); local or remote
        (``gs://...``) checkpoint directories."""
        best, best_n = None, -1
        pat = re.compile(re.escape(prefix) + r"\.(\d+)$")
        for f in File.listdir(path):
            m = pat.match(f)
            if m and int(m.group(1)) > best_n:
                best_n = int(m.group(1))
                best = File.join(path, f)
        return best

    def _restore_latest(self) -> bool:
        d = self._checkpoint_dir()
        if d is None:
            return False
        self._join_checkpoint_write()
        return self._restore_from(d)

    def _restore_from(self, d: str) -> bool:
        """Timed wrapper around :meth:`_restore_from_verified`: the
        restore interval is checkpoint badput the goodput ledger
        (telemetry/ledger.py) must see as a measured out-of-step
        interval, not unattributable idle."""
        t0 = time.perf_counter()
        try:
            return self._restore_from_verified(d)
        finally:
            telemetry.stage("checkpoint/restore",
                            time.perf_counter() - t0, source=d)

    def _restore_from_verified(self, d: str) -> bool:
        """Restore the newest VERIFIED checkpoint under ``d``: content
        digests are checked before anything is loaded, torn candidates
        are quarantined (``*.corrupt`` + ``checkpoint/quarantined``)
        and the walk falls back to the previous good step — a restore
        either loads a byte-verified checkpoint fully or reports there
        is none (``docs/fault_tolerance.md``)."""
        # cluster runs restore ONLY what the commit barrier certified:
        # the manifest step caps the walk, so a checkpoint some host
        # wrote but the cluster never acked is structurally invisible —
        # every host lands on the same step (parallel/cluster.py)
        svc = _cluster.get()
        cap = svc.restore_cap(d) if svc is not None else None
        if cap is not None:
            log.info(f"[Recovery] cluster manifest caps restore at "
                     f"step {cap} under {d}")
        if self._ckpt_backend == "sharded":
            from bigdl_tpu.utils.sharded_ckpt import (
                latest_verified_step_dir, restorable_onto_fn)

            # elastic walk: a verified step whose recorded topology the
            # CURRENT mesh cannot take is skipped (not quarantined) in
            # favor of the newest one this width can restore.  The walk
            # probes restorability only on VERIFIED candidates, so a
            # wrapper recording rejections distinguishes "nothing to
            # resume" from "none restores at this width" without
            # re-hashing every dir a second time.
            base_fn = restorable_onto_fn(self._mesh)
            unrestorable: List[str] = []

            def probing_fn(p: str) -> bool:
                ok = base_fn(p)
                if not ok:
                    unrestorable.append(p)
                return ok

            latest = latest_verified_step_dir(d, max_step=cap,
                                              restorable_fn=probing_fn)
            if latest is None:
                if unrestorable:
                    # checkpoints exist but NONE restores at this width
                    # — silently restarting from step 0 would throw
                    # away all progress behind a log line (e.g. a
                    # --min-n width outside the restorable sizes)
                    raise TopologyMismatchError(
                        f"checkpoints exist under {d} "
                        f"({len(unrestorable)} verified) but none is "
                        f"restorable onto the current mesh — pick a "
                        f"width from the checkpoint's restorable sizes "
                        f"(the preemption resume hint prints them) or "
                        f"resume at the writing width")
                return False
            # applied onto the fresh TrainStep inside _optimize_once (the
            # restore needs the live mesh placement, which the step owns)
            self._pending_sharded_restore = latest
            log.info(f"[Recovery] will restore sharded state from {latest}")
            return True
        from bigdl_tpu.utils.serializer import load_module, load_optim_method

        nums = sorted({int(m.group(1)) for f in File.listdir(d)
                       if (m := re.match(r"model\.(\d+)$", f))},
                      reverse=True)
        if cap is not None:
            nums = [n for n in nums if n <= cap]
        for n in nums:
            ok, problems = self._btpu_verify(d, n)
            mfile = File.join(d, f"model.{n}")
            ofile = File.join(d, f"optimMethod.{n}")
            if ok:
                try:
                    model = load_module(mfile)
                    optim_method = load_optim_method(ofile)
                except Exception as e:  # noqa: BLE001 - treat as torn
                    ok, problems = False, [f"load failed: {e}"]
            if not ok:
                self._quarantine_btpu(d, n, problems)
                continue
            self.model = model
            self.optim_method = optim_method
            self._apply_driver_state(
                self.optim_method.state.get("driver_state", {}))
            log.info(f"[Recovery] restored {mfile} and {ofile}")
            self._announce_btpu_reshard(d, n)
            return True
        return False

    def _announce_btpu_reshard(self, d: str, n: int) -> None:
        """BTPU state is gathered whole-model — portable onto any width
        by construction — but a restore whose recorded topology differs
        from the live one is still a membership change the fleet view
        and the flight recorder must see: announce it as a
        ``cluster/reshard`` instant (docs/fault_tolerance.md "Elastic
        recovery")."""
        from bigdl_tpu.utils import ckpt_topology

        topo = (self._btpu_meta(d, n) or {}).get("topology")
        if not topo:
            return
        fields = ckpt_topology.reshard_fields(topo, self._mesh,
                                              source="restore", step=n)
        if fields is not None:
            log.info(f"[Reshard] restoring a checkpoint "
                     f"{ckpt_topology.describe(topo)} onto "
                     f"{fields['to_processes']} process(es) / "
                     f"{fields['to_devices']} device(s)")
            telemetry.instant("cluster/reshard", **fields)

    def _btpu_meta(self, d: str, n: int) -> Optional[Dict]:
        import json as _json

        try:
            return _json.loads(File.load(
                File.join(d, f"ckptmeta.{n}.json")).decode())
        except (OSError, ValueError):
            return None

    def _btpu_verify(self, d: str, n: int) -> Tuple[bool, List[str]]:
        """Digest check of the ``model.n``/``optimMethod.n`` pair against
        its ``ckptmeta.n.json`` marker — the topology record (when
        present) verifies against its own digest too.  Pairs from before
        the digest era (no marker) pass when both files exist —
        rejecting them would strand every old checkpoint."""
        from bigdl_tpu.utils import ckpt_digest, ckpt_topology

        meta = self._btpu_meta(d, n)
        if meta is None:
            both = all(File.exists(File.join(d, f"{p}.{n}"))
                       for p in ("model", "optimMethod"))
            return both, ([] if both else
                          [f"incomplete pair at {n} (no meta marker)"])
        problems = list(ckpt_topology.verify_digest(meta))
        problems.extend(
            ckpt_digest.verify_digests(d, meta.get("digests") or {}))
        return not problems, problems

    def _quarantine_btpu(self, d: str, n: int, problems: List[str]):
        """Move a torn BTPU pair aside as ``*.corrupt`` (postmortem
        evidence; discovery can never pick it again)."""
        moved = []
        for name in (f"model.{n}", f"optimMethod.{n}", f"ckptmeta.{n}.json"):
            p = File.join(d, name)
            if File.exists(p):
                dest = p + ".corrupt"
                k = 1
                while File.exists(dest):  # never overwrite prior evidence
                    dest = p + f".corrupt.{k}"
                    k += 1
                try:
                    File.rename(p, dest)
                    moved.append(name)
                except OSError:
                    log.error(f"[Checkpoint] could not quarantine {p}")
        log.error(f"[Checkpoint] quarantined checkpoint {n} ({moved}): "
                  f"{'; '.join(problems) or 'integrity check failed'}")
        telemetry.instant("checkpoint/quarantined", step=n, backend="btpu",
                          problems=list(problems))

    def _apply_driver_state(self, driver_state: Dict):
        """Fold a checkpoint's driver state into the live run: position
        counters into ``self.state``, host-RNG state back into ``RNG``
        (so transform randomness and key draws continue the interrupted
        stream instead of forking)."""
        ds = dict(driver_state or {})
        rng_state = ds.pop("rng_state", None)
        self.state.update(ds)
        if rng_state:
            try:
                RNG.set_state(rng_state)
            except Exception as e:  # noqa: BLE001 - resume still works,
                # only host-random reproducibility degrades
                log.warning(f"[Recovery] could not restore RNG state "
                            f"({type(e).__name__}: {e})")

    def resume_hint(self) -> Optional[str]:
        """Operator-facing resume guidance after a preemption: the
        topology the newest checkpoint was written under, the widths it
        can restore onto (topology-portable — docs/fault_tolerance.md
        "Elastic recovery"), and the capacity-aware ``supervise
        --min-n`` recipe.  None when no checkpoint/topology exists."""
        from bigdl_tpu.utils import ckpt_topology

        d = self._checkpoint_dir()
        if d is None:
            return None
        topo = None
        try:
            if self._ckpt_backend == "sharded":
                from bigdl_tpu.utils.sharded_ckpt import (latest_step_dir,
                                                          read_topology)

                latest = latest_step_dir(d)
                if latest:
                    topo = read_topology(latest)
            else:
                nums = [int(m.group(1)) for f in File.listdir(d)
                        if (m := re.match(r"ckptmeta\.(\d+)\.json$", f))]
                if nums:
                    topo = (self._btpu_meta(d, max(nums))
                            or {}).get("topology")
        except OSError:
            return None
        if not topo:
            return None
        lines = [f"checkpoint topology: {ckpt_topology.describe(topo)}"]
        nproc = int(topo.get("process_count") or 1)
        if nproc > 1:
            # suggest a width the checkpoint can actually take: the
            # restorable sizes are MESH sizes, so a candidate process
            # count m maps to m × devices-per-process; prefer the
            # largest restorable width at or below half capacity
            sizes = ckpt_topology.restorable_mesh_sizes(topo)
            dpp = max(1, int(topo.get("device_count") or nproc) // nproc)
            cands = [m for m in range(1, nproc)
                     if sizes is None or m * dpp in sizes]
            if cands:
                min_n = max([m for m in cands if m <= nproc // 2]
                            or cands)
                lines.append(
                    f"shrunk slice? resume on fewer chips: "
                    f"python -m bigdl_tpu.models.cli supervise "
                    f"-n {nproc} --min-n {min_n} -- <your train "
                    f"command> — restart attempts that keep losing "
                    f"the same peer relaunch at {min_n} process(es); "
                    f"this checkpoint reshards onto the smaller mesh "
                    f"on load")
        return "\n".join(lines)

    def _resume_sources(self) -> List[str]:
        """Candidate directories a fresh ``optimize()`` may auto-resume
        from, best first: the checkpoint dir itself under
        ``overwrite_checkpoint`` (stable path), else every PREVIOUS
        stamped subdir holding checkpoint-like files, newest first —
        ALL of them, so a newest run whose only checkpoint turned out
        torn falls back to the run before it."""
        if self._ckpt_overwrite:
            return [self._ckpt_dir]
        stamps = sorted((s for s in File.listdir(self._ckpt_path)
                         if re.fullmatch(r"\d{8}_\d{6}", s)), reverse=True)
        me = os.path.basename(self._ckpt_dir)
        out = []
        for s in stamps:
            if s == me:
                continue
            d = File.join(self._ckpt_path, s)
            if any(f.startswith(("model.", "sharded."))
                   for f in File.listdir(d)):
                out.append(d)
        return out

    def _maybe_resume(self):
        """Preemption-safe resume: when a checkpoint path is configured
        and holds a verified checkpoint, a FRESH run continues from it —
        mid-epoch, on the exact next batch — instead of starting over.
        ``BIGDL_RESUME=off`` restores start-from-scratch semantics; an
        explicitly ``set_state``-positioned run is left alone."""
        if self._ckpt_path is None or get_config().resume in _RESUME_OFF:
            return
        if self.state.get("neval", 0) > 0:
            return
        for src in self._resume_sources():
            if not self._restore_from(src):
                log.warning(f"[Resume] no loadable checkpoint under "
                            f"{src}; trying the run before it")
                continue
            self.state["_resumed_from"] = src
            telemetry.instant("run/resumed", source=src,
                              step=self.state.get("neval", 0))
            log.info(f"[Resume] continuing from {src} at iteration "
                     f"{self.state.get('neval', 0)} "
                     f"(epoch {self.state.get('epoch', 1)}, "
                     f"{self.state.get('records', 0)} records into it)")
            return

    def _fast_forward(self, data_iter, records: int, record_scale: int):
        """Skip the batches a restored position says were already
        consumed this epoch — the second half of mid-epoch resume (the
        first half is the dataset's deterministic epoch order).  Host
        transform work only; no device dispatch."""
        t0 = time.perf_counter()
        skipped = 0
        while skipped < records:
            batch = next(data_iter, None)
            if batch is None:
                log.warning(f"[Resume] dataset exhausted after skipping "
                            f"{skipped}/{records} records")
                break
            skipped += batch.size() * record_scale
        if skipped != records:
            log.warning(f"[Resume] fast-forward skipped {skipped} records "
                        f"but the checkpoint recorded {records} — batch "
                        f"size changed between runs?")
        else:
            log.info(f"[Resume] fast-forwarded {skipped} records in "
                     f"{time.perf_counter() - t0:.2f}s to resume "
                     f"mid-epoch")
        telemetry.stage("resume/fast_forward",
                        time.perf_counter() - t0, records=skipped)
        return data_iter

    # -- validation --------------------------------------------------------
    def _validate(self, eval_step: EvalStep):
        if self._val_dataset is None:
            return
        t0 = time.perf_counter()
        results = None
        count = 0
        # multi-host: round-robin the validation batches across processes
        # and merge collectively — the reference shards validation over
        # the cluster the same way (optim/DistriValidator.scala:35,
        # DistriOptimizer.scala:632) instead of evaluating the full set
        # everywhere.  A DistributedDataSet is ALREADY per-process
        # sharded — iterate it fully and only merge.
        from bigdl_tpu.dataset.dataset import DistributedDataSet

        nproc, pidx = Engine.process_count(), Engine.process_index()
        presharded = isinstance(self._val_dataset, DistributedDataSet) \
            and getattr(self._val_dataset, "num_shards", 1) > 1
        for i, batch in enumerate(self._val_dataset.data(train=False)):
            if nproc > 1 and not presharded and i % nproc != pidx:
                continue
            out = eval_step.run(batch.get_input())
            target = batch.get_target()
            rs = [m(out, target) for m in self._val_methods]
            results = rs if results is None else [a + b for a, b in zip(results, rs)]
            count += batch.size()
        if nproc > 1:
            from bigdl_tpu.optim.validation import merge_across_processes

            results = merge_across_processes(results, self._val_methods)
            count = int(results[0].result()[1]) if results else count
            if count == 0:
                results = None  # no process saw a batch: nothing measured
        if results is None:
            return
        wall = time.perf_counter() - t0
        log.info(f"[Validation] {count} records in {wall:.2f}s, "
                 f"throughput {count / max(wall, 1e-9):.1f} records/s")
        for m, r in zip(self._val_methods, results):
            log.info(f"[Validation] {m} is {r}")
            val, _ = r.result()
            self.state["score"] = val
            if self._val_summary is not None:
                self._val_summary.add_scalar(str(m), val, self.state["neval"])
            sched = getattr(self.optim_method, "schedule", None)
            if sched is not None and hasattr(sched, "on_metric"):
                sched.on_metric(val)

    # -- the loop ----------------------------------------------------------
    def _telemetry_begin(self, cfg):
        """Run-scoped telemetry wiring: auto-start a JSONL run when
        ``BIGDL_TELEMETRY`` names a directory (owned = ended by us),
        attach the retrace-attribution bridge to the dispatch hook bus,
        and forward counter/gauge streams into the TrainSummary writers
        so TensorBoard stays the visual frontend."""
        self._tele_owner = False
        self._tele_retrace = None
        self._tele_summary_sink = None
        try:
            if cfg.telemetry_dir and not telemetry.enabled():
                meta = {"model": type(self.model).__name__,
                        "optimizer": type(self).__name__,
                        "parameter_sync": self.parameter_sync}
                telemetry.start_run(cfg.telemetry_dir, meta=meta)
                self._tele_owner = True
            tracer = telemetry.get()
            if tracer is None:
                return
            from bigdl_tpu.telemetry.bridge import (RetraceBridge,
                                                    SummaryBridge)

            self._tele_retrace = RetraceBridge(tracer).install()
            if self._train_summary is not None:
                self._tele_summary_sink = SummaryBridge(self._train_summary)
                tracer.add_sink(self._tele_summary_sink)
        except Exception as e:  # noqa: BLE001 - observers never kill the run
            log.warning(f"[Telemetry] disabled for this run "
                        f"({type(e).__name__}: {e})")
            try:
                self._telemetry_end()
            except Exception:  # noqa: BLE001
                pass

    def _telemetry_end(self):
        tracer = telemetry.get()
        if self._tele_retrace is not None:
            self._tele_retrace.remove()
            self._tele_retrace = None
        if tracer is not None and self._tele_summary_sink is not None:
            tracer.remove_sink(self._tele_summary_sink)
            self._tele_summary_sink = None
        if self._tele_owner:
            telemetry.end_run()
            self._tele_owner = False
            log.info(f"[Telemetry] run log: {telemetry.last_run_path()} "
                     f"(inspect: python -m bigdl_tpu.telemetry <log>)")

    def optimize(self):
        cfg = get_config()
        # two device clients on one chip deadlock in claim — detect the
        # second driver up front (Engine.checkSingleton parity,
        # DistriOptimizer.scala:543-554)
        Engine.check_singleton()
        retry_times = cfg.failure_retry_times
        retry_window = cfg.failure_retry_interval
        failures: List[float] = []
        # a bad BIGDL_HEALTH / halt_after / BIGDL_FAULTS / BIGDL_RESUME
        # is a CONFIG error — surface it here, before the retry loop, or
        # it would be retried to budget exhaustion as if it were a
        # transient training failure
        self._resolve_health_policy()
        _faults.get_plan()
        if cfg.resume not in _RESUME_ON | _RESUME_OFF:
            raise ValueError(
                f"BIGDL_RESUME={cfg.resume!r}: want auto/on or off "
                f"(falsy spellings 0/false/no also read as off)")
        self._init_checkpoint_dir()
        self._telemetry_begin(cfg)
        # cluster fault tolerance (parallel/cluster.py): peer heartbeat
        # + collective watchdog + commit barrier, active only when
        # BIGDL_CLUSTER_DIR is set on a multi-process run
        _cluster.activate()
        self.preempted = False
        # graceful SIGTERM/SIGINT: finish the step, commit a final
        # checkpoint, return — the TPU-slice preemption contract
        self._preempt = _PreemptGuard().install()
        _LIVE_CKPT_WRITERS.add(self)
        # explicit clean-exit flag for the final heartbeat status:
        # sys.exc_info() in the finally would also see an exception a
        # CALLER is currently handling (optimize() invoked from inside
        # an except block) and misreport a clean run as failed
        self._run_completed = False
        try:
            self._maybe_resume()
            while True:
                try:
                    result = self._optimize_once()
                    self._run_completed = True
                    return result
                except KeyboardInterrupt:
                    self._flight_dump("keyboard_interrupt")
                    raise
                except HealthError as e:
                    # a policy halt is a VERDICT, not a failure — the
                    # model is diverged and a checkpoint restore would
                    # just replay the divergence; never burn the retry
                    # budget on it.  The flight recorder dumps the final
                    # steps' events + the halting evidence for the
                    # postmortem.
                    self._flight_dump("health_halt", e.evidence)
                    raise
                except MemoryExhaustedError:
                    # OOM is deterministic for a fixed program: a
                    # checkpoint restore replays the same allocation
                    # and dies again, so burning the retry budget on it
                    # only delays the verdict.  The evidence (largest
                    # buffers, categories, live-vs-limit) was flight-
                    # dumped at the raise site (telemetry/memory.py).
                    raise
                except TopologyMismatchError:
                    # likewise deterministic: the checkpoint cannot
                    # restore onto this mesh, and a retry replays the
                    # same verdict — surface it (pick a restorable
                    # width) instead of burning the budget
                    raise
                except Exception as e:  # noqa: BLE001 — retry loop parity
                    now = time.time()
                    failures = [t for t in failures if now - t < retry_window] + [now]
                    backoff = self._retry_backoff(len(failures))
                    telemetry.instant("run/retry", error=type(e).__name__,
                                      message=str(e)[:200],
                                      attempt=len(failures),
                                      budget=retry_times,
                                      backoff_s=round(backoff, 3))
                    if isinstance(e, StragglerTimeout):
                        # each firing gets its own dump: the ring holds
                        # the steps LEADING INTO the stall, which a
                        # post-restore log can no longer show
                        self._flight_dump("straggler_timeout")
                    if len(failures) > retry_times:
                        log.error(f"retry budget exhausted ({retry_times} in {retry_window}s)")
                        self._flight_dump(
                            f"retry_exhausted:{type(e).__name__}")
                        raise
                    log.warning(f"training failed with {type(e).__name__}: {e}; "
                                f"retry {len(failures)}/{retry_times} "
                                f"after {backoff:.2f}s backoff")
                    if backoff > 0:
                        # wait on the preempt guard's event, not a bare
                        # sleep: a SIGTERM landing mid-backoff must reach
                        # the grace path NOW, not after the full sleep
                        self._preempt.requested.wait(backoff)
                    if self._preempt.requested.is_set():
                        # preempted between attempts: there is no
                        # in-flight step to finish — join any pending
                        # write and exit clean; the last committed
                        # checkpoint is the resume point
                        self._join_checkpoint_write()
                        self.preempted = True
                        telemetry.instant(
                            "run/preempted",
                            step=self.state.get("neval", 0),
                            epoch=self.state.get("epoch", 1),
                            signum=self._preempt.signum or 0)
                        log.warning(
                            "[Preempt] preemption during retry backoff: "
                            "exiting with the last committed checkpoint "
                            "as the resume point")
                        return self.model
                    if not self._restore_latest():
                        log.warning("no checkpoint to restore; restarting from current weights")
        finally:
            self._preempt.uninstall()
            try:  # an in-flight async write must not be abandoned by an
                # exception unwinding past the happy path's join
                self._join_checkpoint_write()
            except Exception:  # noqa: BLE001 - never mask the real error
                pass
            # final heartbeat status AFTER the write join (the barrier
            # ack rides the write tail): peers read done/preempted as a
            # clean exit, failed as an immediate peer loss
            _cluster.deactivate(
                "preempted" if getattr(self, "preempted", False)
                else ("done" if getattr(self, "_run_completed", False)
                      else "failed"))
            self._telemetry_end()

    def _retry_backoff(self, attempt: int) -> float:
        """Exponential backoff with jitter between restore attempts
        (``BIGDL_RETRY_BACKOFF`` base seconds, cap 30s): a persistently
        failing step must not hot-loop through the retry budget in
        milliseconds.  Jitter desynchronizes a fleet of workers retrying
        the same shared-storage restore.  One shared policy with the
        cluster Supervisor (``utils.config.retry_backoff_s``)."""
        from bigdl_tpu.utils.config import retry_backoff_s

        return retry_backoff_s(attempt)

    def _flight_dump(self, reason: str, evidence: Optional[Dict] = None):
        """Dump the flight recorder (telemetry/flight.py) on the way out
        of a dying run — called BEFORE _telemetry_end so the recorder is
        still attached.  Never raises: the run is already dying."""
        recorder = telemetry.flight_recorder()
        if recorder is None:
            return
        try:
            path = recorder.dump(reason, evidence)
            if path:
                log.info(f"[Flight] recorder dumped to {path}")
        except Exception:  # noqa: BLE001 - a dying run must not die harder
            pass

    def _resolve_health_policy(self) -> Optional[HealthPolicy]:
        policy = self._health_policy
        if policy is None:
            policy = HealthPolicy.from_config(get_config())
        if policy is not None and not policy.enabled:
            return None
        # fresh state per run ATTEMPT: a checkpoint restore rewinds the
        # steps the old counters/EWMA were built on
        return policy.fresh() if policy is not None else None

    def _optimize_once(self):
        mesh = self._mesh
        health = self._resolve_health_policy()
        fault_plan = _faults.get_plan()
        step = TrainStep(
            self.model, self.criterion, self.optim_method, mesh=mesh,
            parameter_sync=self.parameter_sync,
            gradient_compression=self.gradient_compression,
            compute_dtype=self.compute_dtype,
            gradient_clipping=self._grad_clip, max_norm=self._grad_clip_norm,
            health_probe=health is not None,
            skip_nonfinite=health is not None and health.skip_nonfinite,
            grad_fault=fault_plan.has("nan_grads"))
        # exposed for tests/tools that need the compiled-step view of
        # the run just performed (e.g. sparse-sync engagement evidence)
        self.last_train_step = step
        # resume functional optimizer state if the method carries it
        if "func_state" in self.optim_method.state:
            restored = jax.tree.map(np.asarray, self.optim_method.state["func_state"])
            step.opt_state = jax.tree.map(
                lambda a, b: jax.device_put(np.asarray(a), b.sharding) if mesh is not None else jax.numpy.asarray(np.asarray(a)),
                restored, step.opt_state)
        if self._pending_sharded_restore is not None:
            from bigdl_tpu.utils.sharded_ckpt import restore_train_step

            extra = restore_train_step(step, self._pending_sharded_restore)
            self._pending_sharded_restore = None
            self._apply_driver_state(extra.get("driver_state", {}))
            step.sync_to_model()
        from bigdl_tpu.dataset.dataset import DistributedDataSet
        from bigdl_tpu.parallel.mesh import mesh_process_count

        # multi-host validation runs process-locally: a pure data-parallel
        # forward needs no collectives, so each process evaluates the full
        # validation set and reaches identical results
        multihost = mesh_process_count(mesh) > 1
        eval_step = EvalStep(self.model, mesh=None if multihost else mesh)
        if isinstance(self.dataset, DistributedDataSet):
            # epoch accounting is GLOBAL so every process flips the epoch
            # on the same iteration (schedules must stay SPMD-consistent)
            dataset_size = self.dataset.global_size()
            record_scale = self.dataset.num_shards
        else:
            dataset_size = self.dataset.size()
            record_scale = 1
        records_this_epoch = self.state.get("records", 0)
        # dataset position: every attempt (fresh resume OR retry-restore)
        # re-enters the CURRENT epoch's deterministic order and skips the
        # records already consumed — no replayed, no skipped batches
        # (before this, a restore replayed the epoch from its start)
        if hasattr(self.dataset, "set_position"):
            self.dataset.set_position(self.state.get("epoch", 1) - 1)
        data_iter = self.dataset.data(train=True)
        data_iter = fault_plan.wrap_data_iter(data_iter)
        if records_this_epoch > 0:
            data_iter = self._fast_forward(data_iter, records_this_epoch,
                                           record_scale)
        # the step-key seed persists in the driver state: every resume /
        # retry attempt folds the SAME base key by iteration number, so
        # stochastic layers replay the interrupted trajectory instead of
        # forking it.  The draw happens BEFORE the prefetch thread starts
        # pulling batches through (possibly random) transforms, so the
        # shared host RNG sees the same draw order as the synchronous path
        if "key0_seed" not in self.state:
            self.state["key0_seed"] = int(RNG.randint(0, 2**31 - 1))
        key0 = jax.random.key(self.state["key0_seed"])
        # async input: transform + h2d run ahead of the device step on a
        # host thread (BIGDL_PREFETCH=0 restores the synchronous path)
        prefetch_depth = get_config().prefetch_batches
        prefetcher = _BatchPrefetcher(
            data_iter, step._shard_batch, prefetch_depth, self.metrics) \
            if prefetch_depth > 0 else None
        epoch_start = time.perf_counter()

        # on-demand profiler (telemetry/profiler.py): the loop polls one
        # process-wide control each iteration, so a capture can be armed
        # at ANY step — POST /profile on the live endpoint, the health
        # policy's escalation hook, or BIGDL_PROFILE, which now merely
        # pre-arms the same control with the first N iterations
        cfg = get_config()
        from bigdl_tpu.telemetry import profiler as _profiler

        profile_ctl = _profiler.get()
        if cfg.profile_dir and cfg.profile_iters > 0:
            profile_ctl.arm(cfg.profile_iters, cfg.profile_dir,
                            source="startup")
        # BIGDL_PROFILE_ON_HEALTH is one-shot PER RUN ATTEMPT: without
        # this latch a chronic warn-level finding would re-arm after
        # every completed capture and keep the profiler on for the rest
        # of the (sick, already slow) run
        self._health_profile_armed = False
        first_iteration = True

        log.info(f"[Optimizer] start training to {mesh} "
                 f"(sync={self.parameter_sync}, compression={self.gradient_compression})")
        tele = telemetry.get()
        tele_base = tele.depth() if tele else 0
        cluster_svc = _cluster.get()
        local_sync = None
        if self.parameter_sync == "local":
            from bigdl_tpu.parallel.local_sync import LocalSyncDriver

            local_sync = LocalSyncDriver(step, cluster=cluster_svc)
        try:
            while not self.end_when(self.state):
                # peer heartbeat FIRST (parallel/cluster.py): a fault
                # killing this process mid-iteration must leave the
                # step-started beat behind for the peers' watchdogs
                if cluster_svc is not None:
                    cluster_svc.beat(self.state["neval"] + 1)
                # fault plan, iteration point: crash raises into the
                # retry loop, kill_worker/preempt signal this process,
                # wedge stalls INSIDE the straggler-guarded region below
                wedge = fault_plan.poll_iteration(self.state["neval"] + 1)
                profile_ctl.poll_begin()
                t_start = time.perf_counter()
                it_sid = tele.begin("train/iteration",
                                    step=self.state["neval"] + 1) \
                    if tele else None
                dw_sid = tele.begin("data_wait") if tele else None
                if prefetcher is not None:
                    item = prefetcher.next()
                    if item is None:
                        if tele:
                            tele.end(dw_sid)
                            tele.end(it_sid)
                        break  # iterator exhausted (finite feeds)
                    batch_n, placed = item
                else:
                    batch: MiniBatch = next(data_iter)
                    batch_n, placed = batch.size(), None
                if tele:
                    tele.end(dw_sid)
                t_data = time.perf_counter()
                key = jax.random.fold_in(key0, self.state["neval"])

                def one_iteration():
                    th0 = time.perf_counter()
                    if wedge is not None:  # injected stall: the
                        # watchdog, not the iteration, must end this
                        fault_plan.wedge_stall()
                    if placed is not None:
                        xs, ys = placed  # h2d already done by the prefetcher
                    else:
                        xs, ys = step._shard_batch(batch.get_input(),
                                                   batch.get_target())
                    t0 = time.perf_counter()
                    if step.grad_fault:
                        out = step.run_sharded(
                            xs, ys, key, grad_scale=fault_plan.grad_scale(
                                self.state["neval"] + 1))
                    else:  # kwarg omitted: keeps stubbed/run-compatible
                        # run_sharded signatures working unchanged
                        out = step.run_sharded(xs, ys, key)
                    t1 = time.perf_counter()
                    out = float(out)  # device sync: the step actually runs
                    t2 = time.perf_counter()
                    # timings are recorded by the CALLER so an abandoned
                    # straggler thread can't pollute Metrics
                    return out, (t0 - th0, t1 - t0, t2 - t0)

                # the first iteration includes XLA compilation — never
                # under the straggler budget (docs/straggler.md).  An
                # injected wedge is the one exception: unguarded it
                # would stall the driver for the full stall instead of
                # exercising the watchdog it exists to test.
                if first_iteration and wedge is None:
                    loss, stage_times = one_iteration()
                else:
                    loss, stage_times = \
                        self._run_with_straggler_guard(one_iteration)
                h2d_s, dispatch_s, sync_s = stage_times
                if prefetcher is None:  # else the worker thread records it
                    self.metrics.add("host to device time", h2d_s)
                self.metrics.add("dispatch time", dispatch_s)
                self.metrics.add("compile + first iteration time" if
                                 first_iteration else "computing time",
                                 sync_s)
                first_iteration = False
                t_end = time.perf_counter()
                profile_ctl.poll_end()
                n = batch_n * record_scale  # global records this iteration
                self.state["neval"] += 1
                self.state["loss"] = loss
                if cluster_svc is not None:
                    # step COMPLETED: refresh the heartbeat and arm the
                    # watchdog (the first completed step ends the
                    # compile exemption)
                    cluster_svc.beat(self.state["neval"], done=True)
                if local_sync is not None:
                    # every H steps: average the islands under the
                    # bounded-staleness barrier — may SHED a peer stuck
                    # ≥ S rounds behind, or exit this process (43) if
                    # the survivors shed US (parallel/local_sync.py)
                    local_sync.on_step(self.state["neval"])
                records_this_epoch += n
                self.state["records"] = records_this_epoch
                self.metrics.add("data time", t_data - t_start)
                self._iteration_times.append(t_end - t_data)
                throughput = n / max(t_end - t_start, 1e-9)
                if tele:
                    tele.emit("step", step=self.state["neval"],
                              dur=t_end - t_start, loss=loss, records=n,
                              throughput=throughput,
                              epoch=self.state["epoch"])
                if health is not None:
                    # may raise HealthError (never retried — see
                    # optimize()); the probe values are already
                    # materialized by the loss sync above, so this is a
                    # 5-float d2h copy, not a device round-trip
                    self._health_observe(health, step, loss)
                log.info(
                    f"[Epoch {self.state['epoch']} {records_this_epoch}/{dataset_size}]"
                    f"[Iteration {self.state['neval']}] Trained {n} records in "
                    f"{t_end - t_start:.4f} seconds. Throughput is {throughput:.1f} "
                    f"records/second. Loss is {loss:.5f}.")
                self.state["_epoch_boundary"] = False
                if records_this_epoch >= dataset_size:
                    self.state["epoch"] += 1
                    # expose the epoch to compiled schedules
                    step.opt_state = dict(step.opt_state)
                    step.opt_state["epoch"] = jax.numpy.asarray(self.state["epoch"], jax.numpy.int32)
                    records_this_epoch = 0
                    self.state["records"] = 0
                    self.state["_epoch_boundary"] = True
                    log.info(f"[Epoch {self.state['epoch'] - 1}] finished in "
                             f"{time.perf_counter() - epoch_start:.2f}s")
                    if tele:
                        tele.instant("epoch", epoch=self.state["epoch"] - 1,
                                     dur=time.perf_counter() - epoch_start)
                    epoch_start = time.perf_counter()
                if self._train_summary is not None:
                    ts = self._train_summary
                    # default: scalars on, Parameters histograms opt-in
                    # (TrainSummary.scala:64-88)
                    gate = getattr(ts, "should_write",
                                   lambda tag, st: tag != "Parameters")
                    if gate("Loss", self.state):
                        ts.add_scalar("Loss", loss, self.state["neval"])
                    if gate("Throughput", self.state):
                        ts.add_scalar("Throughput", throughput, self.state["neval"])
                    if gate("LearningRate", self.state):
                        lr = self.optim_method.get_learning_rate()
                        ts.add_scalar("LearningRate", lr, self.state["neval"])
                    if gate("Parameters", self.state) and hasattr(ts, "add_histogram"):
                        # fsdp/TP params are cross-process-sharded on a
                        # multi-host mesh: gather before np.asarray
                        gathered = step.gather_replicated(step.params)
                        for pname, arr in gathered.items():
                            ts.add_histogram(pname, np.asarray(arr),
                                             self.state["neval"])
                if self._val_trigger is not None and self._val_trigger(self.state):
                    with self.metrics.timer("validation time"), \
                            telemetry.span("validation"):
                        step.sync_to_model()
                        self._validate(eval_step)
                    if cluster_svc is not None:
                        # beat BETWEEN validation and checkpoint: the
                        # silent window peers must tolerate is one
                        # activity, never the two summed
                        cluster_svc.beat(self.state["neval"], done=True)
                ckpt_fired = self._ckpt_trigger is not None \
                    and self._ckpt_trigger(self.state)
                if ckpt_fired:
                    with self.metrics.timer("checkpoint time"), \
                            telemetry.span("checkpoint"):
                        self._save_checkpoint(step)
                if cluster_svc is not None:
                    # refresh after the (possibly slow) checkpoint too
                    cluster_svc.beat(self.state["neval"], done=True)
                preempt = getattr(self, "_preempt", None)
                if preempt is not None and preempt.requested.is_set():
                    # graceful preemption: the in-flight step finished
                    # above; commit a final checkpoint carrying the
                    # dataset/epoch position + RNG state (unless the
                    # trigger just saved this very step), mark the run,
                    # and return 0-exit clean — a fresh process resumes
                    # from here mid-epoch
                    if self._ckpt_path is not None and not ckpt_fired:
                        with self.metrics.timer("checkpoint time"), \
                                telemetry.span("checkpoint"):
                            self._save_checkpoint(step)
                    self._join_checkpoint_write()
                    self.preempted = True
                    telemetry.instant("run/preempted",
                                      step=self.state["neval"],
                                      epoch=self.state["epoch"],
                                      signum=preempt.signum or 0)
                    log.warning(
                        f"[Preempt] run preempted at iteration "
                        f"{self.state['neval']} (epoch "
                        f"{self.state['epoch']}); final checkpoint "
                        f"committed — a fresh optimize() resumes here")
                    if tele:
                        tele.end(it_sid)
                    break
                if tele:
                    tele.end(it_sid)
        except BaseException:
            if tele:
                # close the spans the exception left open in THIS scope
                # (marked abandoned) — begin/end pairing is an invariant
                # of the log, not of the happy path; spans the CALLER
                # opened around optimize() stay theirs to close
                tele.unwind(to_depth=tele_base)
            # the compiled step DONATES param/opt buffers, so the module
            # tree's original arrays are already deleted after the first
            # iteration — write the last-completed-iteration params back
            # before the retry loop rebuilds a TrainStep from the model
            # ("restart from current weights" must mean CURRENT)
            try:
                step.sync_to_model()
            except Exception:
                log.warning("could not sync params to model after failure")
            raise
        finally:
            if prefetcher is not None:
                prefetcher.close()
            # an in-flight capture is closed (valid trace), a merely
            # armed one cancelled — the control is reusable next run
            profile_ctl.abort()
        if local_sync is not None:
            # the run's final params are the ISLAND MEAN, not whatever
            # island this process happened to train last
            local_sync.finalize(self.state["neval"])
        step.sync_to_model()
        self._join_checkpoint_write()  # run ends with all writes landed
        log.info(self.metrics.summary())
        return self.model

    # -- training health (docs/observability.md) ----------------------------
    def _health_observe(self, policy: HealthPolicy, step: TrainStep,
                        loss: float) -> None:
        """Fold this iteration's in-graph probe into the policy: emit the
        typed ``health`` event + finding instants, mirror the probe into
        TrainSummary scalars, log warnings, and raise
        :class:`HealthError` when the halt predicate fires."""
        if step.last_health is None:
            return
        n = self.state["neval"]
        try:
            stats = probe_stats(np.asarray(step.last_health), loss)
        except Exception as e:  # noqa: BLE001 - a probe fetch must not
            # kill a healthy run; the step itself already succeeded
            log.warning(f"[Health] probe fetch failed at step {n} "
                        f"({type(e).__name__}: {e})")
            return
        telemetry.emit("health", step=n, **stats)
        action, findings = policy.observe(n, stats)
        for name, attrs in findings:
            telemetry.instant(name, **attrs)
        ts = self._train_summary
        if ts is not None:
            gate = getattr(ts, "should_write",
                           lambda tag, st: tag != "Parameters")
            if gate("Health", self.state):
                for key in ("grad_norm", "update_ratio",
                            "nonfinite_grads", "nonfinite_params"):
                    ts.add_scalar(f"health/{key}", stats[key], n)
        if action == "ok":
            return
        # BIGDL_PROFILE_ON_HEALTH=<dir>: the FIRST escalation arms a
        # one-shot profiler capture so the NEXT step — the divergence
        # itself, not a healthy step hours earlier — gets traced.
        # Latched per run attempt: later findings never re-arm.
        on_health = get_config().profile_on_health
        if on_health and action != "halt" \
                and not getattr(self, "_health_profile_armed", True):
            from bigdl_tpu.telemetry import profiler as _profiler

            ctl = _profiler.get()
            base = None if on_health.lower() in ("1", "true", "on", "yes") \
                else on_health
            if ctl.arm(1, ctl.default_dir(base), source="health"):
                self._health_profile_armed = True
        names = ", ".join(name for name, _ in findings)
        log.warning(f"[Health] step {n}: {names} "
                    f"(loss={stats['loss']:.4g}, "
                    f"grad_norm={stats['grad_norm']:.4g}, "
                    f"update_ratio={stats['update_ratio']:.4g})")
        if action == "halt":
            consec = policy.state["consecutive_nonfinite"]
            reason = (f"{consec} consecutive nonfinite step(s)" if consec
                      else "halt_when trigger fired")
            raise HealthError(n, reason, policy.evidence(n, stats))

    # -- straggler guard (docs/straggler.md) --------------------------------
    def _straggler_timeout(self) -> Optional[float]:
        """Current per-iteration budget in seconds, or None when disabled.
        ``BIGDL_ITERATION_TIMEOUT``: unset/"0" = off, a float = fixed
        budget, "auto" = 10x the median of recent iterations (min 60 s,
        armed after 5 samples) — the host-level analogue of the
        reference's kth-largest adaptive threshold
        (``DistriOptimizer.scala:339-367``, ``Util.kthLargest``)."""
        spec = get_config().iteration_timeout
        if not spec or spec == "0":
            return None
        if spec == "auto":
            if len(self._iteration_times) < 5:
                return None
            med = sorted(self._iteration_times)[len(self._iteration_times) // 2]
            return max(60.0, 10.0 * med)
        return float(spec)

    def _run_with_straggler_guard(self, fn):
        timeout = self._straggler_timeout()
        if timeout is None:
            return fn()
        import queue
        import threading

        results: "queue.Queue" = queue.Queue(maxsize=1)

        def runner():
            try:
                results.put(("ok", fn()))
            except BaseException as e:  # noqa: BLE001 — relayed to caller
                results.put(("err", e))

        # daemon: an abandoned thread blocked on a wedged device call must
        # not stall interpreter exit (concurrent.futures workers would)
        threading.Thread(target=runner, daemon=True,
                         name="bigdl-iteration").start()
        try:
            kind, value = results.get(timeout=timeout)
        except queue.Empty:
            # the dispatch thread stays blocked on the device; recovery
            # re-initializes from the last checkpoint (the only safe move
            # on a synchronous SPMD step — see docs/straggler.md).  The
            # firing lands in the telemetry timeline alongside the steps
            # it interrupted, not just in the logger stream.
            telemetry.instant("straggler/timeout", budget_s=timeout,
                              step=self.state["neval"] + 1)
            raise StragglerTimeout(
                f"iteration exceeded the straggler budget of {timeout:.1f}s "
                f"(BIGDL_ITERATION_TIMEOUT)") from None
        if kind == "err":
            raise value
        return value


class LocalOptimizer(Optimizer):
    """Single-chip training (``optim/LocalOptimizer.scala``)."""

    def __init__(self, model, dataset, criterion, batch_size: Optional[int] = None,
                 end_trigger: Optional[Trigger] = None, *,
                 optim_method: Optional[OptimMethod] = None):
        super().__init__(model, dataset, criterion, batch_size, end_trigger,
                         optim_method=optim_method)
        self._mesh = None


class DistriOptimizer(Optimizer):
    """Mesh-parallel training (``optim/DistriOptimizer.scala``): batch
    sharded over the data axis, gradient aggregation + (optionally ZeRO-1
    sharded) update inside the compiled step."""

    def __init__(self, model, dataset, criterion, batch_size: Optional[int] = None,
                 end_trigger: Optional[Trigger] = None, *, mesh=None,
                 optim_method: Optional[OptimMethod] = None):
        # mesh/optim_method keyword-only: positional slot 6 would differ
        # between the two interchangeable Optimizer classes
        super().__init__(model, dataset, criterion, batch_size, end_trigger,
                         optim_method=optim_method)
        self._mesh = mesh if mesh is not None else Engine.mesh
