"""Per-stage training metrics (``optim/Metrics.scala:31-130``).

The reference aggregates six per-stage timings via Spark accumulators
across executors (computing / get-weights / aggregate-gradient /
put-gradient / compute-weight / send-weights, set at
``DistriOptimizer.scala:158-166``).  Under SPMD the gradient exchange
stages are fused into one XLA program, so the stages worth separating are
host-observable instead: data wait, host-to-device transfer, compile,
step dispatch, device sync, validation, and checkpoint — all recorded by
the Optimizer loop into this accumulator and printed by ``summary()``.

Deeper (op-level) timing comes from the profiler hook: set
``BIGDL_PROFILE=<dir>`` to capture a ``jax.profiler`` trace of the first
few training iterations (``BIGDL_PROFILE_ITERS``, default 5).

When a telemetry run is active (``BIGDL_TELEMETRY``, see
docs/observability.md) every recorded sample is ALSO forwarded to the
event log as a ``stage`` event — the accumulator's call sites are the
instrumentation points, so the timeline and the printed summary can
never disagree about what was measured."""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List

from bigdl_tpu import telemetry

__all__ = ["Metrics"]


class Metrics:
    """Thread-safe per-stage accumulator.  ``add``/``set``/``timer`` are
    called concurrently by the driver loop, the prefetch worker, the
    straggler runner, and the async-checkpoint pool — every read and
    write of ``_scalars`` happens under one lock (the telemetry forward
    happens outside it: the tracer has its own).  ``stages()`` and
    ``summary()`` report in STABLE pipeline order — the canonical stage
    sequence first, then unknown stages in first-recorded order — so two
    summaries of the same run are comparable line-by-line."""

    #: the host-loop pipeline order (docs/observability.md): stages are
    #: reported in execution order, not alphabetically
    _STAGE_ORDER = ("data time", "host to device time",
                    "host to device time (overlapped)", "dispatch time",
                    "compile + first iteration time", "computing time",
                    "validation time", "checkpoint time",
                    "checkpoint wait time")

    def __init__(self):
        self._lock = threading.Lock()
        self._scalars: Dict[str, List[float]] = {}

    def _ordered(self) -> List[str]:
        """Stage names in canonical order (call with the lock held)."""
        known = [n for n in self._STAGE_ORDER if n in self._scalars]
        return known + [n for n in self._scalars
                        if n not in self._STAGE_ORDER]

    def set(self, name: str, value: float):
        with self._lock:
            self._scalars[name] = [float(value)]
        telemetry.gauge(name, value)

    def add(self, name: str, value: float):
        with self._lock:
            self._scalars.setdefault(name, []).append(float(value))
        telemetry.stage(name, value)

    def get(self, name: str) -> float:
        """Mean of the recorded values (0.0 when empty)."""
        with self._lock:
            vals = self._scalars.get(name, [])
            return sum(vals) / len(vals) if vals else 0.0

    def total(self, name: str) -> float:
        with self._lock:
            return sum(self._scalars.get(name, []))

    def count(self, name: str) -> int:
        with self._lock:
            return len(self._scalars.get(name, []))

    def stages(self) -> List[str]:
        with self._lock:
            return self._ordered()

    @contextmanager
    def timer(self, name: str):
        """Accumulate the wall time of the with-block under ``name``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def reset(self):
        with self._lock:
            self._scalars.clear()

    def summary(self, unit_scale: float = 1.0) -> str:
        """Pretty printer mirroring ``Metrics.summary``: per-stage mean,
        total, and sample count, in canonical pipeline order."""
        with self._lock:
            lines = ["========== Metrics Summary =========="]
            for name in self._ordered():
                vals = self._scalars[name]
                mean = sum(vals) / len(vals) if vals else 0.0
                lines.append(
                    f"{name} : mean {mean * unit_scale:.6f} s "
                    f"(total {sum(vals) * unit_scale:.4f} s, n={len(vals)})")
            lines.append("=====================================")
            return "\n".join(lines)
