"""Per-stage training metrics (``optim/Metrics.scala:31-130``).

The reference aggregates timings via Spark accumulators across executors;
here a host-side accumulator keyed by stage name (the SPMD step is one
device program, so per-stage wall times come from the host loop and,
optionally, jax profiling)."""

from __future__ import annotations

import threading
from typing import Dict, List

__all__ = ["Metrics"]


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self._scalars: Dict[str, List[float]] = {}

    def set(self, name: str, value: float):
        with self._lock:
            self._scalars[name] = [float(value)]

    def add(self, name: str, value: float):
        with self._lock:
            self._scalars.setdefault(name, []).append(float(value))

    def get(self, name: str) -> float:
        with self._lock:
            vals = self._scalars.get(name, [])
            return sum(vals) / len(vals) if vals else 0.0

    def reset(self):
        with self._lock:
            self._scalars.clear()

    def summary(self, unit_scale: float = 1.0) -> str:
        """Pretty printer mirroring ``Metrics.summary``."""
        with self._lock:
            lines = ["========== Metrics Summary =========="]
            for name, vals in sorted(self._scalars.items()):
                mean = sum(vals) / len(vals) if vals else 0.0
                lines.append(f"{name} : {mean * unit_scale:.6f} s")
            lines.append("=====================================")
            return "\n".join(lines)
