"""Validation methods & results (``optim/ValidationMethod.scala``:
Top1Accuracy, Top5Accuracy, Loss, MAE, TreeNNAccuracy; results merge with
``+`` for distributed/batched aggregation)."""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

__all__ = [
    "ValidationResult", "AccuracyResult", "LossResult", "ValidationMethod",
    "Top1Accuracy", "Top5Accuracy", "Loss", "MAE", "TreeNNAccuracy",
    "merge_across_processes",
]


class ValidationResult:
    def result(self):
        raise NotImplementedError

    def __add__(self, other):
        raise NotImplementedError

    def _state(self):
        """(numerator, count) — the two constructor fields, used for
        cross-process merging."""
        raise NotImplementedError


def merge_across_processes(results, methods):
    """Sum per-process ValidationResults over ALL host processes — the
    sharded-validation merge (``optim/DistriValidator.scala:35``; the
    reference zips validation partitions across the cluster and reduces
    with ``+``).  COLLECTIVE: every process of the cluster must call
    this, even with zero local batches (``results=None``)."""
    from jax.experimental import multihost_utils

    if results is None:
        state = np.zeros((len(methods), 2), np.float64)
        kinds = [m.result_type for m in methods]
    else:
        state = np.asarray([r._state() for r in results], np.float64)
        kinds = [type(r) for r in results]
    # gather the float64 BYTES as uint32 words: process_allgather would
    # otherwise downcast to float32 (x64 disabled), corrupting counts
    # beyond 2^24
    words = np.ascontiguousarray(state).view(np.uint32)
    gathered = np.asarray(multihost_utils.process_allgather(words))
    totals = gathered.reshape(-1, *words.shape).view(np.float64).sum(axis=0)
    return [cls(a, b) for cls, (a, b) in zip(kinds, totals)]


class AccuracyResult(ValidationResult):
    def __init__(self, correct: int, count: int):
        self.correct, self.count = int(correct), int(count)

    def result(self):
        return (self.correct / max(self.count, 1), self.count)

    def _state(self):
        return (self.correct, self.count)

    def __add__(self, other):
        return AccuracyResult(self.correct + other.correct, self.count + other.count)

    def __repr__(self):
        acc, n = self.result()
        return f"Accuracy(correct: {self.correct}, count: {n}, accuracy: {acc:.6f})"

    def __eq__(self, other):
        return isinstance(other, AccuracyResult) and \
            (self.correct, self.count) == (other.correct, other.count)


class LossResult(ValidationResult):
    def __init__(self, loss: float, count: int):
        self.loss, self.count = float(loss), int(count)

    def result(self):
        return (self.loss / max(self.count, 1), self.count)

    def _state(self):
        return (self.loss, self.count)

    def __add__(self, other):
        return LossResult(self.loss + other.loss, self.count + other.count)

    def __repr__(self):
        mean, n = self.result()
        return f"Loss(loss: {self.loss:.6f}, count: {n}, mean: {mean:.6f})"


class ValidationMethod:
    name = "ValidationMethod"
    result_type = AccuracyResult  # Loss/MAE override

    def __call__(self, output, target) -> ValidationResult:
        raise NotImplementedError

    def __repr__(self):
        return self.name


def _to_classes(output, one_based: bool):
    out = np.asarray(output)
    if out.ndim == 1:
        out = out[None, :]
    pred = out.argmax(axis=-1)
    return pred + 1 if one_based else pred


class Top1Accuracy(ValidationMethod):
    """(``ValidationMethod.scala:170``)."""

    name = "Top1Accuracy"

    def __init__(self, one_based: bool = False):
        self.one_based = one_based

    def __call__(self, output, target):
        pred = _to_classes(output, self.one_based)
        t = np.asarray(target).reshape(-1).astype(np.int64)
        return AccuracyResult(int((pred == t).sum()), t.size)


class Top5Accuracy(ValidationMethod):
    """(``ValidationMethod.scala:218``)."""

    name = "Top5Accuracy"

    def __init__(self, one_based: bool = False):
        self.one_based = one_based

    def __call__(self, output, target):
        out = np.asarray(output)
        if out.ndim == 1:
            out = out[None, :]
        top5 = np.argsort(-out, axis=-1)[:, :5]
        if self.one_based:
            top5 = top5 + 1
        t = np.asarray(target).reshape(-1).astype(np.int64)
        correct = int((top5 == t[:, None]).any(axis=1).sum())
        return AccuracyResult(correct, t.size)


class Loss(ValidationMethod):
    """Mean criterion loss (``ValidationMethod.scala:312``)."""

    name = "Loss"
    result_type = LossResult

    def __init__(self, criterion=None):
        if criterion is None:
            from bigdl_tpu.nn.criterion import ClassNLLCriterion

            criterion = ClassNLLCriterion()
        self.criterion = criterion

    def __call__(self, output, target):
        loss = float(self.criterion.update_output(jnp.asarray(output), jnp.asarray(target)))
        n = np.asarray(output).shape[0]
        return LossResult(loss * n, n)


class MAE(ValidationMethod):
    """Mean absolute error on argmax-decoded predictions vs targets
    (``ValidationMethod.scala:332``)."""

    name = "MAE"
    result_type = LossResult

    def __init__(self, one_based: bool = False):
        self.one_based = one_based

    def __call__(self, output, target):
        pred = _to_classes(output, self.one_based).astype(np.float64)
        t = np.asarray(target).reshape(-1).astype(np.float64)
        return LossResult(float(np.abs(pred - t).sum()), t.size)


class TreeNNAccuracy(ValidationMethod):
    """Accuracy on the root-node prediction of a tree output
    (``ValidationMethod.scala:118``): output [batch, nodes, classes],
    evaluated at the first (root) node."""

    name = "TreeNNAccuracy"

    def __init__(self, one_based: bool = False):
        self.one_based = one_based

    def __call__(self, output, target):
        out = np.asarray(output)
        root = out[:, 0, :] if out.ndim == 3 else out
        pred = root.argmax(axis=-1)
        if self.one_based:
            pred = pred + 1
        t = np.asarray(target)
        t = t[:, 0] if t.ndim == 2 else t.reshape(-1)
        return AccuracyResult(int((pred == t.astype(np.int64)).sum()), pred.size)
