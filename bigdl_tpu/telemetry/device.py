"""Device-side facts for the telemetry stream: XLA cost analysis
(flops/bytes -> MFU denominators), compiled-executable memory analysis
(HBM breakdown, donated-buffer aliasing), and live device memory.

Levels (``BIGDL_TELEMETRY_DEVICE``):

- ``off``  — emit nothing;
- ``auto`` (default) — everything that costs at most a re-lower of the
  already-traced program: ``Lowered.cost_analysis()`` flops/bytes,
  host-computed donated-buffer bytes, ``device.memory_stats()``;
- ``full`` — additionally AOT-compiles the lowered program to read
  ``Compiled.memory_analysis()`` (argument/output/temp/alias bytes —
  the HBM breakdown).  NOTE: JAX's AOT compile does NOT share the jit
  dispatch cache, so ``full`` pays one extra XLA compile per step
  object; it is for diagnosis sessions, not always-on production runs.

MFU is *not* computed here — the log carries ``flops_per_step`` +
``peak_flops_per_device`` + ``device_count`` and the CLI divides by the
measured step time, so the estimate stays recomputable from the
artifact alone.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

__all__ = ["peak_flops_per_device", "peak_bw_per_device",
           "hbm_per_device", "normalize_cost_analysis",
           "cost_facts", "memory_facts", "live_memory_facts",
           "donated_bytes", "collect_device_facts", "mfu_estimate"]

#: per-chip dense bf16 peak FLOP/s by device_kind prefix (the bench.py
#: table's sibling — shared convention: BIGDL_PEAK_FLOPS overrides).
_PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4 lite": 137e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops_per_device(device_kind: str) -> Optional[float]:
    """Dense bf16 peak FLOP/s for one device, or None when unknown (CPU
    has no meaningful MFU denominator).  ``BIGDL_PEAK_FLOPS`` (FLOP/s)
    overrides the table — also the escape hatch for new TPU kinds."""
    env = os.environ.get("BIGDL_PEAK_FLOPS")
    if env:
        return float(env)
    kind = (device_kind or "").lower()
    best = None
    for name, peak in _PEAK_FLOPS.items():
        if kind.startswith(name.lower()):
            # longest prefix wins ("TPU v5 lite" over "TPU v5")
            if best is None or len(name) > best[0]:
                best = (len(name), peak)
    return best[1] if best else None


#: per-chip aggregate interconnect (ICI) bandwidth in bytes/s by
#: device_kind prefix — the comms-attribution denominator
#: (telemetry/comms.py), sibling of the peak-FLOPs table above.  These
#: are approximate public aggregate figures; ``BIGDL_PEAK_BW`` overrides
#: (and is the only way to describe a DCN-spanning slice, whose
#: cross-slice links are far slower than ICI).
_PEAK_BW = {
    "TPU v2": 1.0e11,
    "TPU v3": 1.4e11,
    "TPU v4": 3.0e11,
    "TPU v5 lite": 2.0e11,
    "TPU v5e": 2.0e11,
    "TPU v5p": 6.0e11,
    "TPU v5": 6.0e11,
    "TPU v6 lite": 3.6e11,
    "TPU v6e": 3.6e11,
}


def peak_bw_per_device(device_kind: str) -> Optional[float]:
    """Aggregate interconnect bytes/s for one device, or None when
    unknown (CPU collectives have no meaningful peak).  ``BIGDL_PEAK_BW``
    (bytes/s) overrides the table — also the DCN escape hatch."""
    env = os.environ.get("BIGDL_PEAK_BW")
    if env:
        return float(env)
    kind = (device_kind or "").lower()
    best = None
    for name, peak in _PEAK_BW.items():
        if kind.startswith(name.lower()):
            if best is None or len(name) > best[0]:
                best = (len(name), peak)
    return best[1] if best else None


#: per-chip HBM bytes by device_kind prefix (public spec sheets) — the
#: fit estimator's budget denominator (telemetry/memory.py);
#: ``BIGDL_HBM_GB`` overrides (and is the only way to describe a
#: host-capped or MIG-style fractional allocation).
_HBM_GB = {
    "TPU v2": 8,
    "TPU v3": 16,
    "TPU v4 lite": 8,
    "TPU v4": 32,
    "TPU v5 lite": 16,
    "TPU v5e": 16,
    "TPU v5p": 95,
    "TPU v5": 95,
    "TPU v6 lite": 32,
    "TPU v6e": 32,
}


def hbm_per_device(device_kind: str) -> Optional[int]:
    """HBM bytes of one device from the per-chip table, or None when
    unknown (CPU has no fixed budget; ``BIGDL_HBM_GB`` is resolved by
    the caller, ``memory.hbm_limit_bytes``, so this stays a pure table
    lookup)."""
    kind = (device_kind or "").lower()
    best = None
    for name, gb in _HBM_GB.items():
        if kind.startswith(name.lower()):
            if best is None or len(name) > best[0]:
                best = (len(name), gb)
    return best[1] * (1 << 30) if best else None


def normalize_cost_analysis(cost) -> Dict[str, Any]:
    """``cost_analysis()`` returns a dict on some backends/JAX versions
    and a one-element list of dicts on others — always hand back the
    dict (shared by bench.py's two call sites and :func:`cost_facts`)."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost or {}


def cost_facts(lowered) -> Dict[str, Any]:
    """flops / bytes accessed from a ``jax.stages.Lowered`` (HLO-level
    cost analysis — no XLA compile)."""
    out: Dict[str, Any] = {}
    try:
        cost = normalize_cost_analysis(lowered.cost_analysis())
        if cost.get("flops"):
            out["flops_per_step"] = float(cost["flops"])
        if cost.get("bytes accessed"):
            out["bytes_accessed"] = float(cost["bytes accessed"])
    except Exception:  # noqa: BLE001 - facts are best-effort
        pass
    return out


def memory_facts(compiled) -> Dict[str, Any]:
    """HBM breakdown from ``Compiled.memory_analysis()`` (argument /
    output / temp / generated-code / donation-alias bytes)."""
    out: Dict[str, Any] = {}
    try:
        ma = compiled.memory_analysis()
        for key, attr in (("argument_bytes", "argument_size_in_bytes"),
                          ("output_bytes", "output_size_in_bytes"),
                          ("temp_bytes", "temp_size_in_bytes"),
                          ("code_bytes", "generated_code_size_in_bytes"),
                          ("alias_bytes", "alias_size_in_bytes")):
            v = getattr(ma, attr, None)
            if v is not None:
                out[key] = int(v)
    except Exception:  # noqa: BLE001
        pass
    return out


def live_memory_facts(device=None) -> Dict[str, Any]:
    """Live allocator stats of one device (``bytes_in_use`` /
    ``bytes_limit`` / ``peak_bytes_in_use`` where the backend reports
    them; CPU reports nothing)."""
    out: Dict[str, Any] = {}
    try:
        import jax

        dev = device if device is not None else jax.local_devices()[0]
        stats = dev.memory_stats()
        if stats:
            for key in ("bytes_in_use", "bytes_limit",
                        "peak_bytes_in_use", "largest_alloc_size"):
                if key in stats:
                    out[key] = int(stats[key])
    except Exception:  # noqa: BLE001
        pass
    return out


def donated_bytes(*trees) -> int:
    """Host-side accounting of the donated argument trees (params /
    opt_state / buffers): the bytes the step re-uses in place instead of
    double-buffering."""
    total = 0
    try:
        import jax

        for tree in trees:
            for leaf in jax.tree_util.tree_leaves(tree):
                nbytes = getattr(leaf, "nbytes", None)
                if nbytes is None:
                    size = getattr(leaf, "size", 0)
                    itemsize = getattr(getattr(leaf, "dtype", None),
                                       "itemsize", 0)
                    nbytes = size * itemsize
                total += int(nbytes)
    except Exception:  # noqa: BLE001
        pass
    return total


def collect_device_facts(lowered, donated_trees=(), level: str = "auto"
                         ) -> Dict[str, Any]:
    """Assemble one ``device_facts`` payload from a lowered step (see
    module docstring for what each level costs)."""
    if level == "off":
        return {}
    facts = cost_facts(lowered)
    db = donated_bytes(*donated_trees)
    if db:
        facts["donated_bytes"] = db
    # live allocator peaks ride the DEFAULT level (one attr read per
    # device — the runbook's first OOM question must not need `full`);
    # the flat device-0 keys stay for back-compat, the per-device list
    # covers multi-chip hosts
    facts.update(live_memory_facts())
    try:
        from bigdl_tpu.telemetry.memory import live_hbm

        per_dev = live_hbm()
        if len(per_dev) > 1:
            facts["live_memory"] = per_dev
    except Exception:  # noqa: BLE001 - facts are best-effort
        pass
    try:
        import jax

        dev = jax.devices()[0]
        facts["device_kind"] = dev.device_kind
        facts["device_count"] = jax.device_count()
        peak = peak_flops_per_device(dev.device_kind)
        if peak:
            facts["peak_flops_per_device"] = peak
        peak_bw = peak_bw_per_device(dev.device_kind)
        if peak_bw:
            facts["peak_bw_per_device"] = peak_bw
    except Exception:  # noqa: BLE001
        pass
    if level == "full":
        try:
            facts.update(memory_facts(lowered.compile()))
        except Exception:  # noqa: BLE001
            pass
    return facts


def mfu_estimate(flops_per_step: float, step_seconds: float,
                 peak_flops_per_dev: float, device_count: int = 1
                 ) -> Optional[float]:
    """Model FLOP utilization: achieved FLOP/s over the fleet peak.
    ``flops_per_step`` counts the GLOBAL step (XLA cost analysis of the
    SPMD program), so the denominator scales by device count."""
    if not (flops_per_step and step_seconds and peak_flops_per_dev):
        return None
    denom = peak_flops_per_dev * max(device_count, 1)
    return (flops_per_step / step_seconds) / denom
