"""Run summarization: turn one JSONL event log back into the questions
an operator asks — where did the time go (per-stage table), how stable
were the steps (p50/p95), what compiled or retraced when, and how close
to the hardware did the run get (MFU).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from bigdl_tpu.telemetry.device import mfu_estimate

__all__ = ["summarize", "format_summary", "fleet_summarize",
           "format_fleet"]


def _percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (numpy-free so the reader stays light)."""
    if not values:
        return 0.0
    s = sorted(values)
    idx = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate parsed events into one summary dict (the CLI's text and
    ``--json`` views are both renderings of it)."""
    meta: Dict[str, Any] = {}
    stages: Dict[str, Dict[str, float]] = {}
    steps: List[Dict[str, Any]] = []
    compiles: List[Dict[str, Any]] = []
    retraces: List[Dict[str, Any]] = []
    instants: List[Dict[str, Any]] = []
    counters: Dict[str, Dict[str, float]] = {}
    gauges: Dict[str, Dict[str, float]] = {}
    facts: Dict[str, Any] = {}
    attribution: Optional[Dict[str, Any]] = None
    memory: Optional[Dict[str, Any]] = None
    goodput: Optional[Dict[str, Any]] = None
    health: Dict[str, Any] = {"probes": 0, "nonfinite_steps": 0,
                              "events": {}, "last": {}}
    t0 = t1 = None

    def _stage_sample(name: str, dur: float) -> None:
        row = stages.setdefault(name, {"n": 0, "total_s": 0.0})
        row["n"] += 1
        row["total_s"] += dur

    for ev in events:
        kind = ev.get("kind")
        ts = ev.get("ts")
        if isinstance(ts, (int, float)):
            t0 = ts if t0 is None else min(t0, ts)
            t1 = ts if t1 is None else max(t1, ts)
        if kind == "run_start":
            meta.update(ev.get("meta") or {})
        elif kind == "stage":
            _stage_sample(ev.get("name", "?"), float(ev.get("dur", 0.0)))
        elif kind == "span_end":
            _stage_sample(ev.get("name", "?"), float(ev.get("dur", 0.0)))
        elif kind == "step":
            steps.append(ev)
        elif kind == "compile":
            compiles.append(ev)
        elif kind == "retrace":
            retraces.append(ev)
        elif kind == "event":
            instants.append(ev)
            name = str(ev.get("name", "?"))
            if name.startswith("health/"):
                health["events"][name] = health["events"].get(name, 0) + 1
        elif kind == "health":
            health["probes"] += 1
            health["last"] = {k: v for k, v in ev.items()
                              if k not in ("v", "ts", "pid", "tid",
                                           "kind")}
            if ev.get("nonfinite_grads") or ev.get("nonfinite_params"):
                health["nonfinite_steps"] += 1
        elif kind == "counter":
            row = counters.setdefault(ev.get("name", "?"),
                                      {"n": 0, "total": 0.0, "last": 0.0})
            row["n"] += 1
            row["total"] += float(ev.get("value", 0.0))
            row["last"] = float(ev.get("value", 0.0))
        elif kind == "gauge":
            v = float(ev.get("value", 0.0))
            row = gauges.setdefault(ev.get("name", "?"),
                                    {"n": 0, "min": v, "max": v,
                                     "last": v})
            row["n"] += 1
            row["min"] = min(row["min"], v)
            row["max"] = max(row["max"], v)
            row["last"] = v
        elif kind == "device_facts":
            facts.update(ev.get("facts") or {})
        elif kind == "attribution":
            attribution = {k: v for k, v in ev.items()
                           if k not in ("v", "ts", "pid", "tid", "kind")}
        elif kind == "memory":
            memory = {k: v for k, v in ev.items()
                      if k not in ("v", "ts", "pid", "tid", "kind")}
        elif kind == "goodput":
            goodput = {k: v for k, v in ev.items()
                       if k not in ("v", "ts", "pid", "tid", "kind")}

    for row in stages.values():
        row["mean_s"] = row["total_s"] / row["n"] if row["n"] else 0.0

    durs = [float(s.get("dur", 0.0)) for s in steps]
    # the first step carries XLA compile — percentiles describe the
    # steady state, so it is excluded when there is a steady state
    steady = durs[1:] if len(durs) > 1 else durs
    records = sum(int(s.get("records", 0)) for s in steps)
    step_stats: Dict[str, Any] = {
        "count": len(steps),
        "records": records,
        "total_s": sum(durs),
        "p50_s": _percentile(steady, 50),
        "p95_s": _percentile(steady, 95),
        "mean_s": (sum(steady) / len(steady)) if steady else 0.0,
    }
    if steps and records:
        tp = [float(s["throughput"]) for s in steps if "throughput" in s]
        if tp:
            step_stats["throughput_mean"] = sum(tp) / len(tp)

    mfu = None
    if facts.get("flops_per_step") and facts.get("peak_flops_per_device") \
            and step_stats["p50_s"]:
        mfu = mfu_estimate(facts["flops_per_step"], step_stats["p50_s"],
                           facts["peak_flops_per_device"],
                           int(facts.get("device_count", 1)))

    if goodput is None and events:
        # runs that crashed before end_run never wrote their goodput
        # summary event — fold the raw events instead
        from bigdl_tpu.telemetry import ledger

        goodput = ledger.goodput_from_events(events)

    return {"meta": meta,
            "wall_s": (t1 - t0) if (t0 is not None and t1 is not None)
            else 0.0,
            "stages": stages, "steps": step_stats,
            "compiles": compiles, "retraces": retraces,
            "events": instants, "counters": counters, "gauges": gauges,
            "device_facts": facts, "mfu": mfu, "health": health,
            "attribution": attribution, "memory": memory,
            "goodput": goodput}


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"


def _rel(ev: Dict[str, Any], t0: Optional[float]) -> str:
    ts = ev.get("ts")
    if t0 is None or not isinstance(ts, (int, float)):
        return "      ?"
    return f"{ts - t0:7.2f}"


def format_summary(summary: Dict[str, Any],
                   events: Optional[List[Dict[str, Any]]] = None) -> str:
    """Human-readable report (the CLI's default output)."""
    lines: List[str] = []
    meta = summary["meta"]
    head = ["== telemetry run =="]
    for key in ("device_kind", "device_count", "process_count", "model",
                "parameter_sync"):
        if key in meta:
            head.append(f"{key}={meta[key]}")
    lines.append("  ".join(head))
    lines.append(f"wall {summary['wall_s']:.2f}s")

    st = summary["steps"]
    if st["count"]:
        lines.append("")
        lines.append(f"steps: {st['count']} ({st['records']} records)  "
                     f"p50 {st['p50_s']*1e3:.2f} ms  "
                     f"p95 {st['p95_s']*1e3:.2f} ms  "
                     f"mean {st['mean_s']*1e3:.2f} ms")
        if "throughput_mean" in st:
            lines.append(f"throughput: {st['throughput_mean']:.1f} "
                         f"records/s (mean)")

    gp = summary.get("goodput")
    if gp and gp.get("wall_s"):
        from bigdl_tpu.telemetry.ledger import BADPUT_CATEGORIES

        lines.append("")
        lines.append("-- goodput --")
        lines.append(f"goodput           {gp['goodput_pct']:.1f}%  "
                     f"(compute {gp['compute_s']:.2f}s of "
                     f"{gp['wall_s']:.2f}s wall; badput "
                     f"{gp['badput_s']:.2f}s)")
        badput = gp.get("badput") or {}
        top = sorted(((c, badput[c]) for c in BADPUT_CATEGORIES
                      if badput.get(c, 0.0) > 0), key=lambda kv: -kv[1])
        for cat, s in top[:3]:
            lines.append(f"badput {cat:<10} {s:9.2f} s")
        blame = gp.get("blame") or {}
        if blame.get("cause", "none") != "none":
            lines.append(f"blame             {blame['cause']} — "
                         f"{blame.get('evidence', '')}")

    if summary["stages"]:
        lines.append("")
        lines.append("-- stage time --")
        width = max(len(n) for n in summary["stages"])
        order = sorted(summary["stages"].items(),
                       key=lambda kv: -kv[1]["total_s"])
        for name, row in order:
            lines.append(f"{name:<{width}}  total {row['total_s']:9.4f} s"
                         f"  mean {row['mean_s']*1e3:9.3f} ms"
                         f"  n={int(row['n'])}")

    t0 = None
    if events:
        tss = [e["ts"] for e in events
               if isinstance(e.get("ts"), (int, float))]
        t0 = min(tss) if tss else None
    timeline = [("compile", c) for c in summary["compiles"]]
    timeline += [("retrace", r) for r in summary["retraces"]]
    timeline += [("event", e) for e in summary["events"]]
    timeline.sort(key=lambda kv: kv[1].get("ts", 0.0))
    if timeline:
        lines.append("")
        lines.append("-- compile / retrace / event timeline (t+s) --")
        for tag, ev in timeline:
            if tag == "compile":
                lines.append(f"{_rel(ev, t0)}  compile  "
                             f"{ev.get('name', '?')}  "
                             f"{float(ev.get('dur', 0.0)):.3f}s")
            elif tag == "retrace":
                lines.append(f"{_rel(ev, t0)}  retrace  "
                             f"{ev.get('rule', '?')}  "
                             f"{ev.get('where', '')}: "
                             f"{ev.get('message', '')}")
            else:
                extra = ev.get("error") or ev.get("budget_s") or ""
                lines.append(f"{_rel(ev, t0)}  event    "
                             f"{ev.get('name', '?')}"
                             f"{('  ' + str(extra)) if extra else ''}")

    facts = summary["device_facts"]
    if facts:
        lines.append("")
        lines.append("-- device facts --")
        if "flops_per_step" in facts:
            lines.append(f"flops/step        "
                         f"{facts['flops_per_step']/1e9:.2f} GF")
        if "bytes_accessed" in facts:
            lines.append(f"bytes accessed    "
                         f"{_fmt_bytes(facts['bytes_accessed'])}")
        for key, label in (("donated_bytes", "donated buffers"),
                           ("argument_bytes", "hbm arguments"),
                           ("output_bytes", "hbm outputs"),
                           ("temp_bytes", "hbm temporaries"),
                           ("alias_bytes", "hbm donated-alias"),
                           ("code_bytes", "hbm program"),
                           ("bytes_in_use", "hbm live"),
                           ("peak_bytes_in_use", "hbm live peak"),
                           ("bytes_limit", "hbm capacity")):
            if key in facts:
                lines.append(f"{label:<17} {_fmt_bytes(facts[key])}")
        if summary["mfu"] is not None:
            lines.append(f"MFU (p50 step)    {summary['mfu']*100:.2f}% of "
                         f"{facts.get('device_count', 1)}x "
                         f"{facts.get('peak_flops_per_device', 0)/1e12:.0f}"
                         f" TFLOP/s {facts.get('device_kind', '')}")
        elif "flops_per_step" in facts:
            lines.append("MFU               n/a (no peak-FLOPs table entry"
                         " for this device; set BIGDL_PEAK_FLOPS)")

    if summary["gauges"]:
        lines.append("")
        lines.append("-- gauges --")
        width = max(len(n) for n in summary["gauges"])
        for name, row in sorted(summary["gauges"].items()):
            lines.append(f"{name:<{width}}  last {row['last']:g}  "
                         f"min {row['min']:g}  max {row['max']:g}  "
                         f"n={int(row['n'])}")

    attribution = summary.get("attribution")
    if attribution and attribution.get("rows"):
        rows = [r for r in attribution["rows"] if r.get("flops")]
        rows.sort(key=lambda r: -r["flops"])
        total = attribution.get("total_flops") or \
            sum(r["flops"] for r in rows) or 1.0
        lines.append("")
        lines.append("-- per-module cost (top 10 by flops; full table: "
                     "telemetry attribute) --")
        width = max((len(r["path"]) for r in rows[:10]), default=6)
        for r in rows[:10]:
            lines.append(f"{r['path']:<{width}}  "
                         f"{r['flops']/1e9:9.3f} GF  "
                         f"{r['flops']/total*100:5.1f}%  "
                         f"{r.get('class', '')}")

    memory = summary.get("memory")
    if memory and memory.get("peak_bytes"):
        lines.append("")
        lines.append("-- memory (full table: telemetry attribute "
                     "--memory) --")
        lines.append(f"per-device peak   "
                     f"{_fmt_bytes(memory['peak_bytes'])}  (args "
                     f"{_fmt_bytes(memory.get('args_bytes', 0))} + "
                     f"temp "
                     f"{_fmt_bytes(memory.get('temp_peak_bytes', 0))})")
        cats = memory.get("categories") or {}
        for key, label in (("params", "params"),
                           ("opt_state", "optimizer state"),
                           ("activations_at_peak", "activations@peak"),
                           ("workspace_at_peak", "workspace@peak"),
                           ("donated", "donated (in place)")):
            if cats.get(key):
                lines.append(f"{label:<17} {_fmt_bytes(cats[key])}")
        if memory.get("hbm_limit_bytes"):
            lines.append(f"hbm budget        "
                         f"{_fmt_bytes(memory['hbm_limit_bytes'])}"
                         f"/device")

    health = summary.get("health") or {}
    if health.get("probes"):
        lines.append("")
        lines.append("-- training health --")
        lines.append(f"probed steps      {health['probes']}  "
                     f"(nonfinite: {health['nonfinite_steps']})")
        last = health.get("last") or {}
        if last:
            lines.append(
                "last probe        "
                f"step {last.get('step', '?')}  "
                f"grad_norm {last.get('grad_norm', float('nan')):.4g}  "
                f"update_ratio "
                f"{last.get('update_ratio', float('nan')):.4g}")
        for name, count in sorted(health.get("events", {}).items()):
            lines.append(f"{name:<17} x{count}")
    return "\n".join(lines)


# -- fleet view (multi-host) -------------------------------------------------
def fleet_summarize(runs: List[tuple]) -> Dict[str, Any]:
    """Merge per-process run logs into one fleet view — a thin delegate
    to :func:`bigdl_tpu.telemetry.fleet.fleet_view`, which owns the
    cross-host story (rolling per-host table, step-skew, blame verdict,
    re-incarnation merge by latest run per ``process_index``).  Kept
    here for the original import surface; the legacy ``processes`` /
    ``step_lag`` / ``skew`` keys are unchanged."""
    from bigdl_tpu.telemetry.fleet import fleet_view

    return fleet_view(runs)


def format_fleet(fleet: Dict[str, Any]) -> str:
    from bigdl_tpu.telemetry.fleet import format_fleet_view

    return format_fleet_view(fleet)
