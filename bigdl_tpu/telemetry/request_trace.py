"""Request-level tracing for the serving path (docs/observability.md
"Tracing a request").

The training side of the observability stack answers "which host /
module / collective is slow" (fleet skew blame, attribution, comms);
serving until now answered only in aggregate — qps and p50/p99 per
batch.  When ONE user's request is slow there was no record of *which*
request, *where* the time went, or *why*.  This module is the serving
analogue of the fleet step-skew blame, applied per request:

- every request admitted by :class:`~bigdl_tpu.serving.ModelServer`
  carries a **trace id** (an ``X-Request-Id`` header is accepted and
  propagated; otherwise one is minted) which is echoed on the response,
  so a user's "request abc123 was slow" ticket names its own evidence;
- a :class:`RequestTrace` records the **span timeline** at the points
  the request actually crosses: ingress/parse, queue wait, bucket
  selection + padding, executor dispatch, device compute — and for
  ``/v1/generate``: prefill, every decode iteration the request rode
  (with that iteration's co-batch size) and per-token emit stamps — so
  TTFT and inter-token time decompose into attributable parts;
- traces land in a bounded :class:`TraceStore` with **tail-aware
  retention**: a ring of recent traces PLUS the slowest-k per endpoint
  are always kept, so the p99 exemplar is never evicted by the healthy
  requests that followed it.  Surfaced as ``GET /v1/trace/<id>`` and a
  ``/status.traces`` summary, exported as request-lane Chrome/Perfetto
  waterfalls (``chrome_trace.py`` renders ``request`` events), and
  rendered offline by ``python -m bigdl_tpu.telemetry trace run.jsonl
  [--slowest N]``;
- a **slow-request blame verdict** — the fleet-blame pattern applied
  per request: each trace's attributable components (queue_wait,
  prefill_interference, co_batch_stall, padding, compile) are judged
  against the endpoint's rolling :class:`ComponentBaseline`; compute is
  blamed only when nothing attributable explains the excess — on a
  coalesced batch every co-batched request's wall time degrades
  together, so compute excess alone cannot localize a culprit;
- **SLO burn accounting** — declared budgets (``--slo-p99-ms``,
  ``--slo-ttft-ms``) become live burn-rate gauges
  (observed windowed p99 / budget) on ``/metrics``, fleet columns in
  the FleetWatcher, and a ``bench_serving.py --slo-*`` exit-4 gate,
  with every SLO-violating request carrying its trace id.

Knobs (``utils/config.py``): ``BIGDL_TRACE`` (default on),
``BIGDL_TRACE_RING`` (recent ring size), ``BIGDL_TRACE_SLOWEST``
(always-kept slowest-k per endpoint), ``BIGDL_TRACE_SPANS`` (per-trace
span cap — decode iterations past the cap are tallied, not recorded).
"""

from __future__ import annotations

import collections
import json
import math
import re
import threading
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional, Tuple

from bigdl_tpu import telemetry as _telemetry
from bigdl_tpu.telemetry.report import _percentile

__all__ = ["RequestTrace", "TraceStore", "ComponentBaseline",
           "SLOTracker", "LatencyHistogram", "RequestFold",
           "blame_verdict", "mint_id",
           "valid_id", "stamp_dispatch_spans", "format_trace",
           "request_events",
           "summarize_requests", "trace_main", "LATENCY_BUCKETS_MS",
           "ATTRIBUTABLE", "BLAME_MIN_EXCESS_MS", "BLAME_REL_EXCESS",
           "BASELINE_MIN_SAMPLES", "VIOLATING_KEEP"]

#: fixed log-spaced OpenMetrics histogram bucket bounds (milliseconds):
#: external scrapers compute arbitrary quantiles from these, so the
#: bounds must be STABLE across releases — never derived from traffic
LATENCY_BUCKETS_MS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0)

#: blame components judged BEFORE compute, in this order at ties.  The
#: fleet-blame discipline (telemetry/fleet.py): attributable components
#: first, the residual (compute) only when nothing else explains the
#: excess — on a coalesced batch, a straggling co-batch inflates every
#: rider's wall time equally, so compute excess alone cannot localize.
ATTRIBUTABLE: Tuple[str, ...] = (
    "queue_wait", "prefill_interference", "co_batch_stall", "padding",
    "compile")

#: a component excess must clear BOTH floors to be blamed: an absolute
#: ms floor and a fraction of the endpoint's baseline total
BLAME_MIN_EXCESS_MS = 5.0
BLAME_REL_EXCESS = 0.2
#: verdicts need a baseline: with fewer observed requests than this the
#: endpoint is still warming up and every verdict would be noise
BASELINE_MIN_SAMPLES = 8
#: the SLO ledger keeps the trace ids of this many WORST violators (by
#: budget-overshoot ratio) — bounded so a sustained burn cannot grow it
#: without limit, worst-first so the evidence kept is the evidence that
#: matters
VIOLATING_KEEP = 32

_ID_RE = re.compile(r"^[A-Za-z0-9._\-]{1,128}$")


def mint_id() -> str:
    """A fresh trace id (16 hex chars — short enough for a log line,
    collision-safe for a single server's retention window)."""
    return uuid.uuid4().hex[:16]


def valid_id(trace_id: Optional[str]) -> bool:
    """Whether a client-supplied ``X-Request-Id`` is safe to propagate
    (bounded length, header/log-safe charset) — anything else is
    replaced by a minted id rather than rejected."""
    return bool(trace_id) and _ID_RE.match(trace_id) is not None


class RequestTrace:
    """One request's span timeline + component tally.

    Spans are ``{"name", "t0" (epoch seconds), "ms", ...attrs}`` dicts
    appended in completion order; ``max_spans`` bounds the list (a
    2048-token generation must not hold 2048 span dicts) — spans past
    the cap still land in the COMPONENT tally, so accounting stays
    complete even when the timeline is truncated (``spans_dropped``
    says by how many).
    """

    __slots__ = ("trace_id", "endpoint", "started_at", "spans",
                 "components", "attrs", "status", "reason", "total_ms",
                 "finished_at", "max_spans", "spans_dropped", "iters",
                 "blame", "token_ts")

    def __init__(self, trace_id: str, endpoint: str,
                 started_at: Optional[float] = None,
                 max_spans: int = 512):
        self.trace_id = trace_id
        self.endpoint = endpoint
        self.started_at = time.time() if started_at is None \
            else started_at
        self.spans: List[Dict[str, Any]] = []
        self.components: Dict[str, float] = {}
        self.attrs: Dict[str, Any] = {}
        self.status: Optional[str] = None
        self.reason: Optional[str] = None
        self.total_ms: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.max_spans = max_spans
        self.spans_dropped = 0
        # (ms, co_batch) per decode iteration — the co_batch_stall
        # input; bounded like spans
        self.iters: List[Tuple[float, int]] = []
        self.token_ts: List[float] = []
        self.blame: Optional[Dict[str, Any]] = None

    def add_span(self, name: str, t0: float, ms: float,
             component: Optional[str] = None, **attrs) -> None:
        """Record one span; ``component`` (default: ``name``) is the
        blame bucket its milliseconds tally into (None string keeps it
        out of the tally — purely decorative timeline entries)."""
        if len(self.spans) < self.max_spans:
            entry = {"name": name, "t0": round(t0, 6),
                     "ms": round(ms, 3)}
            entry.update(attrs)
            self.spans.append(entry)
        else:
            self.spans_dropped += 1
        key = name if component is None else component
        if key:
            self.components[key] = self.components.get(key, 0.0) + ms

    def add_component(self, name: str, ms: float) -> None:
        self.components[name] = self.components.get(name, 0.0) + ms

    def note_iter(self, ms: float, co_batch: int) -> None:
        if len(self.iters) < self.max_spans:
            self.iters.append((ms, co_batch))

    def note_token(self, ts: float) -> None:
        if len(self.token_ts) < self.max_spans:
            self.token_ts.append(round(ts, 6))

    def finish(self, status: str = "ok", reason: Optional[str] = None,
               now: Optional[float] = None) -> None:
        self.finished_at = time.time() if now is None else now
        self.status = status
        self.reason = reason
        self.total_ms = (self.finished_at - self.started_at) * 1000.0

    def span_sum_ms(self) -> float:
        return sum(s["ms"] for s in self.spans)

    def to_dict(self) -> Dict[str, Any]:
        # "t0", not "ts": these dicts travel verbatim as `request`
        # event fields, and "ts" is the tracer's base emission stamp
        out = {"trace_id": self.trace_id, "endpoint": self.endpoint,
               "t0": round(self.started_at, 6),
               "ms": round(self.total_ms or 0.0, 3),
               "status": self.status or "open",
               "spans": list(self.spans),
               "components": {k: round(v, 3)
                              for k, v in self.components.items()}}
        if self.reason:
            out["reason"] = self.reason
        if self.spans_dropped:
            out["spans_dropped"] = self.spans_dropped
        if self.token_ts:
            out["token_ts"] = list(self.token_ts)
        if self.blame is not None:
            out["blame"] = self.blame
        out.update(self.attrs)
        return out


class ComponentBaseline:
    """Rolling per-endpoint medians of named values — the "what does a
    healthy request cost" reference the blame verdict judges against.
    Medians (not means) so the slow tail being diagnosed does not drag
    its own baseline after it."""

    def __init__(self, window: int = 256):
        self._window = window
        self._vals: Dict[str, collections.deque] = {}
        self._lock = threading.Lock()
        self.samples = 0

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            dq = self._vals.get(name)
            if dq is None:
                dq = self._vals[name] = collections.deque(
                    maxlen=self._window)
            dq.append(float(value))

    def observe_components(self, components: Dict[str, float]) -> None:
        for name, value in components.items():
            self.observe(name, value)
        with self._lock:
            self.samples += 1

    def median(self, name: str) -> float:
        with self._lock:
            dq = self._vals.get(name)
            if not dq:
                return 0.0
            vals = sorted(dq)
        return vals[len(vals) // 2]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            names = list(self._vals)
        return {n: round(self.median(n), 3) for n in names}


def blame_verdict(components: Dict[str, float],
                  baseline: ComponentBaseline,
                  total_ms: Optional[float] = None
                  ) -> Optional[Dict[str, Any]]:
    """Name the component at fault for one request, judged against the
    endpoint's rolling baseline.  Returns ``{cause, excess_ms, floor_ms,
    baseline_ms}`` or None (healthy / baseline still warming up).

    The floor mirrors the fleet skew blame: an excess must clear both an
    absolute ms floor and a fraction of the baseline total — a 2 ms
    queue blip on a 3 ms request is not a verdict."""
    if baseline.samples < BASELINE_MIN_SAMPLES:
        return None
    base_total = sum(baseline.median(c)
                     for c in ATTRIBUTABLE + ("compute",))
    floor = max(BLAME_MIN_EXCESS_MS, BLAME_REL_EXCESS * base_total)
    best: Optional[Tuple[str, float, float]] = None
    for c in ATTRIBUTABLE:
        got = float(components.get(c, 0.0))
        base = baseline.median(c)
        excess = got - base
        if excess > floor and (best is None or excess > best[1]):
            best = (c, excess, base)
    if best is None:
        got = float(components.get("compute", 0.0))
        base = baseline.median("compute")
        excess = got - base
        if excess > floor:
            best = ("compute", excess, base)
    if best is None:
        return None
    return {"cause": best[0], "excess_ms": round(best[1], 3),
            "floor_ms": round(floor, 3),
            "baseline_ms": round(best[2], 3)}


def stamp_dispatch_spans(trace: RequestTrace, t0_ts: float,
                         wall_ms: float, rec: Dict[str, Any],
                         name: str, default_bucket: int = 0,
                         **attrs) -> None:
    """Tile one coalesced dispatch's wall time onto a rider's trace as
    the (compile, ``name``/compute, padding) split: an in-path compile
    is its own blame component, the bucket rows nobody asked for own
    their share of the remaining device time (padding waste), and the
    rest is compute.  ``rec`` is the executor's dispatch record
    (``compile_ms``/``bucket``/``padded_rows``).  Both the predict
    batcher and the generate prefill stamp through here — the
    attribution formula must not diverge between endpoints."""
    compile_ms = float(rec.get("compile_ms", 0.0) or 0.0)
    bucket = int(rec.get("bucket", default_bucket) or default_bucket)
    padded = int(rec.get("padded_rows", 0) or 0)
    pad_ms = (wall_ms - compile_ms) * padded / bucket if bucket else 0.0
    comp_ms = max(0.0, wall_ms - compile_ms - pad_ms)
    t = t0_ts
    if compile_ms:
        trace.add_span("compile", t, compile_ms, component="compile")
        t += compile_ms / 1000.0
    trace.add_span(name, t, comp_ms, component="compute",
                   bucket=bucket, **attrs)
    if pad_ms > 0:
        trace.add_span("padding", t + comp_ms / 1000.0, pad_ms,
                       component="padding", padded_rows=padded)


class TraceStore:
    """Bounded in-server trace retention: a ring of the ``ring`` most
    recent traces PLUS the slowest-``slowest_k`` per endpoint, which are
    never evicted by recency — the p99 exemplar survives the thousand
    healthy requests that follow it.  Rejection reasons are counted here
    too (the ``/metrics`` per-reason counters)."""

    def __init__(self, ring: int = 512, slowest_k: int = 8):
        self.ring = max(1, int(ring))
        self.slowest_k = max(0, int(slowest_k))
        self._lock = threading.Lock()
        self._by_id: "collections.OrderedDict[str, Dict[str, Any]]" = \
            collections.OrderedDict()
        self._recent: collections.deque = collections.deque()
        # set mirrors of the recency deque and the tail slots, so the
        # per-request eviction checks are O(1) under the lock — this
        # runs on the serving hot path
        self._recent_ids: set = set()
        self._pinned_ids: set = set()
        # endpoint -> ascending [(ms, trace_id)] of the kept slowest
        self._slowest: Dict[str, List[Tuple[float, str]]] = {}
        self.rejections: Dict[str, int] = {}
        self.count = 0
        self.by_endpoint: Dict[str, int] = {}

    def add(self, trace: RequestTrace) -> None:
        doc = trace.to_dict()
        tid = doc["trace_id"]
        ms = float(doc.get("ms") or 0.0)
        endpoint = doc.get("endpoint") or "?"
        with self._lock:
            self.count += 1
            self.by_endpoint[endpoint] = \
                self.by_endpoint.get(endpoint, 0) + 1
            if doc.get("status") == "rejected":
                reason = doc.get("reason") or "unknown"
                self.rejections[reason] = \
                    self.rejections.get(reason, 0) + 1
            if tid in self._by_id:
                # a reused client X-Request-Id: the newest doc wins
                # everywhere — release the old recency + tail slots so
                # one id never holds two of them
                try:
                    self._recent.remove(tid)
                except ValueError:
                    pass
                self._recent_ids.discard(tid)
                self._pinned_ids.discard(tid)
                for slow in self._slowest.values():
                    slow[:] = [(m, t) for m, t in slow if t != tid]
            self._by_id[tid] = doc
            self._recent.append(tid)
            self._recent_ids.add(tid)
            # slowest-k pinning per endpoint (completed requests only —
            # a rejected request is fast by construction and must not
            # occupy a tail slot)
            if self.slowest_k and doc.get("status") != "rejected":
                slow = self._slowest.setdefault(endpoint, [])
                slow.append((ms, tid))
                self._pinned_ids.add(tid)
                slow.sort()
                while len(slow) > self.slowest_k:
                    _, old = slow.pop(0)
                    self._pinned_ids.discard(old)
                    self._evict_if_unpinned(old)
            while len(self._recent) > self.ring:
                old = self._recent.popleft()
                self._recent_ids.discard(old)
                self._evict_if_unpinned(old)

    def _evict_if_unpinned(self, tid: str) -> None:
        if tid in self._recent_ids or tid in self._pinned_ids:
            return
        self._by_id.pop(tid, None)

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            doc = self._by_id.get(trace_id)
            return dict(doc) if doc is not None else None

    def slowest(self, endpoint: Optional[str] = None,
                n: int = 1) -> List[Dict[str, Any]]:
        with self._lock:
            pairs: List[Tuple[float, str]] = []
            for ep, slow in self._slowest.items():
                if endpoint is None or ep == endpoint:
                    pairs.extend(slow)
            pairs.sort(reverse=True)
            return [dict(self._by_id[t]) for _, t in pairs[:n]
                    if t in self._by_id]

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            slowest = {ep: [{"trace_id": t, "ms": m,
                             "blame": (self._by_id.get(t) or {}
                                       ).get("blame")}
                            for m, t in sorted(slow, reverse=True)]
                       for ep, slow in self._slowest.items()}
            return {"count": self.count,
                    "by_endpoint": dict(self.by_endpoint),
                    "kept": len(self._by_id),
                    "ring": self.ring,
                    "slowest_k": self.slowest_k,
                    "slowest": slowest,
                    "rejections": dict(self.rejections)}


class LatencyHistogram:
    """Fixed-bucket latency histogram -> OpenMetrics exposition.  The
    ``le`` bounds are :data:`LATENCY_BUCKETS_MS` (log-spaced, stable),
    so an external scraper can compute ANY quantile — the ring-buffer
    p50/p99 gauges stay for ``tpu_watch.sh``, this is for Prometheus."""

    def __init__(self, buckets: Tuple[float, ...] = LATENCY_BUCKETS_MS):
        self.bounds = tuple(buckets)
        self._counts = [0] * (len(self.bounds) + 1)  # +Inf last
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, ms: float) -> None:
        ms = float(ms)
        if not math.isfinite(ms):
            return
        with self._lock:
            self._sum += ms
            self._count += 1
            for i, bound in enumerate(self.bounds):
                if ms <= bound:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def openmetrics(self, name: str, labels: str = "",
                    type_line: bool = True) -> List[str]:
        """Exposition lines (cumulative ``_bucket`` counts, ``_sum``,
        ``_count``).  ``labels`` is the rendered label body WITHOUT
        braces (e.g. ``model="lenet",endpoint="predict"``).  Pass
        ``type_line=False`` for the second-and-later label sets of one
        metric family — the exposition format allows exactly one
        ``# TYPE`` line per family, and a duplicate makes strict
        scrapers drop the whole scrape."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        lines = [f"# TYPE {name} histogram"] if type_line else []
        sep = "," if labels else ""
        cum = 0
        for bound, c in zip(self.bounds, counts[:-1]):
            cum += c
            lines.append(f'{name}_bucket{{{labels}{sep}le="{bound:g}"}} '
                         f"{cum}")
        lines.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} {total}')
        body = f"{{{labels}}}" if labels else ""
        lines.append(f"{name}_sum{body} {s:g}")
        lines.append(f"{name}_count{body} {total}")
        return lines


class SLOTracker:
    """Declared latency budgets -> live burn rates + violation ledger.

    ``p99_ms`` budgets the request-completion p99; ``ttft_ms`` budgets
    time-to-first-token (generation).  Burn = observed windowed p99 /
    budget — 1.0x means the budget is exactly spent, the dashboards'
    multi-window burn-rate alerts divide these.  Every request OVER its
    budget counts as a violation; the ledger keeps the trace ids of the
    :data:`VIOLATING_KEEP` WORST violators by budget overshoot (not the
    newest — under a sustained burn the early catastrophic requests are
    exactly the evidence worth keeping), so the proof for "we burned
    the budget" is always one ``/v1/trace/<id>`` away."""

    def __init__(self, p99_ms: Optional[float] = None,
                 ttft_ms: Optional[float] = None, window: int = 1024):
        # 0 is not "no budget": a falsy check would silently DISABLE
        # the gate for --slo-p99-ms 0 — reject it loudly instead (burn
        # and severity both divide by the budget, so 0 can't mean
        # "everything violates" either)
        for name, v in (("p99_ms", p99_ms), ("ttft_ms", ttft_ms)):
            if v is not None and not (float(v) > 0):
                raise ValueError(f"SLO {name} budget must be > 0 "
                                 f"(got {v!r}); omit it for no budget")
        self.p99_ms = float(p99_ms) if p99_ms is not None else None
        self.ttft_ms = float(ttft_ms) if ttft_ms is not None else None
        self._lat: collections.deque = collections.deque(maxlen=window)
        self._ttft: collections.deque = collections.deque(maxlen=window)
        self.violations = 0
        # descending by severity (max observed/budget ratio), worst
        # VIOLATING_KEEP kept
        self._violating: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._last_gauges = 0.0

    def active(self) -> bool:
        return self.p99_ms is not None or self.ttft_ms is not None

    def observe(self, ms: Optional[float], trace_id: str,
                ttft_ms: Optional[float] = None) -> List[str]:
        """Record one completed request; returns the budgets it violated
        (``["p99"]``, ``["ttft"]``, both, or ``[]``)."""
        violated: List[str] = []
        with self._lock:
            if ms is not None:
                self._lat.append(float(ms))
                if self.p99_ms is not None and ms > self.p99_ms:
                    violated.append("p99")
            if ttft_ms is not None:
                self._ttft.append(float(ttft_ms))
                if self.ttft_ms is not None and ttft_ms > self.ttft_ms:
                    violated.append("ttft")
            if violated:
                self.violations += 1
                severity = 0.0
                if "p99" in violated and ms is not None:
                    severity = max(severity, ms / self.p99_ms)
                if "ttft" in violated and ttft_ms is not None:
                    severity = max(severity, ttft_ms / self.ttft_ms)
                self._violating.append(
                    {"trace_id": trace_id, "ms": round(ms or 0.0, 3),
                     "ttft_ms": (round(ttft_ms, 3)
                                 if ttft_ms is not None else None),
                     "violated": violated,
                     "severity": round(severity, 3)})
                self._violating.sort(key=lambda v: -v["severity"])
                del self._violating[VIOLATING_KEEP:]
        return violated

    @staticmethod
    def _p99(dq: collections.deque) -> Optional[float]:
        # None (not 0.0) when empty: burn is undefined with no data
        return _percentile(list(dq), 99.0) if dq else None

    def burn(self) -> Dict[str, Any]:
        with self._lock:
            lat_p99 = self._p99(self._lat)
            ttft_p99 = self._p99(self._ttft)
        out: Dict[str, Any] = {}
        if self.p99_ms is not None:
            out["p99"] = {"budget_ms": self.p99_ms,
                          "observed_ms": lat_p99,
                          "burn": round(lat_p99 / self.p99_ms, 3)
                          if lat_p99 is not None else None}
        if self.ttft_ms is not None:
            out["ttft"] = {"budget_ms": self.ttft_ms,
                           "observed_ms": ttft_p99,
                           "burn": round(ttft_p99 / self.ttft_ms, 3)
                           if ttft_p99 is not None else None}
        return out

    def status(self) -> Dict[str, Any]:
        with self._lock:
            violating = list(self._violating)
        return {"budgets": {"p99_ms": self.p99_ms,
                            "ttft_ms": self.ttft_ms},
                "burn": self.burn(), "violations": self.violations,
                "violating": violating}

    def maybe_gauges(self, min_interval_s: float = 1.0) -> None:
        """Publish the burn rates as run-log gauges, rate-limited — the
        FleetWatcher and ``telemetry diff`` read the log, Prometheus
        reads ``/metrics`` directly."""
        if not self.active():
            return
        now = time.monotonic()
        with self._lock:
            if now - self._last_gauges < min_interval_s:
                return
            self._last_gauges = now
        burn = self.burn()
        p99 = (burn.get("p99") or {}).get("burn")
        if p99 is not None:
            _telemetry.gauge("serve/slo_p99_burn", p99)
        ttft = (burn.get("ttft") or {}).get("burn")
        if ttft is not None:
            _telemetry.gauge("serve/slo_ttft_burn", ttft)


class RequestFold:
    """The one fold of run-log ``request`` events shared by every live
    consumer (the MetricsSink and the FleetWatcher's per-host state):
    counts, per-endpoint totals, per-reason rejections, SLO violations,
    and the slowest completed request seen.  One implementation so the
    two views can never diverge on the event shape.  Not locked — each
    consumer folds under its own synchronization."""

    __slots__ = ("count", "by_endpoint", "rejections", "slo_violations",
                 "slowest")

    def __init__(self):
        self.count = 0
        self.by_endpoint: Dict[str, int] = {}
        self.rejections: Dict[str, int] = {}
        self.slo_violations = 0
        self.slowest: Dict[str, Any] = {}

    def fold(self, ev: Dict[str, Any]) -> None:
        self.count += 1
        ep = str(ev.get("endpoint", "?"))
        self.by_endpoint[ep] = self.by_endpoint.get(ep, 0) + 1
        if ev.get("status") == "rejected":
            reason = str(ev.get("reason") or "unknown")
            self.rejections[reason] = self.rejections.get(reason, 0) + 1
        # not elif: a 504 dispatch timeout is BOTH rejected and (with
        # its full wall observed) an SLO violation
        if ev.get("slo_violated"):
            self.slo_violations += 1
        ms = float(ev.get("ms", 0.0) or 0.0)
        if ev.get("status") != "rejected" \
                and ms > float(self.slowest.get("ms", 0.0)):
            self.slowest = {"trace_id": ev.get("trace_id"),
                            "endpoint": ep, "ms": round(ms, 3),
                            "blame": (ev.get("blame") or {}).get("cause")}


# -- offline readers ----------------------------------------------------------
def request_events(events: Iterable[Dict[str, Any]]
                   ) -> List[Dict[str, Any]]:
    """The ``request`` events out of a parsed run log."""
    return [e for e in events if e.get("kind") == "request"]


def summarize_requests(events: Iterable[Dict[str, Any]]
                       ) -> Dict[str, Any]:
    """Aggregate view of a run log's request traces: counts, latency
    percentiles and slowest ids per endpoint, rejection reasons — the
    offline twin of ``/status.traces``."""
    reqs = request_events(events)
    by_ep: Dict[str, List[Dict[str, Any]]] = {}
    rejections: Dict[str, int] = {}
    for r in reqs:
        by_ep.setdefault(r.get("endpoint") or "?", []).append(r)
        if r.get("status") == "rejected":
            reason = r.get("reason") or "unknown"
            rejections[reason] = rejections.get(reason, 0) + 1
    endpoints: Dict[str, Any] = {}
    for ep, rows in sorted(by_ep.items()):
        done = [r for r in rows if r.get("status") != "rejected"]
        lats = [float(r.get("ms") or 0.0) for r in done]

        def pct(p: float) -> Optional[float]:
            return _percentile(lats, p) if lats else None

        slowest = sorted(done, key=lambda r: float(r.get("ms") or 0.0),
                         reverse=True)
        endpoints[ep] = {
            "count": len(rows), "completed": len(done),
            "p50_ms": pct(50.0), "p99_ms": pct(99.0),
            "slowest": [{"trace_id": r.get("trace_id"),
                         "ms": r.get("ms"),
                         "blame": (r.get("blame") or {}).get("cause")}
                        for r in slowest[:5]]}
    return {"requests": len(reqs), "endpoints": endpoints,
            "rejections": rejections}


def format_trace(doc: Dict[str, Any]) -> str:
    """One request's text waterfall — offsets from ingress, one line
    per span, the blame verdict and component tally at the end."""
    t0 = float(doc.get("t0") or doc.get("ts") or 0.0)
    head = (f"== request {doc.get('trace_id')} "
            f"[{doc.get('endpoint')}] {doc.get('ms', 0.0):.1f} ms "
            f"{doc.get('status', '?')}")
    if doc.get("reason"):
        head += f" ({doc['reason']})"
    blame = doc.get("blame") or {}
    if blame.get("cause"):
        head += (f"  blame={blame['cause']}"
                 f"(+{blame.get('excess_ms', 0.0):.1f}ms over baseline "
                 f"{blame.get('baseline_ms', 0.0):.1f}ms)")
    lines = [head + " =="]
    for s in doc.get("spans") or []:
        off = (float(s.get("t0", t0)) - t0) * 1000.0
        extra = {k: v for k, v in s.items()
                 if k not in ("name", "t0", "ms")}
        tail = f"  {extra}" if extra else ""
        lines.append(f"  {off:9.1f}ms  {s.get('name', '?'):<22} "
                     f"{float(s.get('ms', 0.0)):9.2f}ms{tail}")
    if doc.get("spans_dropped"):
        lines.append(f"  ... {doc['spans_dropped']} span(s) past the "
                     f"cap (tallied in components)")
    comp = doc.get("components") or {}
    if comp:
        body = "  ".join(f"{k}={v:.1f}ms" for k, v in
                         sorted(comp.items(), key=lambda kv: -kv[1]))
        lines.append(f"  components: {body}")
    if doc.get("token_ts"):
        lines.append(f"  tokens: {len(doc['token_ts'])} emitted, "
                     f"ttft {doc.get('ttft_ms', '?')} ms")
    return "\n".join(lines)


def trace_main(argv=None) -> int:
    """``python -m bigdl_tpu.telemetry trace run.jsonl [--slowest N]``
    — render request waterfalls offline from a run log's ``request``
    events.  Exit 2 when the log has none."""
    import argparse
    import sys

    from bigdl_tpu.telemetry import schema

    p = argparse.ArgumentParser(
        prog="bigdl_tpu.telemetry trace",
        description="per-request waterfalls from a serving run log "
                    "(kind 'request' events)")
    p.add_argument("run", metavar="run.jsonl")
    p.add_argument("--slowest", type=int, default=3, metavar="N",
                   help="render the N slowest completed requests "
                        "(default %(default)s)")
    p.add_argument("--id", default=None, metavar="TRACE_ID",
                   help="render exactly this trace id instead")
    p.add_argument("--chrome", metavar="OUT.json", default=None,
                   help="also write request-lane Chrome/Perfetto "
                        "waterfalls")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    events, parse_errors = schema.read_events(args.run)
    for e in parse_errors:
        print(f"warning: {args.run}: {e}", file=sys.stderr)
    reqs = request_events(events)
    if not reqs:
        print(f"error: {args.run} has no request events (serving runs "
              f"emit one per request under BIGDL_TRACE, default on)",
              file=sys.stderr)
        return 2
    if args.id is not None:
        picked = [r for r in reqs if r.get("trace_id") == args.id]
        if not picked:
            print(f"error: trace id {args.id!r} not in {args.run}",
                  file=sys.stderr)
            return 2
    else:
        done = [r for r in reqs if r.get("status") != "rejected"]
        picked = sorted(done, key=lambda r: float(r.get("ms") or 0.0),
                        reverse=True)[:max(1, args.slowest)]
    summary = summarize_requests(events)
    if args.json:
        print(json.dumps({"summary": summary, "traces": picked},
                         indent=2, default=str))
    else:
        eps = summary["endpoints"]
        head = ", ".join(
            f"{ep}: {v['count']} (p50 {v['p50_ms']} ms, p99 "
            f"{v['p99_ms']} ms)" for ep, v in eps.items())
        print(f"== {summary['requests']} request(s) — {head} ==")
        if summary["rejections"]:
            print(f"rejections: {summary['rejections']}")
        for doc in picked:
            print()
            print(format_trace(doc))
    if args.chrome:
        from bigdl_tpu.telemetry.chrome_trace import write_chrome_trace

        n = write_chrome_trace(picked, args.chrome)
        print(f"\nchrome trace: {args.chrome} ({n} trace events, "
              f"{len(picked)} request lanes) — open in chrome://tracing "
              f"or https://ui.perfetto.dev",
              file=sys.stderr if args.json else sys.stdout)
    return 0
