"""Chrome ``trace_event`` export: render a telemetry run for
chrome://tracing / Perfetto.

Mapping (one lane per pid/tid, as the tracer emitted them):

- ``span_begin``/``span_end`` -> duration events (``ph: B``/``E``) —
  the pairs are LIFO per thread by construction (schema.validate_run
  asserts it), which is exactly Chrome's nesting contract;
- ``stage`` -> complete events (``ph: X``) ending at their emission ts
  (a stage sample records a duration after the fact);
- ``step`` -> complete events named ``step <n>`` carrying loss /
  records / throughput in args;
- ``compile`` -> complete events on their thread;
- ``counter``/``gauge`` -> counter tracks (``ph: C``);
- ``event``/``retrace`` -> instant events (``ph: i``).
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List

__all__ = ["chrome_trace", "write_chrome_trace"]

_BASE_FIELDS = ("v", "ts", "pid", "tid", "kind", "name", "span",
                "parent", "depth", "dur", "value", "step", "meta",
                "facts", "rule", "message")


def _args(event: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in event.items() if k not in _BASE_FIELDS}


def _us(ts: float) -> float:
    return ts * 1e6


def chrome_trace(events: Iterable[Dict[str, Any]],
                 process_names: Dict[int, str] = None) -> Dict[str, Any]:
    """Build the ``{"traceEvents": [...]}`` object from parsed run
    events.  ``process_names`` labels pid lanes (the multi-log fleet
    export passes ``{os pid: "p<idx> (file)"}`` so Perfetto shows one
    named lane per process)."""
    out: List[Dict[str, Any]] = []
    for pid, name in (process_names or {}).items():
        out.append({"ph": "M", "name": "process_name", "pid": pid,
                    "tid": 0, "ts": 0, "args": {"name": name}})
    for ev in events:
        kind = ev.get("kind")
        pid, tid, ts = ev.get("pid", 0), ev.get("tid", 0), ev.get("ts", 0.0)
        if kind == "span_begin":
            out.append({"ph": "B", "name": ev.get("name", "?"),
                        "pid": pid, "tid": tid, "ts": _us(ts),
                        "args": _args(ev)})
        elif kind == "span_end":
            out.append({"ph": "E", "name": ev.get("name", "?"),
                        "pid": pid, "tid": tid, "ts": _us(ts),
                        "args": _args(ev)})
        elif kind in ("stage", "compile"):
            dur = float(ev.get("dur", 0.0))
            out.append({"ph": "X", "name": ev.get("name", "?"),
                        "cat": kind, "pid": pid, "tid": tid,
                        "ts": _us(ts - dur), "dur": _us(dur),
                        "args": _args(ev)})
        elif kind == "step":
            dur = float(ev.get("dur", 0.0))
            args = _args(ev)
            for key in ("loss", "records", "throughput"):
                if key in ev:
                    args[key] = ev[key]
            out.append({"ph": "X", "name": f"step {ev.get('step', '?')}",
                        "cat": "step", "pid": pid, "tid": tid,
                        "ts": _us(ts - dur), "dur": _us(dur),
                        "args": args})
        elif kind in ("counter", "gauge"):
            name = ev.get("name", "?")
            out.append({"ph": "C", "name": name, "pid": pid, "tid": tid,
                        "ts": _us(ts),
                        "args": {name: ev.get("value", 0.0)}})
        elif kind in ("event", "retrace"):
            name = ev.get("name") or ev.get("rule", "?")
            args = _args(ev)
            if kind == "retrace":
                args["message"] = ev.get("message", "")
            out.append({"ph": "i", "name": name, "cat": kind, "pid": pid,
                        "tid": tid, "ts": _us(ts), "s": "t",
                        "args": args})
        elif kind == "run_start":
            if not process_names:  # explicit lane labels win
                out.append({"ph": "M", "name": "process_name", "pid": pid,
                            "tid": tid, "ts": _us(ts),
                            "args": {"name": "bigdl_tpu run"}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_chrome_trace(events: Iterable[Dict[str, Any]], path: str,
                       process_names: Dict[int, str] = None) -> int:
    """Write the Chrome JSON; returns the number of trace events."""
    trace = chrome_trace(events, process_names=process_names)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(trace, fh)
    return len(trace["traceEvents"])
