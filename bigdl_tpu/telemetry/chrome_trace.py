"""Chrome ``trace_event`` export: render a telemetry run for
chrome://tracing / Perfetto.

Mapping (one lane per pid/tid, as the tracer emitted them):

- ``span_begin``/``span_end`` -> duration events (``ph: B``/``E``) —
  the pairs are LIFO per thread by construction (schema.validate_run
  asserts it), which is exactly Chrome's nesting contract;
- ``stage`` -> complete events (``ph: X``) ending at their emission ts
  (a stage sample records a duration after the fact);
- ``step`` -> complete events named ``step <n>`` carrying loss /
  records / throughput in args;
- ``compile`` -> complete events on their thread;
- ``counter``/``gauge`` -> counter tracks (``ph: C``);
- ``event``/``retrace`` -> instant events (``ph: i``);
- ``request`` (serving request traces, telemetry/request_trace.py) ->
  one NAMED LANE per request (synthetic tid from the trace id, labelled
  ``req <id> [endpoint]``) holding the span waterfall as complete
  events plus per-token instants — the per-request timeline view of a
  serving run;
- badput (telemetry/ledger.py taxonomy) -> one synthetic
  ``badput:<category>`` lane per process per category: compile /
  data_wait / checkpoint / replay / retry_backoff / drain / straggler
  slices are re-rendered as ``X`` events on their own lane so the
  goodput decomposition is visible on the timeline, and for merged
  multi-log exports the incarnation chain is stitched — the gap
  between one incarnation's last event and its successor's first
  becomes ``restart`` (minus any ``backoff`` that supervisor
  ``cluster/restart`` instants declare inside the gap).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Iterable, List

__all__ = ["chrome_trace", "write_chrome_trace"]

_BASE_FIELDS = ("v", "ts", "pid", "tid", "kind", "name", "span",
                "parent", "depth", "dur", "value", "step", "meta",
                "facts", "rule", "message")


def _args(event: Dict[str, Any]) -> Dict[str, Any]:
    return {k: v for k, v in event.items() if k not in _BASE_FIELDS}


def _us(ts: float) -> float:
    return ts * 1e6


def _request_lane(ev: Dict[str, Any]) -> List[Dict[str, Any]]:
    """One serving request trace -> a named lane of span waterfalls.
    The tid is a stable hash of the trace id (each request gets its own
    lane; re-exports are deterministic)."""
    trace_id = str(ev.get("trace_id", "?"))
    pid = ev.get("pid", 0)
    tid = int(hashlib.sha1(trace_id.encode()).hexdigest()[:8], 16)
    label = f"req {trace_id} [{ev.get('endpoint', '?')}]"
    out: List[Dict[str, Any]] = [
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
         "ts": 0, "args": {"name": label}}]
    for span in ev.get("spans") or []:
        args = {k: v for k, v in span.items()
                if k not in ("name", "t0", "ms")}
        args["trace_id"] = trace_id
        out.append({"ph": "X", "name": span.get("name", "?"),
                    "cat": "request", "pid": pid, "tid": tid,
                    "ts": _us(float(span.get("t0", 0.0))),
                    "dur": _us(float(span.get("ms", 0.0)) / 1000.0),
                    "args": args})
    for i, tok_ts in enumerate(ev.get("token_ts") or []):
        out.append({"ph": "i", "name": f"token {i}", "cat": "request",
                    "pid": pid, "tid": tid, "ts": _us(float(tok_ts)),
                    "s": "t", "args": {"trace_id": trace_id}})
    return out


def _badput_tid(pid: int, category: str) -> int:
    return int(hashlib.sha1(
        f"badput:{pid}:{category}".encode()).hexdigest()[:8], 16)


def _badput_events(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Synthetic per-process badput lanes.  Every instrument the
    goodput ledger (telemetry/ledger.py) counts as badput also gets an
    ``X`` slice on its own ``badput:<category>`` lane; incarnation gaps
    in merged multi-log traces are stitched into ``restart``/``backoff``
    slices the same way the ledger does it."""
    spans: List[Any] = []       # (pid, t0, dur, category, args)
    first_last: Dict[int, List[float]] = {}
    proc_of_pid: Dict[int, Any] = {}
    inc_of_pid: Dict[int, Any] = {}
    restarts: List[Any] = []    # (ts, backoff_s)
    supervisor_pids = set()

    for ev in events:
        kind = ev.get("kind")
        pid = ev.get("pid", 0)
        ts = float(ev.get("ts", 0.0))
        fl = first_last.setdefault(pid, [ts, ts])
        fl[0] = min(fl[0], ts)
        fl[1] = max(fl[1], ts)
        if kind == "run_start":
            meta = ev.get("meta") or {}
            if meta.get("role") == "supervisor":
                supervisor_pids.add(pid)
            if "process_index" in meta:
                proc_of_pid[pid] = meta["process_index"]
            if "incarnation" in meta:
                inc_of_pid[pid] = meta["incarnation"]
        elif kind == "compile":
            dur = float(ev.get("dur", 0.0))
            spans.append((pid, ts - dur, dur, "compile",
                          {"name": ev.get("name", "?")}))
        elif kind == "span_end":
            name, dur = ev.get("name", ""), float(ev.get("dur", 0.0))
            if name in ("data_wait", "checkpoint"):
                spans.append((pid, ts - dur, dur, name, {}))
        elif kind == "stage":
            name, dur = ev.get("name", ""), float(ev.get("dur", 0.0))
            if name == "resume/fast_forward":
                spans.append((pid, ts - dur, dur, "replay",
                              {"records": ev.get("records")}))
            elif name == "checkpoint/restore":
                spans.append((pid, ts - dur, dur, "checkpoint",
                              {"source": ev.get("source")}))
        elif kind == "event":
            name = ev.get("name", "")
            if name == "run/retry" and ev.get("backoff_s"):
                dur = float(ev["backoff_s"])
                spans.append((pid, ts - dur, dur, "retry_backoff",
                              {"error": ev.get("error")}))
            elif name == "straggler/timeout" and ev.get("budget_s"):
                dur = float(ev["budget_s"])
                spans.append((pid, ts - dur, dur, "straggler", {}))
            elif name == "cluster/drain" and ev.get("dur"):
                dur = float(ev["dur"])
                spans.append((pid, ts - dur, dur, "drain", {}))
            elif name == "cluster/restart":
                restarts.append(
                    (ts, float(ev.get("backoff_s", 0.0) or 0.0)))
                supervisor_pids.add(pid)

    # Incarnation gaps -> restart/backoff slices on the reborn pid.
    chains: Dict[Any, List[Any]] = {}
    for pid, (first, last) in first_last.items():
        if pid in supervisor_pids:
            continue
        idx = proc_of_pid.get(pid)
        if idx is not None:
            chains.setdefault(idx, []).append((first, last, pid))
    for incs in chains.values():
        incs.sort()
        for (_pf, pl, _ppid), (nf, _nl, npid) in zip(incs, incs[1:]):
            gap = nf - pl
            if gap <= 0:
                continue
            backoff = min(gap, sum(b for t, b in restarts
                                   if pl - 1.0 <= t <= nf + 1.0))
            if gap - backoff > 0:
                spans.append((npid, pl, gap - backoff, "restart",
                              {"incarnation": inc_of_pid.get(npid)}))
            if backoff > 0:
                spans.append((npid, pl + (gap - backoff), backoff,
                              "backoff", {}))

    out: List[Dict[str, Any]] = []
    lanes = set()
    for pid, t0, dur, cat, args in spans:
        if dur <= 0:
            continue
        tid = _badput_tid(pid, cat)
        if (pid, cat) not in lanes:
            lanes.add((pid, cat))
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid, "ts": 0,
                        "args": {"name": f"badput:{cat}"}})
        out.append({"ph": "X", "name": cat, "cat": "badput",
                    "pid": pid, "tid": tid, "ts": _us(t0),
                    "dur": _us(dur),
                    "args": {k: v for k, v in args.items()
                             if v is not None}})
    return out


def chrome_trace(events: Iterable[Dict[str, Any]],
                 process_names: Dict[int, str] = None) -> Dict[str, Any]:
    """Build the ``{"traceEvents": [...]}`` object from parsed run
    events.  ``process_names`` labels pid lanes (the multi-log fleet
    export passes ``{os pid: "p<idx> (file)"}`` so Perfetto shows one
    named lane per process)."""
    events = list(events)
    out: List[Dict[str, Any]] = []
    for pid, name in (process_names or {}).items():
        out.append({"ph": "M", "name": "process_name", "pid": pid,
                    "tid": 0, "ts": 0, "args": {"name": name}})
    out.extend(_badput_events(events))
    for ev in events:
        kind = ev.get("kind")
        pid, tid, ts = ev.get("pid", 0), ev.get("tid", 0), ev.get("ts", 0.0)
        if kind == "span_begin":
            out.append({"ph": "B", "name": ev.get("name", "?"),
                        "pid": pid, "tid": tid, "ts": _us(ts),
                        "args": _args(ev)})
        elif kind == "span_end":
            out.append({"ph": "E", "name": ev.get("name", "?"),
                        "pid": pid, "tid": tid, "ts": _us(ts),
                        "args": _args(ev)})
        elif kind in ("stage", "compile"):
            dur = float(ev.get("dur", 0.0))
            out.append({"ph": "X", "name": ev.get("name", "?"),
                        "cat": kind, "pid": pid, "tid": tid,
                        "ts": _us(ts - dur), "dur": _us(dur),
                        "args": _args(ev)})
        elif kind == "step":
            dur = float(ev.get("dur", 0.0))
            args = _args(ev)
            for key in ("loss", "records", "throughput"):
                if key in ev:
                    args[key] = ev[key]
            out.append({"ph": "X", "name": f"step {ev.get('step', '?')}",
                        "cat": "step", "pid": pid, "tid": tid,
                        "ts": _us(ts - dur), "dur": _us(dur),
                        "args": args})
        elif kind in ("counter", "gauge"):
            name = ev.get("name", "?")
            out.append({"ph": "C", "name": name, "pid": pid, "tid": tid,
                        "ts": _us(ts),
                        "args": {name: ev.get("value", 0.0)}})
        elif kind == "request":
            out.extend(_request_lane(ev))
        elif kind in ("event", "retrace"):
            name = ev.get("name") or ev.get("rule", "?")
            args = _args(ev)
            if kind == "retrace":
                args["message"] = ev.get("message", "")
            out.append({"ph": "i", "name": name, "cat": kind, "pid": pid,
                        "tid": tid, "ts": _us(ts), "s": "t",
                        "args": args})
        elif kind == "run_start":
            if not process_names:  # explicit lane labels win
                out.append({"ph": "M", "name": "process_name", "pid": pid,
                            "tid": tid, "ts": _us(ts),
                            "args": {"name": "bigdl_tpu run"}})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_chrome_trace(events: Iterable[Dict[str, Any]], path: str,
                       process_names: Dict[int, str] = None) -> int:
    """Write the Chrome JSON; returns the number of trace events."""
    trace = chrome_trace(events, process_names=process_names)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(trace, fh)
    return len(trace["traceEvents"])
