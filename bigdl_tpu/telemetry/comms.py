"""Per-collective communication attribution: which collectives a
compiled step runs, how many bytes each moves, over which mesh axes, and
on whose module's behalf.

Why a SECOND walker beside ``attribution.py``: the PR-4 walker parses
the lowered StableHLO, which is the program BEFORE SPMD partitioning —
shardings are still ``custom_call @Sharding`` annotations there, and the
collectives do not exist yet.  The all-reduce/all-gather/reduce-scatter
ops XLA inserts for a sharded step appear only in the **post-partitioning
optimized HLO** (``Compiled.as_text()``), so comms attribution parses
that text instead.  The partitioner carries each op's ``op_name``
metadata through, so the same :func:`attribution.scope_of` unwrapping
names the owning module (``transpose(jvp(x))`` = x's gradient
collective); partitioner-invented collectives with no metadata land in
``(unattributed)``.

Bytes convention (HloCostAnalysis-style "bytes accessed"): operand bytes
plus output bytes, with the output derived from the collective's
semantics —

- ``all-reduce`` / ``collective-permute`` / ``all-to-all``: out == in;
- ``all-gather``: out == in * group_size;
- ``reduce-scatter``: out == in / group_size;

so a 2-device gradient all-reduce of N parameter bytes accounts 2N.
``payload_bytes`` (operand side only) is what actually crosses the
interconnect boundary per device, the number to divide by link bandwidth.

Mesh axes: replica groups (both the explicit ``{{0,1},{2,3}}`` and the
iota ``[2,2]<=[4]`` forms) are matched against the groups each subset of
mesh axes would generate over the mesh's row-major device order — the
order ``jax.sharding.Mesh`` hands XLA as the device assignment — so an
all-reduce over ``replica_groups=[1,2]<=[2]`` on a ``("data",)`` mesh
reports ``axes=("data",)`` and a ZeRO reduce-scatter names the axis its
bytes cross.  Groups matching no axis subset report ``axes=()``.

Timing: the walker is static (bytes are exact at trace time, seconds are
not).  Per-collective wall time comes from an on-demand profiler capture
(``ProfilerControl.arm(..., perfetto=True)`` / ``POST
/profile?steps=N&perfetto=1``): :func:`collective_times_from_trace`
reads the capture's Chrome/Perfetto JSON and sums collective event
durations, and the CLI (``telemetry attribute --comms run.jsonl``)
divides expected bytes by measured seconds to report achieved bytes/s
against ``BIGDL_PEAK_BW`` (``device.peak_bw_per_device``).
"""

from __future__ import annotations

import gzip
import json
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from bigdl_tpu.telemetry.attribution import scope_of

__all__ = ["Collective", "parse_hlo_collectives", "infer_axes",
           "comms_facts", "attribute_comms_train_step",
           "attribute_comms_model", "comms_from_events", "format_comms",
           "collective_times_from_trace", "COLLECTIVE_OPS"]

#: canonical collective opcodes (HLO spelling); ``-start`` async halves
#: count as the op, ``-done`` halves are skipped (same bytes twice).
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all",
                  "collective-broadcast")

_HLO_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
    "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8,
    "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_OPS_ALT = "|".join(COLLECTIVE_OPS)
#: one collective op line of optimized HLO text; group(1) = opcode
#: (base or -start form), group(2) = the operand list inside the parens
_COLL_RE = re.compile(
    rf"=\s*(?:\([^=]*?\)|\S+)\s+({_OPS_ALT})(-start)?\((.*?)\)(?:,|\s*$)")
#: typed operand, e.g. ``f32[100,192]{{1,0}} %dot.5``
_SHAPE_RE = re.compile(r"\b(" + "|".join(_HLO_DTYPE_BYTES) +
                       r")\[([0-9,]*)\]")
_GROUPS_BRACE_RE = re.compile(r"replica_groups=\{(\{[\d,\s]*\}"
                              r"(?:\s*,\s*\{[\d,\s]*\})*)\}")
#: iota form: [groups,size]<=[d0,d1,...] with an optional T(perm)
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{\d+,\d+\},?)+)\}")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")


class Collective:
    """One parsed collective op."""

    __slots__ = ("opcode", "path", "direction", "payload_bytes", "bytes",
                 "group_size", "groups", "axes", "channel_id", "op_name")

    def __init__(self, opcode, path, direction, payload_bytes, nbytes,
                 group_size, groups, axes, channel_id, op_name):
        self.opcode = opcode
        self.path = path
        self.direction = direction
        self.payload_bytes = payload_bytes
        self.bytes = nbytes
        self.group_size = group_size
        self.groups = groups
        self.axes = axes
        self.channel_id = channel_id
        self.op_name = op_name


def _operand_bytes(operand_text: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(operand_text):
        n = 1
        for d in dims.split(","):
            if d.strip().isdigit():
                n *= int(d)
        total += n * _HLO_DTYPE_BYTES[dtype]
    return total


def _parse_groups(line: str) -> Optional[List[Tuple[int, ...]]]:
    """Replica groups out of one HLO line, both spellings, or the
    source/target pairs of a collective-permute (as 2-groups)."""
    m = _GROUPS_IOTA_RE.search(line)
    if m is not None:
        import numpy as np

        n_groups, size = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            ids = ids.transpose([int(p) for p in m.group(4).split(",")])
        ids = ids.reshape(n_groups, size)
        return [tuple(int(x) for x in row) for row in ids]
    m = _GROUPS_BRACE_RE.search(line)
    if m is not None:
        groups = []
        for part in re.findall(r"\{([\d,\s]*)\}", m.group(1)):
            ids = [int(t) for t in part.replace(" ", "").split(",") if t]
            if ids:
                groups.append(tuple(ids))
        return groups or None
    m = _PAIRS_RE.search(line)
    if m is not None:
        return [tuple(int(t) for t in p.split(","))
                for p in re.findall(r"\{(\d+,\d+)\}", m.group(1))]
    return None


def infer_axes(groups: Optional[List[Tuple[int, ...]]],
               axis_names: Sequence[str],
               axis_sizes: Sequence[int]) -> Tuple[str, ...]:
    """The mesh axes a replica-group set spans, or ``()`` when it maps
    onto no axis subset.

    Device ids are positions in the mesh's row-major device order (the
    device assignment ``jax.sharding.Mesh`` hands XLA).  For every
    non-empty subset S of axes, the groups S would generate are "vary
    the S coordinates, fix the rest"; the parsed set is matched against
    each (smallest subset first, so a single-axis collective never
    reports a superset).  ``collective-permute`` pairs match via the
    same rule — a ring over one axis yields pairs whose coordinates
    differ only on that axis."""
    import itertools

    import numpy as np

    if not groups or not axis_names:
        return ()
    sizes = tuple(int(s) for s in axis_sizes)
    n = int(np.prod(sizes)) if sizes else 0
    if n == 0 or any(i >= n for g in groups for i in g):
        return ()
    parsed = {frozenset(g) for g in groups}
    coords = {i: np.unravel_index(i, sizes) for i in range(n)}
    # permute pairs (collective-permute source/target): when every pair
    # connects devices differing on exactly one axis, that axis (or
    # those axes, for several rings) is the answer — pairs are not a
    # partition, so the subset matching below can never name them
    if all(len(g) == 2 for g in groups) and all(
            sum(ca != cb for ca, cb in zip(coords[a], coords[b])) == 1
            for a, b in (tuple(g) for g in groups)):
        differing = set()
        for a, b in (tuple(g) for g in groups):
            differing |= {axis_names[d] for d in range(len(sizes))
                          if coords[a][d] != coords[b][d]}
        if differing:
            return tuple(ax for ax in axis_names if ax in differing)
    ids = np.arange(n).reshape(sizes)
    for r in range(1, len(sizes) + 1):
        for subset in itertools.combinations(range(len(sizes)), r):
            rest = [d for d in range(len(sizes)) if d not in subset]
            moved = ids.transpose(rest + list(subset)).reshape(
                -1, int(np.prod([sizes[d] for d in subset])))
            generated = {frozenset(int(x) for x in row) for row in moved}
            if generated == parsed:
                return tuple(axis_names[d] for d in subset)
    return ()


def parse_hlo_collectives(hlo_text: str,
                          axis_names: Sequence[str] = (),
                          axis_sizes: Sequence[int] = ()
                          ) -> List[Collective]:
    """All collective ops of one optimized-HLO module text."""
    out: List[Collective] = []
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if m is None:
            continue
        opcode = m.group(1)
        payload = _operand_bytes(m.group(3))
        if payload == 0:
            continue
        groups = _parse_groups(line)
        group_size = max((len(g) for g in groups), default=1) \
            if groups else 1
        if opcode == "all-gather":
            nbytes = payload + payload * group_size
        elif opcode == "reduce-scatter":
            nbytes = payload + payload // max(group_size, 1)
        else:
            nbytes = 2 * payload
        name_m = _OPNAME_RE.search(line)
        op_name = name_m.group(1) if name_m else ""
        path, direction = scope_of(op_name) if op_name else ("", "fwd")
        ch = _CHANNEL_RE.search(line)
        axes = infer_axes(groups, axis_names, axis_sizes)
        out.append(Collective(opcode, path, direction, payload, nbytes,
                              group_size, groups, axes,
                              int(ch.group(1)) if ch else None, op_name))
    return out


def _mesh_axes(mesh) -> Tuple[Tuple[str, ...], Tuple[int, ...]]:
    if mesh is None:
        return (), ()
    names = tuple(mesh.axis_names)
    return names, tuple(int(mesh.shape[a]) for a in names)


def _module_fold(colls: List[Collective], model=None
                 ) -> List[Dict[str, Any]]:
    """Per-module rows (owning module = longest module-path prefix of
    the op's scope path; no model = raw scope paths)."""
    module_paths: List[str] = []
    if model is not None:
        module_paths = [p for p, _ in model.named_modules() if p]
    rows: Dict[str, Dict[str, Any]] = {}
    for c in colls:
        owner = None
        if module_paths and c.path:
            for mp in module_paths:
                if (c.path == mp or c.path.startswith(mp + ".")) and \
                        (owner is None or len(mp) > len(owner)):
                    owner = mp
        key = owner if owner is not None else (
            c.path if (c.path and model is None) else "(unattributed)")
        row = rows.setdefault(key, {"path": key, "bytes": 0,
                                    "payload_bytes": 0, "count": 0,
                                    "ops": {}})
        row["bytes"] += c.bytes
        row["payload_bytes"] += c.payload_bytes
        row["count"] += 1
        row["ops"][c.opcode] = row["ops"].get(c.opcode, 0) + 1
    return sorted(rows.values(), key=lambda r: -r["bytes"])


def comms_facts(compiled_or_text, mesh=None, model=None) -> Dict[str, Any]:
    """The full comms payload from a compiled executable (or its HLO
    text): totals, per-axis and per-op breakdowns, per-module rows, and
    the expected per-step seconds when a peak-bandwidth figure is known
    (``BIGDL_PEAK_BW`` / the device table)."""
    text = compiled_or_text if isinstance(compiled_or_text, str) \
        else compiled_or_text.as_text()
    names, sizes = _mesh_axes(mesh)
    colls = parse_hlo_collectives(text, names, sizes)
    by_axis: Dict[str, float] = {}
    by_op: Dict[str, Dict[str, Any]] = {}
    for c in colls:
        axis_key = "+".join(c.axes) if c.axes else "(unknown)"
        by_axis[axis_key] = by_axis.get(axis_key, 0) + c.bytes
        row = by_op.setdefault(c.opcode, {"count": 0, "bytes": 0,
                                          "payload_bytes": 0})
        row["count"] += 1
        row["bytes"] += c.bytes
        row["payload_bytes"] += c.payload_bytes
    out: Dict[str, Any] = {
        "count": len(colls),
        "bytes": int(sum(c.bytes for c in colls)),
        "payload_bytes": int(sum(c.payload_bytes for c in colls)),
        "by_axis": by_axis,
        "by_op": by_op,
        "rows": _module_fold(colls, model),
    }
    try:
        import jax

        from bigdl_tpu.telemetry.device import peak_bw_per_device

        peak = peak_bw_per_device(jax.devices()[0].device_kind)
        if peak:
            out["peak_bw_per_device"] = peak
            out["expected_s"] = out["payload_bytes"] / peak
    except Exception:  # noqa: BLE001 - the bandwidth line is best-effort
        pass
    return out


def attribute_comms_train_step(step, x, y, key=None) -> Dict[str, Any]:
    """Comms attribution of a TrainStep's program: lower + XLA-compile
    (the partitioner must run for the collectives to exist), parse.
    ``x``/``y`` may be ShapeDtypeStructs — only the compile needs to
    happen, never a dispatch."""
    import jax

    from bigdl_tpu.nn.module import stamp_scope_names

    stamp_scope_names(step.model)
    if key is None:
        key = jax.random.key(0)
    compiled = step._build().lower(
        step.params, step.opt_state, step.buffers, x, y, key).compile()
    out = comms_facts(compiled, mesh=step.mesh, model=step.model)
    out["program"] = "train_step"
    return out


def attribute_comms_model(name: str, batch: int = 8, devices: int = 0,
                          sync: str = "allreduce",
                          sparse: Optional[str] = None) -> Dict[str, Any]:
    """Registry-model comms attribution over a fresh ``data``-axis mesh
    spanning ``devices`` devices (0 = all local devices) — CPU-friendly:
    one local XLA compile, no run needed.  ``sparse`` overrides the
    ``BIGDL_SPARSE`` mode for this compile (off | auto | on) — the A/B
    that shows an embedding table's sync bytes collapsing to the
    touched-rows fraction (docs/sparse.md)."""
    import dataclasses

    import jax

    import bigdl_tpu.optim as optim
    from bigdl_tpu.models import registry
    from bigdl_tpu.parallel.mesh import DATA_AXIS, make_mesh
    from bigdl_tpu.parallel.train_step import TrainStep
    from bigdl_tpu.utils.config import get_config, set_config

    prev = get_config()
    if sparse is not None:
        set_config(dataclasses.replace(prev, sparse_sync=sparse))
    try:
        n = devices or len(jax.devices())
        mesh = make_mesh((n,), (DATA_AXIS,), devices=jax.devices()[:n])
        model = registry.build_model(name)
        spec = registry.input_spec(name, batch)
        pieces = registry.train_pieces(name, batch)
        if pieces is None:
            raise ValueError(f"registry model {name!r} has no training "
                             f"pieces — comms attribution needs a train "
                             f"step")
        criterion, target_spec = pieces
        step = TrainStep(model, criterion,
                         optim.SGD(learning_rate=0.01, momentum=0.9),
                         mesh=mesh, parameter_sync=sync)
        out = attribute_comms_train_step(step, spec, target_spec)
    finally:
        if sparse is not None:
            set_config(prev)
    out["model"] = name
    out["batch"] = batch
    out["mesh"] = {"devices": n, "sync": sync}
    if sparse is not None:
        out["sparse"] = sparse
    return out


def comms_from_events(events: List[Dict[str, Any]]
                      ) -> Optional[Dict[str, Any]]:
    """The last ``comms`` event of a run log (the read-from-artifact CLI
    path), or None."""
    found = None
    for ev in events:
        if ev.get("kind") == "comms":
            found = ev
    if found is None:
        return None
    return {k: v for k, v in found.items()
            if k not in ("v", "ts", "pid", "tid", "kind")}


# -- measured wall time from a profiler capture ------------------------------
_TRACE_TOKENS = {
    "all-reduce": ("all-reduce", "allreduce", "all_reduce"),
    "all-gather": ("all-gather", "allgather", "all_gather"),
    "reduce-scatter": ("reduce-scatter", "reducescatter", "reduce_scatter"),
    "collective-permute": ("collective-permute", "collectivepermute",
                           "collective_permute"),
    "all-to-all": ("all-to-all", "alltoall", "all_to_all"),
}


def collective_times_from_trace(trace_dir: str) -> Dict[str, float]:
    """Summed collective wall seconds per opcode out of a profiler
    capture's Chrome/Perfetto JSON (``ProfilerControl.arm(...,
    perfetto=True)`` writes one).  Returns ``{}`` when the capture holds
    no parseable trace — TPU captures carry device lanes with the
    collective ops named; plain CPU captures may not."""
    out: Dict[str, float] = {}
    paths: List[str] = []
    perfetto: List[str] = []
    for root, _dirs, files in os.walk(trace_dir):
        for f in files:
            if f in ("perfetto_trace.json.gz", "perfetto_trace.json"):
                perfetto.append(os.path.join(root, f))
            elif f.endswith((".trace.json.gz", ".trace.json")):
                paths.append(os.path.join(root, f))
    # a perfetto-enabled capture may leave BOTH spellings describing the
    # SAME events — summing across them would double every duration, so
    # the perfetto file wins outright when present
    if perfetto:
        paths = perfetto
    for path in paths:
        try:
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rt", encoding="utf-8",
                        errors="replace") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") != "X" or not ev.get("dur"):
                continue
            name = str(ev.get("name", "")).lower()
            for op, tokens in _TRACE_TOKENS.items():
                if any(t in name for t in tokens):
                    out[op] = out.get(op, 0.0) + float(ev["dur"]) / 1e6
                    break
    return out


# -- rendering ---------------------------------------------------------------
def _fmt_bytes(n: float) -> str:
    for div, unit in ((1 << 30, "GiB"), (1 << 20, "MiB"), (1 << 10, "KiB")):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{int(n)} B"


def format_comms(result: Dict[str, Any]) -> str:
    """Human-readable comms attribution report."""
    lines: List[str] = []
    head = ["== per-collective comms attribution =="]
    for key in ("model", "program", "batch"):
        if key in result:
            head.append(f"{key}={result[key]}")
    lines.append("  ".join(head))
    if not result.get("count"):
        lines.append("no collectives in this program (single device, or "
                     "nothing sharded)")
        return "\n".join(lines)
    lines.append(f"collectives: {result['count']}   bytes accessed "
                 f"{_fmt_bytes(result['bytes'])}   payload "
                 f"{_fmt_bytes(result['payload_bytes'])}")
    by_op = result.get("by_op") or {}
    if by_op:
        lines.append("")
        lines.append("-- by collective --")
        width = max(len(op) for op in by_op)
        for op, row in sorted(by_op.items(), key=lambda kv: -kv[1]["bytes"]):
            lines.append(f"{op:<{width}}  x{row['count']:<3} "
                         f"{_fmt_bytes(row['bytes']):>11}  "
                         f"(payload {_fmt_bytes(row['payload_bytes'])})")
    by_axis = result.get("by_axis") or {}
    if by_axis:
        lines.append("")
        lines.append("-- by mesh axis --")
        width = max(len(a) for a in by_axis)
        for axis, nbytes in sorted(by_axis.items(), key=lambda kv: -kv[1]):
            lines.append(f"{axis:<{width}}  {_fmt_bytes(nbytes):>11}")
    rows = result.get("rows") or []
    if rows:
        lines.append("")
        lines.append("-- by module --")
        width = max(len(r["path"]) for r in rows)
        total = result.get("bytes") or 1
        for r in rows:
            ops = ",".join(f"{op}x{n}" for op, n in
                           sorted(r.get("ops", {}).items()))
            lines.append(f"{r['path']:<{width}}  "
                         f"{_fmt_bytes(r['bytes']):>11}  "
                         f"{r['bytes'] / total * 100:5.1f}%  {ops}")
    measured = result.get("measured_s")
    expected = result.get("expected_s")
    peak = result.get("peak_bw_per_device")
    if measured:
        achieved = result.get("payload_bytes", 0) / measured
        line = (f"measured collective time {measured * 1e3:.3f} ms/step  "
                f"-> achieved {_fmt_bytes(achieved)}/s")
        if peak:
            line += f"  ({achieved / peak * 100:.1f}% of peak " \
                    f"{_fmt_bytes(peak)}/s)"
        lines.append("")
        lines.append(line)
    elif expected is not None and peak:
        lines.append("")
        lines.append(f"expected {expected * 1e3:.3f} ms/step at peak "
                     f"{_fmt_bytes(peak)}/s (BIGDL_PEAK_BW; no measured "
                     f"capture — arm one with POST /profile?steps=N"
                     f"&perfetto=1)")
    return "\n".join(lines)
