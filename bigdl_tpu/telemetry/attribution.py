"""Per-module cost attribution: where inside the model the FLOPs, bytes,
and ops of a compiled step go.

The nn layer stamps every module with its registration key
(``nn.stamp_scope_names``, done by TrainStep/EvalStep at build time), so
``Module.forward`` runs each layer under ``jax.named_scope(<key>)`` and
the lowered program's op locations carry the module-tree path::

    loc("jit(step)/jit(main)/jvp(4)/conv_general_dilated")          # fwd
    loc("jit(step)/jit(main)/transpose(jvp(4))/conv_general_dilated")  # bwd

This module parses the lowered StableHLO text (``Lowered.compiler_ir()``
printed with debug info — a re-lower of the already-traced step, NO XLA
compile), groups ops by their scope frames (autodiff wrappers
``jvp(...)``/``transpose(...)`` unwrap onto the same module, tagged
forward/backward; function frames like ``jit(log_softmax)`` fall out to
the unattributed bucket), and estimates per-op cost
HloCostAnalysis-style:

- ``dot_general``: ``2 * out_elems * prod(contracted dims)``;
- ``convolution``: ``2 * out_elems * prod(non-output kernel dims)``;
- elementwise arithmetic: ``out_elems`` flops; transcendentals
  (tanh/exp/...) are tracked in their own column, as XLA does;
- ``reduce``/``reduce_window``: one flop per folded element;
- data movement (reshape/broadcast/slice/...): bytes only.

Bytes are pre-fusion operand+output traffic — an upper bound on real
HBM movement (fusion keeps intermediates in registers), useful for
*ranking* modules, not billing.  The report always prints its FLOPs
total next to XLA's own ``cost_analysis()`` so the estimate's fidelity
is visible.

Scopes are trace-time metadata only — they never enter jit cache keys,
so enabling them causes zero retraces (``tests/test_attribution.py``
asserts this).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["lowered_text", "parse_lowered_text", "aggregate",
           "module_rows", "attribute_lowered", "attribute_train_step",
           "attribute_forward", "attribute_model", "format_attribution",
           "rows_from_events", "scope_of"]

_DTYPE_BYTES = {
    "i1": 1, "i4": 1, "ui4": 1, "i8": 1, "ui8": 1,
    "i16": 2, "ui16": 2, "f16": 2, "bf16": 2,
    "i32": 4, "ui32": 4, "f32": 4,
    "i64": 8, "ui64": 8, "f64": 8,
}

#: pure data movement / bookkeeping — no flops.
_NO_FLOPS = {
    "constant", "iota", "broadcast_in_dim", "broadcast", "reshape",
    "transpose", "slice", "concatenate", "pad", "gather", "convert",
    "bitcast_convert", "reverse", "dynamic_slice", "dynamic_update_slice",
    "rng_bit_generator", "optimization_barrier", "return", "call",
    "custom_call", "tuple", "get_tuple_element", "real", "imag",
    "all_gather", "all_reduce", "reduce_scatter", "collective_permute",
    "all_to_all", "partition_id", "replica_id", "create_token",
    "after_all", "composite", "while", "if", "case",
}

_TRANSCENDENTAL = {
    "exponential", "exponential_minus_one", "log", "log_plus_one",
    "logistic", "tanh", "sqrt", "rsqrt", "cbrt", "power", "sine",
    "cosine", "tan", "tanh_approx", "atan2", "erf", "erf_inv",
}

# "%12 = stablehlo.add ..." / '%12 = "stablehlo.reduce_window"(...'
_OP_RE = re.compile(
    r"^\s*%[\w#]+(?::\d+)?\s*=\s*\"?(?:stablehlo|chlo|mhlo|func)\.([\w]+)\"?")
_LOC_REF_RE = re.compile(r"loc\((#loc\d*)\)\s*$")
_LOC_DEF_RE = re.compile(r"^(#loc\d*)\s*=\s*loc\((.*)\)\s*$")
_LOC_NAME_RE = re.compile(r'^"([^"]*)"')
_LOC_CHILD_RE = re.compile(r"(#loc\d*)")
_TENSOR_RE = re.compile(r"tensor<([^>]*)>")
_CONTRACT_RE = re.compile(r"contracting_dims\s*=\s*\[([0-9,\s]*)\]")
_DIMNUM_RE = re.compile(r"dim_numbers\s*=\s*\[([\w,\s]*)\]"
                        r"\s*x\s*\[([\w,\s]*)\]\s*->\s*\[([\w,\s]*)\]")
_STRIDE_RE = re.compile(r"stride\s*=\s*\[([0-9,\s]*)\]")
_PAD_RE = re.compile(r"pad\s*=\s*\[\[(.*?)\]\]")
_LHS_DIL_RE = re.compile(r"lhs_dilate\s*=\s*\[([0-9,\s]*)\]")
_RHS_DIL_RE = re.compile(r"rhs_dilate\s*=\s*\[([0-9,\s]*)\]")
_WINDOW_DIMS_RE = re.compile(r"window_dimensions\s*=\s*(?:array<i64:"
                             r"\s*([0-9,\s]*)>|\[([0-9,\s]*)\])")
# autodiff / transform wrappers that carry the scope through: unwrap and
# keep the payload.  transpose() marks the backward pass.
_UNWRAP_RE = re.compile(
    r"^(jvp|vjp|transpose|remat|rematted_computation|checkpoint|"
    r"custom_jvp|custom_vjp|vmap|pmap)\((.*)\)$")
# anything else of the form name(...) is a function-call frame
# (jit(log_softmax), ...), not a module scope.
_CALL_FRAME_RE = re.compile(r"^[\w.\-]+\(.*\)$")


def lowered_text(lowered) -> str:
    """StableHLO of a ``jax.stages.Lowered`` printed WITH location info
    (``Lowered.as_text()`` drops it); big constants elided."""
    return lowered.compiler_ir().operation.get_asm(
        enable_debug_info=True, large_elements_limit=16)


def scope_of(op_name: str) -> Tuple[str, str]:
    """(module path, direction) out of one op location name.

    Path frames join with ``.`` so they compare directly against
    ``named_parameters`` paths; direction is ``"fwd"`` or ``"bwd"``
    (``transpose(...)`` anywhere marks the backward pass).  An op with
    no module frame returns path ``""``."""
    frames = op_name.split("/")
    kept: List[str] = []
    bwd = False
    skip_region = 0
    for frame in frames[:-1] if len(frames) > 1 else []:
        while True:
            m = _UNWRAP_RE.match(frame)
            if m is None:
                break
            if m.group(1) == "transpose":
                bwd = True
            frame = m.group(2)
        if skip_region:
            # the region frame following a control-flow op ("body"/
            # "cond" after "while") is loop structure, not a module
            # scope — even when a module is ALSO registered as "body"
            # (ScanLayers), the structural frame is always the one
            # directly after "while"
            skip_region -= 1
            continue
        if frame == "while":
            # lax.scan/while_loop lower their body under "while/body"
            # (condition under "while/cond"): scan-over-layers scopes
            # must fold onto the module tree, not vanish into the loop
            skip_region = 1
            continue
        if not frame or _CALL_FRAME_RE.match(frame) or frame == "pjit":
            continue  # jit(...)/pjit function frames, not module scopes
        if frame in ("checkpoint", "rematted_computation", "remat"):
            # jax.checkpoint's recompute-in-backward inserts these as
            # BARE frames (".../transpose(jvp(2))/checkpoint/
            # rematted_computation/0/fc1/..."): transform structure,
            # not module scopes — a Remat-wrapped block's ops must fold
            # onto the block's own tree path
            continue
        kept.append(frame)
    return ".".join(kept), ("bwd" if bwd else "fwd")


class OpCost:
    """One parsed op's attributed cost."""

    __slots__ = ("path", "direction", "opcode", "flops",
                 "transcendentals", "bytes")

    def __init__(self, path, direction, opcode, flops, transcendentals,
                 nbytes):
        self.path = path
        self.direction = direction
        self.opcode = opcode
        self.flops = flops
        self.transcendentals = transcendentals
        self.bytes = nbytes


def _type_cost(types_text: str) -> Tuple[int, int]:
    """(elements, bytes) summed over every ``tensor<...>`` in the text."""
    elems = total = 0
    for inner in _TENSOR_RE.findall(types_text):
        parts = inner.split("x")
        dtype = parts[-1]
        n = 1
        for d in parts[:-1]:
            if d.isdigit():
                n *= int(d)
        elems += n
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return elems, total


def _split_signature(sig_text: str) -> Tuple[str, str]:
    """Split an op's trailing type signature into (operand text, result
    text).  Handles both the function form ``: (A, B) -> C`` and the
    elementwise shorthand ``: C`` (operands share the result type)."""
    if "->" in sig_text:
        lhs, rhs = sig_text.rsplit("->", 1)
        return lhs, rhs
    return "", sig_text


def _dims(inner: str) -> List[int]:
    return [int(d) for d in inner.split("x")[:-1] if d.isdigit()]


def _ints(text: str) -> List[int]:
    return [int(t) for t in text.replace(" ", "").split(",") if t]


def _conv_flops(head: str, sig: str, out_elems: int) -> float:
    """XLA HloCostAnalysis convolution accounting: 2 FLOPs per VALID
    (output position, kernel position) pair — window positions that read
    only padding (or dilation holes) do not count, which is what makes a
    full-padded gradient conv cost the same as its forward conv."""
    m = _DIMNUM_RE.search(head)
    operand_text, result_text = _split_signature(sig)
    operand_types = _TENSOR_RE.findall(operand_text)
    if m is None or len(operand_types) < 2:
        return 0.0
    in_labels = [t.strip() for t in m.group(1).split(",")]
    k_labels = [t.strip() for t in m.group(2).split(",")]
    out_labels = [t.strip() for t in m.group(3).split(",")]
    in_dims = _dims(operand_types[0])
    k_dims = _dims(operand_types[1])
    out_types = _TENSOR_RE.findall(result_text)
    out_dims = _dims(out_types[0]) if out_types else []
    if len(in_dims) != len(in_labels) or len(k_dims) != len(k_labels) \
            or len(out_dims) != len(out_labels):
        return 0.0
    spatial = sorted(lbl for lbl in k_labels if lbl.isdigit())
    strides = _ints(_STRIDE_RE.search(head).group(1)) \
        if _STRIDE_RE.search(head) else []
    lhs_dil = _ints(_LHS_DIL_RE.search(head).group(1)) \
        if _LHS_DIL_RE.search(head) else []
    rhs_dil = _ints(_RHS_DIL_RE.search(head).group(1)) \
        if _RHS_DIL_RE.search(head) else []
    pad_m = _PAD_RE.search(head)
    pads = [_ints(p.strip(" []")) for p in pad_m.group(1).split("],")] \
        if pad_m else []

    valid = 1
    for d, lbl in enumerate(spatial):
        size_in = in_dims[in_labels.index(lbl)]
        size_k = k_dims[k_labels.index(lbl)]
        size_out = out_dims[out_labels.index(lbl)]
        stride = strides[d] if d < len(strides) else 1
        ld = lhs_dil[d] if d < len(lhs_dil) else 1
        rd = rhs_dil[d] if d < len(rhs_dil) else 1
        pad_lo = pads[d][0] if d < len(pads) and pads[d] else 0
        padded_in = (size_in - 1) * ld + 1 if size_in > 0 else 0
        count = 0
        for k in range(size_k):
            for o in range(size_out):
                i = o * stride + k * rd - pad_lo
                if 0 <= i < padded_in and i % ld == 0:
                    count += 1
        valid *= count
    k_in = 1
    for pos, lbl in enumerate(k_labels):
        if lbl == "i":
            k_in *= k_dims[pos]
    n_spatial_out = 1
    for lbl in spatial:
        n_spatial_out *= max(out_dims[out_labels.index(lbl)], 1)
    batch_feature = out_elems // max(n_spatial_out, 1)
    return 2.0 * batch_feature * k_in * valid


def _instr_flops(opcode: str, head: str, sig: str,
                 out_elems: int) -> Tuple[float, float]:
    """(flops, transcendentals), HloCostAnalysis conventions (fma = 2
    flops; transcendentals counted apart).  ``head`` is the op's first
    physical line (attributes live there), ``sig`` its type signature."""
    if opcode in _NO_FLOPS:
        return 0.0, 0.0
    if opcode in _TRANSCENDENTAL:
        return 0.0, float(out_elems)
    operand_text, _ = _split_signature(sig)
    operand_types = _TENSOR_RE.findall(operand_text)
    if opcode == "dot_general":
        m = _CONTRACT_RE.search(head)
        if m is None or not operand_types:
            return 0.0, 0.0
        lhs_dims = [d for d in operand_types[0].split("x")[:-1] if d.isdigit()]
        k = 1
        for idx in m.group(1).replace(" ", "").split(","):
            if idx and int(idx) < len(lhs_dims):
                k *= int(lhs_dims[int(idx)])
        return 2.0 * out_elems * k, 0.0
    if opcode == "convolution":
        return _conv_flops(head, sig, out_elems), 0.0
    if opcode == "reduce":
        if not operand_types:
            return 0.0, 0.0
        in_elems = _type_cost(f"tensor<{operand_types[0]}>")[0]
        return float(max(in_elems - out_elems, 0)), 0.0
    if opcode in ("reduce_window", "select_and_scatter"):
        m = _WINDOW_DIMS_RE.search(head)
        if m is not None:
            win = 1
            for d in (m.group(1) or m.group(2) or "").replace(
                    " ", "").split(","):
                if d:
                    win *= int(d)
            return float(out_elems * max(win - 1, 1)), 0.0
        return float(out_elems), 0.0
    if opcode == "clamp":
        return 2.0 * out_elems, 0.0
    # default: elementwise arithmetic / comparison / select
    return float(out_elems), 0.0


def _resolve_locs(loc_defs: Dict[str, str]) -> Dict[str, str]:
    """#locN -> op-name string.  A def is either a quoted name
    (possibly wrapping a child loc) or a callsite/file loc — those
    resolve through their first child reference."""
    memo: Dict[str, str] = {}

    def resolve(ref: str, depth: int = 0) -> str:
        if ref in memo:
            return memo[ref]
        if depth > 8:
            return ""
        body = loc_defs.get(ref, "")
        m = _LOC_NAME_RE.match(body)
        if m is not None:
            memo[ref] = m.group(1)
            return m.group(1)
        child = _LOC_CHILD_RE.search(body)
        out = resolve(child.group(1), depth + 1) if child else ""
        memo[ref] = out
        return out

    return {ref: resolve(ref) for ref in loc_defs}


def parse_lowered_text(text: str) -> List[OpCost]:
    """Parse debug-info StableHLO (:func:`lowered_text`) into
    per-op attributed costs.  Region ops (reduce_window, ...) keep their
    attribute head line; their types + loc arrive on the closing line."""
    lines = text.splitlines()
    loc_defs: Dict[str, str] = {}
    for line in lines:
        m = _LOC_DEF_RE.match(line.strip())
        if m is not None:
            loc_defs[m.group(1)] = m.group(2)
    locs = _resolve_locs(loc_defs)

    raw: List[Tuple[str, str, str, str]] = []  # opcode, head, sig, locref
    pending: List[Tuple[str, str]] = []  # (opcode, head) of open region ops

    def sig_and_loc(line: str) -> Tuple[str, Optional[str]]:
        m = _LOC_REF_RE.search(line)
        ref = m.group(1) if m else None
        body = line[: m.start()] if m else line
        idx = body.rfind(" : ")
        return (body[idx + 3:] if idx >= 0 else ""), ref

    for line in lines:
        stripped = line.rstrip()
        m = _OP_RE.match(stripped)
        if m is not None:
            opcode = m.group(1)
            if "loc(" in stripped and " : " in stripped:
                sig, ref = sig_and_loc(stripped)
                raw.append((opcode, stripped, sig, ref))
            else:
                pending.append((opcode, stripped))  # region op opens here
        elif pending and stripped.lstrip().startswith("})") \
                and "loc(" in stripped:
            opcode, head = pending.pop()
            sig, ref = sig_and_loc(stripped)
            raw.append((opcode, head, sig, ref))

    ops: List[OpCost] = []
    for opcode, head, sig, ref in raw:
        if opcode in ("constant", "return", "func", "call"):
            continue
        _, result_text = _split_signature(sig)
        out_elems, out_bytes = _type_cost(result_text)
        operand_text, _ = _split_signature(sig)
        _, operand_bytes = _type_cost(operand_text)
        name = locs.get(ref, "") if ref else ""
        path, direction = scope_of(name)
        flops, trans = _instr_flops(opcode, head, sig, out_elems)
        ops.append(OpCost(path, direction, opcode, flops, trans,
                          out_bytes + operand_bytes))
    return ops


def aggregate(ops: List[OpCost]) -> Dict[str, Dict[str, Any]]:
    """Group parsed ops by scope path."""
    rows: Dict[str, Dict[str, Any]] = {}
    for op in ops:
        row = rows.setdefault(op.path, {
            "flops": 0.0, "flops_fwd": 0.0, "flops_bwd": 0.0,
            "transcendentals": 0.0, "bytes": 0.0, "ops": 0})
        row["flops"] += op.flops
        row[f"flops_{op.direction}"] += op.flops
        row["transcendentals"] += op.transcendentals
        row["bytes"] += op.bytes
        row["ops"] += 1
    return rows


def _module_info(model) -> Dict[str, Dict[str, Any]]:
    """path -> {class, params, param_bytes} for every module of a model
    (own params only — containers aggregate via the rollup)."""
    import numpy as np

    info: Dict[str, Dict[str, Any]] = {}
    for name, m in model.named_modules():
        own = m.__dict__["_params"]
        n = sum(int(np.prod(p.shape)) if getattr(p, "ndim", 0) else 1
                for p in own.values())
        b = sum(int(getattr(p, "nbytes", 0)) for p in own.values())
        info[name] = {"class": type(m).__name__, "params": n,
                      "param_bytes": b}
    return info


def module_rows(scope_rows: Dict[str, Dict[str, Any]],
                model=None) -> List[Dict[str, Any]]:
    """Fold scope rows onto the module tree.

    With a model: one row per module, in ``named_modules`` order, with
    CUMULATIVE cost (own scope + every scope underneath it) plus own
    param count/bytes; scope paths matching no module land in the
    ``(unattributed)`` row (loss/optimizer/collectives).  Without a
    model: one row per raw scope path."""
    def blank(path, cls=""):
        return {"path": path, "class": cls, "flops": 0.0,
                "flops_fwd": 0.0, "flops_bwd": 0.0,
                "transcendentals": 0.0, "bytes": 0.0, "ops": 0,
                "params": 0, "param_bytes": 0}

    if model is None:
        out = []
        for path in sorted(scope_rows):
            row = blank(path or "(unattributed)")
            row.update(scope_rows[path])
            out.append(row)
        return out

    info = _module_info(model)
    module_paths = [p for p in info if p]
    rows = {path: blank(path, info[path]["class"]) for path in info if path}
    unattributed = blank("(unattributed)")
    for spath, srow in scope_rows.items():
        # longest module path that prefixes the scope path on a dot
        # boundary (a module's internal named_scopes roll up to it)
        best = None
        for mp in module_paths:
            if spath == mp or spath.startswith(mp + "."):
                if best is None or len(mp) > len(best):
                    best = mp
        if best is None:
            targets = [unattributed]
        else:
            # cumulative: the owning module and every ancestor
            parts = best.split(".")
            targets = [rows[".".join(parts[:i + 1])]
                       for i in range(len(parts))]
        for row in targets:
            for key in ("flops", "flops_fwd", "flops_bwd",
                        "transcendentals", "bytes"):
                row[key] += srow.get(key, 0.0)
            row["ops"] += srow.get("ops", 0)
    for path, row in rows.items():
        row["params"] = info[path]["params"]
        row["param_bytes"] = info[path]["param_bytes"]
    ordered = [rows[name] for name, _ in model.named_modules() if name]
    if unattributed["ops"]:
        ordered.append(unattributed)
    return ordered


# -- building attribution from live objects ---------------------------------
def attribute_lowered(lowered, model=None) -> Dict[str, Any]:
    """Full attribution payload from a ``jax.stages.Lowered``:
    per-module rows + totals + XLA's own cost-analysis total for
    fidelity.  No XLA compile — text extraction and parsing only."""
    from bigdl_tpu.telemetry.device import normalize_cost_analysis

    ops = parse_lowered_text(lowered_text(lowered))
    rows = module_rows(aggregate(ops), model)
    out: Dict[str, Any] = {
        "rows": rows,
        "total_flops": sum(op.flops for op in ops),
        "total_transcendentals": sum(op.transcendentals for op in ops),
        "total_bytes": sum(op.bytes for op in ops),
    }
    try:
        cost = normalize_cost_analysis(lowered.cost_analysis())
        if cost.get("flops"):
            out["cost_flops"] = float(cost["flops"])
        if cost.get("bytes accessed"):
            out["cost_bytes"] = float(cost["bytes accessed"])
    except Exception:  # noqa: BLE001 - fidelity line is best-effort
        pass
    return out


def attribute_train_step(step, x, y, key=None) -> Dict[str, Any]:
    """Attribute a TrainStep's program.  ``x``/``y`` may be concrete
    arrays or ``jax.ShapeDtypeStruct`` specs — lowering needs only
    abstract values."""
    import jax

    from bigdl_tpu.nn.module import stamp_scope_names

    stamp_scope_names(step.model)
    if key is None:
        key = jax.random.key(0)
    lowered = step._build().lower(
        step.params, step.opt_state, step.buffers, x, y, key)
    out = attribute_lowered(lowered, step.model)
    out["program"] = "train_step"
    return out


def attribute_forward(model, input_spec) -> Dict[str, Any]:
    """Attribute the inference forward only (no criterion needed)."""
    import jax

    from bigdl_tpu.nn.module import (functional_call, stamp_scope_names,
                                     state_dict)

    stamp_scope_names(model)
    state = state_dict(model)

    def fwd(state, x):
        return functional_call(model, state, x, training=False)[0]

    lowered = jax.jit(fwd).lower(state, input_spec)
    out = attribute_lowered(lowered, model)
    out["program"] = "forward"
    return out


def attribute_model(name: str, batch: int = 8,
                    train: bool = True) -> Dict[str, Any]:
    """Registry-model attribution: build the model, a synthetic-spec
    TrainStep (when the registry knows the training pieces), and
    attribute it; ``train=False`` attributes the inference forward."""
    from bigdl_tpu.models import registry

    model = registry.build_model(name)
    spec = registry.input_spec(name, batch)
    pieces = registry.train_pieces(name, batch) if train else None
    if pieces is None:
        out = attribute_forward(model, spec)
    else:
        import bigdl_tpu.optim as optim
        from bigdl_tpu.parallel.train_step import TrainStep

        criterion, target_spec = pieces
        step = TrainStep(model, criterion,
                         optim.SGD(learning_rate=0.01, momentum=0.9))
        out = attribute_train_step(step, spec, target_spec)
    out["model"] = name
    out["batch"] = batch
    return out


def rows_from_events(events: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The last ``attribution`` event of a run log (the CLI's
    read-from-artifact path), or None."""
    found = None
    for ev in events:
        if ev.get("kind") == "attribution":
            found = ev
    if found is None:
        return None
    return {k: v for k, v in found.items()
            if k not in ("v", "ts", "pid", "tid", "kind")}


# -- rendering ---------------------------------------------------------------
def _si(n: float) -> str:
    for div, unit in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n:.0f} "


def format_attribution(result: Dict[str, Any]) -> str:
    """Human-readable per-module cost table."""
    rows = result.get("rows") or []
    lines: List[str] = []
    head = ["== per-module cost attribution =="]
    for key in ("model", "program", "batch"):
        if key in result:
            head.append(f"{key}={result[key]}")
    lines.append("  ".join(head))
    if not rows:
        lines.append("no attribution rows (model compiled without "
                     "module scopes? set BIGDL_SCOPES=on)")
        return "\n".join(lines)
    total = result.get("total_flops") or 1.0
    pw = max(len(r["path"]) for r in rows)
    cw = max((len(r.get("class", "")) for r in rows), default=5)
    lines.append(f"{'module':<{pw}}  {'class':<{cw}}  {'flops':>9}  "
                 f"{'fwd':>9}  {'bwd':>9}  {'%':>6}  {'bytes':>10}  "
                 f"{'params':>10}")
    lines.append("-" * len(lines[-1]))
    for r in rows:
        share = (r["flops"] / total * 100.0) if total else 0.0
        lines.append(
            f"{r['path']:<{pw}}  {r.get('class', ''):<{cw}}  "
            f"{_si(r['flops']):>9}  {_si(r['flops_fwd']):>9}  "
            f"{_si(r['flops_bwd']):>9}  {share:>5.1f}%  "
            f"{_si(r['bytes']):>9}B  {r.get('params', 0):>10}")
    lines.append("-" * len(lines[2]))
    lines.append(f"estimated total: {_si(result.get('total_flops', 0.0))}F"
                 f"  (+ {_si(result.get('total_transcendentals', 0.0))} "
                 f"transcendentals)")
    if result.get("cost_flops"):
        est = result.get("total_flops", 0.0)
        cost = result["cost_flops"]
        dev = (est - cost) / cost * 100.0 if cost else 0.0
        lines.append(f"XLA cost_analysis: {_si(cost)}F  "
                     f"(estimate {dev:+.1f}% vs XLA)")
    return "\n".join(lines)
