"""CLI: ``python -m bigdl_tpu.telemetry ...`` — inspect and compare runs.

Default output: the summary report (per-stage time table, step-time
p50/p95, compile/retrace/event timeline, device facts + MFU estimate,
training-health section).

Options::

    python -m bigdl_tpu.telemetry run.jsonl                  # summary
    python -m bigdl_tpu.telemetry run.jsonl --json           # machine view
    python -m bigdl_tpu.telemetry run.jsonl --chrome t.json  # chrome://tracing
    python -m bigdl_tpu.telemetry run.jsonl --validate       # schema check
    python -m bigdl_tpu.telemetry p0.jsonl p1.jsonl ...      # fleet view
    python -m bigdl_tpu.telemetry p0.jsonl p1.jsonl --chrome fleet.json
    python -m bigdl_tpu.telemetry fleet <dir> [--watch]      # live fleet table
    python -m bigdl_tpu.telemetry trace run.jsonl --slowest 3  # request
    python -m bigdl_tpu.telemetry trace run.jsonl --id abc123  # waterfalls
    python -m bigdl_tpu.telemetry diff old.jsonl new.jsonl   # regression
    python -m bigdl_tpu.telemetry diff old_bench.json new_bench.json
    python -m bigdl_tpu.telemetry goodput run.jsonl ...      # wall-time
    python -m bigdl_tpu.telemetry goodput --supervise-dir d  # ledger
    python -m bigdl_tpu.telemetry attribute --model lenet    # per-module cost
    python -m bigdl_tpu.telemetry attribute run.jsonl        # from a run log
    python -m bigdl_tpu.telemetry attribute --comms --model lenet --mesh 2
    python -m bigdl_tpu.telemetry attribute --comms run.jsonl  # comms view
    python -m bigdl_tpu.telemetry attribute --memory --model lenet --mesh 2
    python -m bigdl_tpu.telemetry attribute --memory run.jsonl # HBM view
    python -m bigdl_tpu.telemetry memory --model transformer --mesh 4 \
        --zero1 --remat                                  # fit estimator

Passing several run logs merges them into the multi-host fleet view
(per-process step progress + step-skew + blame); ``--chrome`` then
writes ONE trace with a pid lane per process, viewable as a fleet
timeline in Perfetto.  ``fleet`` tails/aggregates a telemetry DIRECTORY
(one-shot or ``--watch``) — the offline twin of the coordinator's live
``/status`` fleet block.  ``diff`` compares two runs (JSONL logs or
bench.py JSON, mixed freely) and exits nonzero when the candidate
regressed beyond the thresholds — the CI gate.  ``attribute`` prints
the per-module FLOPs/bytes table — computed fresh for a registry model
(``--model``, CPU-friendly: lower + parse, no run needed) or read back
from a run log's ``attribution`` event; ``--comms`` switches to the
per-collective view (bytes moved, mesh axes, owning modules, bandwidth
vs ``BIGDL_PEAK_BW``), enriched with measured per-collective wall time
when the log names a perfetto profiler capture that still exists.
``trace`` renders per-request serving waterfalls offline from a run
log's ``request`` events (telemetry/request_trace.py) — the slowest N
by default, one exact id with ``--id``, request-lane Chrome output with
``--chrome``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from bigdl_tpu.telemetry import schema
from bigdl_tpu.telemetry.chrome_trace import write_chrome_trace
from bigdl_tpu.telemetry.report import (fleet_summarize, format_fleet,
                                        format_summary, summarize)


def attribute_main(argv) -> int:
    """``python -m bigdl_tpu.telemetry attribute`` entry (also backs the
    ``models/cli.py attribute`` subcommand)."""
    import argparse

    from bigdl_tpu.telemetry import attribution

    p = argparse.ArgumentParser(
        prog="bigdl_tpu.telemetry attribute",
        description="per-module FLOPs/bytes attribution table "
                    "(--comms: per-collective bytes/axes/bandwidth)")
    p.add_argument("run", nargs="?", default=None, metavar="run.jsonl",
                   help="read the attribution event back from a run log "
                        "(recorded with BIGDL_ATTRIBUTION=1; comms "
                        "events are on by default for sharded steps)")
    p.add_argument("--model", default=None,
                   help="compute fresh for a registry model instead")
    p.add_argument("-b", "--batch", type=int, default=8)
    p.add_argument("--forward", action="store_true",
                   help="attribute the inference forward instead of the "
                        "full train step")
    p.add_argument("--comms", action="store_true",
                   help="per-collective comms view: bytes moved, mesh "
                        "axes, owning modules, bandwidth vs "
                        "BIGDL_PEAK_BW")
    p.add_argument("--memory", action="store_true",
                   help="per-module HBM view: params / optimizer state "
                        "/ activations-at-peak / workspace per device "
                        "(telemetry/memory.py)")
    p.add_argument("--mesh", type=int, default=0, metavar="N",
                   help="(--comms/--memory --model) data-axis mesh size "
                        "to shard over (default: all local devices for "
                        "--comms, single device for --memory)")
    p.add_argument("--sync", default="allreduce",
                   choices=("allreduce", "sharded", "fsdp"),
                   help="(--comms/--memory --model) parameter_sync "
                        "mode to compile with")
    p.add_argument("--sparse", default=None,
                   choices=("off", "auto", "on"),
                   help="(--comms --model) override BIGDL_SPARSE for "
                        "this compile — A/B the sparse embedding sync "
                        "vs the dense table all-reduce "
                        "(docs/sparse.md)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    if (args.run is None) == (args.model is None):
        p.error("pass exactly one of run.jsonl or --model NAME")
    if args.comms and args.memory:
        p.error("--comms and --memory are different views — pass one")
    if args.memory:
        from bigdl_tpu.telemetry import memory as memory_mod

        if args.model is not None:
            result = memory_mod.attribute_memory_model(
                args.model, batch=args.batch, devices=args.mesh,
                sync=args.sync)
        else:
            events, parse_errors = schema.read_events(args.run)
            for e in parse_errors:
                print(f"warning: {args.run}: {e}", file=sys.stderr)
            result = memory_mod.memory_from_events(events)
            if result is None:
                print(f"error: {args.run} has no memory event (sharded "
                      f"steps emit one by default; BIGDL_MEMORY=on "
                      f"forces it, or use --model)", file=sys.stderr)
                return 2
        if args.json:
            print(json.dumps(result, indent=2, default=str))
        else:
            print(memory_mod.format_memory(result))
        return 0
    if args.comms:
        from bigdl_tpu.telemetry import comms as comms_mod

        if args.model is not None:
            result = comms_mod.attribute_comms_model(
                args.model, batch=args.batch, devices=args.mesh,
                sync=args.sync, sparse=args.sparse)
        else:
            events, parse_errors = schema.read_events(args.run)
            for e in parse_errors:
                print(f"warning: {args.run}: {e}", file=sys.stderr)
            result = comms_mod.comms_from_events(events)
            if result is None:
                print(f"error: {args.run} has no comms event (sharded "
                      f"steps emit one by default; BIGDL_COMMS=on "
                      f"forces it, or use --model)", file=sys.stderr)
                return 2
            _enrich_measured(result, events)
        if args.json:
            print(json.dumps(result, indent=2, default=str))
        else:
            print(comms_mod.format_comms(result))
        return 0
    if args.model is not None:
        result = attribution.attribute_model(
            args.model, batch=args.batch, train=not args.forward)
    else:
        events, parse_errors = schema.read_events(args.run)
        for e in parse_errors:
            print(f"warning: {args.run}: {e}", file=sys.stderr)
        result = attribution.rows_from_events(events)
        if result is None:
            print(f"error: {args.run} has no attribution event (record "
                  f"with BIGDL_ATTRIBUTION=1, or use --model)",
                  file=sys.stderr)
            return 2
    if args.json:
        print(json.dumps(result, indent=2, default=str))
    else:
        print(attribution.format_attribution(result))
    return 0


def _enrich_measured(result, events) -> None:
    """Fold measured per-collective wall time into a comms result when
    the log records a perfetto profiler capture whose trace dir still
    exists (``POST /profile?steps=N&perfetto=1`` wrote it)."""
    import os

    from bigdl_tpu.telemetry import comms as comms_mod

    captures = [e for e in events
                if e.get("kind") == "event"
                and e.get("name") == "profile/captured"
                and e.get("perfetto") and e.get("dir")]
    armed_steps = {e.get("dir"): e.get("steps")
                   for e in events
                   if e.get("kind") == "event"
                   and e.get("name") == "profile/armed"}
    for cap in reversed(captures):  # newest capture wins
        trace_dir = cap["dir"]
        if not os.path.isdir(trace_dir):
            continue
        times = comms_mod.collective_times_from_trace(trace_dir)
        if not times:
            continue
        steps = max(int(armed_steps.get(trace_dir) or 1), 1)
        # one unit everywhere: per-STEP seconds (the capture spans
        # `steps` iterations), for the total and the per-op split alike
        result["measured_by_op"] = {op: t / steps
                                    for op, t in times.items()}
        result["measured_s"] = sum(times.values()) / steps
        result["measured_from"] = trace_dir
        return


def memory_main(argv) -> int:
    """``python -m bigdl_tpu.telemetry memory`` — the device-free fit
    estimator: lower a registry TrainStep on CPU with the requested
    mesh/sharding, predict per-device peak HBM, compare against the
    budget (``BIGDL_HBM_GB`` / the per-chip table), and rank blocks by
    remat payoff.  Exit 0 = fits (or no budget known), 1 = predicted
    peak exceeds the budget, 2 = nothing to estimate."""
    import argparse

    from bigdl_tpu.telemetry import memory as memory_mod

    p = argparse.ArgumentParser(
        prog="bigdl_tpu.telemetry memory",
        description="device-free fit estimator: will this model fit on "
                    "N chips? (predicted per-device peak HBM vs "
                    "BIGDL_HBM_GB, with a remat advisor)")
    p.add_argument("--model", required=True,
                   help="registry model name")
    p.add_argument("-b", "--batch", type=int, default=8,
                   help="GLOBAL batch size (default %(default)s)")
    p.add_argument("--mesh", type=int, default=1, metavar="N",
                   help="data-axis mesh size to predict for (CPU "
                        "emulation needs XLA_FLAGS=--xla_force_host_"
                        "platform_device_count=N)")
    p.add_argument("--zero1", action="store_true",
                   help="ZeRO-1 layout: optimizer state sharded over "
                        "the data axis (parameter_sync='sharded')")
    p.add_argument("--fsdp", action="store_true",
                   help="ZeRO-3 layout: params + optimizer state "
                        "sharded (parameter_sync='fsdp')")
    p.add_argument("--remat", action="store_true",
                   help="estimate WITH whole-model rematerialization "
                        "(activations recomputed, not stored)")
    p.add_argument("--no-advice", action="store_true",
                   help="skip the remat advisor (one fewer re-lower)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    sync = "fsdp" if args.fsdp else ("sharded" if args.zero1
                                     else "allreduce")
    try:
        result = memory_mod.fit_estimate(
            args.model, batch=args.batch, devices=args.mesh, sync=sync,
            remat=args.remat, advise=not args.no_advice)
    except (KeyError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(result, indent=2, default=str))
    else:
        print(memory_mod.format_memory(result))
    if result.get("fits") is False:
        return 1
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "diff":
        from bigdl_tpu.telemetry import diff as diff_mod

        return diff_mod.main(argv[1:])
    if argv and argv[0] == "attribute":
        return attribute_main(argv[1:])
    if argv and argv[0] == "memory":
        return memory_main(argv[1:])
    if argv and argv[0] == "fleet":
        from bigdl_tpu.telemetry import fleet as fleet_mod

        return fleet_mod.main(argv[1:])
    if argv and argv[0] == "trace":
        from bigdl_tpu.telemetry import request_trace

        return request_trace.trace_main(argv[1:])
    if argv and argv[0] == "goodput":
        from bigdl_tpu.telemetry import ledger

        return ledger.goodput_main(argv[1:])

    p = argparse.ArgumentParser(
        prog="bigdl_tpu.telemetry",
        description="summarize / compare / export telemetry run logs "
                    "(subcommands: diff <runA> <runB>, fleet <dir> "
                    "[--watch], trace run.jsonl [--slowest N|--id ID], "
                    "goodput <run.jsonl...|--supervise-dir DIR>, "
                    "attribute [run.jsonl | --model NAME] "
                    "[--comms|--memory], memory --model NAME --mesh N)")
    p.add_argument("runs", nargs="+", metavar="run.jsonl",
                   help="path(s) to run-*.jsonl event logs; several "
                        "merge into the fleet view")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as JSON instead of text")
    p.add_argument("--chrome", metavar="OUT.json", default=None,
                   help="also write a Chrome trace_event JSON for "
                        "chrome://tracing / Perfetto (several runs "
                        "merge into one trace with a pid lane per "
                        "process)")
    p.add_argument("--validate", action="store_true",
                   help="only validate the log(s) against the schema; "
                        "exit 1 on any violation")
    args = p.parse_args(argv)

    if args.validate:
        total_events = 0
        errors = []
        for path in args.runs:
            events, parse_errors = schema.read_events(path)
            total_events += len(events)
            errors += [f"{path}: {e}" for e in
                       parse_errors + schema.validate_events(events)]
        if errors:
            for e in errors:
                print(e, file=sys.stderr)
            print(f"{total_events} events, {len(errors)} problems")
            return 1
        print(f"{total_events} events, schema ok")
        return 0

    loaded = []
    for path in args.runs:
        events, parse_errors = schema.read_events(path)
        for e in parse_errors:  # non-fatal: a crashed run truncates a line
            print(f"warning: {path}: {e}", file=sys.stderr)
        loaded.append((path, events))

    if len(loaded) > 1:
        fleet = fleet_summarize(loaded)
        if args.json:
            print(json.dumps(fleet, indent=2, default=str))
        else:
            print(format_fleet(fleet))
        if args.chrome:
            # one merged trace, a pid lane per process — the fleet
            # timeline view (each log keeps its own OS pid; the lane
            # label names the process_index and file)
            merged = []
            names = {}
            for path, events in loaded:
                merged.extend(events)
                pidx = next((e.get("meta", {}).get("process_index")
                             for e in events
                             if e.get("kind") == "run_start"), None)
                for e in events:
                    if isinstance(e.get("pid"), int):
                        label = f"p{pidx}" if pidx is not None else "p?"
                        names[e["pid"]] = \
                            f"{label} ({os.path.basename(path)})"
                        break
            merged.sort(key=lambda e: e.get("ts", 0.0))
            n = write_chrome_trace(merged, args.chrome,
                                   process_names=names)
            print(f"\nchrome trace: {args.chrome} ({n} trace events, "
                  f"{len(loaded)} process lanes) — open in "
                  f"chrome://tracing or https://ui.perfetto.dev",
                  file=sys.stderr if args.json else sys.stdout)
        return 0

    path, events = loaded[0]
    summary = summarize(events)
    if args.json:
        print(json.dumps(summary, indent=2, default=str))
    else:
        print(format_summary(summary, events))
    if args.chrome:
        n = write_chrome_trace(events, args.chrome)
        print(f"\nchrome trace: {args.chrome} ({n} trace events) — open "
              f"in chrome://tracing or https://ui.perfetto.dev",
              file=sys.stderr if args.json else sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
