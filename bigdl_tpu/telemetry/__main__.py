"""CLI: ``python -m bigdl_tpu.telemetry ...`` — inspect and compare runs.

Default output: the summary report (per-stage time table, step-time
p50/p95, compile/retrace/event timeline, device facts + MFU estimate,
training-health section).

Options::

    python -m bigdl_tpu.telemetry run.jsonl                  # summary
    python -m bigdl_tpu.telemetry run.jsonl --json           # machine view
    python -m bigdl_tpu.telemetry run.jsonl --chrome t.json  # chrome://tracing
    python -m bigdl_tpu.telemetry run.jsonl --validate       # schema check
    python -m bigdl_tpu.telemetry p0.jsonl p1.jsonl ...      # fleet view
    python -m bigdl_tpu.telemetry diff old.jsonl new.jsonl   # regression
    python -m bigdl_tpu.telemetry diff old_bench.json new_bench.json
    python -m bigdl_tpu.telemetry attribute --model lenet    # per-module cost
    python -m bigdl_tpu.telemetry attribute run.jsonl        # from a run log

Passing several run logs merges them into the multi-host fleet view
(per-process step progress + step-skew).  ``diff`` compares two runs
(JSONL logs or bench.py JSON, mixed freely) and exits nonzero when the
candidate regressed beyond the thresholds — the CI gate.  ``attribute``
prints the per-module FLOPs/bytes table — computed fresh for a registry
model (``--model``, CPU-friendly: lower + parse, no run needed) or read
back from a run log's ``attribution`` event.
"""

from __future__ import annotations

import argparse
import json
import sys

from bigdl_tpu.telemetry import schema
from bigdl_tpu.telemetry.chrome_trace import write_chrome_trace
from bigdl_tpu.telemetry.report import (fleet_summarize, format_fleet,
                                        format_summary, summarize)


def attribute_main(argv) -> int:
    """``python -m bigdl_tpu.telemetry attribute`` entry (also backs the
    ``models/cli.py attribute`` subcommand)."""
    import argparse

    from bigdl_tpu.telemetry import attribution

    p = argparse.ArgumentParser(
        prog="bigdl_tpu.telemetry attribute",
        description="per-module FLOPs/bytes attribution table")
    p.add_argument("run", nargs="?", default=None, metavar="run.jsonl",
                   help="read the attribution event back from a run log "
                        "(recorded with BIGDL_ATTRIBUTION=1)")
    p.add_argument("--model", default=None,
                   help="compute fresh for a registry model instead")
    p.add_argument("-b", "--batch", type=int, default=8)
    p.add_argument("--forward", action="store_true",
                   help="attribute the inference forward instead of the "
                        "full train step")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)
    if (args.run is None) == (args.model is None):
        p.error("pass exactly one of run.jsonl or --model NAME")
    if args.model is not None:
        result = attribution.attribute_model(
            args.model, batch=args.batch, train=not args.forward)
    else:
        events, parse_errors = schema.read_events(args.run)
        for e in parse_errors:
            print(f"warning: {args.run}: {e}", file=sys.stderr)
        result = attribution.rows_from_events(events)
        if result is None:
            print(f"error: {args.run} has no attribution event (record "
                  f"with BIGDL_ATTRIBUTION=1, or use --model)",
                  file=sys.stderr)
            return 2
    if args.json:
        print(json.dumps(result, indent=2, default=str))
    else:
        print(attribution.format_attribution(result))
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "diff":
        from bigdl_tpu.telemetry import diff as diff_mod

        return diff_mod.main(argv[1:])
    if argv and argv[0] == "attribute":
        return attribute_main(argv[1:])

    p = argparse.ArgumentParser(
        prog="bigdl_tpu.telemetry",
        description="summarize / compare / export telemetry run logs "
                    "(subcommands: diff <runA> <runB>, attribute "
                    "[run.jsonl | --model NAME])")
    p.add_argument("runs", nargs="+", metavar="run.jsonl",
                   help="path(s) to run-*.jsonl event logs; several "
                        "merge into the fleet view")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as JSON instead of text")
    p.add_argument("--chrome", metavar="OUT.json", default=None,
                   help="also write a Chrome trace_event JSON for "
                        "chrome://tracing / Perfetto (single run only)")
    p.add_argument("--validate", action="store_true",
                   help="only validate the log(s) against the schema; "
                        "exit 1 on any violation")
    args = p.parse_args(argv)
    if args.chrome and len(args.runs) > 1:
        p.error("--chrome exports one run; pass a single run log")

    if args.validate:
        total_events = 0
        errors = []
        for path in args.runs:
            events, parse_errors = schema.read_events(path)
            total_events += len(events)
            errors += [f"{path}: {e}" for e in
                       parse_errors + schema.validate_events(events)]
        if errors:
            for e in errors:
                print(e, file=sys.stderr)
            print(f"{total_events} events, {len(errors)} problems")
            return 1
        print(f"{total_events} events, schema ok")
        return 0

    loaded = []
    for path in args.runs:
        events, parse_errors = schema.read_events(path)
        for e in parse_errors:  # non-fatal: a crashed run truncates a line
            print(f"warning: {path}: {e}", file=sys.stderr)
        loaded.append((path, events))

    if len(loaded) > 1:
        fleet = fleet_summarize(loaded)
        if args.json:
            print(json.dumps(fleet, indent=2, default=str))
        else:
            print(format_fleet(fleet))
        return 0

    path, events = loaded[0]
    summary = summarize(events)
    if args.json:
        print(json.dumps(summary, indent=2, default=str))
    else:
        print(format_summary(summary, events))
    if args.chrome:
        n = write_chrome_trace(events, args.chrome)
        print(f"\nchrome trace: {args.chrome} ({n} trace events) — open "
              f"in chrome://tracing or https://ui.perfetto.dev",
              file=sys.stderr if args.json else sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
