"""CLI: ``python -m bigdl_tpu.telemetry <run.jsonl>`` — inspect a run.

Default output: the summary report (per-stage time table, step-time
p50/p95, compile/retrace/event timeline, device facts + MFU estimate).

Options::

    python -m bigdl_tpu.telemetry run.jsonl                  # summary
    python -m bigdl_tpu.telemetry run.jsonl --json           # machine view
    python -m bigdl_tpu.telemetry run.jsonl --chrome t.json  # chrome://tracing
    python -m bigdl_tpu.telemetry run.jsonl --validate       # schema check
"""

from __future__ import annotations

import argparse
import json
import sys

from bigdl_tpu.telemetry import schema
from bigdl_tpu.telemetry.chrome_trace import write_chrome_trace
from bigdl_tpu.telemetry.report import format_summary, summarize


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bigdl_tpu.telemetry",
        description="summarize / export a telemetry run log")
    p.add_argument("run", help="path to a run-*.jsonl event log")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as JSON instead of text")
    p.add_argument("--chrome", metavar="OUT.json", default=None,
                   help="also write a Chrome trace_event JSON for "
                        "chrome://tracing / Perfetto")
    p.add_argument("--validate", action="store_true",
                   help="only validate the log against the schema; "
                        "exit 1 on any violation")
    args = p.parse_args(argv)

    events, parse_errors = schema.read_events(args.run)
    if args.validate:
        errors = parse_errors + schema.validate_events(events)
        if errors:
            for e in errors:
                print(e, file=sys.stderr)
            print(f"{len(events)} events, {len(errors)} problems")
            return 1
        print(f"{len(events)} events, schema ok")
        return 0

    for e in parse_errors:  # non-fatal: a crashed run truncates a line
        print(f"warning: {e}", file=sys.stderr)

    summary = summarize(events)
    if args.json:
        print(json.dumps(summary, indent=2, default=str))
    else:
        print(format_summary(summary, events))
    if args.chrome:
        n = write_chrome_trace(events, args.chrome)
        print(f"\nchrome trace: {args.chrome} ({n} trace events) — open "
              f"in chrome://tracing or https://ui.perfetto.dev",
              file=sys.stderr if args.json else sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
