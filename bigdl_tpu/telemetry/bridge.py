"""Bridges between the telemetry stream and the existing observers.

``RetraceBridge`` — wires the static analyzer's retrace detector
(``analysis/retrace.py``) onto the dispatch hook bus and re-emits every
diagnostic as a ``retrace`` event, so which-argument-retraced-what lands
in the same timeline as the compile it caused.

``SummaryBridge`` — a tracer *sink* that forwards counter/gauge samples
into a ``TrainSummary``/``ValidationSummary`` writer as
``telemetry/<name>`` scalars, keeping TensorBoard the visual frontend
without a second instrumentation path.  The scalar step is the latest
training step seen in the stream (0 before the first step event).
"""

from __future__ import annotations

from typing import Any, Dict

__all__ = ["RetraceBridge", "SummaryBridge"]


class RetraceBridge:
    """hooks-bus monitor: retrace diagnostics -> telemetry events."""

    def __init__(self, tracer):
        from bigdl_tpu.analysis.retrace import RetraceMonitor

        self._tracer = tracer
        self._monitor = RetraceMonitor()
        self._emitted = 0
        self._installed = False

    # the hooks bus calls these (analysis/hooks.py contract)
    def on_dispatch(self, owner, kind: str, args) -> None:
        self._monitor.on_dispatch(owner, kind, args)
        self._drain()

    def on_cache(self, owner, kind: str, size) -> None:
        self._monitor.on_cache(owner, kind, size)
        self._drain()

    def _drain(self) -> None:
        diags = self._monitor.report.diagnostics
        for d in diags[self._emitted:]:
            self._tracer.emit("retrace", rule=d.rule, message=d.message,
                              where=d.where, hint=d.hint)
        self._emitted = len(diags)

    def install(self) -> "RetraceBridge":
        from bigdl_tpu.analysis import hooks

        if not self._installed:
            hooks.register(self)
            self._installed = True
        return self

    def remove(self) -> None:
        from bigdl_tpu.analysis import hooks

        if self._installed:
            hooks.unregister(self)
            self._installed = False


class SummaryBridge:
    """Tracer sink: counter/gauge events -> TensorBoard scalars."""

    def __init__(self, summary, prefix: str = "telemetry/"):
        self._summary = summary
        self._prefix = prefix
        self._step = 0

    def emit(self, event: Dict[str, Any]) -> None:
        kind = event.get("kind")
        if kind == "step" and isinstance(event.get("step"), int):
            self._step = event["step"]
        elif kind in ("counter", "gauge"):
            self._summary.add_scalar(
                self._prefix + str(event.get("name", "?")),
                float(event.get("value", 0.0)), self._step)
        # NOT forwarded: "health" events — the Optimizer already mirrors
        # the probe into gated `health/*` scalars itself (and does so
        # even when no telemetry run is active); forwarding here would
        # write the same four values per step under a second tag

    def flush(self) -> None:
        pass

    def close(self) -> None:
        # the summary writer is owned by whoever created it (the user /
        # the Optimizer), not by the tracer — never close it here
        pass
