"""Per-module HBM attribution: where a compiled step's peak device
memory goes — params / optimizer state / activations-at-peak /
workspace / donated — and which module owns each byte.

Why a THIRD walker beside ``attribution.py`` (FLOPs/bytes, lowered
StableHLO) and ``comms.py`` (collectives, post-partitioning HLO):
memory is a property of the **scheduled** program.  ``Compiled
.as_text()`` prints the post-optimization HLO with
``is_scheduled=true`` — instructions appear in execution order — so a
single sweep over the ENTRY computation reconstructs the live-buffer
timeline: each instruction births a buffer of its output size, the
buffer dies after its last textual use, and the running sum's maximum
is the program's temp peak.  Cross-checked against XLA's own
``Compiled.memory_analysis()`` (lenet 0.2% off, transformer ~7% off on
the CPU backend; ``tests/test_memory.py`` pins 10%).

What the text gives us that no API does:

- ENTRY parameters carry the **argument tree paths** as ``op_name``
  metadata (``params['0.weight']``, ``opt_state['velocity']['2.bias']``,
  ``buffers[...]``, ``x``/``y``) with **post-SPMD per-device shapes** —
  so per-device params/opt-state/buffers/batch bytes are exact, and a
  ZeRO-1 run's sharded optimizer state is visibly 1/N the dense run's
  (the accounting question of arXiv 2004.13336).
- body instructions carry the same ``op_name`` module scopes the PR-4
  walker reads, so every live-at-peak buffer folds onto the owning
  module via :func:`attribution.scope_of` — forward-direction buffers
  live at the peak are the **activations the backward is holding**,
  the number ``nn.Remat`` exists to shrink (and measurably does:
  wrapping transformer blocks drops it ~10x).
- the ``input_output_alias`` header names the donated buffers, so
  updated params/opt-state are never double-counted as temp.

Alias handling: ``get-tuple-element`` / ``tuple`` / ``bitcast`` /
``optimization-barrier`` forward views, a same-layout ``copy`` of an
argument is treated as aliasing it (XLA's buffer assignment elides or
donates these), and ``while``/``call`` bodies contribute their own
internal peak at the call site (which is what makes the scan-over-steps
executable report the peak *inside* the loop body, not the tuple
shuffle around it).

The device-free **fit estimator** (``python -m bigdl_tpu.telemetry
memory --model NAME --mesh N``) lowers a registry TrainStep on CPU with
the requested sharding and compares predicted per-device peak against
the HBM budget (``BIGDL_HBM_GB`` / the per-chip table in
``telemetry/device.py`` / the live allocator limit), including a remat
advisor ranking top-level blocks by activation-bytes-saved per
recompute-FLOP.

OOM forensics: :func:`raise_oom` turns a backend RESOURCE_EXHAUSTED
into a :class:`MemoryExhaustedError` carrying the top-k largest known
buffers, per-category byte totals, and live-vs-limit allocator stats —
flight-dumped (``telemetry/flight.py``) before the re-raise so the
evidence survives the crash.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from bigdl_tpu.telemetry.attribution import scope_of

__all__ = ["Instr", "parse_hlo_computations", "analyze_hlo_memory",
           "memory_facts_compiled", "attribute_memory_train_step",
           "attribute_memory_model", "memory_from_events",
           "fit_estimate", "remat_advice", "format_memory",
           "MemoryExhaustedError", "is_oom", "oom_evidence", "raise_oom",
           "live_hbm", "hbm_limit_bytes", "live_peak_and_limit",
           "pressured_device", "PRESSURE_FRACTION"]

_HLO_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
    "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8,
    "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(r"\b(" + "|".join(_HLO_DTYPE_BYTES) +
                       r")\[([0-9,]*)\]")
#: one scheduled-HLO instruction: name, result type (tuple or single),
#: opcode.  The operand list and attrs are scanned separately.
_INSTR_RE = re.compile(
    r"^\s*(ROOT\s+)?%([\w.\-]+)\s*=\s*(\([^)]*\)|\S+)\s+([\w\-]+)\(")
_REF_RE = re.compile(r"%([\w.\-]+)")
_OPNAME_RE = re.compile(r'op_name="((?:[^"\\]|\\.)*)"')
_ALIAS_PAIR_RE = re.compile(r"\{\s*(\d+)\s*\}:\s*\((\d+),")
_PARAMNO_RE = re.compile(r"parameter\((\d+)\)")
_COMP_HEAD_RE = re.compile(r"^(ENTRY\s+)?%([\w.\-]+)\s*\(")
_KEY_RE = re.compile(r"\['((?:[^'\\]|\\.)*)'\]")

#: ops whose output is a view of an operand — they allocate nothing and
#: forward liveness to their sources.  ``while`` is here because XLA
#: requires its output to alias the input state tuple.
_VIEW_OPS = frozenset({"get-tuple-element", "tuple", "bitcast",
                       "optimization-barrier", "while"})
#: ops whose referenced computations run INSIDE the instruction — their
#: internal temp peak is live while the instruction executes.  NOT
#: ``fusion``: a fused computation's intermediates live in registers,
#: sweeping its body would invent buffers that never materialize.
_NESTED_OPS = frozenset({"while", "call", "conditional"})


class Instr:
    """One parsed scheduled-HLO instruction."""

    __slots__ = ("name", "bytes", "opcode", "refs", "op_name",
                 "param_no", "root")

    def __init__(self, name, nbytes, opcode, refs, op_name, param_no,
                 root):
        self.name = name
        self.bytes = nbytes
        self.opcode = opcode
        self.refs = refs
        self.op_name = op_name
        self.param_no = param_no
        self.root = root


def _type_bytes(type_text: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_text):
        n = 1
        for d in dims.split(","):
            if d.strip().isdigit():
                n *= int(d)
        total += n * _HLO_DTYPE_BYTES[dtype]
    return total


def _unescape(s: str) -> str:
    return re.sub(r"\\(.)", r"\1", s)


def parse_hlo_computations(text: str) -> Tuple[Dict[str, List[Instr]],
                                               Optional[str],
                                               Dict[int, int]]:
    """All computations of one post-optimization HLO module text.

    Returns ``(computations, entry_name, alias)`` where ``alias`` maps
    output tuple index -> donated parameter number (the
    ``input_output_alias`` header)."""
    lines = text.splitlines()
    alias: Dict[int, int] = {}
    if lines and "input_output_alias" in lines[0]:
        seg = lines[0].split("input_output_alias=", 1)[1]
        for out_idx, pnum in _ALIAS_PAIR_RE.findall(seg):
            alias[int(out_idx)] = int(pnum)
    comps: Dict[str, List[Instr]] = {}
    entry_name: Optional[str] = None
    current: Optional[str] = None
    for line in lines:
        if current is None:
            m = _COMP_HEAD_RE.match(line)
            if m is not None and "{" in line:
                current = m.group(2)
                comps[current] = []
                if m.group(1):
                    entry_name = current
            continue
        if line.startswith("}"):
            current = None
            continue
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        root, name, type_text, opcode = (bool(m.group(1)), m.group(2),
                                         m.group(3), m.group(4))
        refs = _REF_RE.findall(line[m.end():])
        nm = _OPNAME_RE.search(line)
        pm = _PARAMNO_RE.search(line) if opcode == "parameter" else None
        comps[current].append(Instr(
            name, _type_bytes(type_text), opcode, refs,
            _unescape(nm.group(1)) if nm else "",
            int(pm.group(1)) if pm else None, root))
    return comps, entry_name, alias


def _root_of(instrs: List[Instr]) -> Optional[Instr]:
    for ins in instrs:
        if ins.root:
            return ins
    return instrs[-1] if instrs else None


def _sweep(instrs: List[Instr], comps: Dict[str, List[Instr]],
           memo: Dict[str, int], donated: frozenset = frozenset(),
           outputs_live: bool = False, depth: int = 0
           ) -> Tuple[int, int, List[str], List[int],
                      Dict[str, int], Dict[str, set]]:
    """Liveness sweep over one computation's scheduled instructions.

    Returns ``(peak, peak_index, live_value_names_at_peak, series,
    births, sources)``.  ``donated`` values are excluded (they write
    into argument buffers); with ``outputs_live`` the root's operands
    stay live to the end (the ENTRY's outputs are real buffers until
    the caller takes them)."""
    defs = {ins.name: i for i, ins in enumerate(instrs)}
    param_names = {ins.name for ins in instrs
                   if ins.opcode == "parameter"}
    # same-layout copies of arguments: buffer assignment aliases or
    # donates these (the old-weight copy XLA inserts for a donated
    # param) — treat as views of the argument
    copy_like: set = set()
    for ins in instrs:
        if ins.opcode == "copy" and any(
                r in param_names or r in copy_like for r in ins.refs):
            copy_like.add(ins.name)
    sources: Dict[str, set] = {}
    for ins in instrs:
        if ins.opcode in _VIEW_OPS or ins.name in copy_like:
            s: set = set()
            for r in ins.refs:
                if r in sources:
                    s |= sources[r]
            sources[ins.name] = s
        else:
            sources[ins.name] = {ins.name}
    last_use = {ins.name: i for i, ins in enumerate(instrs)}
    for i, ins in enumerate(instrs):
        for r in ins.refs:
            if r in defs:
                for src in sources.get(r, ()):
                    last_use[src] = max(last_use.get(src, i), i)
    n = len(instrs)
    root = _root_of(instrs)
    root_values: set = set()
    if root is not None:
        for r in root.refs:
            if r in defs:
                for src in sources.get(r, {r}):
                    root_values.add(src)
                    if outputs_live:
                        last_use[src] = n
    births: Dict[str, int] = {}
    deaths: Dict[str, int] = {}
    for i, ins in enumerate(instrs):
        if ins.opcode == "parameter" or ins.opcode in _VIEW_OPS \
                or ins.name in copy_like or ins.name in donated:
            continue
        if not outputs_live and ins.name in root_values:
            # a nested computation's root is the CALLER's buffer
            continue
        births[ins.name] = i
        deaths[ins.name] = last_use.get(ins.name, i)
    delta = [0] * (n + 2)
    for name, b in births.items():
        sz = instrs[defs[name]].bytes
        delta[b] += sz
        delta[min(deaths[name], n - 1) + 1] -= sz
    # nested computations (while bodies, CPU parallel-fusion calls):
    # their internal peak is live exactly while the instruction runs
    for i, ins in enumerate(instrs):
        if ins.opcode not in _NESTED_OPS or depth > 6:
            continue
        inner = 0
        for r in ins.refs:
            if r in comps and r not in defs:
                inner = max(inner, _comp_peak(r, comps, memo, depth + 1))
        if inner:
            delta[i] += inner
            delta[i + 1] -= inner
    live = 0
    series: List[int] = []
    peak, peak_i = 0, 0
    for i in range(n):
        live += delta[i]
        series.append(live)
        if live > peak:
            peak, peak_i = live, i
    live_at_peak = [name for name, b in births.items()
                    if b <= peak_i <= deaths[name]]
    return peak, peak_i, live_at_peak, series, births, sources


def _comp_peak(name: str, comps: Dict[str, List[Instr]],
               memo: Dict[str, int], depth: int = 0) -> int:
    """Internal temp peak of a non-entry computation (its parameters
    and root output are the caller's buffers)."""
    if name in memo:
        return memo[name]
    memo[name] = 0  # cycle guard
    peak, *_ = _sweep(comps.get(name, []), comps, memo, depth=depth)
    memo[name] = peak
    return peak


# -- argument categorization --------------------------------------------------
def _arg_category(op_name: str) -> Tuple[str, str]:
    """(category, owner path) of one ENTRY parameter from its op_name
    metadata (the argument tree path jax stamps)."""
    keys = _KEY_RE.findall(op_name)
    if op_name.startswith("params[") or op_name.startswith("state["):
        return "params", keys[0] if keys else ""
    if op_name.startswith("opt_state["):
        # the innermost key of a per-param moment tree is the param
        # path (velocity/m/v...); bare scalars (neval) stay unowned
        return "opt_state", keys[-1] if len(keys) > 1 else ""
    if op_name.startswith("buffers["):
        return "buffers", keys[0] if keys else ""
    head = op_name.split("[", 1)[0]
    if head in ("x", "y"):
        return "batch", ""
    return "other", ""


def _module_paths(model) -> Tuple[List[str], Dict[str, str]]:
    if model is None:
        return [], {}
    paths, classes = [], {}
    for name, m in model.named_modules():
        if name:
            paths.append(name)
            classes[name] = type(m).__name__
    return paths, classes


def _owner_module(path: str, module_paths: List[str]) -> Optional[str]:
    best = None
    for mp in module_paths:
        if (path == mp or path.startswith(mp + ".")) and \
                (best is None or len(mp) > len(best)):
            best = mp
    return best


# -- the walker ---------------------------------------------------------------
def analyze_hlo_memory(text: str, model=None) -> Dict[str, Any]:
    """Decompose one post-optimization scheduled HLO module into the
    per-device HBM story: argument categories, donated bytes, the
    live-buffer timeline, activations-vs-workspace at the peak, and
    per-module rows."""
    comps, entry_name, alias = parse_hlo_computations(text)
    instrs = comps.get(entry_name or "", [])
    defs = {ins.name: i for i, ins in enumerate(instrs)}
    # donated values: the root operands at aliased output positions
    root = _root_of(instrs)
    donated_values: set = set()
    donated_bytes = 0
    memo: Dict[str, int] = {}
    if root is not None and alias:
        # views must forward before we can resolve root operand sources
        _, _, _, _, _, sources = _sweep(instrs, comps, memo, frozenset(),
                                        outputs_live=True)
        opers = [r for r in root.refs if r in defs]
        for out_idx, r in enumerate(opers):
            if out_idx in alias:
                for src in sources.get(r, {r}):
                    if src not in donated_values and src in defs:
                        donated_values.add(src)
                        donated_bytes += instrs[defs[src]].bytes
    peak, peak_i, live_at_peak, series, _births, _src = _sweep(
        instrs, comps, memo, frozenset(donated_values),
        outputs_live=True)

    # arguments
    cats = {"params": 0, "opt_state": 0, "buffers": 0, "batch": 0,
            "other": 0}
    arg_rows: List[Tuple[str, str, int]] = []  # (category, path, bytes)
    for ins in instrs:
        if ins.opcode != "parameter":
            continue
        cat, path = _arg_category(ins.op_name)
        cats[cat] += ins.bytes
        arg_rows.append((cat, path, ins.bytes))
    args_total = sum(cats.values())

    # the live set at the peak, split activations (forward values the
    # backward is holding) vs workspace (gradients / scratch)
    act_at_peak = ws_at_peak = 0
    largest: List[Dict[str, Any]] = []
    live_rows: List[Tuple[str, str, int]] = []  # (kind, scope path, b)
    # nested while/call bodies contribute their internal peak at the
    # peak index without a named ENTRY value — it is loop-body scratch,
    # accounted as workspace so the categories tile the peak exactly
    nested_at_peak = series[peak_i] if series else 0
    for name in live_at_peak:
        ins = instrs[defs[name]]
        path, direction = scope_of(ins.op_name) if ins.op_name \
            else ("", "fwd")
        is_act = bool(ins.op_name) and direction == "fwd"
        if is_act:
            act_at_peak += ins.bytes
        else:
            ws_at_peak += ins.bytes
        live_rows.append(("activation" if is_act else "workspace",
                          path, ins.bytes))
        largest.append({"bytes": ins.bytes, "opcode": ins.opcode,
                        "path": path, "direction": direction,
                        "kind": "activation" if is_act else "workspace"})
        nested_at_peak -= ins.bytes
    nested_at_peak = max(nested_at_peak, 0)
    if nested_at_peak:
        ws_at_peak += nested_at_peak
        live_rows.append(("workspace", "", nested_at_peak))
        largest.append({"bytes": nested_at_peak, "opcode": "(loop body)",
                        "path": "", "direction": "fwd",
                        "kind": "workspace"})
    largest.sort(key=lambda r: -r["bytes"])

    # per-module fold (cumulative onto ancestors, PR-4 convention)
    module_paths, classes = _module_paths(model)

    def blank(path: str) -> Dict[str, Any]:
        return {"path": path, "class": classes.get(path, ""),
                "param_bytes": 0, "opt_bytes": 0, "act_bytes": 0,
                "workspace_bytes": 0, "total_bytes": 0}

    rows: Dict[str, Dict[str, Any]] = {p: blank(p) for p in module_paths}
    unattributed = blank("(unattributed)")

    def fold(path: str, column: str, nbytes: int) -> None:
        owner = _owner_module(path, module_paths) if path else None
        if owner is None and path and model is None:
            row = rows.setdefault(path, blank(path))
            row[column] += nbytes
            return
        if owner is None:
            unattributed[column] += nbytes
            return
        parts = owner.split(".")
        for i in range(len(parts)):
            rows[".".join(parts[:i + 1])][column] += nbytes

    for cat, path, nbytes in arg_rows:
        if cat == "params":
            # the owning module is the path minus the leaf param name
            fold(path.rsplit(".", 1)[0] if "." in path else path,
                 "param_bytes", nbytes)
        elif cat == "opt_state":
            fold(path.rsplit(".", 1)[0] if "." in path else "",
                 "opt_bytes", nbytes)
    for kind, path, nbytes in live_rows:
        fold(path, "act_bytes" if kind == "activation"
             else "workspace_bytes", nbytes)
    if model is not None:
        ordered = [rows[name] for name, _ in model.named_modules()
                   if name]
    else:
        ordered = [rows[p] for p in sorted(rows)]
    for row in ordered + [unattributed]:
        row["total_bytes"] = (row["param_bytes"] + row["opt_bytes"]
                              + row["act_bytes"]
                              + row["workspace_bytes"])
    ordered = [r for r in ordered if r["total_bytes"]]
    if unattributed["total_bytes"]:
        ordered.append(unattributed)

    # downsampled timeline (index, live temp bytes) — the CLI sparkline
    stride = max(1, len(series) // 120)
    timeline = [[i, series[i]] for i in range(0, len(series), stride)]
    return {
        "peak_bytes": args_total + peak,
        "args_bytes": args_total,
        "temp_peak_bytes": peak,
        "donated_bytes": donated_bytes,
        "categories": {**cats,
                       "activations_at_peak": act_at_peak,
                       "workspace_at_peak": ws_at_peak,
                       "donated": donated_bytes},
        "rows": ordered,
        "largest": largest[:12],
        "timeline": timeline,
        "n_instructions": len(instrs),
    }


def memory_facts_compiled(compiled_or_text, model=None) -> Dict[str, Any]:
    """The full memory payload from a compiled executable (or its HLO
    text): the walker's decomposition plus XLA's own
    ``memory_analysis()`` numbers for cross-checking, the HBM limit
    when one is known, and the live allocator stats."""
    text = compiled_or_text if isinstance(compiled_or_text, str) \
        else compiled_or_text.as_text()
    out = analyze_hlo_memory(text, model=model)
    if not isinstance(compiled_or_text, str):
        try:
            from bigdl_tpu.telemetry.device import memory_facts

            ma = memory_facts(compiled_or_text)
            if ma:
                out["memory_analysis"] = ma
        except Exception:  # noqa: BLE001 - the cross-check is optional
            pass
    limit = hbm_limit_bytes()
    if limit:
        out["hbm_limit_bytes"] = limit
    live = live_hbm()
    if live:
        out["live"] = live
    return out


# -- live allocator + HBM budget ----------------------------------------------
#: live-peak / limit fraction past which a device is one allocation
#: from RESOURCE_EXHAUSTED — the memory/pressure instant, the fleet
#: blame note, and tools/tpu_watch.sh's !PRESSURE all use this line
PRESSURE_FRACTION = 0.95


def live_peak_and_limit(live: Optional[List[Dict[str, Any]]],
                        budget: Optional[int] = None
                        ) -> Tuple[int, int]:
    """(max live peak bytes, display limit) over per-device allocator
    rows.  The limit prefers the rows' own ``bytes_limit`` — the
    allocator's reservation-adjusted ceiling is the BINDING constraint,
    tighter than the spec-sheet budget — falling back to ``budget``."""
    peak = 0
    limits: List[int] = []
    for row in live or []:
        p = row.get("peak_bytes_in_use") or row.get("bytes_in_use") or 0
        peak = max(peak, int(p))
        if row.get("bytes_limit"):
            limits.append(int(row["bytes_limit"]))
    limit = max(limits) if limits else int(budget or 0)
    return peak, limit


def pressured_device(live: Optional[List[Dict[str, Any]]],
                     budget: Optional[int] = None
                     ) -> Optional[Dict[str, int]]:
    """The first device whose live peak is within
    :data:`PRESSURE_FRACTION` of its OWN allocator limit (its
    ``bytes_limit``; the configured budget only when the allocator
    reports none) — judged per row, because the allocator ceiling is
    what RESOURCE_EXHAUSTED actually fires against."""
    for row in live or []:
        p = row.get("peak_bytes_in_use") or row.get("bytes_in_use") or 0
        lim = row.get("bytes_limit") or budget
        if lim and p >= PRESSURE_FRACTION * int(lim):
            return {"device": row.get("device"), "peak_bytes": int(p),
                    "limit_bytes": int(lim)}
    return None


def live_hbm() -> List[Dict[str, Any]]:
    """Per-local-device allocator stats (bytes in use / peak / limit)
    — empty on backends that report none (CPU)."""
    out: List[Dict[str, Any]] = []
    try:
        import jax

        for dev in jax.local_devices():
            stats = dev.memory_stats()
            if not stats:
                continue
            row: Dict[str, Any] = {"device": dev.id}
            for key in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit", "largest_alloc_size"):
                if key in stats:
                    row[key] = int(stats[key])
            out.append(row)
    except Exception:  # noqa: BLE001 - stats are best-effort
        pass
    return out


def hbm_limit_bytes() -> Optional[int]:
    """The per-device HBM budget: ``BIGDL_HBM_GB`` wins, else the
    per-chip table (``device.hbm_per_device``), else the live
    allocator's ``bytes_limit``.  None when nothing knows."""
    env = os.environ.get("BIGDL_HBM_GB")
    if env:
        try:
            return int(float(env) * (1 << 30))
        except ValueError:
            pass
    try:
        import jax

        from bigdl_tpu.telemetry.device import hbm_per_device

        dev = jax.devices()[0]
        table = hbm_per_device(dev.device_kind)
        if table:
            return int(table)
        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001
        pass
    return None


# -- building attribution from live objects -----------------------------------
def attribute_memory_train_step(step, x, y, key=None) -> Dict[str, Any]:
    """Memory attribution of a TrainStep's program: lower + XLA-compile
    (the scheduler must run for the timeline to exist), walk the text.
    ``x``/``y`` may be ShapeDtypeStructs — only a compile happens, never
    a dispatch (the fit estimator's device-free path)."""
    import jax

    from bigdl_tpu.nn.module import stamp_scope_names

    stamp_scope_names(step.model)
    if key is None:
        key = jax.random.key(0)
    compiled = step._build().lower(
        step.params, step.opt_state, step.buffers, x, y, key).compile()
    out = memory_facts_compiled(compiled, model=step.model)
    out["program"] = "train_step"
    return out


def attribute_memory_model(name: str, batch: int = 8, devices: int = 0,
                           sync: str = "allreduce",
                           remat: bool = False) -> Dict[str, Any]:
    """Registry-model memory attribution over a fresh ``data``-axis
    mesh spanning ``devices`` devices (0 = single device) — CPU
    friendly: one local XLA compile, no run, no data.  ``remat`` builds
    the step with whole-model rematerialization so the estimator can
    answer "would remat make it fit"."""
    import jax

    import bigdl_tpu.optim as optim
    from bigdl_tpu.models import registry
    from bigdl_tpu.parallel.mesh import DATA_AXIS, make_mesh
    from bigdl_tpu.parallel.train_step import TrainStep

    n = devices or 1
    avail = len(jax.devices())
    if n > avail:
        raise ValueError(
            f"--mesh {n} needs {n} local devices but only {avail} exist "
            f"— on CPU set XLA_FLAGS=--xla_force_host_platform_device_"
            f"count={n} (with JAX_PLATFORMS=cpu) to emulate the mesh")
    mesh = make_mesh((n,), (DATA_AXIS,), devices=jax.devices()[:n]) \
        if n > 1 else None
    model = registry.build_model(name)
    spec = registry.input_spec(name, batch)
    pieces = registry.train_pieces(name, batch)
    if pieces is None:
        raise ValueError(f"registry model {name!r} has no training "
                         f"pieces — memory attribution needs a train "
                         f"step")
    criterion, target_spec = pieces
    step = TrainStep(model, criterion,
                     optim.SGD(learning_rate=0.01, momentum=0.9),
                     mesh=mesh, parameter_sync=sync, remat=remat)
    out = attribute_memory_train_step(step, spec, target_spec)
    out["model"] = name
    out["batch"] = batch
    out["mesh"] = {"devices": n, "sync": sync}
    out["remat"] = bool(remat)
    return out


def memory_from_events(events: List[Dict[str, Any]]
                       ) -> Optional[Dict[str, Any]]:
    """The last ``memory`` event of a run log (the read-from-artifact
    CLI path), or None."""
    found = None
    for ev in events:
        if ev.get("kind") == "memory":
            found = ev
    if found is None:
        return None
    return {k: v for k, v in found.items()
            if k not in ("v", "ts", "pid", "tid", "kind")}


# -- the fit estimator --------------------------------------------------------
def remat_advice(mem_result: Dict[str, Any],
                 attr_result: Optional[Dict[str, Any]]
                 ) -> List[Dict[str, Any]]:
    """Rank top-level blocks by activation-bytes-saved per
    recompute-FLOP: wrapping the highest-ratio block in ``nn.Remat``
    (or enabling ``BIGDL_SCAN_LAYERS`` remat for scanned stacks) buys
    the most HBM for the least recompute."""
    flops_by_path: Dict[str, float] = {}
    for row in (attr_result or {}).get("rows", []):
        flops_by_path[row.get("path", "")] = float(
            row.get("flops_fwd", 0.0))
    advice = []
    for row in mem_result.get("rows", []):
        path = row.get("path", "")
        if not path or "." in path or path.startswith("("):
            continue  # top-level blocks only — the wrappable units
        act = int(row.get("act_bytes", 0))
        if act <= 0:
            continue
        flops = flops_by_path.get(path, 0.0)
        advice.append({
            "path": path, "class": row.get("class", ""),
            "act_bytes": act, "recompute_flops": flops,
            "bytes_per_mflop": act / max(flops / 1e6, 1e-9),
        })
    advice.sort(key=lambda r: -r["bytes_per_mflop"])
    return advice


def fit_estimate(name: str, batch: int = 8, devices: int = 0,
                 sync: str = "allreduce", remat: bool = False,
                 advise: bool = True) -> Dict[str, Any]:
    """Device-free fit check: predicted per-device peak vs the HBM
    budget, plus the remat advisor (computed from the same step)."""
    out = attribute_memory_model(name, batch=batch, devices=devices,
                                 sync=sync, remat=remat)
    limit = out.get("hbm_limit_bytes") or hbm_limit_bytes()
    if limit:
        out["hbm_limit_bytes"] = limit
        out["fits"] = out["peak_bytes"] <= limit
        out["headroom_pct"] = round(
            (limit - out["peak_bytes"]) / limit * 100.0, 2)
    if advise:
        try:
            from bigdl_tpu.telemetry.attribution import attribute_model

            attr = attribute_model(name, batch=batch)
        except Exception:  # noqa: BLE001 - advice is optional
            attr = None
        out["remat_advice"] = remat_advice(out, attr)
    return out


# -- OOM forensics ------------------------------------------------------------
class MemoryExhaustedError(RuntimeError):
    """A device RESOURCE_EXHAUSTED enriched with the memory evidence
    (largest buffers, per-category totals, live-vs-limit) — the
    postmortem travels WITH the exception and was flight-dumped before
    the raise."""

    def __init__(self, message: str,
                 evidence: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.evidence = evidence or {}


_OOM_TOKENS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
               "OOM: ")


def is_oom(exc: BaseException) -> bool:
    """Whether an exception is a device out-of-memory (the backend
    spells it RESOURCE_EXHAUSTED; jaxlib wraps it in XlaRuntimeError)."""
    text = f"{type(exc).__name__}: {exc}"
    return any(tok in text for tok in _OOM_TOKENS)


def _leaf_device_bytes(leaf) -> int:
    """Per-device bytes of one array leaf (a sharded leaf costs each
    device only its shard)."""
    import numpy as np

    shape = tuple(getattr(leaf, "shape", ()) or ())
    itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", 4)
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None:
        try:
            shape = tuple(sharding.shard_shape(shape))
        except Exception:  # noqa: BLE001 - fall back to global bytes
            pass
    n = 1
    for d in shape:
        n *= int(d)
    return int(n * itemsize)


def oom_evidence(trees: Dict[str, Any], context: str = "",
                 error: str = "", top_k: int = 16) -> Dict[str, Any]:
    """Host-side postmortem of a device OOM: the top-k largest known
    buffers (with tree paths), per-category byte totals, and the live
    allocator stats vs the HBM limit.  Deliberately NO device work —
    the device just proved it has no memory to spare."""
    import jax

    buffers: List[Dict[str, Any]] = []
    categories: Dict[str, int] = {}
    for cat, tree in (trees or {}).items():
        total = 0
        try:
            flat = jax.tree_util.tree_flatten_with_path(tree)[0]
        except Exception:  # noqa: BLE001 - any tree shape
            flat = []
        for path, leaf in flat:
            nbytes = _leaf_device_bytes(leaf)
            total += nbytes
            buffers.append({"category": cat,
                            "path": jax.tree_util.keystr(path),
                            "bytes": nbytes})
        categories[cat] = total
    buffers.sort(key=lambda b: -b["bytes"])
    out: Dict[str, Any] = {
        "context": context,
        "error": error[:2000],
        "categories": categories,
        "known_bytes": sum(categories.values()),
        "largest_buffers": buffers[:top_k],
        "live": live_hbm(),
    }
    limit = hbm_limit_bytes()
    if limit:
        out["hbm_limit_bytes"] = limit
        for row in out["live"]:
            if row.get("peak_bytes_in_use"):
                row["pct_of_limit"] = round(
                    row["peak_bytes_in_use"] / limit * 100.0, 2)
    return out


def raise_oom(exc: BaseException, trees: Dict[str, Any],
              context: str = "") -> None:
    """Enrich a RESOURCE_EXHAUSTED with the memory postmortem, flight-
    dump it (the evidence must survive the crash), and re-raise as
    :class:`MemoryExhaustedError`."""
    evidence = oom_evidence(trees, context=context, error=str(exc))
    try:
        from bigdl_tpu import telemetry

        recorder = telemetry.flight_recorder()
        if recorder is not None:
            path = recorder.dump("oom", evidence)
            if path:
                evidence["flight_dump"] = path
    except Exception:  # noqa: BLE001 - a dying step must not die harder
        pass
    lines = [f"device out of memory in {context or 'a compiled step'}"]
    if evidence.get("known_bytes"):
        lines.append(f"resident (known): "
                     f"{_fmt_bytes(evidence['known_bytes'])} in "
                     + ", ".join(f"{k}={_fmt_bytes(v)}" for k, v in
                                 evidence["categories"].items()))
    for row in evidence.get("live", [])[:1]:
        if row.get("peak_bytes_in_use") and row.get("bytes_limit"):
            lines.append(f"allocator peak "
                         f"{_fmt_bytes(row['peak_bytes_in_use'])} of "
                         f"{_fmt_bytes(row['bytes_limit'])} limit")
    top = evidence.get("largest_buffers", [])[:3]
    if top:
        lines.append("largest buffers: " + ", ".join(
            f"{b['category']}{b['path']}={_fmt_bytes(b['bytes'])}"
            for b in top))
    if evidence.get("flight_dump"):
        lines.append(f"evidence: {evidence['flight_dump']}")
    raise MemoryExhaustedError(" | ".join(lines), evidence) from exc


# -- rendering ---------------------------------------------------------------
def _fmt_bytes(n: float) -> str:
    for div, unit in ((1 << 30, "GiB"), (1 << 20, "MiB"),
                      (1 << 10, "KiB")):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{int(n)} B"


def format_memory(result: Dict[str, Any]) -> str:
    """Human-readable per-module HBM table + fit verdict."""
    lines: List[str] = []
    head = ["== per-module HBM attribution =="]
    for key in ("model", "program", "batch"):
        if key in result:
            head.append(f"{key}={result[key]}")
    mesh = result.get("mesh")
    if mesh:
        head.append(f"mesh={mesh.get('devices')}x{mesh.get('sync')}")
    if result.get("remat"):
        head.append("remat=on")
    lines.append("  ".join(head))
    lines.append(
        f"per-device peak {_fmt_bytes(result.get('peak_bytes', 0))}  "
        f"= args {_fmt_bytes(result.get('args_bytes', 0))} + live temp "
        f"{_fmt_bytes(result.get('temp_peak_bytes', 0))}   (donated "
        f"{_fmt_bytes(result.get('donated_bytes', 0))} re-used in "
        f"place)")
    cats = result.get("categories") or {}
    if cats:
        order = ("params", "opt_state", "buffers", "batch",
                 "activations_at_peak", "workspace_at_peak")
        lines.append("  ".join(f"{k}={_fmt_bytes(cats[k])}"
                               for k in order if cats.get(k)))
    ma = result.get("memory_analysis") or {}
    if ma.get("temp_bytes") is not None:
        est = result.get("temp_peak_bytes", 0)
        xla = ma["temp_bytes"]
        dev = (est - xla) / xla * 100.0 if xla else 0.0
        lines.append(f"XLA memory_analysis: temp "
                     f"{_fmt_bytes(xla)}  (walker {dev:+.1f}% vs XLA)")
    rows = result.get("rows") or []
    if rows:
        lines.append("")
        lines.append("-- by module --")
        pw = max(len(r["path"]) for r in rows)
        cw = max((len(r.get("class", "")) for r in rows), default=5)
        lines.append(f"{'module':<{pw}}  {'class':<{cw}}  "
                     f"{'params':>10}  {'opt':>10}  {'acts@peak':>10}  "
                     f"{'scratch':>10}  {'total':>10}")
        total = max(result.get("peak_bytes", 0), 1)
        for r in rows:
            lines.append(
                f"{r['path']:<{pw}}  {r.get('class', ''):<{cw}}  "
                f"{_fmt_bytes(r['param_bytes']):>10}  "
                f"{_fmt_bytes(r['opt_bytes']):>10}  "
                f"{_fmt_bytes(r['act_bytes']):>10}  "
                f"{_fmt_bytes(r['workspace_bytes']):>10}  "
                f"{_fmt_bytes(r['total_bytes']):>10} "
                f"({r['total_bytes'] / total * 100.0:4.1f}%)")
    largest = result.get("largest") or []
    if largest:
        lines.append("")
        lines.append("-- largest live buffers at peak --")
        for b in largest[:8]:
            lines.append(f"  {_fmt_bytes(b['bytes']):>10}  "
                         f"{b.get('kind', '?'):<10} "
                         f"{b.get('opcode', ''):<16} "
                         f"{b.get('path') or '(unattributed)'}")
    limit = result.get("hbm_limit_bytes")
    if limit:
        fits = result.get("fits")
        verdict = "FITS" if fits else ("DOES NOT FIT"
                                       if fits is not None else "?")
        lines.append("")
        lines.append(f"HBM budget {_fmt_bytes(limit)}/device "
                     f"(BIGDL_HBM_GB / device table): {verdict}"
                     + (f", headroom {result['headroom_pct']:.1f}%"
                        if result.get("headroom_pct") is not None
                        else ""))
    advice = result.get("remat_advice") or []
    if advice:
        lines.append("")
        lines.append("-- remat advisor (activation bytes saved per "
                     "recompute-MFLOP; wrap the top block in nn.Remat) "
                     "--")
        for a in advice[:6]:
            lines.append(f"  {a['path']:<12} {a.get('class', ''):<18} "
                         f"acts {_fmt_bytes(a['act_bytes']):>10}   "
                         f"recompute {a['recompute_flops'] / 1e6:9.1f} "
                         f"MF   {a['bytes_per_mflop']:10.1f} B/MF")
    live = result.get("live") or []
    for row in live[:1]:
        if row.get("peak_bytes_in_use"):
            lines.append("")
            lines.append(
                f"live allocator: peak "
                f"{_fmt_bytes(row['peak_bytes_in_use'])} in use "
                f"{_fmt_bytes(row.get('bytes_in_use', 0))}"
                + (f" limit {_fmt_bytes(row['bytes_limit'])}"
                   if row.get("bytes_limit") else ""))
    return "\n".join(lines)
