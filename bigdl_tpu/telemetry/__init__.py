"""Unified runtime telemetry (docs/observability.md).

One process-wide event stream that every hot layer emits into — the
Optimizer loop stages, TrainStep/EvalStep compile + retrace events,
dataset prefetch depth, checkpointing, the straggler watchdog — persisted
as an append-only JSONL log per run and summarized by
``python -m bigdl_tpu.telemetry <run.jsonl>`` (per-stage table, step
percentiles, compile/retrace timeline, MFU, Chrome trace export).

Enable with ``BIGDL_TELEMETRY=<dir>`` (the Optimizer starts/ends the run
around ``optimize()``), or programmatically::

    from bigdl_tpu import telemetry
    with telemetry.run("/tmp/tele", meta={"job": "resnet"}):
        optimizer.optimize()

The module-level helpers (``span``/``stage``/``counter``/``gauge``/
``instant``) are no-ops costing one falsy check when no run is active,
so instrumented code needs no gating of its own.
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager, nullcontext
from typing import Any, Dict, Optional

from bigdl_tpu.telemetry.tracer import (SCHEMA_VERSION, JsonlSink,
                                        MemorySink, Tracer)

__all__ = ["SCHEMA_VERSION", "Tracer", "JsonlSink", "MemorySink",
           "enabled", "get", "start_run", "end_run", "run", "maybe_run",
           "last_run_path", "metrics_server", "flight_recorder",
           "fleet_watcher", "goodput", "span",
           "stage", "counter", "gauge", "instant", "emit"]

_active: Optional[Tracer] = None
_last_run_path: Optional[str] = None
_metrics_server = None
_flight = None
_fleet = None
_ledger = None
_lifecycle_lock = threading.Lock()


def enabled() -> bool:
    """True when a run is active — the one-check fast path."""
    return _active is not None


def get() -> Optional[Tracer]:
    """The active tracer, or None.  Hot loops fetch it once per run and
    branch on the local."""
    return _active


def last_run_path() -> Optional[str]:
    """Path of the most recent JSONL run log (survives ``end_run`` so a
    CLI can point the user at the artifact it just produced)."""
    return _last_run_path


def metrics_server():
    """The live OpenMetrics HTTP server bound to the active run, or None
    (``BIGDL_METRICS_PORT`` unset / no run active).  ``.port`` carries
    the bound port — the way to discover an ephemeral ``:0`` bind."""
    return _metrics_server


def flight_recorder():
    """The crash flight recorder bound to the active run, or None
    (``BIGDL_FLIGHT=0`` / no run active).  ``.dump(reason)`` writes the
    ring to a ``flight-<stamp>.json``; the Optimizer calls it on
    HealthError, straggler firings, and crashes."""
    return _flight


def fleet_watcher():
    """The live cross-host fleet aggregator bound to the active run, or
    None (non-coordinator process, single-process run,
    ``BIGDL_FLEET_INTERVAL=0``, or no JSONL dir to tail).  ``.snapshot()``
    is the /status ``fleet`` block (telemetry/fleet.py)."""
    return _fleet


def goodput() -> Optional[Dict[str, Any]]:
    """Live goodput/badput decomposition of the active run (the ledger
    fold every sink shares), or None when no run is active or nothing
    has been emitted yet.  The same report is written as the run's
    final ``goodput`` event by :func:`end_run`."""
    ledger = _ledger
    return ledger.event_fields() if ledger is not None else None


def _default_meta() -> Dict[str, Any]:
    meta: Dict[str, Any] = {"schema": SCHEMA_VERSION}
    inc = os.environ.get("BIGDL_SUPERVISOR_INCARNATION")
    if inc is not None:
        try:  # stitchable chains: which supervisor incarnation is this
            meta["incarnation"] = int(inc)
        except ValueError:
            pass
    try:  # device facts are best-effort: telemetry must work sans jax
        import jax

        dev = jax.devices()[0]
        meta.update(device_kind=dev.device_kind,
                    device_count=jax.device_count(),
                    process_index=jax.process_index(),
                    process_count=jax.process_count())
    except Exception:  # noqa: BLE001 - meta only
        pass
    return meta


def start_run(path_or_dir: Optional[str] = None,
              meta: Optional[Dict[str, Any]] = None,
              sinks=None) -> Tracer:
    """Install the process-wide tracer.  ``path_or_dir``: a ``.jsonl``
    path is used as-is; a directory gets a fresh
    ``run-<stamp>-<pid>.jsonl``; None writes to no file (pass ``sinks``,
    e.g. a MemorySink, instead).  Raises if a run is already active —
    nested runs would interleave two schedules into one file."""
    global _active, _last_run_path, _metrics_server, _flight, _fleet, \
        _ledger
    with _lifecycle_lock:
        if _active is not None:
            raise RuntimeError("a telemetry run is already active; "
                               "end_run() it first")
        full_meta = _default_meta()
        full_meta.update(meta or {})
        all_sinks = list(sinks or [])
        try:  # the run-level goodput ledger rides as one more sink
            from bigdl_tpu.telemetry.ledger import LedgerFold

            _ledger = LedgerFold()
            all_sinks.append(_ledger)
        except Exception:  # noqa: BLE001 - observers never kill the run
            _ledger = None
        run_dir = None
        if path_or_dir is not None:
            path = path_or_dir
            if not path.endswith(".jsonl"):
                stamp = time.strftime("%Y%m%d_%H%M%S")
                pidx = full_meta.get("process_index", 0)
                path = os.path.join(
                    path_or_dir,
                    f"run-{stamp}-p{pidx}-{os.getpid()}.jsonl")
            all_sinks.append(JsonlSink(path))
            _last_run_path = path
            run_dir = os.path.dirname(os.path.abspath(path))
        _flight = _maybe_flight()
        if _flight is not None:
            all_sinks.append(_flight)
        tracer = Tracer(sinks=all_sinks, meta=full_meta)
        tracer.start()
        _active = tracer
        _metrics_server = _maybe_serve_metrics(tracer)
        _fleet = _maybe_fleet(run_dir, full_meta)
        return tracer


def _maybe_flight():
    """A FlightRecorder sink sized by ``BIGDL_FLIGHT`` (default 2048
    events; 0 disables)."""
    from bigdl_tpu.utils.config import get_config

    capacity = get_config().flight_events
    if capacity <= 0:
        return None
    try:
        from bigdl_tpu.telemetry.flight import FlightRecorder

        return FlightRecorder(capacity)
    except Exception:  # noqa: BLE001 - observers never kill the run
        return None


def _maybe_serve_metrics(tracer):
    """Bring up the OpenMetrics/status HTTP endpoint for this run when
    ``BIGDL_METRICS_PORT`` names a port (0 = ephemeral).  Failure to
    bind degrades to a warning — the exporter is an observer."""
    from bigdl_tpu.utils.config import get_config

    port = get_config().metrics_port
    if port is None:
        return None
    try:
        from bigdl_tpu.telemetry.metrics_http import start_server

        server = start_server(tracer, port)
        tracer.emit("event", name="metrics/serving", port=server.port)
        return server
    except Exception as e:  # noqa: BLE001 - observers never kill the run
        import logging

        logging.getLogger("bigdl_tpu.telemetry").warning(
            "metrics endpoint disabled (%s: %s)", type(e).__name__, e)
        return None


def _maybe_fleet(run_dir, meta):
    """A live FleetWatcher over the run-log directory, coordinator of a
    multi-process run only (``BIGDL_FLEET_INTERVAL`` seconds poll; 0
    disables).  Non-coordinators write their log and are tailed by the
    coordinator's watcher — one aggregator per fleet."""
    from bigdl_tpu.utils.config import get_config

    interval = get_config().fleet_interval
    if run_dir is None or interval <= 0:
        return None
    if meta.get("process_index", 0) != 0 \
            or meta.get("process_count", 1) < 2:
        return None
    try:
        from bigdl_tpu.telemetry.fleet import FleetWatcher

        return FleetWatcher(run_dir, interval).start()
    except Exception:  # noqa: BLE001 - observers never kill the run
        return None


def end_run() -> None:
    """Close the active run (flushes and closes sinks, stops the metrics
    endpoint and the fleet watcher); no-op when no run is active."""
    global _active, _metrics_server, _flight, _fleet, _ledger
    if _fleet is not None:
        try:
            # one final poll under the still-open tracer so a short
            # run's last flushed events make it into the fleet gauges
            _fleet.poll_once()
        except Exception:  # noqa: BLE001
            pass
    with _lifecycle_lock:
        tracer, _active = _active, None
        server, _metrics_server = _metrics_server, None
        watcher, _fleet = _fleet, None
        ledger, _ledger = _ledger, None
        _flight = None
    if tracer is not None and ledger is not None:
        try:
            # the run's last word: the goodput/badput decomposition of
            # everything emitted before it (written before run_end)
            fields = ledger.event_fields()
            if fields is not None:
                tracer.emit("goodput", **fields)
        except Exception:  # noqa: BLE001 - shutdown must never raise
            pass
    if watcher is not None:
        try:
            watcher.stop()
        except Exception:  # noqa: BLE001 - shutdown must never raise
            pass
    if server is not None:
        try:
            server.stop()
        except Exception:  # noqa: BLE001 - shutdown must never raise
            pass
    if tracer is not None:
        tracer.close()


@contextmanager
def run(path_or_dir: Optional[str] = None,
        meta: Optional[Dict[str, Any]] = None, sinks=None):
    tracer = start_run(path_or_dir, meta=meta, sinks=sinks)
    try:
        yield tracer
    finally:
        end_run()


@contextmanager
def maybe_run(meta: Optional[Dict[str, Any]] = None):
    """Config-gated run ownership for entry points (bench.py,
    profile_bench, models/cli perf): start a JSONL run when
    ``BIGDL_TELEMETRY`` names a directory and no run is active yet.
    Yields the owned run-log path, or None when telemetry is off or an
    OUTER scope owns the stream — in which case that run is left
    untouched.  The owned run is ended on every exit path, so an
    exception inside the block can never leak the process-wide tracer
    or an unflushed log."""
    from bigdl_tpu.utils.config import get_config

    if not get_config().telemetry_dir or enabled():
        yield None
        return
    start_run(get_config().telemetry_dir, meta=meta)
    try:
        yield _last_run_path
    finally:
        end_run()


# -- no-op-when-disabled emit helpers ---------------------------------------
def span(name: str, **attrs):
    """Context manager timing a with-block as a span (nullcontext when
    disabled)."""
    tracer = _active
    if tracer is None:
        return nullcontext()
    return tracer.span(name, **attrs)


def stage(name: str, dur: float, **attrs) -> None:
    tracer = _active
    if tracer is not None:
        tracer.stage(name, dur, **attrs)


def counter(name: str, value: float, **attrs) -> None:
    tracer = _active
    if tracer is not None:
        tracer.counter(name, value, **attrs)


def gauge(name: str, value: float, **attrs) -> None:
    tracer = _active
    if tracer is not None:
        tracer.gauge(name, value, **attrs)


def instant(name: str, **attrs) -> None:
    tracer = _active
    if tracer is not None:
        tracer.instant(name, **attrs)


def emit(kind: str, **fields) -> None:
    tracer = _active
    if tracer is not None:
        tracer.emit(kind, **fields)
