"""Training-health layer: probe vocabulary, host-side detectors, and the
policy that turns findings into warn / skip-step / halt decisions.

The in-graph half lives in ``parallel/train_step.py``: when a TrainStep
is built with ``health_probe=True`` its compiled step computes ONE fused
reduction per iteration — global gradient norm, parameter norm, update
norm, and nonfinite gradient/parameter counts — returned as a 5-vector
next to the loss, so reading it costs a d2h copy of five floats after
the step fetch the driver already performs (no extra device sync).

This module is deliberately stdlib-only (no jax/numpy at import): the
report/diff readers and the HTTP exporter consume the same vocabulary
without dragging a device runtime in.

Event vocabulary (all carried by the run log, docs/observability.md):

- kind ``health`` — one per probed step: ``step``, ``grad_norm``,
  ``param_norm``, ``update_norm``, ``update_ratio``, ``nonfinite_grads``,
  ``nonfinite_params``, ``loss``.
- instants ``health/nonfinite``, ``health/skip``, ``health/loss_spike``,
  ``health/plateau``, ``health/grad_explosion``, ``health/halt`` — the
  detector/policy findings, in the same timeline as the steps they
  describe.

Policy (``HealthPolicy``): ``on_nonfinite`` escalates warn → skip →
halt.  ``skip`` additionally makes the compiled step KEEP the previous
params/opt-state/buffers whenever the step was nonfinite (in-graph
select — the poisoned update never lands).  Halting is expressed as a
trigger-style predicate over the policy's running state
(``halt_when``), so "halt after 3 nonfinite steps" is::

    HealthPolicy(on_nonfinite="halt", halt_after=3)
    # or, with an explicit optim.Trigger over the health state:
    HealthPolicy(halt_when=Trigger(lambda s: s["consecutive_nonfinite"] >= 3))

The driver raises :class:`HealthError` carrying the offending step's
evidence when the predicate fires.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["PROBE_FIELDS", "HealthError", "HealthPolicy", "LossEwma",
           "probe_stats"]

#: order of the scalars in the TrainStep health vector (device layout).
PROBE_FIELDS = ("grad_norm", "param_norm", "update_norm",
                "nonfinite_grads", "nonfinite_params")


def probe_stats(vec, loss: float) -> Dict[str, float]:
    """Decode a fetched health 5-vector (any indexable of floats) into
    the named stats dict the events/policy/exporter share.  NaN norms are
    kept as-is (they ARE the finding); counts are rounded to ints."""
    stats: Dict[str, Any] = {}
    for i, name in enumerate(PROBE_FIELDS):
        v = float(vec[i])
        if name.startswith("nonfinite"):
            stats[name] = int(v) if math.isfinite(v) else -1
        else:
            stats[name] = v
    denom = stats["param_norm"]
    stats["update_ratio"] = (stats["update_norm"] / denom
                             if denom and math.isfinite(denom) else 0.0)
    stats["loss"] = float(loss)
    return stats


def _nonfinite(stats: Dict[str, Any]) -> bool:
    return bool(stats.get("nonfinite_grads") or stats.get("nonfinite_params")
                or not math.isfinite(stats.get("loss", 0.0)))


class HealthError(RuntimeError):
    """Training halted by the health policy.  Carries the offending
    step and the evidence that tripped the halt — the probe stats of the
    final step plus the policy's running counters — so a postmortem
    needs no log spelunking."""

    def __init__(self, step: int, reason: str,
                 evidence: Optional[Dict[str, Any]] = None):
        self.step = step
        self.reason = reason
        self.evidence = dict(evidence or {})
        super().__init__(f"training halted at step {step}: {reason} "
                         f"(evidence: {self.evidence})")


class LossEwma:
    """Host-side loss-spike / plateau detector over the step-loss stream.

    Spike: the loss exceeds the running EWMA by ``spike_factor`` EWMA
    standard deviations AND by ``min_rel`` of the EWMA's magnitude
    (after ``warmup`` finite samples) — the relative floor keeps the
    early, still-converging variance estimate from flagging ordinary
    minibatch noise.  Plateau: the
    EWMA's relative improvement stays below ``plateau_rtol`` for
    ``plateau_patience`` consecutive steps (0 disables).  Nonfinite
    losses are not folded into the EWMA — they are the nonfinite
    detector's finding, and folding them in would blind this one."""

    def __init__(self, alpha: float = 0.1, spike_factor: float = 4.0,
                 warmup: int = 8, min_rel: float = 0.1,
                 plateau_patience: int = 0,
                 plateau_rtol: float = 1e-3):
        self.alpha = alpha
        self.spike_factor = spike_factor
        self.min_rel = min_rel
        self.warmup = max(1, warmup)
        self.plateau_patience = plateau_patience
        self.plateau_rtol = plateau_rtol
        self.mean: Optional[float] = None
        self.var = 0.0
        self.samples = 0
        self._flat = 0
        self._plateau_fired = False

    def update(self, step: int, loss: float) -> List[Tuple[str, Dict]]:
        """Feed one step's loss; returns findings as (instant name,
        attrs) pairs."""
        findings: List[Tuple[str, Dict]] = []
        if not math.isfinite(loss):
            return findings
        if self.mean is None:
            self.mean, self.samples = loss, 1
            return findings
        std = math.sqrt(max(self.var, 0.0))
        deviation = loss - self.mean
        if self.samples >= self.warmup \
                and deviation > self.spike_factor * max(std, 1e-12) \
                and deviation > self.min_rel * max(abs(self.mean), 1e-12):
            findings.append(("health/loss_spike", {
                "step": step, "loss": loss, "ewma": self.mean,
                "ewma_std": std, "factor": self.spike_factor}))
        prev = self.mean
        delta = loss - self.mean
        self.mean += self.alpha * delta
        self.var = (1.0 - self.alpha) * (self.var + self.alpha * delta * delta)
        self.samples += 1
        if self.plateau_patience:
            improved = prev - self.mean > self.plateau_rtol * abs(prev)
            self._flat = 0 if improved else self._flat + 1
            if improved:
                self._plateau_fired = False
            if self._flat >= self.plateau_patience \
                    and self.samples > self.warmup \
                    and not self._plateau_fired:
                self._plateau_fired = True  # once per plateau, not per step
                findings.append(("health/plateau", {
                    "step": step, "ewma": self.mean,
                    "flat_steps": self._flat,
                    "rtol": self.plateau_rtol}))
        return findings


class HealthPolicy:
    """Decides what a step's probe stats mean for the run.

    ``on_nonfinite``: ``"off"`` (no probes), ``"warn"`` (log + events
    only), ``"skip"`` (in-graph skip of the poisoned update, then the
    halt predicate still applies), ``"halt"`` (events + halt predicate).
    ``halt_after``: the default halt predicate — ``halt_when`` fires when
    ``consecutive_nonfinite >= halt_after``.  Pass an ``optim.Trigger``
    (or any callable over the state dict) as ``halt_when`` to express a
    different condition; the state dict carries ``step``,
    ``nonfinite_steps`` (total), ``consecutive_nonfinite``,
    ``skipped_steps``, ``spikes``, ``plateaus``, ``grad_explosions``.
    ``max_grad_norm``: warn-level gradient-explosion threshold (None
    disables).
    """

    ACTIONS = ("off", "warn", "skip", "halt")

    def __init__(self, on_nonfinite: str = "halt", halt_after: int = 3,
                 max_grad_norm: Optional[float] = None,
                 spike_factor: float = 4.0, ewma_alpha: float = 0.1,
                 ewma_warmup: int = 8, plateau_patience: int = 0,
                 plateau_rtol: float = 1e-3,
                 halt_when: Optional[Callable[[Dict], bool]] = None):
        if on_nonfinite not in self.ACTIONS:
            raise ValueError(f"unknown on_nonfinite {on_nonfinite!r} "
                             f"({' | '.join(self.ACTIONS)})")
        if halt_after < 1:
            raise ValueError("halt_after must be >= 1")
        # kept for fresh(): a policy is CONFIG + running state; each run
        # attempt needs the config with pristine state
        self._ctor = dict(
            on_nonfinite=on_nonfinite, halt_after=halt_after,
            max_grad_norm=max_grad_norm, spike_factor=spike_factor,
            ewma_alpha=ewma_alpha, ewma_warmup=ewma_warmup,
            plateau_patience=plateau_patience, plateau_rtol=plateau_rtol,
            halt_when=halt_when)
        self.on_nonfinite = on_nonfinite
        self.halt_after = halt_after
        self.max_grad_norm = max_grad_norm
        self._halt_when = halt_when
        self.ewma = LossEwma(alpha=ewma_alpha, spike_factor=spike_factor,
                             warmup=ewma_warmup,
                             plateau_patience=plateau_patience,
                             plateau_rtol=plateau_rtol)
        self.state: Dict[str, Any] = {
            "step": 0, "nonfinite_steps": 0, "consecutive_nonfinite": 0,
            "skipped_steps": 0, "spikes": 0, "plateaus": 0,
            "grad_explosions": 0}

    @classmethod
    def from_config(cls, cfg) -> Optional["HealthPolicy"]:
        """Policy from the typed config (BIGDL_HEALTH /
        BIGDL_HEALTH_HALT_AFTER); None when probes are off."""
        if cfg.health_action == "off":
            return None
        return cls(on_nonfinite=cfg.health_action,
                   halt_after=cfg.health_halt_after)

    def fresh(self) -> "HealthPolicy":
        """Same configuration, pristine running state — one per run
        attempt, so counters/EWMA from before a checkpoint restore (or a
        previous ``optimize()`` call) never leak into the next."""
        return HealthPolicy(**self._ctor)

    @property
    def enabled(self) -> bool:
        return self.on_nonfinite != "off"

    @property
    def skip_nonfinite(self) -> bool:
        return self.on_nonfinite == "skip"

    def _should_halt(self) -> bool:
        if self._halt_when is not None:
            return bool(self._halt_when(self.state))
        if self.on_nonfinite in ("skip", "halt"):
            return self.state["consecutive_nonfinite"] >= self.halt_after
        return False

    def observe(self, step: int,
                stats: Dict[str, Any]) -> Tuple[str, List[Tuple[str, Dict]]]:
        """Fold one step's stats into the running state.  Returns
        ``(action, findings)`` — action is ``"ok"``/``"warn"``/
        ``"skip"``/``"halt"``; findings are (instant name, attrs) pairs
        for the caller to emit.  On ``"halt"`` the caller raises
        :class:`HealthError` with ``self.evidence(step, stats)``."""
        st = self.state
        st["step"] = step
        findings = list(self.ewma.update(step, stats.get("loss", 0.0)))
        for name, _ in findings:
            if name == "health/loss_spike":
                st["spikes"] += 1
            elif name == "health/plateau":
                st["plateaus"] += 1
        action = "ok" if not findings else "warn"
        gn = stats.get("grad_norm", 0.0)
        if self.max_grad_norm is not None and math.isfinite(gn) \
                and gn > self.max_grad_norm:
            st["grad_explosions"] += 1
            findings.append(("health/grad_explosion", {
                "step": step, "grad_norm": gn,
                "max_grad_norm": self.max_grad_norm}))
            action = "warn"
        if _nonfinite(stats):
            st["nonfinite_steps"] += 1
            st["consecutive_nonfinite"] += 1
            findings.append(("health/nonfinite", {
                "step": step, "consecutive": st["consecutive_nonfinite"],
                **{k: stats[k] for k in ("nonfinite_grads",
                                         "nonfinite_params", "loss")}}))
            action = "warn"
            if self.skip_nonfinite:
                st["skipped_steps"] += 1
                findings.append(("health/skip", {
                    "step": step, "skipped": st["skipped_steps"]}))
                action = "skip"
        else:
            st["consecutive_nonfinite"] = 0
        if self._should_halt():
            findings.append(("health/halt", {
                "step": step, "reason": "nonfinite",
                "consecutive": st["consecutive_nonfinite"]}))
            action = "halt"
        return action, findings

    def evidence(self, step: int, stats: Dict[str, Any]) -> Dict[str, Any]:
        """The HealthError payload: the final step's probe stats plus the
        policy's counters."""
        return {**stats, **{k: v for k, v in self.state.items()
                            if k != "step"}, "step": step}
