"""The event-log schema: one catalog of event kinds + a validator.

Every line of a run log is one JSON object.  Base fields (all kinds):

| field | type  | meaning                          |
|-------|-------|----------------------------------|
| v     | int   | schema version (currently 1)     |
| ts    | float | epoch seconds at emission        |
| pid   | int   | OS process id                    |
| tid   | int   | thread id (one Chrome lane each) |
| kind  | str   | one of :data:`KINDS`             |

Kind-specific required fields are listed in :data:`KINDS`; extra fields
are always allowed (attrs travel with their event).  ``validate_run``
additionally checks the *structural* invariants the Chrome exporter and
the summary reader rely on: every ``span_begin`` has a matching
``span_end`` on the same thread, pairs close LIFO (proper nesting), and
span ids are unique.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Tuple

__all__ = ["KINDS", "STREAM_NAMES", "validate_event", "validate_events",
           "validate_run", "read_events"]

_NUM = (int, float)

#: kind -> {required field: type tuple}
KINDS: Dict[str, Dict[str, tuple]] = {
    "run_start": {"meta": (dict,)},
    "run_end": {"dur": _NUM},
    "span_begin": {"name": (str,), "span": (int,), "parent": (int,),
                   "depth": (int,)},
    "span_end": {"name": (str,), "span": (int,), "dur": _NUM},
    "stage": {"name": (str,), "dur": _NUM},
    "counter": {"name": (str,), "value": _NUM},
    "gauge": {"name": (str,), "value": _NUM},
    "event": {"name": (str,)},
    "step": {"step": (int,), "dur": _NUM},
    "compile": {"name": (str,), "dur": _NUM},
    "retrace": {"rule": (str,), "message": (str,)},
    "device_facts": {"facts": (dict,)},
    # one per probed training step: grad/param/update norms + nonfinite
    # counts (telemetry/health.py PROBE_FIELDS travel as extra fields)
    "health": {"step": (int,)},
    # per-module cost attribution (telemetry/attribution.py): rows is a
    # list of {path, class, flops, flops_fwd, flops_bwd, bytes, params}
    "attribution": {"rows": (list,)},
    # one per executed serving batch (bigdl_tpu/serving/batcher.py):
    # size = rows carried, dur = assemble+infer seconds; queue_ms /
    # infer_ms / fill / requests travel as extra fields — the raw
    # material for `telemetry diff`'s serve_p50/p99/qps metrics
    "serve": {"size": (int,), "dur": _NUM},
    # one per COMPLETED generation (serving/generate/batcher.py):
    # tokens = emitted count, dur = submit-to-last-token seconds;
    # ttft_ms / itl_p99_ms / finish / queue_ms travel as extra fields —
    # the raw material for the bigdl_gen_* metrics and the fleet view's
    # decode-replica columns
    "generate": {"tokens": (int,), "dur": _NUM},
    # one per serving request (telemetry/request_trace.py): the span
    # timeline + component tally + blame verdict of one request's trip
    # through the server.  trace_id = the X-Request-Id echoed to the
    # client, endpoint = predict|generate, ms = ingress-to-done wall,
    # status = ok|rejected|error|cancelled; spans / components / blame /
    # reason / ttft_ms / slo_violated travel as extra fields — the raw
    # material for `telemetry trace`, the chrome request lanes, and the
    # fleet SLO columns
    "request": {"trace_id": (str,), "endpoint": (str,), "ms": _NUM,
                "status": (str,)},
    # per-collective comms attribution (telemetry/comms.py): count =
    # collective ops in the compiled step, bytes = HloCostAnalysis-style
    # bytes accessed; payload_bytes / by_axis / by_op / rows /
    # expected_s / measured_s travel as extra fields — the raw material
    # for `telemetry diff`'s comms_bytes/comms_s and fleet skew blame
    "comms": {"count": (int,), "bytes": _NUM},
    # per-run goodput/badput ledger (telemetry/ledger.py): emitted once
    # at end_run — goodput_pct = 100*compute/wall, wall_s = run wall
    # seconds; compute_s / badput_s / badput (per-category seconds) /
    # counts / blame / conservation_err_pct travel as extra fields — the
    # raw material for `telemetry diff`'s goodput gate and the bench
    # rows' goodput columns
    "goodput": {"goodput_pct": _NUM, "wall_s": _NUM},
    # per-step memory attribution (telemetry/memory.py): peak_bytes =
    # predicted per-device peak HBM (args + live-buffer-timeline temp
    # peak off the scheduled post-opt HLO); categories / rows / largest
    # / live (allocator stats per device) / hbm_limit_bytes travel as
    # extra fields — the raw material for `telemetry diff`'s
    # peak_hbm_bytes gate and the fleet memory-pressure note
    "memory": {"peak_bytes": _NUM},
}

_BASE: Dict[str, tuple] = {"v": (int,), "ts": _NUM, "pid": (int,),
                           "tid": (int,), "kind": (str,)}

#: every span/stage/counter/gauge/instant name the framework emits,
#: plus the compile-event names.  ``tests/test_schema_registry.py``
#: greps the sources for emitted literals and asserts membership here,
#: so a new event stream cannot silently bypass ``--validate`` and the
#: readers (report/diff/metrics_http) that key off names.
STREAM_NAMES = frozenset({
    # spans
    "train/iteration", "data_wait", "validation", "checkpoint",
    "perf/warmup", "perf/timed", "profile/trace", "profile/warmup",
    # serving (bigdl_tpu/serving/, docs/serving.md): startup AOT warmup
    # span, server lifecycle instants, queue gauge, admission counters
    "serve/warmup", "serve/started", "serve/drain", "serve/load",
    "serve/queue_depth", "serve/requests", "serve/rejected",
    # the LLM decode subsystem (serving/generate/, docs/serving.md
    # "Autoregressive generation"): tokens-emitted counter per coalesced
    # decode iteration, live active-sequence + KV-cache-occupancy gauges
    "serve/generate", "serve/active_seqs", "serve/cache_occupancy",
    # SLO burn accounting (telemetry/request_trace.py SLOTracker):
    # observed windowed p99 / declared budget, published rate-limited
    # into the run log so the FleetWatcher and `telemetry diff` see the
    # burn without scraping /metrics
    "serve/slo_p99_burn", "serve/slo_ttft_burn",
    # instants
    "epoch", "checkpoint/saved", "straggler/timeout", "run/retry",
    "metrics/serving", "profile/armed", "profile/captured",
    "flight/dump",
    # managed persistent compile cache (utils/compile_cache.py,
    # docs/compile.md): one instant per persistent-cache hit/miss (the
    # per-run counts `telemetry diff` and /metrics key off), plus the
    # once-per-run cache-key ingredients announcement
    "compile/cache_hit", "compile/cache_miss", "compile/cache",
    # kernel dispatch (bigdl_tpu/ops/dispatch.py): one instant per
    # TRACE-time backend decision — op, backend (pallas|xla), reason —
    # so attribution can name which backend each module compiled to
    "kernel/dispatch",
    # fault tolerance (bigdl_tpu/faults.py + docs/fault_tolerance.md):
    # injected faults, quarantined torn checkpoints, graceful
    # preemption, and checkpoint auto-resume
    "fault/injected", "checkpoint/quarantined", "run/preempted",
    "run/resumed",
    # cluster fault tolerance (bigdl_tpu/parallel/cluster.py): peer
    # declared lost by the collective watchdog, a checkpoint step
    # certified cluster-consistent by the commit barrier, and a
    # supervised full-cluster restart
    "cluster/peer_lost", "cluster/commit", "cluster/restart",
    # elastic resharding (docs/fault_tolerance.md "Elastic recovery"):
    # a topology change — a restore onto a different mesh than wrote
    # the checkpoint (source=restore, old→new process/device counts)
    # or a supervised capacity-aware width change (source=supervisor,
    # from_n/to_n/declared_n).  The fleet view folds it so hosts of a
    # legitimately-shrunk cluster are marked departed, not stalled.
    "cluster/reshard",
    # straggler-tolerant local-SGD (bigdl_tpu/parallel/local_sync.py,
    # docs/fault_tolerance.md "Straggler tolerance"): one instant per
    # parameter averaging (round, step, h, bytes, dur), one per
    # bounded-staleness barrier pass (round, waited_s, lag, stale), and
    # the shed verdict — a peer S averaging rounds behind excused from
    # the fleet, which continues averaging at reduced width
    "sync/average", "sync/staleness", "cluster/shed",
    # goodput ledger inputs (telemetry/ledger.py): checkpoint-restore
    # wall (stage), preempt-resume fast-forward replay (stage), and the
    # supervisor's drain interval (instant with dur) — the measured
    # out-of-step intervals the run-level conservation check needs
    "checkpoint/restore", "resume/fast_forward", "cluster/drain",
    # fleet aggregation (telemetry/fleet.py): the coordinator's live
    # watcher publishes the completed-step gap and the blamed per-step
    # excess as gauges, and a rate-limited skew-blame instant whenever
    # the fleet diverges — the PR-7 watchdog's flight dump carries them
    "cluster/skew", "fleet/lag_steps", "fleet/skew_s",
    # memory observability (telemetry/memory.py): one rate-limited
    # instant when a device's live allocator peak crosses 95% of its
    # HBM limit — the step before RESOURCE_EXHAUSTED, surfaced so the
    # fleet blame and tpu_watch can call it BEFORE the crash
    "memory/pressure",
    # sparse embedding-gradient sync (nn/layers/embedding.py +
    # parallel/train_step.py, docs/sparse.md): once per step object,
    # the static per-step sync accounting — touched-row caps per table,
    # bytes the coalesced (indices, rows) sync moves, and the dense
    # table all-reduce bytes it replaced (saved_bytes = the win
    # tpu_watch prints and the comms walker confirms)
    "train/sparse",
    # health findings (telemetry/health.py detectors + policy)
    "health/nonfinite", "health/skip", "health/loss_spike",
    "health/plateau", "health/grad_explosion", "health/halt",
    # counters / gauges
    "perf/records_per_sec", "prefetch/queue_depth",
    # pipeline stages (optim.Metrics forwarding + bench.py)
    "host to device time", "host to device time (overlapped)",
    "dispatch time", "computing time",
    "compile + first iteration time", "data time", "validation time",
    "checkpoint time", "checkpoint wait time", "h2d", "dispatch",
    "device",
    # compile-event names (TrainStep/EvalStep dispatch kinds; the
    # serving executor splits startup warmup compiles from the
    # in-request-path compiles a healthy server never emits)
    "TrainStep.run", "TrainStep.run_sharded", "TrainStep.run_scan",
    "TrainStep.aot_scan", "EvalStep.run",
    "ServeExecutor.warmup", "ServeExecutor.compile",
    # the generation executor's prefill/decode compiles split the same
    # way: warmup names are paid once at startup, the in-request-path
    # name never appears in a healthy server
    "GenerateExecutor.warmup", "GenerateExecutor.compile",
})


def validate_event(event: Dict[str, Any]) -> List[str]:
    """Field-level check of one event; returns human-readable problems
    (empty when valid)."""
    errors: List[str] = []
    if not isinstance(event, dict):
        return [f"event is {type(event).__name__}, not an object"]
    for field, types in _BASE.items():
        if field not in event:
            errors.append(f"missing base field {field!r}")
        elif not isinstance(event[field], types) \
                or isinstance(event[field], bool):
            errors.append(f"base field {field!r} has type "
                          f"{type(event[field]).__name__}")
    kind = event.get("kind")
    if kind not in KINDS:
        errors.append(f"unknown kind {kind!r}")
        return errors
    for field, types in KINDS[kind].items():
        if field not in event:
            errors.append(f"{kind}: missing field {field!r}")
        elif not isinstance(event[field], types) \
                or isinstance(event[field], bool):
            errors.append(f"{kind}: field {field!r} has type "
                          f"{type(event[field]).__name__}")
    return errors


def validate_events(events: Iterable[Dict[str, Any]]) -> List[str]:
    """Per-event checks plus the structural span invariants: matched
    begin/end per id, LIFO close order per thread, unique span ids."""
    errors: List[str] = []
    stacks: Dict[int, List[Tuple[int, str]]] = {}
    seen_ids: set = set()
    for i, ev in enumerate(events):
        for problem in validate_event(ev):
            errors.append(f"event {i}: {problem}")
        kind = ev.get("kind")
        tid = ev.get("tid")
        if kind == "span_begin" and isinstance(ev.get("span"), int):
            sid = ev["span"]
            if sid in seen_ids:
                errors.append(f"event {i}: span id {sid} reused")
            seen_ids.add(sid)
            stack = stacks.setdefault(tid, [])
            if ev.get("depth") != len(stack):
                errors.append(f"event {i}: span {sid} depth "
                              f"{ev.get('depth')} != stack depth "
                              f"{len(stack)}")
            parent = stack[-1][0] if stack else 0
            if ev.get("parent") != parent:
                errors.append(f"event {i}: span {sid} parent "
                              f"{ev.get('parent')} != open span {parent}")
            stack.append((sid, ev.get("name", "")))
        elif kind == "span_end" and isinstance(ev.get("span"), int):
            sid = ev["span"]
            stack = stacks.setdefault(tid, [])
            if not stack:
                errors.append(f"event {i}: span_end {sid} with no open "
                              f"span on tid {tid}")
            else:
                top_sid, top_name = stack.pop()
                if top_sid != sid:
                    errors.append(f"event {i}: span_end {sid} closes out "
                                  f"of order (open span is {top_sid} "
                                  f"{top_name!r})")
                elif ev.get("name") != top_name:
                    errors.append(f"event {i}: span_end {sid} name "
                                  f"{ev.get('name')!r} != begin name "
                                  f"{top_name!r}")
    for tid, stack in stacks.items():
        for sid, name in stack:
            errors.append(f"span {sid} {name!r} never closed "
                          f"(tid {tid})")
    return errors


def read_events(path: str) -> Tuple[List[Dict[str, Any]], List[str]]:
    """Parse a JSONL run log; returns (events, parse errors).  Malformed
    lines are reported, not fatal — a crashed run may truncate its final
    line."""
    events: List[Dict[str, Any]] = []
    errors: List[str] = []
    with open(path, "r", encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError as e:
                errors.append(f"line {lineno}: not valid JSON ({e})")
    return events, errors


def validate_run(path: str) -> Tuple[int, List[str]]:
    """Full-file validation: parse + per-event + structural checks.
    Returns (event count, problems)."""
    events, errors = read_events(path)
    errors.extend(validate_events(events))
    return len(events), errors
