"""Live cross-host fleet aggregation: tail every per-process run log,
keep a rolling per-host table, and blame step skew on the component that
actually caused it.

PRs 2-4 built the observability stack for ONE process; a multi-host job
writes one ``run-<stamp>-p<idx>-<pid>.jsonl`` per process and until now
the only cross-host view was an after-the-fact merge
(``report.fleet_summarize``).  This module makes the fleet view *live*
and *diagnostic*:

- :class:`FleetWatcher` — a coordinator-side daemon thread (same
  shared-directory pattern as the PR-7 heartbeat mesh: it works wherever
  the run logs do, local disk or NFS) that tails every ``run-*.jsonl``
  under the telemetry dir incrementally and folds new events into
  per-host rolling state.  Surfaced as a ``fleet`` block on ``/status``,
  ``bigdl_fleet_*`` gauges on ``/metrics``, ``fleet/lag_steps`` /
  ``fleet/skew_s`` gauges in the coordinator's own run log, and
  ``cluster/skew`` instants when the fleet diverges — which the PR-7
  collective watchdog's flight dump then carries as evidence.

- **Step-skew blame**: when one host falls behind (or the fleet runs in
  SPMD lock-step but one host drags every step), the gap is attributed
  from each host's OWN spans: ``data_wait`` (input stall), ``checkpoint``
  (save stall), comms (measured collective seconds from ``comms``
  events), and compute (the residual).  The verdict prefers the
  *attributable* components: on a synchronous step, a straggler's excess
  shows up on every OTHER host as collective wait inside compute — the
  Blink observation — so a host with genuine data-wait excess is named
  the culprit even though everyone's step time degraded equally.
  Compute is blamed only when no attributable component explains the
  gap.

- :func:`fleet_view` — the one-shot merge (``python -m
  bigdl_tpu.telemetry fleet <dir>`` and the multi-log positional CLI
  both land here; ``report.fleet_summarize`` delegates).  Re-incarnation
  logs (a PR-7 supervisor restart writes a second log for the same
  rank) are MERGED by taking the latest run per ``process_index``
  rather than double-counting skew across incarnations; superseded
  paths are reported, not warned about.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from bigdl_tpu.telemetry import ledger, request_trace

__all__ = ["HostState", "FleetWatcher", "fleet_view", "blame",
           "fleet_width", "apply_topology", "fleet_goodput",
           "format_fleet_view", "fleet_openmetrics", "main",
           "WINDOW_STEPS", "SKEW_LAG_STEPS", "SKEW_MIN_EXCESS_S",
           "SKEW_REL_EXCESS"]

#: rolling window of steps kept per host — the table describes the
#: recent past, not the whole run (a warmup hiccup must age out)
WINDOW_STEPS = 64
#: completed-step gap that alone counts as divergence
SKEW_LAG_STEPS = 3
#: a component excess must clear BOTH floors to be blamed: an absolute
#: seconds floor and a fraction of the fleet's best step time
SKEW_MIN_EXCESS_S = 0.02
SKEW_REL_EXCESS = 0.2

#: blame components read from each host's own spans; compute is the
#: residual and deliberately last — on a synchronous step every healthy
#: host's compute inflates with the straggler's excess (collective
#: wait), so compute excess on ONE host is a symptom unless nothing
#: attributable explains the gap
ATTRIBUTABLE = ("data_wait", "comms", "checkpoint")


class HostState:
    """Rolling per-host state folded from one run log's events."""

    def __init__(self, path: str):
        self.path = path
        self.process_index: Optional[int] = None
        self.process_count: Optional[int] = None  # run_start meta width
        self.run_ts: Optional[float] = None   # run_start ts = run id
        self.meta: Dict[str, Any] = {}
        # latest cluster/reshard instant seen in THIS log (elastic
        # recovery, docs/fault_tolerance.md): the fleet folds these so
        # a host absent because the cluster legitimately shrank is
        # marked departed, never blamed `stalled`
        self.reshard: Optional[Dict[str, Any]] = None
        self.departed = False  # recomputed by apply_topology()
        self.n_steps = 0
        self.last_step = 0
        self.last_step_ts: Optional[float] = None
        self.first_ts: Optional[float] = None
        self.last_ts: Optional[float] = None
        self.ended = False
        self.nonfinite_steps = 0
        self.ckpt_step: Optional[int] = None
        self.ckpt_ts: Optional[float] = None
        self.comms_s_per_step = 0.0   # latest comms event's seconds
        self.comms_bytes = 0
        # latest memory event (telemetry/memory.py): compiled peak +
        # live allocator peak + limit — the fleet's hbm columns and
        # the memory-pressure note on the blame verdict
        self.hbm_peak_bytes = 0
        self.hbm_live_bytes = 0
        self.hbm_limit_bytes = 0
        self._memory_pressured = False
        # generation events (serving/generate): decode-replica columns —
        # token totals, the latest TTFT / inter-token tail, and a
        # (ts, tokens) window for a per-host tokens/s rate
        self.gen_tokens = 0
        self.gen_requests = 0
        self.gen_ttft_ms = 0.0
        self.gen_itl_p99_ms = 0.0
        self._gen_window: deque = deque(maxlen=WINDOW_STEPS)
        # SLO burn accounting (telemetry/request_trace.py SLOTracker):
        # the serving replica's latest windowed-p99 / declared-budget
        # gauges plus its violation count and slowest traced request —
        # the fleet's "which replica is burning its budget" columns
        self.slo_p99_burn: Optional[float] = None
        self.slo_ttft_burn: Optional[float] = None
        # shared request_trace.RequestFold — one fold implementation
        # with the MetricsSink, so the two live views can't diverge
        self.requests = request_trace.RequestFold()
        # shared goodput ledger (telemetry/ledger.py LedgerFold) — the
        # same fold the MetricsSink serves on /status.goodput, so the
        # per-host badput columns can't diverge from the host's own view
        self.ledger = ledger.LedgerFold()
        # (step, ts, dur, components) rows, newest last
        self.window: deque = deque(maxlen=WINDOW_STEPS)
        self._pending: Dict[str, float] = {}

    # -- folding -------------------------------------------------------------
    def fold(self, events: List[Dict[str, Any]]) -> None:
        for ev in events:
            self.ledger.fold_event(ev)
            kind = ev.get("kind")
            ts = ev.get("ts")
            if isinstance(ts, (int, float)):
                self.first_ts = ts if self.first_ts is None \
                    else min(self.first_ts, ts)
                self.last_ts = ts if self.last_ts is None \
                    else max(self.last_ts, ts)
            if kind == "run_start":
                self.meta.update(ev.get("meta") or {})
                if self.run_ts is None and isinstance(ts, (int, float)):
                    self.run_ts = ts
                if self.process_index is None:
                    pidx = self.meta.get("process_index")
                    if isinstance(pidx, int):
                        self.process_index = pidx
                pcount = self.meta.get("process_count")
                if isinstance(pcount, int):
                    self.process_count = pcount
            elif kind == "span_end":
                # blame components read from the host's own spans;
                # validation deliberately rides the compute residual
                name = ev.get("name")
                if name in ("data_wait", "checkpoint"):
                    self._pending[name] = self._pending.get(name, 0.0) \
                        + float(ev.get("dur", 0.0))
            elif kind == "step":
                step = ev.get("step")
                dur = float(ev.get("dur", 0.0))
                if isinstance(step, int):
                    self.n_steps += 1
                    self.last_step = max(self.last_step, step)
                    self.last_step_ts = ts if isinstance(ts, (int, float)) \
                        else self.last_step_ts
                    comp = dict(self._pending)
                    comp["comms"] = self.comms_s_per_step
                    self.window.append((step, ts, dur, comp))
                    self._pending = {}
            elif kind == "health":
                if ev.get("nonfinite_grads") or ev.get("nonfinite_params"):
                    self.nonfinite_steps += 1
            elif kind == "gauge":
                name = ev.get("name")
                if name == "serve/slo_p99_burn":
                    self.slo_p99_burn = float(ev.get("value", 0.0) or 0.0)
                elif name == "serve/slo_ttft_burn":
                    self.slo_ttft_burn = float(ev.get("value", 0.0)
                                               or 0.0)
            elif kind == "request":
                self.requests.fold(ev)
            elif kind == "generate":
                toks = int(ev.get("tokens", 0) or 0)
                self.gen_tokens += toks
                self.gen_requests += 1
                self.gen_ttft_ms = float(ev.get("ttft_ms", 0.0) or 0.0)
                self.gen_itl_p99_ms = float(ev.get("itl_p99_ms", 0.0)
                                            or 0.0)
                if isinstance(ts, (int, float)):
                    self._gen_window.append((ts, toks))
            elif kind == "comms":
                self.comms_bytes = int(ev.get("bytes", 0) or 0)
                s = ev.get("measured_s")
                if s is None:
                    s = ev.get("expected_s")
                self.comms_s_per_step = float(s or 0.0)
            elif kind == "memory":
                from bigdl_tpu.telemetry.memory import (
                    live_peak_and_limit, pressured_device)

                self.hbm_peak_bytes = int(ev.get("peak_bytes", 0) or 0)
                live = ev.get("live")
                budget = ev.get("hbm_limit_bytes")
                peak, limit = live_peak_and_limit(live, budget)
                if peak:
                    self.hbm_live_bytes = peak
                if limit:
                    self.hbm_limit_bytes = limit
                self._memory_pressured = \
                    pressured_device(live, budget) is not None
            elif kind == "event":
                if ev.get("name") == "checkpoint/saved":
                    self.ckpt_step = ev.get("step")
                    self.ckpt_ts = ts if isinstance(ts, (int, float)) \
                        else self.ckpt_ts
                elif ev.get("name") == "cluster/reshard":
                    rec = {"ts": ts if isinstance(ts, (int, float))
                           else 0.0,
                           "source": ev.get("source"),
                           "to": ev.get("to_processes", ev.get("to_n")),
                           "from": ev.get("from_processes",
                                          ev.get("from_n")),
                           "declared": ev.get("declared_n")}
                    if self.reshard is None \
                            or rec["ts"] >= self.reshard["ts"]:
                        self.reshard = rec
            elif kind == "run_end":
                self.ended = True

    # -- derived -------------------------------------------------------------
    def _percentile(self, q: float) -> float:
        durs = sorted(d for _, _, d, _ in self.window)
        if not durs:
            return 0.0
        idx = min(len(durs) - 1,
                  max(0, int(round(q / 100.0 * (len(durs) - 1)))))
        return durs[idx]

    def memory_pressure(self) -> bool:
        """True when any of this host's devices last reported a live
        peak within ``memory.PRESSURE_FRACTION`` of its own allocator
        limit — the step before RESOURCE_EXHAUSTED; the blame verdict
        carries it as a note (judged per device in ``fold``, the same
        rule the ``memory/pressure`` instant fires on)."""
        return self._memory_pressured

    def components(self) -> Dict[str, float]:
        """Mean per-step seconds per blame component over the window
        (compute = residual, floored at 0)."""
        n = len(self.window)
        if n == 0:
            return {c: 0.0 for c in ATTRIBUTABLE + ("compute",)}
        totals: Dict[str, float] = {c: 0.0 for c in ATTRIBUTABLE}
        dur_total = 0.0
        for _, _, dur, comp in self.window:
            dur_total += dur
            for c in ATTRIBUTABLE:
                totals[c] += float(comp.get(c, 0.0))
        out = {c: totals[c] / n for c in ATTRIBUTABLE}
        out["compute"] = max(dur_total / n - sum(out.values()), 0.0)
        return out

    def gen_tokens_s(self, now: Optional[float] = None,
                     window_s: float = 60.0) -> float:
        """Per-host generated tokens/s over the recent window (0.0 for
        hosts that never generated — training hosts stay clean)."""
        now = time.time() if now is None else now
        recent = [(at, n) for (at, n) in self._gen_window
                  if now - at <= window_s]
        if not recent:
            return 0.0
        span = min(window_s, max(0.25, now - min(at for at, _ in recent)))
        return round(sum(n for _, n in recent) / span, 2)

    def row(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = time.time() if now is None else now
        comp = self.components()
        p50 = self._percentile(50)
        shares = {f"{c}_share": (comp[c] / p50 if p50 else 0.0)
                  for c in ("data_wait", "comms", "checkpoint", "compute")}
        gp = self.ledger.snapshot()
        badput_top = None
        if gp and gp.get("wall_s"):
            badput = gp.get("badput") or {}
            cat = max(badput, key=badput.get, default=None)
            if cat is not None and badput[cat] > 0:
                badput_top = {"category": cat,
                              "seconds": round(badput[cat], 3)}
        return {"path": self.path,
                "goodput_pct": (gp.get("goodput_pct")
                                if gp and gp.get("wall_s") else None),
                "badput_s": (gp.get("badput_s")
                             if gp and gp.get("wall_s") else None),
                "badput_top": badput_top,
                "process_index": self.process_index,
                "last_step": self.last_step,
                "age_s": (round(now - self.last_step_ts, 3)
                          if self.last_step_ts else None),
                "steps": self.n_steps,
                "p50_s": p50, "p95_s": self._percentile(95),
                "wall_s": ((self.last_ts - self.first_ts)
                           if self.first_ts is not None
                           and self.last_ts is not None else 0.0),
                "components_s": comp, **shares,
                "comms_bytes": self.comms_bytes,
                "hbm_peak_bytes": self.hbm_peak_bytes,
                "hbm_live_bytes": self.hbm_live_bytes,
                "hbm_limit_bytes": self.hbm_limit_bytes,
                "memory_pressure": self.memory_pressure(),
                "nonfinite_steps": self.nonfinite_steps,
                "gen_tokens": self.gen_tokens,
                "gen_requests": self.gen_requests,
                "gen_tokens_s": self.gen_tokens_s(now),
                "gen_ttft_ms": self.gen_ttft_ms,
                "gen_itl_p99_ms": self.gen_itl_p99_ms,
                "slo_p99_burn": self.slo_p99_burn,
                "slo_ttft_burn": self.slo_ttft_burn,
                "slo_violations": self.requests.slo_violations,
                "request_count": self.requests.count,
                "slowest_request": dict(self.requests.slowest),
                "checkpoint_step": self.ckpt_step,
                "checkpoint_age_s": (round(now - self.ckpt_ts, 3)
                                     if self.ckpt_ts else None),
                "departed": self.departed,
                "ended": self.ended}


# -- elastic topology (docs/fault_tolerance.md "Elastic recovery") ------------
def fleet_width(states: List[HostState]) -> Optional[Dict[str, Any]]:
    """The fleet's CURRENT vs DECLARED width from the newest
    ``cluster/reshard`` instant across the kept logs, or None (no
    reshard ever announced — the run_start widths are authoritative)."""
    best: Optional[Dict[str, Any]] = None
    for st in states:
        r = st.reshard
        if r and isinstance(r.get("to"), int) \
                and (best is None or r["ts"] > best["ts"]):
            best = r
    if best is None:
        return None
    declared = best.get("declared")
    if not isinstance(declared, int):
        declared = max((st.process_count or 0 for st in states),
                       default=0) or None
    return {"current": int(best["to"]), "declared": declared,
            "ts": best["ts"], "source": best.get("source")}


def apply_topology(states: List[HostState]) -> Optional[Dict[str, Any]]:
    """Fold topology changes into the per-host states: a host whose
    process index falls outside the current width and whose stepping
    stopped at/before the reshard is DEPARTED — the cluster
    legitimately shrank around it, so the blame verdict must not call
    it ``stalled`` forever.  Recomputes every ``departed`` flag (a host
    stepping AFTER the reshard is alive whatever its index says —
    never hidden from blame).  Returns the width record."""
    width = fleet_width(states)
    cur = (width or {}).get("current")
    ts = (width or {}).get("ts") or 0.0
    for st in states:
        st.departed = (
            cur is not None
            and st.process_index is not None
            and st.process_index >= cur
            and (st.last_step_ts is None or st.last_step_ts <= ts))
    return width


def fleet_goodput(hosts: Dict[str, Dict[str, Any]]
                  ) -> Optional[Dict[str, Any]]:
    """Fleet goodput = the WORST host's — on a synchronous step the
    slowest host's wasted wall is every host's wasted wall, so the
    fleet can never be doing better than its unluckiest member.
    ``hosts`` is the label->row dict the views build; None when no row
    carries a goodput number yet."""
    worst: Optional[Dict[str, Any]] = None
    for row in hosts.values():
        pct = row.get("goodput_pct")
        if pct is None:
            continue
        if worst is None or pct < worst["pct"]:
            worst = {"pct": pct, "worst": row.get("process_index"),
                     "badput_top": row.get("badput_top")}
    return worst


# -- skew blame ---------------------------------------------------------------
def blame(hosts: List[HostState]) -> Optional[Dict[str, Any]]:
    """Name the host dragging the fleet and the component at fault.

    Baseline per component = the fleet MINIMUM (the best host shows what
    the step costs without the problem).  Attributable components
    (data-wait, comms, checkpoint) are judged first; compute residual
    only when nothing attributable clears the significance floor — on a
    synchronous step, every healthy host's compute carries the
    straggler's excess as collective wait, so compute excess alone
    cannot localize the culprit.  Returns None with fewer than two
    hosts carrying steps, or when nothing clears the floor and the
    fleet is in lock-step.  Departed hosts (``apply_topology`` — the
    cluster legitimately shrank around them) are not part of the
    cluster anymore and never enter the verdict."""
    active = [h for h in hosts if h.window and not h.departed]
    if len(active) < 2:
        return None
    comp = {h: h.components() for h in active}
    p50s = [h._percentile(50) for h in active]
    floor = max(SKEW_MIN_EXCESS_S, SKEW_REL_EXCESS * min(p50s))
    base = {c: min(comp[h][c] for h in active)
            for c in ATTRIBUTABLE + ("compute",)}

    def verdict(h: HostState, cause: str, excess: float) -> Dict[str, Any]:
        last_steps = [x.last_step for x in active]
        out = {"laggard": h.process_index, "cause": cause,
               "excess_s": round(excess, 6),
               "lag_steps": max(last_steps) - min(last_steps),
               "floor_s": round(floor, 6),
               "components": {f"p{x.process_index}":
                              {k: round(v, 6)
                               for k, v in comp[x].items()}
                              for x in active}}
        # a host running within 5% of its HBM limit is one allocation
        # away from RESOURCE_EXHAUSTED — allocator churn near the
        # ceiling also SLOWS the host, so the verdict names it
        pressured = [f"p{x.process_index}" for x in active
                     if x.memory_pressure()]
        if pressured:
            out["memory_pressure"] = pressured
        return out

    best: Optional[Tuple[HostState, str, float]] = None
    for h in active:
        for c in ATTRIBUTABLE:
            excess = comp[h][c] - base[c]
            if excess > floor and (best is None or excess > best[2]):
                best = (h, c, excess)
    if best is not None:
        return verdict(*best)
    for h in active:
        excess = comp[h]["compute"] - base["compute"]
        if excess > floor and (best is None or excess > best[2]):
            best = (h, "compute", excess)
    if best is not None:
        return verdict(*best)
    # no per-step component gap: a host that stopped stepping entirely
    # (crash/wedge) still lags — blame by progress
    last_steps = [h.last_step for h in active]
    if max(last_steps) - min(last_steps) >= SKEW_LAG_STEPS:
        laggard = min(active, key=lambda h: h.last_step)
        return verdict(laggard, "stalled",
                       float(max(last_steps) - laggard.last_step))
    return None


# -- one-shot merge (absorbs report.fleet_summarize) --------------------------
def _dedupe_latest(states: List[HostState]
                   ) -> Tuple[List[HostState], List[str], List[str]]:
    """Keep one log per process_index — the latest run (by run_start
    ts, path as tiebreak).  A supervisor restart writes a fresh log for
    every rank; skew across incarnations is meaningless, so older
    incarnations are superseded, not double-counted.  Logs with no
    process_index stay (each its own row).  Returns (kept, superseded
    paths, notes)."""
    by_pidx: Dict[int, List[HostState]] = {}
    kept: List[HostState] = []
    superseded: List[str] = []
    notes: List[str] = []
    for st in states:
        if isinstance(st.process_index, int):
            by_pidx.setdefault(st.process_index, []).append(st)
        else:
            kept.append(st)
    for pidx, group in sorted(by_pidx.items()):
        group.sort(key=lambda s: (s.run_ts or s.first_ts or 0.0, s.path))
        kept.append(group[-1])
        for old in group[:-1]:
            superseded.append(old.path)
        if len(group) > 1:
            notes.append(
                f"process {pidx}: kept latest of {len(group)} logs "
                f"({os.path.basename(group[-1].path)}); superseded "
                f"{[os.path.basename(o.path) for o in group[:-1]]}")
    kept.sort(key=lambda s: (s.process_index is None,
                             s.process_index or 0, s.path))
    return kept, superseded, notes


def fleet_view(runs: List[Tuple[str, List[Dict[str, Any]]]],
               now: Optional[float] = None) -> Dict[str, Any]:
    """Merge per-process run logs into one fleet view: the rich rolling
    rows + blame verdict, plus the legacy ``processes``/``step_lag``/
    ``skew`` surface ``report.fleet_summarize`` promised."""
    states: List[HostState] = []
    for path, events in runs:
        st = HostState(path)
        st.fold(events)
        states.append(st)
    kept, superseded, notes = _dedupe_latest(states)
    width = apply_topology(kept)
    departed = [st for st in kept if st.departed]
    if departed:
        notes.append(
            f"cluster resharded to width {width['current']}"
            + (f" (declared {width['declared']})"
               if width.get("declared") else "")
            + f": host(s) "
            + ", ".join(f"p{st.process_index}" for st in departed)
            + " departed legitimately — excluded from lag and blame")
    # legacy cross-host step-completion skew over the kept logs
    step_ts: Dict[int, Dict[int, float]] = {}
    for st in kept:
        if st.process_index is None:
            continue
        for step, ts, _dur, _c in st.window:
            if isinstance(ts, (int, float)):
                step_ts.setdefault(step, {})[st.process_index] = ts
    skew: Dict[str, Any] = {"max_s": 0.0, "at_step": None, "mean_s": 0.0}
    spreads = []
    for step, by_proc in step_ts.items():
        if len(by_proc) < 2:
            continue
        spread = max(by_proc.values()) - min(by_proc.values())
        spreads.append(spread)
        if spread > skew["max_s"]:
            skew["max_s"], skew["at_step"] = spread, step
    if spreads:
        skew["mean_s"] = sum(spreads) / len(spreads)
    last_steps = [st.last_step for st in kept if not st.departed]
    rows = [st.row(now) for st in kept]
    # legacy per-process rows (fleet_summarize's exact field set)
    processes = []
    for i, st in enumerate(kept):
        pidx = st.process_index if st.process_index is not None \
            else -(i + 1)
        processes.append({"path": st.path, "process_index": pidx,
                          "steps": st.n_steps,
                          "last_step": st.last_step,
                          "p50_s": st._percentile(50),
                          "p95_s": st._percentile(95),
                          "wall_s": rows[i]["wall_s"],
                          "nonfinite_steps": st.nonfinite_steps})
    hosts = {f"p{p['process_index']}": r
             for p, r in zip(processes, rows)}
    return {"hosts": hosts,
            "goodput": fleet_goodput(hosts),
            "processes": processes,
            "step_lag": (max(last_steps) - min(last_steps))
            if last_steps else 0,
            "skew": skew,
            "width": width,
            "blame": blame(kept),
            "superseded": superseded,
            "notes": notes,
            "warnings": []}


def discover_logs(target: str) -> List[str]:
    """Run logs under ``target``: a directory globs ``run-*.jsonl``
    (recursively one level is enough — runs write flat), a file is
    itself."""
    if os.path.isdir(target):
        return sorted(glob.glob(os.path.join(target, "run-*.jsonl")))
    return [target]


# -- rendering ---------------------------------------------------------------
def _pct(x: float) -> str:
    return f"{x * 100:4.0f}%"


def format_fleet_view(view: Dict[str, Any]) -> str:
    hosts = view.get("processes") or []
    lines = [f"== fleet view ({len(hosts)} processes) =="]
    for w in view.get("warnings", []):
        lines.append(f"WARNING: {w}")
    for note in view.get("notes", []):
        lines.append(f"note: {note}")
    rich = view.get("hosts") or {}
    for p in sorted(hosts, key=lambda r: r["process_index"]):
        r = rich.get(f"p{p['process_index']}", {})
        age = r.get("age_s")
        hbm = ""
        if r.get("hbm_peak_bytes"):
            hbm = f"hbm {r['hbm_peak_bytes'] / (1 << 30):.1f}G"
            if r.get("hbm_limit_bytes"):
                hbm += f"/{r['hbm_limit_bytes'] / (1 << 30):.1f}G"
            hbm += "  "
            if r.get("memory_pressure"):
                hbm = hbm.rstrip() + "!  "
        if r.get("gen_tokens"):
            # decode replica: the host's useful work is tokens, not
            # steps — show the rate and tail next to the step columns
            hbm += (f"gen {r.get('gen_tokens_s', 0.0)}tok/s "
                    f"ttft {r.get('gen_ttft_ms', 0.0):.0f}ms  ")
        if r.get("slo_p99_burn") is not None \
                or r.get("slo_ttft_burn") is not None:
            # serving replica with declared budgets: burn = windowed
            # p99 / budget, 1.0x means the budget is exactly spent
            cells = []
            if r.get("slo_p99_burn") is not None:
                cells.append(f"p99 {r['slo_p99_burn']:.2f}x")
            if r.get("slo_ttft_burn") is not None:
                cells.append(f"ttft {r['slo_ttft_burn']:.2f}x")
            hbm += f"slo {'/'.join(cells)}"
            if r.get("slo_violations"):
                hbm += f" viol {r['slo_violations']}"
            slow = r.get("slowest_request") or {}
            if slow.get("trace_id"):
                hbm += (f" slowest {slow['trace_id']}"
                        f"@{slow.get('ms', 0.0):.0f}ms")
            hbm += "  "
        good = ""
        if r.get("goodput_pct") is not None:
            good = f"good {r['goodput_pct']:3.0f}%  "
            top = r.get("badput_top") or {}
            if top.get("category"):
                good += (f"bad {top['category']}:"
                         f"{top['seconds']:.1f}s  ")
        lines.append(
            f"p{p['process_index']:<3} step {p['last_step']:<6} "
            f"age {age if age is not None else '?':>7}s  "
            f"p50 {p['p50_s'] * 1e3:8.2f} ms  "
            f"data {_pct(r.get('data_wait_share', 0.0))}  "
            f"comms {_pct(r.get('comms_share', 0.0))}  "
            f"ckpt {_pct(r.get('checkpoint_share', 0.0))}  "
            f"{good}{hbm}"
            f"nonfinite {p['nonfinite_steps']}"
            f"{'  DEPARTED' if r.get('departed') else ''}"
            f"{'  ENDED' if r.get('ended') else ''}  ({p['path']})")
    width = view.get("width")
    if width and width.get("current"):
        line = f"width: {width['current']}"
        if width.get("declared"):
            line += f"/{width['declared']} declared"
            if width["current"] != width["declared"]:
                line += "  (DEGRADED — cluster resharded)"
        lines.append(line)
    fg = view.get("goodput")
    if fg:
        line = (f"fleet goodput: {fg['pct']:.1f}% "
                f"(worst host: p{fg.get('worst')})")
        top = fg.get("badput_top") or {}
        if top.get("category"):
            line += (f"  dominant badput {top['category']} "
                     f"{top['seconds']:.1f}s")
        lines.append(line)
    lines.append(f"step lag (fastest - slowest last step): "
                 f"{view['step_lag']}")
    skew = view["skew"]
    if skew["at_step"] is not None:
        lines.append(f"step skew: max {skew['max_s'] * 1e3:.2f} ms at "
                     f"step {skew['at_step']}, mean "
                     f"{skew['mean_s'] * 1e3:.2f} ms")
    else:
        lines.append("step skew: n/a (no step index seen by >1 process)")
    verdict = view.get("blame")
    if verdict:
        line = (
            f"skew blame: p{verdict['laggard']} — {verdict['cause']} "
            f"(+{verdict['excess_s'] * 1e3:.1f} ms/step over the best "
            f"host, floor {verdict['floor_s'] * 1e3:.1f} ms)")
        if verdict.get("memory_pressure"):
            line += (f"  [memory pressure: "
                     f"{','.join(verdict['memory_pressure'])} within "
                     f"5% of HBM limit]")
        lines.append(line)
    else:
        lines.append("skew blame: none (fleet healthy or <2 active hosts)")
    return "\n".join(lines)


# -- the live watcher ---------------------------------------------------------
class _Tail:
    """Incremental JSONL reader: remembers the byte offset, keeps a
    partial trailing line until its newline lands."""

    def __init__(self, path: str):
        self.path = path
        self.pos = 0
        self._buf = ""

    def read_new(self) -> List[Dict[str, Any]]:
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                fh.seek(self.pos)
                chunk = fh.read()
                self.pos = fh.tell()
        except OSError:
            return []
        if not chunk:
            return []
        text = self._buf + chunk
        lines = text.split("\n")
        self._buf = lines.pop()  # partial (or empty) tail
        events = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                pass  # torn write mid-line: the next read won't heal a
                # complete-but-bad line, so just skip it
        return events


class FleetWatcher:
    """Coordinator-side live aggregator over a telemetry directory.

    Started by ``telemetry.start_run`` on the coordinator of a
    multi-process run (``BIGDL_FLEET_INTERVAL`` > 0); every poll it
    discovers/tails ``run-*.jsonl`` files, folds new events, and
    publishes: ``snapshot()`` (the /status block), ``fleet/lag_steps``
    + ``fleet/skew_s`` gauges and ``cluster/skew`` instants into the
    active tracer (rate-limited, and only on a meaningful change)."""

    #: min seconds between cluster/skew instants for the SAME verdict
    SKEW_COOLDOWN_S = 20.0

    def __init__(self, directory: str, interval: float = 2.0):
        self.directory = directory
        self.interval = max(float(interval), 0.2)
        self._tails: Dict[str, _Tail] = {}
        self._states: Dict[str, HostState] = {}
        self._lock = threading.Lock()
        # serializes whole polls: end_run's final poll_once and the
        # daemon thread's scheduled one must not interleave on the same
        # _Tail offsets (a shared read would fold every event twice)
        self._poll_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_emit: Dict[str, Any] = {}
        self._last_skew_at = 0.0

    def start(self) -> "FleetWatcher":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="bigdl-fleet-watcher")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval * 4 + 1.0)

    # -- polling -------------------------------------------------------------
    def poll_once(self) -> None:
        """One discovery+fold pass (the loop body; tests call it
        directly for determinism).  Polls are serialized — concurrent
        callers (end_run's final poll vs the daemon thread) wait."""
        with self._poll_lock:
            for path in discover_logs(self.directory):
                if path not in self._tails:
                    self._tails[path] = _Tail(path)
                    self._states[path] = HostState(path)
                events = self._tails[path].read_new()
                if events:
                    with self._lock:
                        self._states[path].fold(events)
            self._publish()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - an observer never kills
                pass  # the run (transient fs errors on shared dirs)

    # -- views ---------------------------------------------------------------
    def _kept(self) -> List[HostState]:
        with self._lock:
            states = list(self._states.values())
        kept, _sup, _notes = _dedupe_latest(states)
        return kept

    def snapshot(self) -> Dict[str, Any]:
        kept = self._kept()
        width = apply_topology(kept)
        now = time.time()
        last_steps = [h.last_step for h in kept
                      if h.window and not h.departed]
        hosts = {f"p{h.process_index}"
                 if h.process_index is not None
                 else f"?{i}": h.row(now)
                 for i, h in enumerate(kept)}
        return {"dir": self.directory,
                "files": len(self._tails),
                "hosts": hosts,
                "goodput": fleet_goodput(hosts),
                "lag_steps": (max(last_steps) - min(last_steps))
                if last_steps else 0,
                "width": width,
                "blame": blame(kept)}

    # -- publishing ----------------------------------------------------------
    def _publish(self) -> None:
        from bigdl_tpu import telemetry

        if not telemetry.enabled():
            return
        kept = self._kept()
        apply_topology(kept)
        active = [h for h in kept if h.window and not h.departed]
        last_steps = [h.last_step for h in active]
        lag = (max(last_steps) - min(last_steps)) if last_steps else 0
        verdict = blame(kept)
        skew_s = float(verdict["excess_s"]) if verdict else 0.0
        if lag != self._last_emit.get("lag"):
            telemetry.gauge("fleet/lag_steps", lag)
            self._last_emit["lag"] = lag
        prev_skew = self._last_emit.get("skew_s")
        if prev_skew is None or abs(skew_s - prev_skew) \
                > 0.1 * max(prev_skew, 1e-9):
            telemetry.gauge("fleet/skew_s", skew_s)
            self._last_emit["skew_s"] = skew_s
        if verdict is None:
            self._last_emit.pop("verdict", None)
            return
        key = (verdict["laggard"], verdict["cause"])
        now = time.time()
        if key != self._last_emit.get("verdict") \
                or now - self._last_skew_at > self.SKEW_COOLDOWN_S:
            telemetry.instant("cluster/skew", laggard=verdict["laggard"],
                              cause=verdict["cause"],
                              excess_s=verdict["excess_s"],
                              lag_steps=verdict["lag_steps"],
                              hosts=len(active))
            self._last_emit["verdict"] = key
            self._last_skew_at = now


def fleet_openmetrics() -> List[str]:
    """``bigdl_fleet_*`` exposition lines for the /metrics endpoint
    (empty when no watcher is live — non-coordinators and single-process
    runs export nothing)."""
    from bigdl_tpu import telemetry

    watcher = telemetry.fleet_watcher()
    if watcher is None:
        return []
    snap = watcher.snapshot()
    lines = ["# HELP bigdl_fleet_hosts run logs the fleet watcher tails",
             "# TYPE bigdl_fleet_hosts gauge",
             f"bigdl_fleet_hosts {len(snap['hosts'])}",
             "# HELP bigdl_fleet_lag_steps fastest minus slowest host "
             "last step",
             "# TYPE bigdl_fleet_lag_steps gauge",
             f"bigdl_fleet_lag_steps {snap['lag_steps']}"]
    verdict = snap.get("blame")
    lines += ["# HELP bigdl_fleet_skew_seconds blamed per-step excess of "
              "the laggard host",
              "# TYPE bigdl_fleet_skew_seconds gauge",
              f"bigdl_fleet_skew_seconds "
              f"{verdict['excess_s'] if verdict else 0}"]
    per_host = [("bigdl_fleet_last_step", "last_step",
                 "latest completed step per host"),
                ("bigdl_fleet_step_p50_seconds", "p50_s",
                 "rolling p50 step seconds per host"),
                ("bigdl_fleet_data_wait_share", "data_wait_share",
                 "data-wait share of step time per host"),
                ("bigdl_fleet_comms_share", "comms_share",
                 "comms share of step time per host"),
                ("bigdl_fleet_hbm_peak_bytes", "hbm_peak_bytes",
                 "per-device compiled peak HBM per host"),
                ("bigdl_fleet_hbm_live_bytes", "hbm_live_bytes",
                 "live allocator peak bytes per host"),
                ("bigdl_fleet_gen_tokens_total", "gen_tokens",
                 "generated tokens per decode replica"),
                ("bigdl_fleet_gen_tokens_s", "gen_tokens_s",
                 "generated tokens/s per decode replica"),
                ("bigdl_fleet_gen_ttft_ms", "gen_ttft_ms",
                 "latest generation TTFT per decode replica"),
                ("bigdl_fleet_gen_itl_p99_ms", "gen_itl_p99_ms",
                 "latest generation p99 inter-token latency per "
                 "decode replica"),
                ("bigdl_fleet_slo_p99_burn", "slo_p99_burn",
                 "serving p99 SLO burn rate per replica (observed "
                 "windowed p99 / declared budget)"),
                ("bigdl_fleet_slo_ttft_burn", "slo_ttft_burn",
                 "TTFT SLO burn rate per replica"),
                ("bigdl_fleet_slo_violations_total", "slo_violations",
                 "requests over a declared SLO budget per replica"),
                ("bigdl_fleet_goodput_pct", "goodput_pct",
                 "run-level goodput percent per host "
                 "(telemetry/ledger.py)"),
                ("bigdl_fleet_badput_seconds", "badput_s",
                 "run-level badput seconds per host")]
    for metric, field, help_ in per_host:
        lines.append(f"# HELP {metric} {help_}")
        lines.append(f"# TYPE {metric} gauge")
        for name, row in sorted(snap["hosts"].items()):
            pidx = row.get("process_index")
            if pidx is None:
                continue
            val = row.get(field)
            if val is None:
                continue
            lines.append(f'{metric}{{process_index="{pidx}"}} '
                         f"{float(val):g}")
    return lines


# -- CLI ----------------------------------------------------------------------
def main(argv=None) -> int:
    """``python -m bigdl_tpu.telemetry fleet <dir-or-logs> [--watch]``."""
    import argparse
    import sys

    from bigdl_tpu.telemetry import schema

    p = argparse.ArgumentParser(
        prog="bigdl_tpu.telemetry fleet",
        description="live/one-shot cross-host fleet table with step-skew "
                    "blame over per-process run logs")
    p.add_argument("targets", nargs="+", metavar="DIR|run.jsonl",
                   help="telemetry dir (globs run-*.jsonl) or explicit "
                        "run logs")
    p.add_argument("--watch", action="store_true",
                   help="redraw every --interval seconds until ^C")
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    def load() -> List[Tuple[str, List[Dict[str, Any]]]]:
        paths: List[str] = []
        for t in args.targets:
            paths.extend(discover_logs(t))
        loaded = []
        for path in paths:
            events, _errs = schema.read_events(path)
            loaded.append((path, events))
        return loaded

    while True:
        loaded = load()
        if not loaded:
            print(f"error: no run-*.jsonl under {args.targets}",
                  file=sys.stderr)
            return 2
        view = fleet_view(loaded)
        if args.json:
            print(json.dumps(view, indent=2, default=str))
        else:
            print(format_fleet_view(view))
        if not args.watch:
            return 0
        try:
            time.sleep(max(args.interval, 0.2))
            print()
        except KeyboardInterrupt:
            return 0
