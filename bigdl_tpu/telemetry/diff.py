"""Run-regression diff: compare two telemetry artifacts and say whether
the second one got worse.

``python -m bigdl_tpu.telemetry diff <runA> <runB>`` accepts either
JSONL run logs (anything ``schema.read_events`` parses) or ``bench.py``
output JSON (one object with a ``configs`` table) — in any combination,
as long as both sides expose comparable metrics.  Compared, when
present on both sides:

- step p50 / p95 / mean seconds        (lower is better, pct threshold)
- throughput (records/s, images/s)     (higher is better, pct threshold)
- data-wait share of iteration time    (lower is better, pct threshold)
- MFU                                  (higher is better, pct threshold)
- compile / retrace counts             (count slack, default 0)
- health-event counts (nonfinite steps, spikes, ...) (count slack)
- goodput_pct / badput_s (run ledger)  (dedicated goodput threshold)

Exit code contract (CI-ready): 0 = no regression, 1 = at least one
metric regressed beyond its threshold, 2 = inputs not comparable.
``bench.py --diff-against <baseline.json>`` delegates here.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["load_metrics", "run_log_metrics", "bench_metrics",
           "diff_metrics", "format_diff", "DEFAULT_THRESHOLD_PCT",
           "DEFAULT_COMPILE_THRESHOLD_PCT",
           "DEFAULT_MEMORY_THRESHOLD_PCT",
           "DEFAULT_GOODPUT_THRESHOLD_PCT", "MIN_GOODPUT_WALL_S"]

DEFAULT_THRESHOLD_PCT = 10.0

#: peak_hbm_bytes regression threshold (the memory budget,
#: docs/observability.md): its own knob because HBM regressions are
#: STEP-function failures — a model that grew 10% past the headroom
#: OOMs outright, so CI legs near the budget tighten this to ~2-5%
#: (``bench.py --memory-budget`` / ``telemetry diff
#: --memory-threshold-pct``) while roomy legs leave the default.
DEFAULT_MEMORY_THRESHOLD_PCT = 10.0

#: compile_s regression threshold (the compile budget, docs/compile.md):
#: looser than the runtime threshold by design — compile wall time is
#: noisier run-to-run than step time, and the class of outlier this
#: gate exists for (lenet 445 s vs a 2.7 s sibling, BENCH_banked_r5) is
#: an order of magnitude, not ten percent.  ``bench.py --compile-budget``
#: / ``telemetry diff --compile-threshold-pct`` tighten it per CI leg.
DEFAULT_COMPILE_THRESHOLD_PCT = 50.0

#: goodput regression threshold (telemetry/ledger.py,
#: docs/observability.md "Goodput"): its own knob because goodput is the
#: run-level roll-up the wall-time-reclaiming PRs (overlap, local SGD,
#: autoscaling) gate against — a 5% drop in the fraction of wall time
#: that trained the model is a real loss even when every step-level
#: metric still passes the looser 10% default.  Applied to
#: ``goodput_pct`` (higher is better) and total ``badput_s`` (lower).
DEFAULT_GOODPUT_THRESHOLD_PCT = 5.0

#: run logs with less wall time than this carry no goodput metrics —
#: a run-level wall-time roll-up over a sub-second smoke run is noise,
#: and gating on it would fail CI on scheduler jitter
MIN_GOODPUT_WALL_S = 1.0

#: metric name -> (direction, kind); direction "lower"/"higher" is the
#: GOOD direction, kind "pct" uses the relative threshold, "count" the
#: absolute slack.  Per-config bench metrics are matched by suffix.
_RULES: List[Tuple[str, str, str]] = [
    ("step_p50_s", "lower", "pct"),
    ("step_p95_s", "lower", "pct"),
    ("step_mean_s", "lower", "pct"),
    ("throughput", "higher", "pct"),
    ("data_wait_share", "lower", "pct"),
    ("mfu", "higher", "pct"),
    ("compiles", "lower", "count"),
    # cumulative compile seconds — per run log and per bench config —
    # gate on the dedicated compile threshold ("pct_compile"), not the
    # runtime threshold: the compile budget (docs/compile.md)
    ("compile_s", "lower", "pct_compile"),
    (".compile_s", "lower", "pct_compile"),
    ("retraces", "lower", "count"),
    ("health_events", "lower", "count"),
    ("nonfinite_steps", "lower", "count"),
    # comms metrics (telemetry/comms.py): collective bytes per step and
    # collective seconds per step — the ZeRO/pipeline bytes-moved gate
    # ("did this sharding change move more data than it saved?"),
    # pct-thresholded like MFU
    ("comms_bytes", "lower", "pct"),
    ("comms_s", "lower", "pct"),
    (".comms_bytes", "lower", "pct"),
    (".comms_s", "lower", "pct"),
    # achieved training loss on bench rows (bench.py --local-sgd): the
    # convergence side of the local-SGD trade — the comms_bytes gate
    # alone would bless H=10^6 (zero comms, junk model)
    ("final_loss", "lower", "pct"),
    (".final_loss", "lower", "pct"),
    # memory metrics (telemetry/memory.py): predicted per-device peak
    # HBM per run log (last memory event) and per bench row — the
    # "ZeRO-1 drops per-device optimizer HBM" gate, on the dedicated
    # memory threshold ("pct_memory")
    ("peak_hbm_bytes", "lower", "pct_memory"),
    (".peak_hbm_bytes", "lower", "pct_memory"),
    (".images_per_sec", "higher", "pct"),
    (".mfu", "higher", "pct"),
    # serving metrics (bigdl_tpu/serving + bench_serving.py): latency
    # percentiles regress UP, sustained rate regresses DOWN; steady-
    # state recompiles and shed load are zero-slack counts — ONE
    # in-request-path compile is a p99 spike worth failing CI over
    ("serve_p50_ms", "lower", "pct"),
    ("serve_p99_ms", "lower", "pct"),
    ("serve_qps", "higher", "pct"),
    (".p50_ms", "lower", "pct"),
    (".p99_ms", "lower", "pct"),
    (".qps", "higher", "pct"),
    (".rejected", "lower", "count"),
    (".steady_compiles", "lower", "count"),
    (".retrace_diagnostics", "lower", "count"),
    # generation serving (bench_serving.py --generate): sustained token
    # rate regresses DOWN; time-to-first-token and the inter-token tail
    # regress UP — the decode-path p99 gate for the next TPU round
    (".tokens_s", "higher", "pct"),
    (".ttft_p50_ms", "lower", "pct"),
    (".ttft_p99_ms", "lower", "pct"),
    (".itl_p99_ms", "lower", "pct"),
    # request-level tracing (telemetry/request_trace.py): end-to-end
    # per-request latency from `request` events (ingress to done —
    # includes queue, padding, respond; the batch-level serve_p99_ms
    # above sees only queue+infer), and SLO violations as a zero-slack
    # count — a candidate that starts blowing a declared budget fails
    # even when the percentile drift stays under the pct threshold
    ("request_p50_ms", "lower", "pct"),
    ("request_p99_ms", "lower", "pct"),
    ("slo_violations", "lower", "count"),
    (".slo_violations", "lower", "count"),
    # goodput ledger (telemetry/ledger.py): the run-level roll-up —
    # fraction of wall time that trained the model, and the badput
    # seconds it lost — on the dedicated tighter threshold
    # ("pct_goodput"); per run log (last goodput event, else folded
    # fresh) and per bench row
    ("goodput_pct", "higher", "pct_goodput"),
    ("badput_s", "lower", "pct_goodput"),
    (".goodput_pct", "higher", "pct_goodput"),
    (".badput_s", "lower", "pct_goodput"),
]


def _rule_for(name: str) -> Optional[Tuple[str, str]]:
    for key, direction, kind in _RULES:
        if name == key or (key.startswith(".") and name.endswith(key)):
            return direction, kind
    return None


# -- loading -----------------------------------------------------------------
def run_log_metrics(path: str) -> Dict[str, Any]:
    """Comparable metrics out of one JSONL run log (via the report
    summarizer)."""
    from bigdl_tpu.telemetry import schema
    from bigdl_tpu.telemetry.report import summarize

    events, _ = schema.read_events(path)
    summary = summarize(events)
    st = summary["steps"]
    stages = summary["stages"]
    out: Dict[str, Any] = {"kind": "run_log", "path": path,
                           "steps": st["count"]}
    if st["count"]:
        out["step_p50_s"] = st["p50_s"]
        out["step_p95_s"] = st["p95_s"]
        out["step_mean_s"] = st["mean_s"]
        if "throughput_mean" in st:
            out["throughput"] = st["throughput_mean"]
    # data-wait share: driver stall waiting for input, over the total
    # iteration time.  The Optimizer records the SAME interval twice —
    # as the data_wait span and as the Metrics-forwarded "data time"
    # stage — so take one (the span when present), never their sum
    if "data_wait" in stages:
        wait = stages["data_wait"]["total_s"]
    else:
        wait = stages.get("data time", {}).get("total_s", 0.0)
    iter_total = stages.get("train/iteration", {}).get("total_s", 0.0) \
        or st.get("total_s", 0.0)
    if iter_total:
        out["data_wait_share"] = wait / iter_total
    if summary.get("mfu") is not None:
        out["mfu"] = summary["mfu"]
    out["compiles"] = len(summary["compiles"])
    out["compile_s"] = sum(float(c.get("dur", 0.0))
                           for c in summary["compiles"])
    out["retraces"] = len(summary["retraces"])
    # comms snapshot (telemetry/comms.py, kind "comms"): the LAST event
    # describes the step program that ran — bytes are exact at trace
    # time; seconds prefer a measured profiler capture over the
    # peak-bandwidth expectation
    comms_events = [e for e in events if e.get("kind") == "comms"]
    if comms_events:
        last = comms_events[-1]
        if last.get("bytes") is not None:
            out["comms_bytes"] = float(last["bytes"])
        measured = [e for e in comms_events
                    if e.get("measured_s") is not None]
        if measured:
            out["comms_s"] = float(measured[-1]["measured_s"])
        elif last.get("expected_s") is not None:
            out["comms_s"] = float(last["expected_s"])
    # memory snapshot (telemetry/memory.py, kind "memory"): the LAST
    # event describes the step program that ran — peak is exact at
    # compile time, the number the HBM budget gates
    memory_events = [e for e in events if e.get("kind") == "memory"]
    if memory_events and memory_events[-1].get("peak_bytes") is not None:
        out["peak_hbm_bytes"] = float(memory_events[-1]["peak_bytes"])
    # goodput roll-up (telemetry/ledger.py): the run's goodput event
    # when end_run wrote one, else summarize() folded the raw events.
    # Sub-second walls are all noise (a smoke run's goodput is whatever
    # the interpreter was doing that millisecond) — don't offer them to
    # the gate
    gp = summary.get("goodput")
    if gp and gp.get("wall_s", 0.0) >= MIN_GOODPUT_WALL_S:
        out["goodput_pct"] = float(gp["goodput_pct"])
        out["badput_s"] = float(gp.get("badput_s", 0.0))
    health = summary.get("health", {})
    out["health_events"] = sum(health.get("events", {}).values())
    out["nonfinite_steps"] = health.get("nonfinite_steps", 0)
    # serving runs: fold per-batch `serve` events into the same
    # latency/rate metrics bench_serving.py emits, so a serve run log
    # diffs against another run log OR a bench_serving JSON
    serves = [e for e in events if e.get("kind") == "serve"]
    if serves:
        lats = sorted(float(e.get("queue_ms", 0.0))
                      + float(e.get("infer_ms", 0.0)) for e in serves)
        out["serve_p50_ms"] = lats[int(0.50 * (len(lats) - 1))]
        out["serve_p99_ms"] = lats[int(round(0.99 * (len(lats) - 1)))]
        rows = sum(int(e.get("size", 0)) for e in serves)
        span = max(e["ts"] for e in serves) - min(e["ts"] for e in serves)
        if span > 0:
            out["serve_qps"] = rows / span
    # request traces (telemetry/request_trace.py, kind "request"): the
    # TRUE end-to-end per-request percentiles (the serve fold above is
    # per batch and sees only queue+infer), plus the SLO violation count
    reqs = [e for e in events if e.get("kind") == "request"]
    if reqs:
        from bigdl_tpu.telemetry.report import _percentile

        # latency percentiles: completed requests PLUS dispatch
        # timeouts — a 504's wall is real waiting the client did and
        # the live histograms include it; instant 429/503 rejections
        # stay out (their ~0ms walls would dilute the percentiles)
        timed = [e for e in reqs if e.get("status") != "rejected"
                 or e.get("reason") == "dispatch_timeout"]
        if timed:
            lats = [float(e.get("ms", 0.0) or 0.0) for e in timed]
            out["request_p50_ms"] = _percentile(lats, 50.0)
            out["request_p99_ms"] = _percentile(lats, 99.0)
        # violations count over EVERY event: a rejected-504 that blew
        # the budget is precisely the violation the zero-slack gate
        # must see (the RequestFold counts it the same way)
        out["slo_violations"] = sum(1 for e in reqs
                                    if e.get("slo_violated"))
    return out


def bench_metrics(doc: Dict[str, Any], path: str = "?") -> Dict[str, Any]:
    """Comparable metrics out of one bench.py JSON line (the object with
    the per-config ``configs`` table)."""
    out: Dict[str, Any] = {"kind": "bench", "path": path}
    for name, row in (doc.get("configs") or {}).items():
        if not isinstance(row, dict) or "error" in row:
            continue
        if row.get("images_per_sec") is not None:
            out[f"{name}.images_per_sec"] = float(row["images_per_sec"])
        if row.get("mfu") is not None:
            out[f"{name}.mfu"] = float(row["mfu"])
        # per-leg compile seconds: the explicit field on new rows, the
        # stages_s breakdown on banked pre-budget artifacts
        compile_s = row.get("compile_s")
        if compile_s is None:
            compile_s = (row.get("stages_s") or {}).get("compile")
        if compile_s is not None:
            out[f"{name}.compile_s"] = float(compile_s)
        # serving rows (bench_serving.py): latency/rate + the zero-
        # slack steady-state counters; generation rows (--generate)
        # add sustained tokens/s, TTFT percentiles, and the
        # inter-token tail
        for key in ("p50_ms", "p99_ms", "qps", "rejected",
                    "steady_compiles", "retrace_diagnostics",
                    "tokens_s", "ttft_p50_ms", "ttft_p99_ms",
                    "itl_p99_ms", "slo_violations"):
            if row.get(key) is not None:
                out[f"{name}.{key}"] = float(row[key])
        # comms snapshot on bench rows (bench.py reads it off the scan
        # executable) — lets ZeRO/pipeline PRs gate on bytes moved
        for key in ("comms_bytes", "comms_s", "final_loss"):
            if row.get(key) is not None:
                out[f"{name}.{key}"] = float(row[key])
        # memory snapshot on bench rows (bench.py off the scan
        # executable, bench_serving.py off the warm bucket set) — the
        # --memory-budget gate's input
        if row.get("peak_hbm_bytes") is not None:
            out[f"{name}.peak_hbm_bytes"] = float(row["peak_hbm_bytes"])
        # goodput roll-up on bench rows (telemetry/ledger.py via the
        # live telemetry.goodput() accessor at artifact time)
        for key in ("goodput_pct", "badput_s"):
            if row.get(key) is not None:
                out[f"{name}.{key}"] = float(row[key])
    if doc.get("value") is not None and not doc.get("configs"):
        out["throughput"] = float(doc["value"])
    if doc.get("mfu") is not None:
        out["mfu"] = float(doc["mfu"])
    # whole-artifact goodput (both benches stamp it off the run that
    # produced the artifact)
    for key in ("goodput_pct", "badput_s"):
        if doc.get(key) is not None:
            out[key] = float(doc[key])
    return out


def load_metrics(path: str) -> Dict[str, Any]:
    """Sniff ``path`` (bench JSON object vs JSONL run log) and load the
    comparable metrics."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with open(path, "r", encoding="utf-8") as fh:
        head = fh.read(1 << 20)
    try:
        doc = json.loads(head)
        if isinstance(doc, dict) and "kind" not in doc:
            return bench_metrics(doc, path)
    except ValueError:
        pass
    return run_log_metrics(path)


# -- comparing ---------------------------------------------------------------
def diff_metrics(a: Dict[str, Any], b: Dict[str, Any],
                 threshold_pct: float = DEFAULT_THRESHOLD_PCT,
                 count_slack: int = 0,
                 compile_threshold_pct: Optional[float] = None,
                 memory_threshold_pct: Optional[float] = None,
                 goodput_threshold_pct: Optional[float] = None
                 ) -> List[Dict[str, Any]]:
    """Compare metric dicts (A = baseline, B = candidate).  Returns one
    row per comparable metric: ``{name, a, b, delta_pct, better,
    regressed}``, regressions first.  ``compile_threshold_pct`` is the
    compile budget applied to ``compile_s`` metrics (None = the default
    :data:`DEFAULT_COMPILE_THRESHOLD_PCT`); ``memory_threshold_pct``
    the memory budget applied to ``peak_hbm_bytes`` metrics (None =
    :data:`DEFAULT_MEMORY_THRESHOLD_PCT`); ``goodput_threshold_pct``
    the goodput gate applied to ``goodput_pct``/``badput_s`` metrics
    (None = :data:`DEFAULT_GOODPUT_THRESHOLD_PCT`)."""
    if compile_threshold_pct is None:
        compile_threshold_pct = DEFAULT_COMPILE_THRESHOLD_PCT
    if memory_threshold_pct is None:
        memory_threshold_pct = DEFAULT_MEMORY_THRESHOLD_PCT
    if goodput_threshold_pct is None:
        goodput_threshold_pct = DEFAULT_GOODPUT_THRESHOLD_PCT
    rows: List[Dict[str, Any]] = []
    for name in sorted(set(a) & set(b)):
        rule = _rule_for(name)
        if rule is None:
            continue
        direction, kind = rule
        va, vb = a[name], b[name]
        if not isinstance(va, (int, float)) \
                or not isinstance(vb, (int, float)):
            continue
        delta = vb - va
        delta_pct = (delta / abs(va) * 100.0) if va else None
        worse = delta > 0 if direction == "lower" else delta < 0
        if kind == "count":
            regressed = worse and abs(delta) > count_slack
        elif delta_pct is None:
            # zero baseline: any move in the bad direction IS the
            # regression (0 -> anything is an infinite pct change)
            regressed = worse and abs(delta) > 1e-9
        elif kind == "pct_compile":
            regressed = worse and abs(delta_pct) > compile_threshold_pct
        elif kind == "pct_memory":
            regressed = worse and abs(delta_pct) > memory_threshold_pct
        elif kind == "pct_goodput":
            if name.endswith("goodput_pct"):
                # already a percentage: compare in percentage POINTS —
                # relative change would make a 10%->9.4% drop regress
                # while 90%->85% (nine times the lost wall) passed
                regressed = worse and abs(va - vb) > goodput_threshold_pct
            else:
                regressed = worse and abs(delta_pct) > goodput_threshold_pct
        else:
            regressed = worse and abs(delta_pct) > threshold_pct
        rows.append({"name": name, "a": va, "b": vb,
                     "delta_pct": delta_pct, "better": direction,
                     "regressed": bool(regressed)})
    rows.sort(key=lambda r: (not r["regressed"], r["name"]))
    return rows


def format_diff(rows: List[Dict[str, Any]], a: Dict[str, Any],
                b: Dict[str, Any]) -> str:
    lines = [f"== telemetry diff ==",
             f"A (baseline):  {a.get('path', '?')} [{a.get('kind')}]",
             f"B (candidate): {b.get('path', '?')} [{b.get('kind')}]"]
    if not rows:
        lines.append("no comparable metrics on both sides")
        return "\n".join(lines)
    width = max(len(r["name"]) for r in rows)
    for r in rows:
        pct = (f"{r['delta_pct']:+8.2f}%" if r["delta_pct"] is not None
               else f"{r['b'] - r['a']:+9.3g}")  # 0-baseline: abs delta
        flag = "REGRESSED" if r["regressed"] else "ok"
        lines.append(f"{r['name']:<{width}}  {r['a']:>12.6g} -> "
                     f"{r['b']:>12.6g}  {pct}  "
                     f"({r['better']} is better)  {flag}")
    n_reg = sum(r["regressed"] for r in rows)
    lines.append(f"{n_reg} regression(s) out of {len(rows)} compared "
                 f"metric(s)")
    return "\n".join(lines)


def main(argv=None) -> int:
    """``python -m bigdl_tpu.telemetry diff`` entry (also callable from
    bench.py)."""
    import argparse
    import sys

    p = argparse.ArgumentParser(
        prog="bigdl_tpu.telemetry diff",
        description="compare two runs (JSONL run logs or bench.py JSON) "
                    "and exit nonzero on a regression")
    p.add_argument("run_a", help="baseline artifact")
    p.add_argument("run_b", help="candidate artifact")
    p.add_argument("--threshold-pct", type=float,
                   default=DEFAULT_THRESHOLD_PCT,
                   help="relative regression threshold for timing/"
                        "throughput/MFU metrics (default %(default)s)")
    p.add_argument("--count-slack", type=int, default=0,
                   help="allowed increase for compile/retrace/health "
                        "counts (default 0)")
    p.add_argument("--compile-threshold-pct", type=float, default=None,
                   help="compile budget: relative regression threshold "
                        "for compile_s metrics (default "
                        f"{DEFAULT_COMPILE_THRESHOLD_PCT})")
    p.add_argument("--memory-threshold-pct", type=float, default=None,
                   help="memory budget: relative regression threshold "
                        "for peak_hbm_bytes metrics (default "
                        f"{DEFAULT_MEMORY_THRESHOLD_PCT})")
    p.add_argument("--goodput-threshold-pct", type=float, default=None,
                   help="goodput gate: relative regression threshold "
                        "for goodput_pct/badput_s metrics (default "
                        f"{DEFAULT_GOODPUT_THRESHOLD_PCT})")
    p.add_argument("--json", action="store_true",
                   help="emit rows as JSON instead of the table")
    args = p.parse_args(argv)

    try:
        a = load_metrics(args.run_a)
        b = load_metrics(args.run_b)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    rows = diff_metrics(a, b, threshold_pct=args.threshold_pct,
                        count_slack=args.count_slack,
                        compile_threshold_pct=args.compile_threshold_pct,
                        memory_threshold_pct=args.memory_threshold_pct,
                        goodput_threshold_pct=args.goodput_threshold_pct)
    n_regressed = sum(r["regressed"] for r in rows)
    exit_code = 2 if not rows else (1 if n_regressed else 0)
    if args.json:
        # CI-consumable: the verdict and exit code travel IN the
        # payload, so a pipeline can archive one artifact and decide
        # later without re-running (exit-code contract unchanged)
        verdict = {0: "ok", 1: "regressed", 2: "not_comparable"}[exit_code]
        print(json.dumps({"a": a, "b": b, "rows": rows,
                          "verdict": verdict, "regressions": n_regressed,
                          "compared": len(rows),
                          "threshold_pct": args.threshold_pct,
                          "compile_threshold_pct":
                              (args.compile_threshold_pct
                               if args.compile_threshold_pct is not None
                               else DEFAULT_COMPILE_THRESHOLD_PCT),
                          "memory_threshold_pct":
                              (args.memory_threshold_pct
                               if args.memory_threshold_pct is not None
                               else DEFAULT_MEMORY_THRESHOLD_PCT),
                          "goodput_threshold_pct":
                              (args.goodput_threshold_pct
                               if args.goodput_threshold_pct is not None
                               else DEFAULT_GOODPUT_THRESHOLD_PCT),
                          "count_slack": args.count_slack,
                          "exit_code": exit_code}, indent=2))
    else:
        print(format_diff(rows, a, b))
    if not rows:
        print("error: nothing comparable", file=sys.stderr)
    return exit_code
