"""Crash flight recorder: a bounded ring of the most recent telemetry
events plus the last health evidence, dumped to ``flight-<stamp>.json``
when training dies — ``HealthError`` halt, straggler firing, retry
exhaustion, or any crash escaping ``optimize()``.

The recorder is a tracer sink (attached by ``telemetry.start_run``
whenever ``BIGDL_FLIGHT`` > 0, the default), so it costs one deque
append per event while healthy and needs no log file to exist: the dump
is self-contained postmortem evidence even when the JSONL sink was
disabled or its tail lost to a hard crash.

Dump layout::

    {"reason": "...", "dumped_at": <epoch>, "meta": {...},
     "evidence": {...},            # HealthError evidence, if any
     "last_health": {...},         # most recent health probe event
     "events": [...]}              # the ring, oldest first

``python -m json.tool flight-*.json`` is all a postmortem needs.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["FlightRecorder"]


class FlightRecorder:
    """Tracer sink keeping the last ``capacity`` events in memory."""

    def __init__(self, capacity: int = 2048):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: "deque" = deque(maxlen=max(self.capacity, 1))
        self._last_health: Dict[str, Any] = {}
        self.meta: Dict[str, Any] = {}
        self.last_dump_path: Optional[str] = None
        self.dumps = 0

    # -- sink protocol -----------------------------------------------------
    def emit(self, event: Dict[str, Any]) -> None:
        with self._lock:
            kind = event.get("kind")
            if kind == "run_start":
                self.meta.update(event.get("meta") or {})
            elif kind == "health":
                self._last_health = event
            self._ring.append(event)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    # -- the dump ----------------------------------------------------------
    def dump(self, reason: str, evidence: Optional[Dict[str, Any]] = None,
             directory: Optional[str] = None) -> Optional[str]:
        """Write the ring to ``flight-<stamp>.json`` and return its path
        (None when the write itself fails — a dying process must not die
        harder).  ``directory`` defaults to the telemetry dir, else the
        cwd."""
        if directory is None:
            from bigdl_tpu.utils.config import get_config

            directory = get_config().telemetry_dir or "."
        with self._lock:
            events: List[Dict[str, Any]] = list(self._ring)
            payload = {"reason": reason,
                       "dumped_at": time.time(),
                       "pid": os.getpid(),
                       "meta": dict(self.meta),
                       "evidence": dict(evidence or {}),
                       "last_health": dict(self._last_health),
                       "events": events}
        stamp = time.strftime("%Y%m%d_%H%M%S")
        with self._lock:
            seq = self.dumps  # two dumps in one second must not collide
        path = os.path.join(
            directory, f"flight-{stamp}-{os.getpid()}-{seq}.json")
        try:
            os.makedirs(directory, exist_ok=True)
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(payload, fh, default=repr)
            with self._lock:
                self.last_dump_path = path
                self.dumps += 1
        except Exception:  # noqa: BLE001 - dumping is best-effort
            return None
        from bigdl_tpu import telemetry

        telemetry.instant("flight/dump", path=path, reason=reason,
                          events=len(events))
        return path

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {"capacity": self.capacity,
                    "events_buffered": len(self._ring),
                    "dumps": self.dumps,
                    "last_dump_path": self.last_dump_path}
