"""The process-wide tracer: structured spans, counters, gauges, and
instant events appended to one JSON-lines run log.

Deliberately dependency-light (stdlib only at module load): the hot
layers (``optim/metrics.py``, ``optim/optimizer.py``,
``parallel/train_step.py``) import this at module load, and when no run
is active every emit helper is one falsy check — the same contract as
``analysis/hooks.py``.

Event stream shape (see ``telemetry/schema.py`` for the full schema):
every line is one JSON object with the base fields ``v`` (schema
version), ``ts`` (epoch seconds), ``pid`` (OS pid), ``tid`` (thread id),
``kind``, plus kind-specific fields.  Spans are emitted as explicit
``span_begin``/``span_end`` pairs (ids, parent, depth) so nesting and
pairing are checkable properties of the log itself, not of the reader.

Thread model: one lock around sink emission; span stacks are
thread-local, so each thread's spans nest independently (the Chrome
exporter renders one lane per tid).  A span left open by an exception is
closed by :meth:`Tracer.unwind` with ``abandoned: true`` — every begin
always has an end.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

__all__ = ["SCHEMA_VERSION", "Tracer", "JsonlSink", "MemorySink"]

SCHEMA_VERSION = 1


class JsonlSink:
    """Append-only JSON-lines file sink (one event per line)."""

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._pending = 0

    def emit(self, event: Dict[str, Any]) -> None:
        self._f.write(json.dumps(event, separators=(",", ":"),
                                 default=_json_default) + "\n")
        self._pending += 1
        if self._pending >= 32:  # bound loss on a crashed run
            self._f.flush()
            self._pending = 0

    def flush(self) -> None:
        self._f.flush()
        self._pending = 0

    def close(self) -> None:
        try:
            self._f.flush()
        finally:
            self._f.close()


class MemorySink:
    """In-memory sink for tests and programmatic inspection."""

    def __init__(self):
        self.events: List[Dict[str, Any]] = []

    def emit(self, event: Dict[str, Any]) -> None:
        self.events.append(event)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


def _json_default(obj):
    """Last-resort encoder: numpy scalars and arrays show up in attrs
    (losses, shapes) — render them as plain Python, everything else as
    its repr rather than failing the write."""
    try:
        import numpy as np

        if isinstance(obj, np.generic):
            return obj.item()
        if isinstance(obj, np.ndarray):
            return obj.tolist()
    except Exception:  # noqa: BLE001 - encoding must never raise
        pass
    return repr(obj)


class _OpenSpan:
    __slots__ = ("sid", "name", "t0")

    def __init__(self, sid: int, name: str, t0: float):
        self.sid = sid
        self.name = name
        self.t0 = t0


class Tracer:
    """Emit structured events into a set of sinks.  Construct directly
    for tests; production code goes through ``telemetry.start_run``."""

    def __init__(self, sinks=(), meta: Optional[Dict[str, Any]] = None):
        self._lock = threading.Lock()
        self._sinks = list(sinks)
        self._ids = itertools.count(1)
        self._tls = threading.local()
        # tid -> the same list the thread-local holds, so close() can
        # unwind spans a WORKER thread left open (the thread-local view
        # alone would orphan them and break begin/end pairing)
        self._stacks: Dict[int, List[_OpenSpan]] = {}
        self._t_start = time.time()
        self.meta = dict(meta or {})
        self.closed = False

    # -- sink management ---------------------------------------------------
    def add_sink(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def remove_sink(self, sink) -> None:
        with self._lock:
            try:
                self._sinks.remove(sink)
            except ValueError:
                pass

    # -- raw emission ------------------------------------------------------
    def emit(self, kind: str, **fields) -> None:
        event = {"v": SCHEMA_VERSION, "ts": time.time(),
                 "pid": os.getpid(), "tid": threading.get_ident(),
                 "kind": kind}
        event.update(fields)
        with self._lock:
            if self.closed:
                return
            for sink in self._sinks:
                try:
                    sink.emit(event)
                except Exception:  # noqa: BLE001 - observers never kill the run
                    pass

    # -- spans -------------------------------------------------------------
    def _stack(self) -> List[_OpenSpan]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
            with self._lock:
                self._stacks[threading.get_ident()] = stack
        return stack

    def begin(self, name: str, **attrs) -> int:
        """Open a span on the calling thread; returns its id for
        :meth:`end`.  Prefer :meth:`span` where a with-block fits."""
        stack = self._stack()
        sid = next(self._ids)
        parent = stack[-1].sid if stack else 0
        depth = len(stack)
        stack.append(_OpenSpan(sid, name, time.perf_counter()))
        self.emit("span_begin", name=name, span=sid, parent=parent,
                  depth=depth, **attrs)
        return sid

    def end(self, sid: int, **attrs) -> None:
        """Close the span ``sid``; any deeper spans still open on this
        thread are closed first (``abandoned: true``) so begin/end pairs
        stay LIFO in the log.  Unknown ids are a no-op."""
        stack = self._stack()
        if not any(s.sid == sid for s in stack):
            return
        now = time.perf_counter()
        while stack:
            top = stack.pop()
            if top.sid == sid:
                self.emit("span_end", name=top.name, span=top.sid,
                          dur=now - top.t0, **attrs)
                return
            self.emit("span_end", name=top.name, span=top.sid,
                      dur=now - top.t0, abandoned=True)

    @contextmanager
    def span(self, name: str, **attrs):
        sid = self.begin(name, **attrs)
        try:
            yield sid
        finally:
            self.end(sid)

    def depth(self) -> int:
        """Number of spans open on the calling thread — capture it at a
        scope's entry to :meth:`unwind` back to exactly that scope."""
        return len(self._stack())

    def unwind(self, to_depth: int = 0, **attrs) -> None:
        """Close spans open on the calling thread down to ``to_depth``
        (exception paths), newest first, marked ``abandoned: true`` —
        spans an enclosing caller opened above ``to_depth`` are left
        untouched."""
        stack = self._stack()
        now = time.perf_counter()
        while len(stack) > to_depth:
            top = stack.pop()
            self.emit("span_end", name=top.name, span=top.sid,
                      dur=now - top.t0, abandoned=True, **attrs)

    # -- scalar streams ----------------------------------------------------
    def stage(self, name: str, dur: float, **attrs) -> None:
        """One sample of a named pipeline stage (seconds) — the Metrics
        accumulator forwards every ``add`` here."""
        self.emit("stage", name=name, dur=float(dur), **attrs)

    def counter(self, name: str, value: float, **attrs) -> None:
        self.emit("counter", name=name, value=float(value), **attrs)

    def gauge(self, name: str, value: float, **attrs) -> None:
        self.emit("gauge", name=name, value=float(value), **attrs)

    def instant(self, name: str, **attrs) -> None:
        """A point-in-time marker (straggler firing, retry, epoch
        boundary, checkpoint commit)."""
        self.emit("event", name=name, **attrs)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self.emit("run_start", meta=self.meta)

    def close(self) -> None:
        if self.closed:
            return
        self.unwind()
        # spans other threads left open (a worker that died inside a
        # span, a straggler still blocked): close them under THEIR tid,
        # so per-thread pairing stays valid in the final log
        with self._lock:
            me = threading.get_ident()
            others = [(tid, st) for tid, st in self._stacks.items()
                      if tid != me and st]
        now = time.perf_counter()
        for tid, stack in others:
            while True:
                # the owning thread may race us ending its own spans:
                # pop-or-stop, never crash the shutdown
                try:
                    top = stack.pop()
                except IndexError:
                    break
                self.emit("span_end", name=top.name, span=top.sid,
                          dur=now - top.t0, abandoned=True, tid=tid)
        self.emit("run_end", dur=time.time() - self._t_start)
        with self._lock:
            self.closed = True
            sinks, self._sinks = self._sinks, []
        for sink in sinks:
            try:
                sink.close()
            except Exception:  # noqa: BLE001
                pass
