"""Run-level goodput/badput ledger (docs/observability.md "Goodput").

Answers the question production actually asks of a training job: *of the
wall time this run held the hardware, how many seconds trained the
model?*  Every step-level instrument already exists (stage timings,
compile events, comms expected-vs-measured, checkpoint spans, the
supervisor's restart instants); this module folds them into one
exhaustive decomposition of wall time::

    wall = compute + compile + data_wait + comms + straggler
         + checkpoint + replay + retry_backoff + restart + backoff
         + drain + idle

with the conservation contract lifted from per-step (PR 14's request
waterfalls) to the whole run: the categories must sum to wall time
within a pinned tolerance, and a *blame* verdict names the dominant
badput category with evidence.

Three consumption shapes share one fold:

- :class:`LedgerFold` — streaming, one event at a time.  Installed as a
  side-accumulator by the telemetry runtime (the per-run ``goodput``
  summary event + ``telemetry.goodput()``), by the /metrics sink
  (``/status.goodput``, ``bigdl_goodput_pct``), and by the fleet
  watcher's per-host state.
- :func:`goodput_from_events` — fold a parsed single-process log.
- :func:`ledger_from_events` — the offline multi-log stitcher: groups
  run logs into per-process incarnation chains, classifies the
  inter-incarnation gaps (supervisor backoff vs restart overhead) off
  the ``cluster/restart`` instants, and checks conservation per chain
  so time is never double-counted across a restart boundary.

Category semantics (the taxonomy the docs pin):

- ``compute``   productive: in-step device time after carving the
  overheads below out of each step, plus validation spans (evaluating
  the model is the job's purpose too).
- ``compile``   XLA compilation (in-step first-iteration traces plus
  AOT/warmup compiles outside any step).
- ``data_wait`` input pipeline stalls (the ``data_wait`` span inside
  each step).
- ``comms``     unoverlapped collective time: the comms walker's
  per-step measured (or expected) seconds times the step count.
- ``straggler`` collective watchdog budgets burned waiting on a slow
  or dead peer.
- ``checkpoint`` save spans plus restore stages.
- ``replay``    preempt-resume fast-forward through already-consumed
  input records.
- ``retry_backoff`` in-process retry sleeps (``run/retry``).
- ``restart``/``backoff`` supervised incarnation gaps: the part of the
  gap covered by the supervisor's recorded backoff vs the residual
  process teardown + respawn overhead.
- ``drain``     graceful drain before exit (serving drain span, the
  supervisor's SIGTERM grace).
- ``idle``      wall time with no attributable activity.

Stdlib only — this is imported (lazily) by the tracer runtime and the
metrics sink, which must work without jax.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["BADPUT_CATEGORIES", "DEFAULT_TOLERANCE_PCT", "LedgerFold",
           "goodput_from_events", "ledger_from_events", "blame_verdict",
           "format_goodput", "goodput_main"]

#: display/JSON order of the badput categories (compute is not badput)
BADPUT_CATEGORIES: Tuple[str, ...] = (
    "compile", "data_wait", "comms", "straggler", "checkpoint", "replay",
    "retry_backoff", "restart", "backoff", "drain", "idle")

#: run-level conservation tolerance: |compute + Σbadput - wall| / wall
DEFAULT_TOLERANCE_PCT = 5.0

#: when a restart instant's timestamp must be matched to an incarnation
#: gap, allow this much slack (instants are emitted by the supervisor,
#: whose clock samples bracket the children's first/last events)
_GAP_SLACK_S = 1.0


def _num(x, default=0.0) -> float:
    return float(x) if isinstance(x, (int, float)) \
        and not isinstance(x, bool) else default


class LedgerFold:
    """Streaming accumulator for one process's event stream.

    ``fold_event`` is cheap (one kind dispatch, a few float adds) so it
    can ride inside the /metrics sink's emit path; ``snapshot`` runs the
    decomposition on demand and never mutates state.
    """

    def __init__(self):
        self.first_ts: Optional[float] = None
        self.last_ts: Optional[float] = None
        self.step_n = 0
        self.step_s = 0.0
        self.data_wait_s = 0.0
        self.data_wait_n = 0
        self.compile_s = 0.0
        self.compile_n = 0
        self.validation_s = 0.0
        self.checkpoint_s = 0.0
        self.checkpoint_n = 0
        self.replay_s = 0.0
        self.replay_records = 0
        self.retry_backoff_s = 0.0
        self.retry_n = 0
        #: furthest point in time any retry's charged sleep reaches
        #: (``ts + backoff_s``) — ``run/retry`` is emitted BEFORE the
        #: sleep, so a worker killed mid-backoff charged time the log's
        #: wall never contained; snapshot() trims the unelapsed tail
        self.retry_extent_ts: Optional[float] = None
        self.drain_s = 0.0
        self.drain_n = 0
        self.straggler_s = 0.0
        self.straggler_n = 0
        self.comms_per_step_s = 0.0
        #: cluster/restart instants seen in THIS stream (supervisor
        #: logs); (ts, backoff_s, exits) — evidence + gap classification
        self.restarts: List[Tuple[float, float, Any]] = []

    # -- folding -----------------------------------------------------------
    def fold_event(self, ev: Dict[str, Any]) -> None:
        ts = ev.get("ts")
        if isinstance(ts, (int, float)) and not isinstance(ts, bool):
            if self.first_ts is None or ts < self.first_ts:
                self.first_ts = float(ts)
            if self.last_ts is None or ts > self.last_ts:
                self.last_ts = float(ts)
        kind = ev.get("kind")
        if kind == "step":
            self.step_n += 1
            self.step_s += _num(ev.get("dur"))
        elif kind == "compile":
            self.compile_n += 1
            self.compile_s += _num(ev.get("dur"))
        elif kind == "span_end":
            name, dur = ev.get("name"), _num(ev.get("dur"))
            if name == "data_wait":
                self.data_wait_n += 1
                self.data_wait_s += dur
            elif name == "validation":
                self.validation_s += dur
            elif name == "checkpoint":
                self.checkpoint_n += 1
                self.checkpoint_s += dur
            elif name == "serve/drain":
                self.drain_n += 1
                self.drain_s += dur
        elif kind == "stage":
            name, dur = ev.get("name"), _num(ev.get("dur"))
            if name == "resume/fast_forward":
                self.replay_s += dur
                self.replay_records += int(_num(ev.get("records")))
            elif name == "checkpoint/restore":
                self.checkpoint_n += 1
                self.checkpoint_s += dur
        elif kind == "event":
            name = ev.get("name")
            if name == "run/retry":
                self.retry_n += 1
                backoff = _num(ev.get("backoff_s"))
                self.retry_backoff_s += backoff
                if isinstance(ts, (int, float)) \
                        and not isinstance(ts, bool):
                    extent = float(ts) + backoff
                    if self.retry_extent_ts is None \
                            or extent > self.retry_extent_ts:
                        self.retry_extent_ts = extent
            elif name == "straggler/timeout":
                self.straggler_n += 1
                self.straggler_s += _num(ev.get("budget_s"))
            elif name == "sync/staleness":
                # a fast host holding the local-SGD barrier open for a
                # laggard (parallel/local_sync.py) is waiting on a slow
                # host exactly like a straggler-guard trip — same blame
                # column, whichever instrument caught it
                waited = _num(ev.get("waited_s"))
                if waited > 0:
                    self.straggler_n += 1
                    self.straggler_s += waited
            elif name == "cluster/drain":
                self.drain_n += 1
                self.drain_s += _num(ev.get("dur"))
            elif name == "cluster/restart":
                self.restarts.append((_num(ev.get("ts")),
                                      _num(ev.get("backoff_s")),
                                      ev.get("exits")))
        elif kind == "comms":
            # latest per-step collective seconds: measured when the
            # walker timed the step, predicted otherwise
            per = ev.get("measured_s")
            if not isinstance(per, (int, float)) or isinstance(per, bool):
                per = ev.get("expected_s")
            if isinstance(per, (int, float)) and not isinstance(per, bool):
                self.comms_per_step_s = float(per)

    def fold_events(self, events: Iterable[Dict[str, Any]]) -> None:
        for ev in events:
            self.fold_event(ev)

    # sink protocol: a LedgerFold can ride directly on a Tracer's sink
    # list (the runtime installs one per run for the end-of-run goodput
    # event and the live ``telemetry.goodput()`` accessor)
    def emit(self, event: Dict[str, Any]) -> None:
        self.fold_event(event)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    # -- decomposition -----------------------------------------------------
    def snapshot(self) -> Optional[Dict[str, Any]]:
        """The current decomposition, or None before any event.

        In-step overheads (data_wait, compile, comms, straggler) are
        carved out of the summed step durations — each capped at the
        remainder so a mis-scaled instrument can never push in-step
        badput past the time the steps actually took; what is left of
        the step time is productive compute.  Measured intervals outside
        steps (validation, checkpoint, replay, backoffs, drain) are
        charged at face value; ``idle`` is the unexplained residual,
        floored at zero.  Leftover compile time (AOT/warmup compiles
        that ran outside any step) is reassigned from idle.  When the
        instruments don't overlap, the categories sum to wall exactly;
        overlap shows up as a conservation error the ±tolerance check
        catches.
        """
        if self.first_ts is None or self.last_ts is None:
            return None
        wall = max(0.0, self.last_ts - self.first_ts)
        in_step = min(self.step_s, wall)
        rem = in_step
        data_wait = min(self.data_wait_s, rem)
        rem -= data_wait
        compile_in = min(self.compile_s, rem)
        rem -= compile_in
        comms = min(self.comms_per_step_s * self.step_n, rem)
        rem -= comms
        straggler = min(self.straggler_s, rem)
        rem -= straggler
        compute_step = rem
        restart_backoff = sum(b for _, b, _ in self.restarts)
        # trim the retry sleep that was charged but never slept: the
        # instant fires BEFORE the backoff, so a process killed
        # mid-backoff would otherwise carry badput past its own wall
        retry_backoff = self.retry_backoff_s
        if self.retry_extent_ts is not None:
            retry_backoff -= min(
                retry_backoff,
                max(0.0, self.retry_extent_ts - self.last_ts))
        outside = (self.validation_s + self.checkpoint_s + self.replay_s
                   + retry_backoff + self.drain_s + restart_backoff)
        idle = max(0.0, wall - in_step - outside)
        extra_compile = min(max(0.0, self.compile_s - compile_in), idle)
        idle -= extra_compile
        compute = compute_step + self.validation_s
        badput = {
            "compile": compile_in + extra_compile,
            "data_wait": data_wait,
            "comms": comms,
            "straggler": straggler,
            "checkpoint": self.checkpoint_s,
            "replay": self.replay_s,
            "retry_backoff": retry_backoff,
            "restart": 0.0,
            "backoff": restart_backoff,
            "drain": self.drain_s,
            "idle": idle,
        }
        counts = {
            "steps": self.step_n,
            "compiles": self.compile_n,
            "data_waits": self.data_wait_n,
            "checkpoints": self.checkpoint_n,
            "replay_records": self.replay_records,
            "retries": self.retry_n,
            "stragglers": self.straggler_n,
            "drains": self.drain_n,
            "restarts": len(self.restarts),
            "incarnations": 1,
            "exits": [x for _, _, x in self.restarts if x is not None],
        }
        return _finish_report(wall, compute, badput, counts)

    def event_fields(self) -> Optional[Dict[str, Any]]:
        """Fields of the per-run ``goodput`` summary event (None before
        any event): the snapshot plus the blame verdict."""
        report = self.snapshot()
        if report is None:
            return None
        report["blame"] = blame_verdict(report)
        return report


def _finish_report(wall: float, compute: float, badput: Dict[str, float],
                   counts: Dict[str, Any]) -> Dict[str, Any]:
    badput = {k: round(max(0.0, v), 6) for k, v in badput.items()}
    badput_total = sum(badput.values())
    total = compute + badput_total
    err_pct = 100.0 * abs(total - wall) / wall if wall > 0 else 0.0
    return {
        "wall_s": round(wall, 6),
        "compute_s": round(compute, 6),
        "badput_s": round(badput_total, 6),
        "goodput_pct": round(100.0 * compute / wall, 3) if wall > 0 else 0.0,
        "badput": badput,
        "counts": counts,
        "conservation_err_pct": round(err_pct, 3),
    }


# -- blame -------------------------------------------------------------------
def _evidence(cause: str, seconds: float, counts: Dict[str, Any]) -> str:
    if cause == "compile":
        return (f"{counts.get('compiles', 0)} compilation(s) totalling "
                f"{seconds:.1f}s")
    if cause == "data_wait":
        return (f"input pipeline stalled {counts.get('data_waits', 0)} "
                f"time(s) across {counts.get('steps', 0)} step(s)")
    if cause == "comms":
        return (f"unoverlapped collective time across "
                f"{counts.get('steps', 0)} step(s)")
    if cause == "straggler":
        return (f"{counts.get('stragglers', 0)} straggler watchdog "
                f"budget(s) burned")
    if cause == "checkpoint":
        return (f"{counts.get('checkpoints', 0)} checkpoint "
                f"save/restore interval(s)")
    if cause == "replay":
        return (f"fast-forward replay of "
                f"{counts.get('replay_records', 0)} record(s)")
    if cause == "retry_backoff":
        return f"{counts.get('retries', 0)} in-process retry backoff(s)"
    if cause == "restart":
        exits = counts.get("exits") or []
        tail = f"; exits {exits}" if exits else ""
        return (f"{counts.get('restarts', 0)} supervised restart(s) "
                f"across {counts.get('incarnations', 1)} "
                f"incarnation(s){tail}")
    if cause == "backoff":
        return (f"supervisor backoff before "
                f"{counts.get('restarts', 0)} restart(s)")
    if cause == "drain":
        return f"{counts.get('drains', 0)} graceful drain(s) before exit"
    if cause == "idle":
        return "wall time with no attributable activity"
    return ""


def blame_verdict(report: Dict[str, Any]) -> Dict[str, Any]:
    """Name the dominant badput category with evidence, or ``none``
    when badput is negligible (< 1% of wall)."""
    wall = report.get("wall_s", 0.0)
    badput = report.get("badput") or {}
    counts = report.get("counts") or {}
    cause, seconds = "none", 0.0
    for cat in BADPUT_CATEGORIES:
        if badput.get(cat, 0.0) > seconds:
            cause, seconds = cat, badput[cat]
    total = sum(badput.values())
    if seconds <= 0 or (wall > 0 and total < 0.01 * wall):
        return {"cause": "none", "seconds": 0.0, "share_pct": 0.0,
                "evidence": "badput negligible"}
    share = 100.0 * seconds / total if total > 0 else 0.0
    return {"cause": cause, "seconds": round(seconds, 6),
            "share_pct": round(share, 1),
            "evidence": _evidence(cause, seconds, counts)}


# -- single-log / multi-log entry points -------------------------------------
def goodput_from_events(
        events: Iterable[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Fold one parsed event list into a goodput report (with blame),
    or None when the list is empty."""
    fold = LedgerFold()
    fold.fold_events(events)
    report = fold.snapshot()
    if report is not None:
        report["blame"] = blame_verdict(report)
    return report


def _run_meta(events: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    for ev in events:
        if ev.get("kind") == "run_start" and isinstance(ev.get("meta"),
                                                        dict):
            return ev["meta"]
    return {}


def _sum_counts(into: Dict[str, Any], counts: Dict[str, Any]) -> None:
    for k, v in counts.items():
        if k == "exits":
            into.setdefault("exits", []).extend(v or [])
        else:
            into[k] = into.get(k, 0) + (v or 0)


def ledger_from_events(runs: Sequence[Tuple[str, Sequence[Dict[str, Any]]]],
                       tolerance_pct: float = DEFAULT_TOLERANCE_PCT,
                       ) -> Optional[Dict[str, Any]]:
    """Stitch one or more run logs into the full-run ledger.

    ``runs`` is a list of ``(path, parsed events)``.  Logs carrying a
    supervisor role (``meta.role == "supervisor"`` or any
    ``cluster/restart`` instant) form the supervisor timeline: their
    restart instants classify the workers' inter-incarnation gaps, but
    they do not contribute a wall-time chain of their own — the same
    seconds already belong to the worker chains.  Worker logs group by
    ``meta.process_index`` into incarnation chains ordered by start
    time; each chain's wall is the sum of its incarnation walls plus
    the gaps between them (so no second is counted twice across a
    restart boundary), each gap split into supervisor ``backoff`` vs
    residual ``restart`` overhead.  Conservation is checked per chain.

    Returns None when no run has any events.
    """
    folded = []
    restart_instants: List[Tuple[float, float, Any]] = []
    n_supervisor = 0
    for path, events in runs:
        fold = LedgerFold()
        fold.fold_events(events)
        report = fold.snapshot()
        if report is None:
            continue
        meta = _run_meta(events)
        is_supervisor = (meta.get("role") == "supervisor"
                         or bool(fold.restarts))
        restart_instants.extend(fold.restarts)
        if is_supervisor:
            n_supervisor += 1
        folded.append({"path": path, "meta": meta, "fold": fold,
                       "report": report, "supervisor": is_supervisor})
    if not folded:
        return None
    workers = [f for f in folded if not f["supervisor"]]
    if not workers:  # supervisor-only input: fold it as its own chain
        workers = folded
    chains: Dict[Any, List[Dict[str, Any]]] = {}
    for f in workers:
        pidx = f["meta"].get("process_index", 0)
        chains.setdefault(pidx, []).append(f)

    chain_reports = []
    totals_badput = {c: 0.0 for c in BADPUT_CATEGORIES}
    totals_counts: Dict[str, Any] = {}
    total_wall = total_compute = 0.0
    for pidx in sorted(chains, key=lambda x: (str(type(x)), str(x))):
        incs = sorted(chains[pidx], key=lambda f: (
            f["fold"].first_ts or 0.0,
            _num(f["meta"].get("incarnation"))))
        wall = compute = 0.0
        badput = {c: 0.0 for c in BADPUT_CATEGORIES}
        counts: Dict[str, Any] = {}
        for f in incs:
            r = f["report"]
            wall += r["wall_s"]
            compute += r["compute_s"]
            for c in BADPUT_CATEGORIES:
                badput[c] += r["badput"].get(c, 0.0)
            _sum_counts(counts, r["counts"])
        counts["incarnations"] = len(incs)
        gap_restart = gap_backoff = 0.0
        for prev, nxt in zip(incs, incs[1:]):
            lo = (prev["fold"].last_ts or 0.0) - _GAP_SLACK_S
            hi = (nxt["fold"].first_ts or 0.0) + _GAP_SLACK_S
            gap = max(0.0, (nxt["fold"].first_ts or 0.0)
                      - (prev["fold"].last_ts or 0.0))
            booked = sum(b for ts, b, _ in restart_instants
                         if lo <= ts <= hi)
            backoff = min(gap, booked)
            gap_backoff += backoff
            gap_restart += gap - backoff
            wall += gap
        if len(incs) > 1:
            counts["restarts"] = max(counts.get("restarts", 0),
                                     len(incs) - 1)
            exits = [x for _, _, x in restart_instants if x is not None]
            if exits and not counts.get("exits"):
                counts["exits"] = exits
        badput["restart"] += gap_restart
        badput["backoff"] += gap_backoff
        report = _finish_report(wall, compute, badput, counts)
        report["process_index"] = pidx
        report["incarnations"] = len(incs)
        report["paths"] = [f["path"] for f in incs]
        report["ok"] = report["conservation_err_pct"] <= tolerance_pct
        chain_reports.append(report)
        total_wall += wall
        total_compute += compute
        for c in BADPUT_CATEGORIES:
            totals_badput[c] += badput[c]
        _sum_counts(totals_counts, counts)

    out = _finish_report(total_wall, total_compute, totals_badput,
                         totals_counts)
    out["blame"] = blame_verdict(out)
    out["chains"] = chain_reports
    out["n_runs"] = len(folded)
    out["n_supervisor_runs"] = n_supervisor
    worst = max((c["conservation_err_pct"] for c in chain_reports),
                default=0.0)
    out["conservation"] = {
        "tolerance_pct": tolerance_pct,
        "worst_err_pct": worst,
        "ok": all(c["ok"] for c in chain_reports),
    }
    return out


# -- rendering + CLI ---------------------------------------------------------
def format_goodput(report: Dict[str, Any]) -> str:
    lines = ["== goodput =="]
    lines.append(f"wall {report['wall_s']:.1f}s   "
                 f"compute {report['compute_s']:.1f}s   "
                 f"goodput {report['goodput_pct']:.1f}%   "
                 f"badput {report['badput_s']:.1f}s")
    badput = report.get("badput") or {}
    total = sum(badput.values())
    nonzero = [(c, badput[c]) for c in BADPUT_CATEGORIES
               if badput.get(c, 0.0) > 0]
    nonzero.sort(key=lambda kv: -kv[1])
    if nonzero:
        lines.append("badput by category:")
        for cat, s in nonzero:
            share = 100.0 * s / total if total > 0 else 0.0
            lines.append(f"  {cat:<14} {s:>9.2f}s  {share:5.1f}%")
    for chain in report.get("chains") or []:
        flag = "ok" if chain.get("ok") else "CONSERVATION VIOLATED"
        lines.append(
            f"chain p{chain.get('process_index')}: "
            f"{chain.get('incarnations', 1)} incarnation(s)   "
            f"wall {chain['wall_s']:.1f}s   "
            f"goodput {chain['goodput_pct']:.1f}%   "
            f"err {chain['conservation_err_pct']:.1f}% {flag}")
    blame = report.get("blame") or {}
    if blame.get("cause", "none") != "none":
        lines.append(f"blame: {blame['cause']} ({blame['seconds']:.1f}s, "
                     f"{blame['share_pct']:.0f}% of badput) — "
                     f"{blame['evidence']}")
    else:
        lines.append("blame: none (badput negligible)")
    cons = report.get("conservation")
    if cons:
        verdict = "ok" if cons["ok"] else "VIOLATED"
        lines.append(f"conservation: {verdict} (worst err "
                     f"{cons['worst_err_pct']:.1f}% vs "
                     f"{cons['tolerance_pct']:.1f}% tolerance)")
    return "\n".join(lines)


def discover_logs(supervise_dir: str) -> List[str]:
    """All run logs under a supervised telemetry dir, recursively —
    the supervisor's own log plus every incarnation's worker logs."""
    return sorted(glob.glob(os.path.join(supervise_dir, "**",
                                         "run-*.jsonl"), recursive=True))


def goodput_main(argv=None) -> int:
    """``telemetry goodput`` — exit 0 on a conserving ledger, 1 on a
    conservation violation, 2 when there is nothing to read."""
    from bigdl_tpu.telemetry import schema

    p = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.telemetry goodput",
        description="run-level goodput/badput ledger over one or more "
                    "run logs (a supervised incarnation chain stitches "
                    "into one timeline)")
    p.add_argument("runs", nargs="*", metavar="RUN_JSONL",
                   help="run logs to fold (merged into one ledger)")
    p.add_argument("--supervise-dir", metavar="DIR",
                   help="fold every run-*.jsonl under DIR (recursive) — "
                        "point it at a supervised run's telemetry dir")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--tolerance-pct", type=float,
                   default=DEFAULT_TOLERANCE_PCT,
                   help="conservation tolerance (default %(default)s%%)")
    args = p.parse_args(argv)

    paths = list(args.runs)
    if args.supervise_dir:
        paths.extend(x for x in discover_logs(args.supervise_dir)
                     if x not in paths)
    if not paths:
        print("no run logs: pass run.jsonl paths or --supervise-dir",
              file=sys.stderr)
        return 2
    runs = []
    for path in paths:
        try:
            events, errors = schema.read_events(path)
        except OSError as e:
            print(f"{path}: {e}", file=sys.stderr)
            return 2
        for err in errors:
            print(f"{path}: {err}", file=sys.stderr)
        runs.append((path, events))
    report = ledger_from_events(runs, tolerance_pct=args.tolerance_pct)
    if report is None:
        print("no events in any run log", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(format_goodput(report))
    return 0 if report["conservation"]["ok"] else 1


if __name__ == "__main__":
    sys.exit(goodput_main())
