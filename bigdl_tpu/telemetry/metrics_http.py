"""Live metrics export: an OpenMetrics/Prometheus text endpoint plus a
JSON status endpoint over the running telemetry stream.

A :class:`MetricsSink` is attached to the active tracer and folds the
event stream into the current state (latest step/loss/throughput, last
health probe, counter totals, gauge levels, compile/retrace/health-event
counts).  A stdlib ``ThreadingHTTPServer`` on a daemon thread serves it:

- ``GET /metrics``  — Prometheus/OpenMetrics exposition text
  (``# HELP``/``# TYPE`` lines, ``# EOF`` terminator), every sample
  labelled with ``process_index`` so a multi-host fleet scrapes into one
  Prometheus without series collisions;
- ``GET /status``   — the same state as one JSON object (per-process
  step progress for ``tools/tpu_watch.sh`` and humans with curl), plus
  the on-demand profiler state (armed / capturing / last trace dir) and
  the flight-recorder state (ring fill, last dump path);
- ``POST /profile?steps=N`` — arm an on-demand ``jax.profiler`` capture
  of the next N training iterations (``telemetry/profiler.py``); the
  optimizer loop starts/stops the trace, training never blocks.  409
  when a capture is already armed or running; optional ``dir=<path>``
  overrides the trace directory;
- ``GET /healthz``  — liveness: 200 while the run is alive, **503 when
  the cluster watchdog presumes a peer lost** (``parallel/cluster.py``;
  ``/status`` then carries ``cluster: {state: degraded, peers: ...}``
  with the per-peer heartbeat table).

Enabled by ``BIGDL_METRICS_PORT`` (or ``--metrics-port`` on
``models/cli.py``); port ``0`` binds an ephemeral port, logged at run
start and readable from :func:`bigdl_tpu.telemetry.metrics_server`.
The server lives exactly as long as the telemetry run: ``start_run``
brings it up, ``end_run`` tears it down.  Serving never blocks or fails
the run — handler errors return 500 and are swallowed.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

__all__ = ["MetricsSink", "MetricsServer", "start_server"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _request_fold():
    # lazy like the memory/fleet imports below: request_trace pulls in
    # the telemetry package, which imports this module
    from bigdl_tpu.telemetry.request_trace import RequestFold
    return RequestFold()


def _ledger_fold():
    # lazy for the same reason
    from bigdl_tpu.telemetry.ledger import LedgerFold
    return LedgerFold()


def _metric_name(name: str, prefix: str = "bigdl_") -> str:
    """Telemetry stream name -> legal Prometheus metric name."""
    return prefix + _NAME_RE.sub("_", str(name)).strip("_")


class MetricsSink:
    """Tracer sink folding the live event stream into current state."""

    def __init__(self):
        self._lock = threading.Lock()
        self._t0 = time.time()
        self.meta: Dict[str, Any] = {}
        self.step: Dict[str, Any] = {}      # latest step event
        self.health: Dict[str, Any] = {}    # latest health probe
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.events: Dict[str, int] = {}    # instant name -> count
        self.compiles = 0
        self.compile_s = 0.0   # cumulative wall seconds spent compiling
        self.cache_hits = 0    # persistent compile cache (docs/compile.md)
        self.cache_misses = 0
        self.retraces = 0
        self.nonfinite_steps = 0
        # fault-tolerance state (docs/fault_tolerance.md): the watcher
        # and humans-with-curl read checkpoint freshness and the last
        # injected fault from /status
        self.checkpoint: Dict[str, Any] = {}  # last checkpoint/saved
        self.last_fault: Dict[str, Any] = {}  # last fault/injected
        self.quarantined = 0
        self.preempted = False
        # serving batches (kind "serve", bigdl_tpu/serving/batcher.py)
        self.serve_batches = 0
        self.serve_rows = 0
        self.last_serve: Dict[str, Any] = {}
        # completed generations (kind "generate",
        # serving/generate/batcher.py): token totals + the latest
        # request's TTFT / inter-token tail — the decode-replica view
        self.gen_requests = 0
        self.gen_tokens = 0
        self.last_gen: Dict[str, Any] = {}
        # serving request traces (kind "request"): the shared
        # request_trace.RequestFold — one fold implementation with the
        # FleetWatcher's per-host state, the run-log twin of the
        # server's own /status.traces summary
        self.requests = _request_fold()
        # per-collective comms attribution (kind "comms",
        # telemetry/comms.py): the latest per-step snapshot
        self.last_comms: Dict[str, Any] = {}
        # per-step memory attribution (kind "memory",
        # telemetry/memory.py): the latest compiled-peak + live
        # allocator snapshot — tpu_watch's hbm= block
        self.last_memory: Dict[str, Any] = {}
        # sparse embedding sync accounting (train/sparse instant,
        # docs/sparse.md): the latest static per-step caps —
        # tpu_watch's sparse= block
        self.sparse: Dict[str, Any] = {}
        # run-level goodput/badput ledger (telemetry/ledger.py): every
        # event folds into it, /status.goodput and the
        # bigdl_goodput_pct / bigdl_badput_seconds gauges read it
        self.ledger = _ledger_fold()
        # straggler-tolerant local SGD (parallel/local_sync.py): the
        # latest averaging round + staleness verdict + shed events —
        # tpu_watch's sync= block
        self.local_sync: Dict[str, Any] = {}

    # -- sink protocol -----------------------------------------------------
    def emit(self, event: Dict[str, Any]) -> None:
        kind = event.get("kind")
        with self._lock:
            self.ledger.fold_event(event)
            if kind == "run_start":
                self.meta.update(event.get("meta") or {})
            elif kind == "step":
                self.step = {k: event[k] for k in
                             ("step", "dur", "loss", "records",
                              "throughput", "epoch") if k in event}
            elif kind == "health":
                self.health = {k: v for k, v in event.items()
                               if k not in ("v", "ts", "pid", "tid",
                                            "kind")}
                if event.get("nonfinite_grads") \
                        or event.get("nonfinite_params"):
                    self.nonfinite_steps += 1
            elif kind == "counter":
                name = str(event.get("name", "?"))
                self.counters[name] = self.counters.get(name, 0.0) \
                    + float(event.get("value", 0.0))
            elif kind == "gauge":
                self.gauges[str(event.get("name", "?"))] = \
                    float(event.get("value", 0.0))
            elif kind == "event":
                name = str(event.get("name", "?"))
                self.events[name] = self.events.get(name, 0) + 1
                if name == "checkpoint/saved":
                    self.checkpoint = {
                        "step": event.get("step"),
                        "backend": event.get("backend"),
                        "saved_at": event.get("ts")}
                elif name == "fault/injected":
                    self.last_fault = {
                        "fault": event.get("fault"),
                        "step": event.get("step"),
                        "point": event.get("point"),
                        "at": event.get("ts")}
                elif name == "checkpoint/quarantined":
                    self.quarantined += 1
                elif name == "run/preempted":
                    self.preempted = True
                elif name == "compile/cache_hit":
                    self.cache_hits += 1
                elif name == "compile/cache_miss":
                    self.cache_misses += 1
                elif name == "train/sparse":
                    # sparse embedding sync accounting (docs/sparse.md):
                    # static per-step caps — what tpu_watch prints
                    self.sparse = {k: event[k] for k in
                                   ("tables", "touched_rows",
                                    "sync_bytes", "dense_bytes",
                                    "saved_bytes") if k in event}
                elif name == "sync/average":
                    self.local_sync.update(
                        {k: event[k] for k in
                         ("round", "h", "peers", "islands", "bytes")
                         if k in event})
                elif name == "sync/staleness":
                    self.local_sync.update(
                        {k: event[k] for k in ("lag", "stale")
                         if k in event})
                    self.local_sync["waited_s"] = round(
                        self.local_sync.get("waited_s", 0.0)
                        + float(event.get("waited_s", 0.0)), 6)
                elif name == "cluster/shed":
                    shed = self.local_sync.setdefault("shed", [])
                    peer = event.get("peer")
                    if event.get("role") == "survivor" \
                            and peer not in shed:
                        shed.append(peer)
            elif kind == "compile":
                self.compiles += 1
                self.compile_s += float(event.get("dur", 0.0))
            elif kind == "retrace":
                self.retraces += 1
            elif kind == "serve":
                self.serve_batches += 1
                self.serve_rows += int(event.get("size", 0))
                self.last_serve = {k: event[k] for k in
                                   ("size", "queue_ms", "infer_ms",
                                    "fill") if k in event}
            elif kind == "generate":
                self.gen_requests += 1
                self.gen_tokens += int(event.get("tokens", 0))
                self.last_gen = {k: event[k] for k in
                                 ("tokens", "ttft_ms", "itl_p99_ms",
                                  "finish", "dur") if k in event}
            elif kind == "request":
                self.requests.fold(event)
            elif kind == "comms":
                self.last_comms = {k: event[k] for k in
                                   ("count", "bytes", "payload_bytes",
                                    "by_axis", "expected_s",
                                    "measured_s", "program")
                                   if k in event}
            elif kind == "memory":
                from bigdl_tpu.telemetry.memory import live_peak_and_limit

                mem = {k: event[k] for k in
                       ("peak_bytes", "args_bytes", "temp_peak_bytes",
                        "donated_bytes", "hbm_limit_bytes", "program")
                       if k in event}
                peak, limit = live_peak_and_limit(event.get("live"))
                if peak:
                    mem["live_bytes"] = peak
                if limit:
                    mem["limit_bytes"] = limit
                self.last_memory = mem

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    # -- views -------------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        with self._lock:
            checkpoint = dict(self.checkpoint)
            if checkpoint.get("saved_at"):
                checkpoint["age_s"] = round(
                    time.time() - float(checkpoint["saved_at"]), 3)
            return {"uptime_s": round(time.time() - self._t0, 3),
                    "process_index": self.meta.get("process_index", 0),
                    "process_count": self.meta.get("process_count", 1),
                    "meta": dict(self.meta), "step": dict(self.step),
                    "health": dict(self.health),
                    "health_events": dict(self.events),
                    "counters": dict(self.counters),
                    "gauges": dict(self.gauges),
                    "compiles": self.compiles,
                    "compile_s": round(self.compile_s, 3),
                    "compile_cache": {"hits": self.cache_hits,
                                      "misses": self.cache_misses},
                    "retraces": self.retraces,
                    "nonfinite_steps": self.nonfinite_steps,
                    "checkpoint": checkpoint,
                    "last_fault": dict(self.last_fault),
                    "quarantined_checkpoints": self.quarantined,
                    "preempted": self.preempted,
                    "serve_batches": self.serve_batches,
                    "serve_rows": self.serve_rows,
                    "last_serve": dict(self.last_serve),
                    "gen_requests": self.gen_requests,
                    "gen_tokens": self.gen_tokens,
                    "last_gen": dict(self.last_gen),
                    "requests": {
                        "count": self.requests.count,
                        "by_endpoint": dict(self.requests.by_endpoint),
                        "rejections": dict(self.requests.rejections),
                        "slo_violations": self.requests.slo_violations,
                        "slowest": dict(self.requests.slowest)},
                    "comms": dict(self.last_comms),
                    "memory": dict(self.last_memory),
                    "sparse": dict(self.sparse),
                    "local_sync": dict(self.local_sync),
                    "goodput": self.ledger.event_fields() or {}}

    def openmetrics(self) -> str:
        """Prometheus/OpenMetrics exposition text of the current state."""
        with self._lock:
            pidx = self.meta.get("process_index", 0)
            label = f'{{process_index="{pidx}"}}'
            lines = []

            def sample(name: str, mtype: str, value, help_: str) -> None:
                try:
                    v = float(value)
                except (TypeError, ValueError):
                    return
                if math.isnan(v):  # exposition-format spellings
                    text = "NaN"
                elif math.isinf(v):
                    text = "+Inf" if v > 0 else "-Inf"
                else:
                    text = f"{v:g}"
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} {mtype}")
                lines.append(f"{name}{label} {text}")

            sample("bigdl_up", "gauge", 1, "run alive")
            sample("bigdl_uptime_seconds", "gauge",
                   time.time() - self._t0, "seconds since run start")
            st = self.step
            if st:
                sample("bigdl_step", "gauge", st.get("step"),
                       "latest completed training step")
                sample("bigdl_loss", "gauge", st.get("loss"),
                       "latest step loss")
                sample("bigdl_step_duration_seconds", "gauge",
                       st.get("dur"), "latest step wall time")
                sample("bigdl_throughput_records_per_second", "gauge",
                       st.get("throughput"), "latest step throughput")
                sample("bigdl_epoch", "gauge", st.get("epoch"),
                       "current epoch")
            for key in ("grad_norm", "param_norm", "update_norm",
                        "update_ratio", "nonfinite_grads",
                        "nonfinite_params"):
                if key in self.health:
                    sample(f"bigdl_health_{key}", "gauge",
                           self.health[key], f"latest probe {key}")
            sample("bigdl_health_nonfinite_steps_total", "counter",
                   self.nonfinite_steps, "steps with any nonfinite probe")
            if self.checkpoint.get("saved_at"):
                sample("bigdl_checkpoint_last_step", "gauge",
                       self.checkpoint.get("step"),
                       "step of the newest committed checkpoint")
                sample("bigdl_checkpoint_age_seconds", "gauge",
                       time.time() - float(self.checkpoint["saved_at"]),
                       "seconds since the newest committed checkpoint")
            sample("bigdl_checkpoints_quarantined_total", "counter",
                   self.quarantined, "torn checkpoints quarantined")
            sample("bigdl_serve_batches_total", "counter",
                   self.serve_batches, "serving batches executed")
            sample("bigdl_serve_rows_total", "counter", self.serve_rows,
                   "serving rows (requests' samples) executed")
            sample("bigdl_gen_tokens_total", "counter", self.gen_tokens,
                   "tokens emitted by completed generations")
            sample("bigdl_gen_requests_total", "counter",
                   self.gen_requests, "completed generation requests")
            if self.last_gen:
                sample("bigdl_gen_ttft_ms", "gauge",
                       self.last_gen.get("ttft_ms"),
                       "latest completed generation's time to first "
                       "token")
                sample("bigdl_gen_itl_p99_ms", "gauge",
                       self.last_gen.get("itl_p99_ms"),
                       "latest completed generation's p99 inter-token "
                       "latency")
            if self.requests.count:
                sample("bigdl_request_traces_total", "counter",
                       self.requests.count,
                       "serving request traces observed")
                sample("bigdl_request_slo_violations_total", "counter",
                       self.requests.slo_violations,
                       "requests over a declared SLO budget")
                sample("bigdl_request_slowest_ms", "gauge",
                       self.requests.slowest.get("ms"),
                       "slowest completed request seen "
                       f"(trace_id="
                       f"{self.requests.slowest.get('trace_id', '?')})")
            sample("bigdl_compiles_total", "counter", self.compiles,
                   "XLA compiles observed")
            sample("bigdl_compile_seconds_total", "counter",
                   self.compile_s, "cumulative wall seconds compiling")
            sample("bigdl_compile_cache_hits_total", "counter",
                   self.cache_hits,
                   "persistent compile cache hits (this run)")
            sample("bigdl_compile_cache_misses_total", "counter",
                   self.cache_misses,
                   "persistent compile cache misses (this run)")
            sample("bigdl_retraces_total", "counter", self.retraces,
                   "retrace attributions observed")
            if self.last_comms:
                sample("bigdl_comms_bytes_per_step", "gauge",
                       self.last_comms.get("bytes"),
                       "collective bytes accessed per compiled step")
                sample("bigdl_comms_collectives", "gauge",
                       self.last_comms.get("count"),
                       "collective op count per compiled step")
            if self.last_memory:
                sample("bigdl_hbm_peak_bytes", "gauge",
                       self.last_memory.get("peak_bytes"),
                       "predicted per-device peak HBM of the compiled "
                       "step")
                sample("bigdl_hbm_live_bytes", "gauge",
                       self.last_memory.get("live_bytes"),
                       "live allocator peak bytes in use")
                sample("bigdl_hbm_limit_bytes", "gauge",
                       self.last_memory.get("limit_bytes")
                       or self.last_memory.get("hbm_limit_bytes"),
                       "per-device HBM limit")
            gp = self.ledger.snapshot()
            if gp and gp.get("wall_s"):
                sample("bigdl_goodput_pct", "gauge",
                       gp.get("goodput_pct"),
                       "run-level goodput percent (productive compute "
                       "over wall time, telemetry/ledger.py)")
                # per-category badput needs a second label, which
                # sample() doesn't speak — emit the family by hand
                lines.append("# HELP bigdl_badput_seconds run-level "
                             "badput seconds by category")
                lines.append("# TYPE bigdl_badput_seconds gauge")
                for cat, s in sorted((gp.get("badput") or {}).items()):
                    lines.append(
                        f'bigdl_badput_seconds{{process_index="{pidx}",'
                        f'category="{cat}"}} {float(s):g}')
            for name, count in sorted(self.events.items()):
                sample(_metric_name(name, "bigdl_event_") + "_total",
                       "counter", count, f"instant events named {name}")
            for name, total in sorted(self.counters.items()):
                sample(_metric_name(name) + "_total", "counter", total,
                       f"telemetry counter {name}")
            for name, value in sorted(self.gauges.items()):
                sample(_metric_name(name), "gauge", value,
                       f"telemetry gauge {name}")
            # live fleet gauges (telemetry/fleet.py; coordinator only —
            # elsewhere the watcher is None and nothing is exported)
            try:
                from bigdl_tpu.telemetry.fleet import fleet_openmetrics

                lines.extend(fleet_openmetrics())
            except Exception:  # noqa: BLE001 - observers never fail
                pass  # the scrape
            lines.append("# EOF")
            return "\n".join(lines) + "\n"


def _observer_status() -> Dict[str, Any]:
    """Profiler + flight-recorder + cluster state for /status
    (process-wide singletons, not per-sink state)."""
    out: Dict[str, Any] = {}
    try:
        from bigdl_tpu.telemetry import profiler

        out["profiler"] = profiler.get().status()
    except Exception:  # noqa: BLE001 - status is best-effort
        pass
    try:
        from bigdl_tpu import telemetry

        fr = telemetry.flight_recorder()
        out["flight"] = fr.status() if fr is not None else None
    except Exception:  # noqa: BLE001
        pass
    try:
        from bigdl_tpu.utils import compile_cache

        # process-lifetime view (the per-run sink counters above only
        # see events after the run attached): hits/misses/compile_s
        # since process start, plus the cache-key ingredients — the
        # "why was this restart cold" diagnosis surface
        out["compile_cache_process"] = compile_cache.monitor().snapshot()
        out["compile_cache_ingredients"] = \
            compile_cache.cache_key_ingredients()
    except Exception:  # noqa: BLE001
        pass
    try:
        cl = _cluster_service()
        if cl is not None:
            # the per-peer heartbeat table (step, age, status, lost
            # reason) — docs/fault_tolerance.md "Distributed failures"
            out["cluster"] = cl.status()
    except Exception:  # noqa: BLE001
        pass
    try:
        from bigdl_tpu import telemetry

        fw = telemetry.fleet_watcher()
        if fw is not None:
            # the live cross-host table + skew blame — coordinator only
            # (telemetry/fleet.py); tpu_watch prints the one-line form
            out["fleet"] = fw.snapshot()
    except Exception:  # noqa: BLE001
        pass
    try:
        from bigdl_tpu import serving

        srv = serving.get()
        if srv is not None:
            # live serving stats (qps, p50/p99, queue depth, warm
            # buckets) — the same block the serving frontend's own
            # /status carries, so tpu_watch reads either endpoint
            out["serving"] = srv.status()
    except Exception:  # noqa: BLE001
        pass
    return out


def _cluster_service():
    """The active cluster fault-tolerance service
    (``parallel/cluster.py``), or None outside cluster runs."""
    from bigdl_tpu.parallel import cluster

    return cluster.get()


class _Handler(BaseHTTPRequestHandler):
    # the sink is attached to the server object by start_server
    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        try:
            sink: MetricsSink = self.server.metrics_sink  # type: ignore
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            if path == "/metrics":
                body = sink.openmetrics().encode("utf-8")
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path in ("/", "/status"):
                status = sink.status()
                status.update(_observer_status())
                body = (json.dumps(status, default=str) + "\n"
                        ).encode("utf-8")
                ctype = "application/json"
            elif path == "/healthz":
                # liveness turns 503 when any peer is presumed lost —
                # an external prober (or the supervisor's cluster
                # manager analogue) reads "this process is about to
                # abort the dead collective" without parsing /status
                degraded = False
                try:
                    cl = _cluster_service()
                    degraded = cl is not None and cl.degraded()
                except Exception:  # noqa: BLE001 - liveness stays up
                    pass
                if degraded:
                    self._respond(
                        503, b'{"ok": false, "cluster": "degraded"}\n',
                        "application/json")
                    return
                body = b'{"ok": true}\n'
                ctype = "application/json"
            else:
                self.send_error(404)
                return
            self._respond(200, body, ctype)
        except Exception:  # noqa: BLE001 - observers never kill the run
            try:
                self.send_error(500)
            except Exception:  # noqa: BLE001 - client already gone
                pass

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        """``POST /profile?steps=N[&dir=...]`` — arm an on-demand
        profiler capture; the training loop does the rest."""
        try:
            from urllib.parse import parse_qs, urlparse

            parsed = urlparse(self.path)
            path = parsed.path.rstrip("/") or "/"
            if path != "/profile":
                self.send_error(404)
                return
            from bigdl_tpu.telemetry import profiler

            control = profiler.get()
            query = parse_qs(parsed.query)
            try:
                steps = int(query.get("steps", ["5"])[0])
            except ValueError:
                steps = 0
            trace_dir = query.get("dir", [None])[0] \
                or control.default_dir()
            if steps < 1:
                body = json.dumps({"armed": False,
                                   "error": "steps must be >= 1"})
                self._respond(400, (body + "\n").encode("utf-8"),
                              "application/json")
                return
            # perfetto=1: also write the Chrome/Perfetto JSON trace —
            # the artifact telemetry/comms.py reads per-collective wall
            # time from (docs/observability.md "Is my all-reduce the
            # bottleneck?")
            perfetto = (query.get("perfetto", ["0"])[0].lower()
                        in ("1", "true", "yes", "on"))
            armed = control.arm(steps, trace_dir, source="http",
                                perfetto=perfetto)
            payload = {"armed": armed, **control.status()}
            if not armed:
                payload["error"] = "a capture is already armed or running"
            self._respond(200 if armed else 409,
                          (json.dumps(payload, default=str) + "\n"
                           ).encode("utf-8"), "application/json")
        except Exception:  # noqa: BLE001 - observers never kill the run
            try:
                self.send_error(500)
            except Exception:  # noqa: BLE001
                pass

    def _respond(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args) -> None:  # silence per-request stderr spam
        pass


class MetricsServer:
    """The sink + HTTP server pair, bound to one telemetry run."""

    def __init__(self, tracer, port: int, host: str = "0.0.0.0"):
        self.sink = MetricsSink()
        # seed meta before the first scrape: run_start was emitted
        # before this sink attached
        self.sink.meta.update(getattr(tracer, "meta", {}) or {})
        self._tracer = tracer
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.metrics_sink = self.sink  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        tracer.add_sink(self.sink)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="bigdl-metrics-http",
            kwargs={"poll_interval": 0.2}, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        try:
            self._tracer.remove_sink(self.sink)
        except Exception:  # noqa: BLE001 - tracer may already be closed
            pass
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def start_server(tracer, port: int) -> MetricsServer:
    """Attach a MetricsSink to ``tracer`` and serve it on ``port``
    (0 = ephemeral; read the bound port from ``.port``)."""
    return MetricsServer(tracer, port)
