"""On-demand profiler capture: arm a ``jax.profiler`` trace window from
anywhere (HTTP ``POST /profile``, health-policy escalation, or
``BIGDL_PROFILE`` at startup) and let the training loop capture exactly
the next N steps.

This replaces capture-at-startup-only profiling: ``BIGDL_PROFILE``
used to trace the first N iterations and nothing else, which is useless
for the slowdown that appears at step 10,000.  Now the env knob merely
pre-arms the same control the live endpoints use, and the optimizer loop
polls it every iteration:

- :meth:`ProfilerControl.arm` — request a capture of the next ``steps``
  iterations into ``trace_dir`` (one in flight at a time; re-arming
  while armed/capturing is refused, not queued);
- :meth:`ProfilerControl.poll_begin` / :meth:`poll_end` — called by the
  loop around each iteration; one attribute check when idle;
- :meth:`ProfilerControl.abort` — stop an open capture on the way out
  of the loop (crash/halt), so the trace directory is always valid.

The singleton (:func:`get`) is process-wide, like the telemetry tracer:
profiling is a per-process activity (``jax.profiler`` allows one active
trace), so one control serializes all requesters.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

__all__ = ["ProfilerControl", "get"]

IDLE, ARMED, CAPTURING = "idle", "armed", "capturing"


class ProfilerControl:
    """Arm/poll/abort state machine around ``jax.profiler`` traces."""

    def __init__(self):
        self._lock = threading.Lock()
        self.state = IDLE
        self.steps_left = 0
        self.trace_dir: Optional[str] = None
        self.source: Optional[str] = None
        self.perfetto = False
        self.last_trace_dir: Optional[str] = None
        self.captures = 0
        self.last_error: Optional[str] = None

    def arm(self, steps: int, trace_dir: str,
            source: str = "api", perfetto: bool = False) -> bool:
        """Request a capture of the next ``steps`` iterations.  Returns
        False (without queueing) when a capture is already armed or in
        flight.  ``perfetto=True`` additionally writes the
        Chrome/Perfetto JSON trace — the per-collective wall-time
        artifact ``telemetry/comms.py`` parses."""
        if steps < 1 or not trace_dir:
            return False
        with self._lock:
            if self.state != IDLE:
                return False
            self.state = ARMED
            self.steps_left = int(steps)
            self.trace_dir = trace_dir
            self.source = source
            self.perfetto = bool(perfetto)
        from bigdl_tpu import telemetry

        telemetry.instant("profile/armed", steps=int(steps),
                          dir=trace_dir, source=source,
                          perfetto=bool(perfetto))
        return True

    def poll_begin(self) -> None:
        """Iteration is about to run: start the trace if armed.  One
        attribute read when idle — safe in the hot loop."""
        if self.state != ARMED:
            return
        with self._lock:
            if self.state != ARMED:
                return
            try:
                import jax

                os.makedirs(self.trace_dir, exist_ok=True)
                if self.perfetto:
                    try:
                        jax.profiler.start_trace(
                            self.trace_dir, create_perfetto_trace=True)
                    except TypeError:  # older jax: no perfetto kwarg
                        jax.profiler.start_trace(self.trace_dir)
                else:
                    jax.profiler.start_trace(self.trace_dir)
                self.state = CAPTURING
            except Exception as e:  # noqa: BLE001 - observer, never fatal
                self.last_error = f"{type(e).__name__}: {e}"
                self.state = IDLE
                self.steps_left = 0

    def poll_end(self) -> None:
        """Iteration finished: count it and stop the trace when the
        window is exhausted."""
        if self.state != CAPTURING:
            return
        done = False
        with self._lock:
            if self.state != CAPTURING:
                return
            self.steps_left -= 1
            if self.steps_left <= 0:
                done = True
        if done:
            self._stop()

    def abort(self) -> None:
        """Close an in-flight capture (loop exit / crash path); armed
        but not yet started requests are cancelled."""
        with self._lock:
            state = self.state
            if state == ARMED:
                self.state = IDLE
                self.steps_left = 0
                return
        if state == CAPTURING:
            self._stop()

    def _stop(self) -> None:
        from bigdl_tpu import telemetry

        with self._lock:
            trace_dir, source = self.trace_dir, self.source
            ok = False
            try:
                import jax

                jax.profiler.stop_trace()
                self.captures += 1
                self.last_trace_dir = trace_dir
                ok = True
            except Exception as e:  # noqa: BLE001
                self.last_error = f"{type(e).__name__}: {e}"
            self.state = IDLE
            self.steps_left = 0
            self.trace_dir = None
            self.source = None
            perfetto, self.perfetto = self.perfetto, False
        if ok:  # a failed stop wrote no trace: don't announce one
            telemetry.instant("profile/captured", dir=trace_dir,
                              source=source or "api", perfetto=perfetto)

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self.state, "steps_left": self.steps_left,
                    "trace_dir": self.trace_dir, "source": self.source,
                    "last_trace_dir": self.last_trace_dir,
                    "captures": self.captures,
                    "last_error": self.last_error}

    def default_dir(self, base: Optional[str] = None) -> str:
        """A fresh trace directory under ``base`` (or the telemetry dir,
        or the cwd)."""
        if base is None:
            from bigdl_tpu.utils.config import get_config

            base = get_config().telemetry_dir or "."
        return os.path.join(base,
                            f"profile-{time.strftime('%Y%m%d_%H%M%S')}")


_control = ProfilerControl()


def get() -> ProfilerControl:
    """The process-wide profiler control."""
    return _control
