"""Fused subtractive / divisive / contrastive normalization with exact
VJPs.

These Torch-legacy ops are built around a kernel-weighted spatial
smoothing of a channel-reduced map.  The reference (and the previous
layer implementation) expresses the smoothing as a 1-channel depthwise
``lax.conv`` — on TPU that is the WORST conv shape there is: a single
input/output channel leaves the 128x128 MXU >99% idle and the op runs
as serialized HBM-bound window traffic.  The smoothing is really a
``kh*kw``-tap shift-accumulate on the VPU, which is exactly what the
Pallas kernel here does (one padded plane per block, unrolled static
shifts, one write).  Channel reduction, division and thresholding stay
in XLA — they are elementwise/small reductions XLA fuses into the
adjacent kernels already.

VJP derivations (g = upstream cotangent, C = channel count):

- subtractive (``nn/SpatialSubtractiveNormalization.scala``):
  ``y = x - sm(u)/coef`` with ``u = mean_c(x)``, ``coef = sm(1)`` the
  edge-coverage mass.  Exact:
  ``dx = g - (1/C) * sm^T(sum_c(g) / coef)``
  where ``sm^T`` is correlation with the FLIPPED kernel under swapped
  pads — the transpose of the forward smoothing.
- divisive (``nn/SpatialDivisiveNormalization.scala``):
  ``y = x / d``, ``d = thresh(max(sigma, mean_hw(sigma)))``,
  ``sigma = sqrt(clip(sm(mean_c(x^2))/coef, 0))``.  Exact backward
  chains the pieces: ``gd = -sum_c(g*x)/d^2``, gated through the
  threshold (``e >= t``), split across the ``max`` (position vs the
  spatial-mean branch, which re-broadcasts ``1/(H*W)``), through
  ``1/(2*sigma)`` (guarded at 0), ``/coef``, ``sm^T``, and finally
  ``dx = g/d + (2/C) * x * gusq``.  Ties and the clip/threshold corners
  are measure-zero for continuous activations.
- contrastive = divisive(subtractive(x)) — composing the two exact
  custom VJPs keeps the chain exact by construction.

The smoothing kernel is a module BUFFER, never trained — its cotangent
is defined as zero (``lax.stop_gradient`` semantics), matching the
framework's buffer contract.  Backend per leg via ``ops.dispatch``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.ops import dispatch as _dispatch
from bigdl_tpu.ops.pallas_util import (TPU_DTYPES as _TPU_DTYPES,
                                       VMEM_BUDGET as _VMEM_BUDGET,
                                       plane_call as _plane_call)

__all__ = ["smooth2d", "smooth2d_supported", "subtractive_norm",
           "divisive_norm", "contrastive_norm"]


def _fwd_pads(kh: int, kw: int):
    return (kh // 2, (kh - 1) // 2), (kw // 2, (kw - 1) // 2)


def _transpose_pads(kh: int, kw: int):
    (alo, ahi), (blo, bhi) = _fwd_pads(kh, kw)
    return (ahi, alo), (bhi, blo)


def smooth2d_supported(stack, kernel) -> bool:
    """Pallas-leg gate for the smoothing kernel: [B, H, W] stack, 2-D
    kernel; on real TPU additionally a Mosaic dtype + VMEM fit."""
    if stack.ndim != 3 or kernel.ndim != 2:
        return False
    if not _dispatch.use_interpret():
        if stack.dtype not in _TPU_DTYPES:
            return False
        hp = stack.shape[1] + kernel.shape[0] - 1
        wp = stack.shape[2] + kernel.shape[1] - 1
        if 3 * hp * wp * jnp.dtype(stack.dtype).itemsize > _VMEM_BUDGET:
            return False
    return True


def _smooth_kernel(vp_ref, w_ref, out_ref, *, h: int, w: int, kh: int,
                   kw: int, flip: bool):
    vp = vp_ref[0]                      # [Hp, Wp] padded plane
    acc = None
    for i in range(kh):
        for j in range(kw):
            wt = w_ref[kh - 1 - i, kw - 1 - j] if flip else w_ref[i, j]
            tap = vp[i:i + h, j:j + w] * wt
            acc = tap if acc is None else acc + tap
    out_ref[0] = acc


def _smooth_pallas(stack, kernel, pads, flip: bool):
    b, h, w = stack.shape
    kh, kw = kernel.shape
    (alo, ahi), (blo, bhi) = pads
    vp = jnp.pad(stack, ((0, 0), (alo, ahi), (blo, bhi)))
    kern = functools.partial(_smooth_kernel, h=h, w=w, kh=kh, kw=kw,
                             flip=flip)
    return _plane_call(kern, [vp, kernel.astype(stack.dtype)],
                       [((h, w), stack.dtype)], b,
                       _dispatch.use_interpret(), bcast=(1,))


def _smooth_xla(stack, kernel, pads, flip: bool):
    k = kernel[::-1, ::-1] if flip else kernel
    v = stack[:, None]                  # [B, 1, H, W]
    w4 = k.astype(stack.dtype)[None, None]
    dn = lax.conv_dimension_numbers(v.shape, w4.shape,
                                    ("NCHW", "OIHW", "NCHW"))
    out = lax.conv_general_dilated(v, w4, (1, 1), pads,
                                   dimension_numbers=dn)
    return out[:, 0]


def smooth2d(stack, kernel, pads, flip: bool = False):
    """Kernel-weighted window sum over a [B, H, W] plane stack (the
    shared primitive under all three normalizations; ``flip=True`` with
    swapped pads is the exact transpose).  NOT differentiable on its
    own — always called inside a custom-vjp fwd/bwd rule."""
    op = "norm_smooth.bwd" if flip else "norm_smooth.fwd"
    return _dispatch.dispatch(
        op, _smooth_pallas, _smooth_xla,
        smooth2d_supported(stack, kernel), stack, kernel, pads, flip)


def _coef(kernel, h: int, w: int, dtype):
    """Edge-coverage mass: the kernel weight actually inside the image
    at each position (the reference divides the smoothed map by it)."""
    ones = jnp.ones((1, h, w), dtype)
    kh, kw = kernel.shape
    return smooth2d(ones, kernel, _fwd_pads(kh, kw))


# ---------------------------------------------------------------------------
# subtractive
# ---------------------------------------------------------------------------

@jax.custom_vjp
def subtractive_norm(x, kernel):
    """``x - local kernel-weighted mean`` over NCHW with exact custom
    VJP; the smoothing kernel is buffer-semantics (zero cotangent)."""
    y, _ = _sub_fwd(x, kernel)
    return y


def _sub_fwd(x, kernel):
    n, c, h, w = x.shape
    kh, kw = kernel.shape
    u = jnp.mean(x, axis=1)             # [N, H, W]
    coef = _coef(kernel, h, w, x.dtype)
    m = smooth2d(u, kernel, _fwd_pads(kh, kw)) / coef
    return x - m[:, None], coef


def _sub_vjp_fwd(x, kernel):
    y, coef = _sub_fwd(x, kernel)
    return y, (kernel, coef, x.shape[1])


def _sub_vjp_bwd(res, g):
    kernel, coef, c = res
    kh, kw = kernel.shape
    v = jnp.sum(g, axis=1) / coef
    corr_t = smooth2d(v, kernel, _transpose_pads(kh, kw), flip=True)
    return g - corr_t[:, None] / c, jnp.zeros_like(kernel)


subtractive_norm.defvjp(_sub_vjp_fwd, _sub_vjp_bwd)


# ---------------------------------------------------------------------------
# divisive
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def divisive_norm(x, kernel, threshold: float = 1e-4,
                  thresval: float = 1e-4):
    """``x / thresholded local std`` over NCHW with exact custom VJP."""
    y, _, _, _, _ = _div_fwd(x, kernel, threshold, thresval)
    return y


def _div_fwd(x, kernel, threshold, thresval):
    n, c, h, w = x.shape
    kh, kw = kernel.shape
    usq = jnp.mean(x * x, axis=1)       # [N, H, W]
    coef = _coef(kernel, h, w, x.dtype)
    s = smooth2d(usq, kernel, _fwd_pads(kh, kw)) / coef
    sigma = jnp.sqrt(jnp.clip(s, 0.0))
    mu = jnp.mean(sigma, axis=(1, 2), keepdims=True)
    e = jnp.maximum(sigma, mu)
    d = jnp.where(e < threshold, jnp.asarray(thresval, x.dtype), e)
    return x / d[:, None], sigma, mu, d, coef


def _div_vjp_fwd(x, kernel, threshold, thresval):
    y, sigma, mu, d, coef = _div_fwd(x, kernel, threshold, thresval)
    return y, (x, kernel, sigma, mu, d, coef)


def _div_vjp_bwd(threshold, thresval, res, g):
    x, kernel, sigma, mu, d, coef = res
    kh, kw = kernel.shape
    c = x.shape[1]
    hw = sigma.shape[1] * sigma.shape[2]
    gd = -jnp.sum(g * x, axis=1) / (d * d)
    e = jnp.maximum(sigma, mu)
    ge = jnp.where(e >= threshold, gd, 0.0)
    mask_sig = sigma >= mu              # ties -> position branch
    gmu = jnp.sum(jnp.where(mask_sig, 0.0, ge), axis=(1, 2),
                  keepdims=True)
    gsig = jnp.where(mask_sig, ge, 0.0) + gmu / hw
    gs = jnp.where(sigma > 0, gsig / (2.0 * sigma), 0.0)
    gusq = smooth2d(gs / coef, kernel, _transpose_pads(kh, kw),
                    flip=True)
    dx = g / d[:, None] + x * (2.0 / c) * gusq[:, None]
    return dx, jnp.zeros_like(kernel)


divisive_norm.defvjp(_div_vjp_fwd, _div_vjp_bwd)


def contrastive_norm(x, kernel, threshold: float = 1e-4,
                     thresval: float = 1e-4):
    """Subtractive then divisive normalization — composing the two
    exact custom VJPs keeps the whole chain exact."""
    return divisive_norm(subtractive_norm(x, kernel), kernel, threshold,
                         thresval)
