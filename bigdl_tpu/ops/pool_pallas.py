"""Tie-split max pooling + Torch-semantics average pooling as fused
kernels with exact custom VJPs.

Two ops the autodiff path got subtly wrong and XLA lowers expensively:

- ``maxpool_tie_split``: max pooling whose gradient is split EQUALLY
  among tied maxima (gradient mass conserved — the reference's
  ``split_ties()`` contract, vs select-and-scatter's first-argmax).
  The backward must compare every window tap against the window max
  and divide by the tie count; XLA expresses that as k*k interior-pad
  scatter kernels (the ~50%-of-Inception-step pathology the
  residue-class rewrite in PR-era ``nn/layers/pooling.py`` addressed).
  Here the whole backward — tie count, weight, residue-class gather,
  stride interleave — is ONE Pallas pass per (n, c) plane.
- ``avg_pool``: Torch ceil-mode average pooling with the asymmetric
  declared-vs-overflow divisor (declared padding counts toward the
  divisor under ``count_include_pad``; ceil-overflow padding never
  does).  The divisor map is pure geometry, computed in numpy at trace
  time (a separable outer product) and baked into the kernel as a
  constant — forward is one windowed-sum pass, backward one
  residue-class scatter of ``gy / counts``.

Residue-class geometry (shared with ``ops/pooling_pallas.py``'s argmax
kernel and the XLA reference leg): padded input positions split into
``stride`` residue classes per axis; within a class the windows
touching a position are a fixed ``ceil(k/s)`` set of plain shifts on
the output grid, so every slice in the kernel is static.  The output
grid is extended by ``jmax = ceil(k/s)-1`` leading rows so no shift
ever indexes negative — those rows are provably pad and are cut by the
final slice.

Both ops run their XLA reference legs for non-4D inputs (temporal /
volumetric pooling) and under ``BIGDL_KERNELS=xla``; the custom VJP is
identical math on either leg.  The avg-pool XLA backward is the true
linear transpose of ``reduce_window(add)`` (obtained via ``jax.vjp`` of
the window sum — exact, since the op is linear in x).
"""

from __future__ import annotations

import functools
import itertools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.ops import dispatch as _dispatch
from bigdl_tpu.ops.pallas_util import (TPU_DTYPES as _TPU_DTYPES,
                                       VMEM_BUDGET as _VMEM_BUDGET,
                                       plane_call as _shared_plane_call)

__all__ = ["maxpool_tie_split", "avg_pool", "pool_plane_supported"]

#: beyond this tap count the unrolled shift structure bloats compile
#: time (global-pool-sized windows) — XLA select-and-scatter territory
_MAX_TAPS = 64


def _axis_geom(n: int, k: int, s: int, lo: int, hi: int):
    """(P, out, L, jmax, M) per axis: padded extent, output size,
    residue-class length, max window shift, extended out-grid length."""
    p = lo + n + hi
    out = (p - k) // s + 1
    l = -(-p // s)
    jmax = -(-k // s) - 1
    return p, out, l, jmax, jmax + l


def pool_plane_supported(x, dims, strides) -> bool:
    """Pallas-leg gate: 4-D with the window on the trailing (H, W)
    axes, bounded taps; Mosaic dtype + VMEM fit on real TPU."""
    if x.ndim != 4 or dims[0] != 1 or dims[1] != 1:
        return False
    if strides[0] != 1 or strides[1] != 1:
        return False
    if dims[2] * dims[3] > _MAX_TAPS:
        return False
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return False
    if not _dispatch.use_interpret():
        if x.dtype not in _TPU_DTYPES:
            return False
        esz = jnp.dtype(x.dtype).itemsize
        # ~10 live planes: padded input, padded y/gy, tie count, weight,
        # residue accumulators and the interleave stack
        if 10 * (x.shape[2] + dims[2]) * (x.shape[3] + dims[3]) \
                * max(1, esz) * 4 > _VMEM_BUDGET:
            return False
    return True


# ---------------------------------------------------------------------------
# Pallas kernels (per (n*c) plane; grid = (N*C,))
# ---------------------------------------------------------------------------

def _taps(xp, k2, s2, out2):
    """All window taps of a padded 2-D plane as strided [out_h, out_w]
    views — static slices only."""
    (kh, kw), (sh, sw), (oh, ow) = k2, s2, out2
    for dh in range(kh):
        for dw in range(kw):
            yield lax.slice(xp, (dh, dw),
                            (dh + (oh - 1) * sh + 1,
                             dw + (ow - 1) * sw + 1), (sh, sw))


def _interleave(parts, s2, l2):
    """[sh][sw] residue planes of shape [Lh, Lw] -> [Lh*sh, Lw*sw]."""
    (sh, sw), (lh, lw) = s2, l2
    rows = []
    for rh in range(sh):
        cols = parts[rh]
        if sw == 1:
            rows.append(cols[0])
        else:
            rows.append(jnp.stack(cols, axis=2).reshape(lh, lw * sw))
    if sh == 1:
        return rows[0]
    return jnp.stack(rows, axis=1).reshape(lh * sh, rows[0].shape[1])


def _maxpool_fwd_kernel(xp_ref, y_ref, *, k2, s2, out2):
    xp = xp_ref[0]
    y = None
    for tap in _taps(xp, k2, s2, out2):
        y = tap if y is None else jnp.maximum(y, tap)
    y_ref[0] = y


def _tie_bwd_kernel(xp_ref, yp_ref, gp_ref, dx_ref, *, k2, s2, l2, m2,
                    j2, lo2, n2):
    """One plane: tie count -> equal-split weight -> residue gather."""
    (kh, kw), (sh, sw) = k2, s2
    (lh, lw), (mh, mw) = l2, m2
    (jh_max, jw_max), (lo_h, lo_w), (h, w) = j2, lo2, n2
    xp = xp_ref[0]
    yp = yp_ref[0]
    gp = gp_ref[0]

    cnt = None
    for tap in _taps(xp, k2, s2, (mh, mw)):
        e = (tap == yp).astype(gp.dtype)
        cnt = e if cnt is None else cnt + e
    wgt = jnp.where(cnt > 0, gp / jnp.where(cnt > 0, cnt, 1), 0.0)

    parts = []
    for rh in range(sh):
        cols = []
        for rw in range(sw):
            xr = lax.slice(xp, (rh + jh_max * sh, rw + jw_max * sw),
                           (rh + jh_max * sh + (lh - 1) * sh + 1,
                            rw + jw_max * sw + (lw - 1) * sw + 1),
                           (sh, sw))
            acc = jnp.zeros((lh, lw), gp.dtype)
            for jh in range(-(-(kh - rh) // sh)):
                if rh + sh * jh >= kh:
                    continue
                for jw in range(-(-(kw - rw) // sw)):
                    if rw + sw * jw >= kw:
                        continue
                    yj = yp[jh_max - jh:jh_max - jh + lh,
                            jw_max - jw:jw_max - jw + lw]
                    wj = wgt[jh_max - jh:jh_max - jh + lh,
                             jw_max - jw:jw_max - jw + lw]
                    acc = acc + jnp.where(xr == yj, wj, 0.0)
            cols.append(acc)
        parts.append(cols)
    dxp = _interleave(parts, s2, l2)
    dx_ref[0] = dxp[lo_h:lo_h + h, lo_w:lo_w + w]


def _avg_fwd_kernel(xp_ref, inv_ref, y_ref, *, k2, s2, out2):
    xp = xp_ref[0]
    s = None
    for tap in _taps(xp, k2, s2, out2):
        s = tap if s is None else s + tap
    y_ref[0] = s * inv_ref[0]


def _avg_bwd_kernel(wp_ref, dx_ref, *, k2, s2, l2, j2, lo2, n2):
    (kh, kw), (sh, sw) = k2, s2
    (lh, lw) = l2
    (jh_max, jw_max), (lo_h, lo_w), (h, w) = j2, lo2, n2
    wp = wp_ref[0]
    parts = []
    for rh in range(sh):
        cols = []
        for rw in range(sw):
            acc = jnp.zeros((lh, lw), wp.dtype)
            for jh in range(-(-(kh - rh) // sh)):
                if rh + sh * jh >= kh:
                    continue
                for jw in range(-(-(kw - rw) // sw)):
                    if rw + sw * jw >= kw:
                        continue
                    acc = acc + wp[jh_max - jh:jh_max - jh + lh,
                                   jw_max - jw:jw_max - jw + lw]
            cols.append(acc)
        parts.append(cols)
    dxp = _interleave(parts, s2, l2)
    dx_ref[0] = dxp[lo_h:lo_h + h, lo_w:lo_w + w]


def _plane_call(kernel, inputs, out_hw, b, dtype, bcast=()):
    """Thin adapter onto the shared per-plane launcher
    (``ops/pallas_util.py``) — single [out_hw, dtype] output."""
    return _shared_plane_call(kernel, inputs, [(out_hw, dtype)], b,
                              _dispatch.use_interpret(), bcast=bcast)


def _hw_geom(x_shape, dims, strides, pads):
    h, w = x_shape[2], x_shape[3]
    kh, kw, sh, sw = dims[2], dims[3], strides[2], strides[3]
    gh = _axis_geom(h, kh, sh, *pads[2])
    gw = _axis_geom(w, kw, sw, *pads[3])
    return (kh, kw), (sh, sw), gh, gw


def _pad_out_grid(v, geom_h, geom_w, out_h, out_w, fill=0.0):
    """Pad an out-grid plane stack to the extended [M_h, M_w] grid:
    jmax leading rows/cols (shift room), residue tail trailing."""
    _, _, lh, jh, mh = geom_h
    _, _, lw, jw, mw = geom_w
    return jnp.pad(v, ((0, 0), (jh, mh - jh - out_h),
                       (jw, mw - jw - out_w)), constant_values=fill)


# ---------------------------------------------------------------------------
# tie-split max pooling
# ---------------------------------------------------------------------------

def _max_init(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return -jnp.inf
    return jnp.iinfo(dtype).min


def _tie_fwd_pallas(x, dims, strides, pads):
    n, c, h, w = x.shape
    k2, s2, gh, gw = _hw_geom(x.shape, dims, strides, pads)
    (ph, oh, *_), (pw, ow, *_) = gh, gw
    (lo_h, _), (lo_w, _) = pads[2], pads[3]
    planes = x.reshape(n * c, h, w)
    xp = jnp.pad(planes, ((0, 0), (lo_h, ph - lo_h - h),
                          (lo_w, pw - lo_w - w)),
                 constant_values=_max_init(x.dtype))
    kern = functools.partial(_maxpool_fwd_kernel, k2=k2, s2=s2,
                             out2=(oh, ow))
    y = _plane_call(kern, [xp], (oh, ow), n * c, x.dtype)
    return y.reshape(n, c, oh, ow)


def _tie_bwd_pallas(x, y, gy, dims, strides, pads):
    n, c, h, w = x.shape
    k2, s2, gh, gw = _hw_geom(x.shape, dims, strides, pads)
    (ph, oh, lh, jh_max, mh), (pw, ow, lw, jw_max, mw) = gh, gw
    (sh, sw) = s2
    (lo_h, _), (lo_w, _) = pads[2], pads[3]
    b = n * c
    # extended padded input: jmax*s extra leading -inf so the extended
    # out grid's windows all read in range; trailing out to the largest
    # static tap/residue slice
    xlen_h = max((mh - 1) * sh + k2[0], mh * sh)
    xlen_w = max((mw - 1) * sw + k2[1], mw * sw)
    top_h, top_w = lo_h + jh_max * sh, lo_w + jw_max * sw
    xp = jnp.pad(x.reshape(b, h, w),
                 ((0, 0), (top_h, xlen_h - top_h - h),
                  (top_w, xlen_w - top_w - w)),
                 constant_values=_max_init(x.dtype))
    yp = _pad_out_grid(y.reshape(b, oh, ow), gh, gw, oh, ow)
    gp = _pad_out_grid(gy.reshape(b, oh, ow), gh, gw, oh, ow)
    kern = functools.partial(
        _tie_bwd_kernel, k2=k2, s2=s2, l2=(lh, lw), m2=(mh, mw),
        j2=(jh_max, jw_max), lo2=(lo_h, lo_w), n2=(h, w))
    dx = _plane_call(kern, [xp, yp, gp], (h, w), b, gy.dtype)
    return dx.reshape(n, c, h, w).astype(x.dtype)


def _tie_bwd_xla(x, y, gy, dims, strides, pads):
    """Residue-class gather backward on the XLA leg (the PR-era rewrite
    of the k*k interior-pad transpose — one fused kernel per residue
    class instead of one strided-write kernel per tap)."""
    nd = x.ndim
    zero = jnp.zeros((), gy.dtype)
    P = [lo + n + hi for (lo, hi), n in zip(pads, x.shape)]
    L = [-(-p // s) for p, s in zip(P, strides)]
    xpad = [(lo, l * s - lo - n)
            for (lo, _), n, s, l in zip(pads, x.shape, strides, L)]
    xp = jnp.pad(x, xpad, constant_values=_max_init(x.dtype))

    cnt = None
    for off in itertools.product(*[range(d) for d in dims]):
        limits = [o + (n - 1) * s + 1
                  for o, n, s in zip(off, y.shape, strides)]
        e = (lax.slice(xp, off, limits, strides) == y).astype(gy.dtype)
        cnt = e if cnt is None else cnt + e
    wgt = gy / cnt

    parts = []
    for r in itertools.product(*[range(s) for s in strides]):
        xr = lax.slice(xp, r,
                       [ri + (l - 1) * s + 1
                        for ri, l, s in zip(r, L, strides)], strides)
        m = [max(0, -(-(k - ri) // s))
             for k, ri, s in zip(dims, r, strides)]
        acc = None
        for j in itertools.product(*[range(mi) for mi in m]):
            cfg = [(ji, li - oi - ji, 0)
                   for ji, li, oi in zip(j, L, y.shape)]
            yj = lax.pad(y, jnp.zeros((), y.dtype), cfg)
            wj = lax.pad(wgt, zero, cfg)
            t = jnp.where(xr == yj, wj, zero)
            acc = t if acc is None else acc + t
        parts.append(acc if acc is not None else jnp.zeros(L, gy.dtype))

    if len(parts) == 1:
        gxp = parts[0]
    else:
        d = jnp.stack(parts, axis=-1).reshape(tuple(L) + tuple(strides))
        perm = []
        for ax in range(nd):
            perm += [ax, nd + ax]
        gxp = d.transpose(perm).reshape(
            [l * s for l, s in zip(L, strides)])
    gx = lax.slice(gxp, [lo for lo, _ in pads],
                   [lo + n for (lo, _), n in zip(pads, x.shape)])
    return gx.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def maxpool_tie_split(x, dims, strides, pads):
    """Max pooling with the equal-tie-split exact gradient (mass
    conserved across tied maxima); any ndim on the XLA leg, fused
    per-plane Pallas kernels for 4-D trailing-(H, W) windows."""
    return _dispatch.dispatch(
        "pool_tie_split.fwd", _tie_fwd_pallas,
        lambda x, d, s, p: lax.reduce_window(
            x, _max_init(x.dtype), lax.max, d, s, p),
        pool_plane_supported(x, dims, strides), x, dims, strides, pads)


def _tie_vjp_fwd(x, dims, strides, pads):
    y = maxpool_tie_split(x, dims, strides, pads)
    return y, (x, y)


def _tie_vjp_bwd(dims, strides, pads, res, gy):
    x, y = res
    dx = _dispatch.dispatch(
        "pool_tie_split.bwd", _tie_bwd_pallas, _tie_bwd_xla,
        pool_plane_supported(x, dims, strides), x, y, gy, dims, strides,
        pads)
    return (dx,)


maxpool_tie_split.defvjp(_tie_vjp_fwd, _tie_vjp_bwd)


# ---------------------------------------------------------------------------
# average pooling (Torch divisor semantics)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=256)
def _np_inv_counts(shape, dims, strides, pads, declared,
                   count_include_pad: bool):
    """Trace-constant reciprocal divisor map, broadcast-shaped: per
    windowed axis, the overlap of each window with the counted region —
    data plus declared padding under ``count_include_pad``
    (ceil-overflow padding never counts:
    ``SpatialAveragePooling.scala:133-135``), data only otherwise.
    Separable, so the map is an outer product over the windowed axes
    with extent 1 on the rest (broadcasts against the pooled output)."""
    axis_counts = []
    bshape = []
    for n, k, s, (lo, hi), (dlo, dhi) in zip(shape, dims, strides, pads,
                                             declared):
        p = lo + n + hi
        out = (p - k) // s + 1
        if k == 1 and s == 1 and lo == 0 and hi == 0:
            bshape.append(1)
            continue
        if count_include_pad:
            start, end = 0, dlo + n + dhi  # declared lo == lo always
        else:
            start, end = lo, lo + n
        o = np.arange(out)
        cnt = (np.minimum(o * s + k, end)
               - np.maximum(o * s, start)).clip(min=0)
        axis_counts.append(cnt.astype(np.float64))
        bshape.append(out)
    if not axis_counts:
        return np.ones(bshape)
    counts = functools.reduce(np.multiply.outer, axis_counts)
    return (1.0 / np.maximum(counts, 1.0)).reshape(bshape)


def _avg_fwd_pallas(x, dims, strides, pads, inv):
    n, c, h, w = x.shape
    k2, s2, gh, gw = _hw_geom(x.shape, dims, strides, pads)
    (ph, oh, *_), (pw, ow, *_) = gh, gw
    (lo_h, _), (lo_w, _) = pads[2], pads[3]
    planes = x.reshape(n * c, h, w)
    xp = jnp.pad(planes, ((0, 0), (lo_h, ph - lo_h - h),
                          (lo_w, pw - lo_w - w)))
    kern = functools.partial(_avg_fwd_kernel, k2=k2, s2=s2,
                             out2=(oh, ow))
    y = _plane_call(kern, [xp, inv[None]], (oh, ow), n * c, x.dtype,
                    bcast=(1,))
    return y.reshape(n, c, oh, ow)


def _avg_bwd_pallas(wgt, x_shape, dims, strides, pads, dtype):
    n, c, h, w = x_shape
    b = n * c
    k2, s2, gh, gw = _hw_geom(x_shape, dims, strides, pads)
    (_, oh, lh, jh_max, _), (_, ow, lw, jw_max, _) = gh, gw
    (lo_h, _), (lo_w, _) = pads[2], pads[3]
    wp = _pad_out_grid(wgt.reshape(b, oh, ow), gh, gw, oh, ow)
    kern = functools.partial(
        _avg_bwd_kernel, k2=k2, s2=s2, l2=(lh, lw),
        j2=(jh_max, jw_max), lo2=(lo_h, lo_w), n2=(h, w))
    dx = _plane_call(kern, [wp], (h, w), b, wgt.dtype)
    return dx.reshape(n, c, h, w).astype(dtype)


def _avg_bwd_xla(wgt, x_shape, dims, strides, pads, dtype):
    """Exact linear transpose of the strided window sum, closed form:
    interior-dilate the out-grid weights by the strides, edge-pad by
    k-1, window-sum with stride 1 — then every padded input position q
    reads exactly the windows containing it (``sum_{o: o*s <= q <
    o*s+k} wgt[o]``); slice off the declared padding."""
    cfg = [(k - 1, k - 1, s - 1) for k, s in zip(dims, strides)]
    dil = lax.pad(wgt, jnp.zeros((), wgt.dtype), cfg)
    full = lax.reduce_window(dil, jnp.zeros((), wgt.dtype), lax.add,
                             dims, (1,) * len(dims),
                             ((0, 0),) * len(dims))
    dx = lax.slice(full, [lo for lo, _ in pads],
                   [lo + n for (lo, _), n in zip(pads, x_shape)])
    return dx.astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def avg_pool(x, dims, strides, pads, declared, count_include_pad: bool,
             divide: bool):
    """Torch-semantics average pooling (declared-vs-overflow divisors,
    ceil mode via the caller's asymmetric ``pads``) with exact custom
    VJP; ``divide=False`` returns the plain window sum.  Any ndim on
    the XLA leg, fused per-plane Pallas kernels for 4-D trailing-(H, W)
    windows."""
    # divide is a nondiff_argnum: a static Python bool at trace time,
    # not a tracer — the branch is resolved per compilation
    if not divide:  # noqa: lint/tracer-branch
        return lax.reduce_window(x, jnp.zeros((), x.dtype), lax.add,
                                 dims, strides, pads)
    inv = _np_inv_counts(x.shape, tuple(dims), tuple(strides),
                         tuple(pads), tuple(declared), count_include_pad)
    supported = pool_plane_supported(x, dims, strides) \
        and inv.shape[:2] == (1, 1)
    return _dispatch.dispatch(
        "pool_avg.fwd",
        lambda x, d, s, p, i: _avg_fwd_pallas(
            x, d, s, p, jnp.asarray(i[0, 0], x.dtype)),
        lambda x, d, s, p, i: lax.reduce_window(
            x, jnp.zeros((), x.dtype), lax.add, d, s, p)
        * jnp.asarray(i, x.dtype),
        supported, x, dims, strides, pads, inv)


def _avg_vjp_fwd(x, dims, strides, pads, declared, count_include_pad,
                 divide):
    y = avg_pool(x, dims, strides, pads, declared, count_include_pad,
                 divide)
    # the backward needs only x's shape/dtype (the op is linear in x) —
    # a zero-length leading axis encodes both at zero residual memory
    return y, jnp.zeros((0,) + x.shape, x.dtype)


def _avg_vjp_bwd(dims, strides, pads, declared, count_include_pad,
                 divide, res, gy):
    x_shape, x_dtype = res.shape[1:], res.dtype
    if divide:
        inv = _np_inv_counts(tuple(x_shape), tuple(dims), tuple(strides),
                             tuple(pads), tuple(declared),
                             count_include_pad)
        wgt = gy * jnp.asarray(inv, gy.dtype)
    else:
        wgt = gy
    dx = _dispatch.dispatch(
        "pool_avg.bwd", _avg_bwd_pallas, _avg_bwd_xla,
        pool_plane_supported(jax.ShapeDtypeStruct(tuple(x_shape),
                                                  x_dtype),
                             dims, strides),
        wgt, x_shape, dims, strides, pads, x_dtype)
    return (dx,)


avg_pool.defvjp(_avg_vjp_fwd, _avg_vjp_bwd)
