"""Scaled dot-product attention: dense reference + Pallas flash kernel.

The reference framework has no attention of any kind (SURVEY §5
"Long-context ... Absent"); this is new TPU-first design work.  Three
entry points:

- ``dot_product_attention``: dense O(S^2)-memory reference (XLA-fused).
- ``flash_attention``: Pallas TPU kernel, O(S) memory, online softmax,
  with a full flash *backward* (dq / dkv kernels) via ``jax.custom_vjp``.
  Runs in interpret mode automatically off-TPU so tests exercise the same
  code path on the CPU mesh.
- ``attention_partial`` / ``combine_partials``: blockwise partial
  attention state (acc, m, l) and its merge — the algebra ring attention
  (``bigdl_tpu.parallel.sequence``) accumulates around the ICI ring.

Shapes follow [batch, heads, seq, head_dim] throughout.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "dot_product_attention",
    "flash_attention",
    "flash_min_seq",
    "is_tpu_device",
    "select_attention_backend",
    "flash_auto",
    "attention_partial",
    "combine_partials",
]

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# dense reference
# ---------------------------------------------------------------------------

def dot_product_attention(q, k, v, mask=None, causal: bool = False,
                          scale: Optional[float] = None):
    """Dense softmax(q k^T / sqrt(d)) v.  mask: broadcastable to
    [B, H, Sq, Sk], True = attend."""
    d = q.shape[-1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        q_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 0) + (sk - sq)
        k_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows output 0 (matching the flash/ring convention)
    # instead of softmax's uniform distribution over masked positions
    valid = jnp.max(s, axis=-1, keepdims=True) > _NEG_INF / 2
    p = jnp.where(valid, p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# blockwise partial state (used by ring attention)
# ---------------------------------------------------------------------------

def attention_partial(q, k, v, scale: float, mask=None):
    """One blockwise attention partial: returns (acc, m, l) where
    out = acc / l after all partials are combined.  mask broadcastable to
    [B, H, Sq, Sk], True = attend."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) = 1 would pollute l
    p = jnp.where((s > _NEG_INF / 2)[..., :], jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return acc, m, l


def combine_partials(state_a, state_b):
    """Merge two attention partials with the online-softmax rescale."""
    acc_a, m_a, l_a = state_a
    acc_b, m_b, l_b = state_b
    m = jnp.maximum(m_a, m_b)
    alpha = jnp.exp(m_a - m)
    beta = jnp.exp(m_b - m)
    return (acc_a * alpha[..., None] + acc_b * beta[..., None],
            m, l_a * alpha + l_b * beta)


# ---------------------------------------------------------------------------
# Pallas flash attention
# ---------------------------------------------------------------------------

def is_tpu_device() -> bool:
    """True when the default jax device is TPU hardware.  The check must
    look at the DEVICE, not ``jax.default_backend()``: proxied TPU
    plugins (e.g. the axon PJRT tunnel) register under their own
    platform name, and a name test would silently drop the bench onto
    the interpreter."""
    try:
        dev = jax.devices()[0]
    except Exception:  # noqa: BLE001 — no backend at all
        return False
    kind = (getattr(dev, "device_kind", "") or "").lower()
    plat = (getattr(dev, "platform", "") or "").lower()
    return "tpu" in kind or plat == "tpu"


def _use_interpret() -> bool:
    """Mosaic-compile on TPU; Pallas interpret mode elsewhere (tests)."""
    return not is_tpu_device()


def flash_min_seq() -> int:
    """Sequence length at which ``backend='auto'`` switches from dense
    to flash attention (``BIGDL_FLASH_MIN_SEQ``, default 512).

    History of this threshold (both decisions measured on TPU v5e):
    the round-5 profile first showed flash at the OLD 128x128 default
    blocks consuming 53% of the seq-512 transformer_lm step (tiny
    per-head tiles underfill the 128x128 MXU; grid iteration dominates),
    so the gate was introduced at 1024.  The round-5 block sweep
    (`exp_flash_blocks`, BASELINE.md) then fixed the block defaults to
    1024/512 — 3.5x faster at seq 4096 — and the re-run A/B
    (`exp_attention_backend`) showed properly-blocked flash BEATING
    dense at seq 512 (734 vs 562 seq/s: the S^2 score tensor never
    round-trips HBM), so the default dropped to 512.  Below 512 the
    sequence is shorter than one k block and dense's single fused
    matmul still wins."""
    raw = os.environ.get("BIGDL_FLASH_MIN_SEQ", "512")
    try:
        return int(raw)
    except ValueError as e:
        # loud: a silently-defaulted threshold would make an A/B sweep
        # compare the wrong legs
        raise ValueError(
            f"BIGDL_FLASH_MIN_SEQ={raw!r} is not an integer") from e


def select_attention_backend(sq: int, sk: int,
                             masked: bool = False) -> Tuple[str, str]:
    """THE auto-backend routing decision — (backend, reason) with
    backend in {"flash", "dense"} — shared by ``MultiHeadAttention``
    and ``bench.py``'s flash-MFU correction so the two can never drift
    (round-5 advisor finding: the bench re-derived this predicate and
    omitted the mask condition).

    Rules, in order: the ``BIGDL_KERNELS`` kill switch (``xla`` ->
    dense everywhere, ``pallas`` -> flash wherever structurally legal),
    then the measured auto policy — flash on TPU hardware from
    ``flash_min_seq()`` up (judged on BOTH lengths so a short-query
    cross-attention over a long k/v still streams), dense below it or
    off-TPU.  Dense masks (beyond ``causal``) always route dense: the
    flash kernel does not take a mask operand.  ``sq == 1`` — the KV-
    cached DECODE shape — always routes dense regardless of kv length
    (and regardless of ``BIGDL_KERNELS=pallas``): a flash q block is
    128 MXU rows of which decode fills exactly one, so the kernel would
    compute 127/128 padding per k block, while dense q_len=1 is a
    single batched matvec — exactly the shape the MXU handles without
    tiling ceremony."""
    from bigdl_tpu.ops.dispatch import kernel_mode

    mode = kernel_mode()
    if mode == "xla":
        return "dense", "forced:BIGDL_KERNELS=xla"
    if sq == 1:
        return "dense", "decode:q_len=1"
    if masked:
        return "dense", "masked"
    if mode == "pallas":
        return "flash", "forced:BIGDL_KERNELS=pallas"
    if not is_tpu_device():
        return "dense", "auto:off-tpu"
    if max(sq, sk) < flash_min_seq():
        return "dense", "auto:below-min-seq"
    return "flash", "auto:tpu"


def flash_auto(sq: int, sk: int, masked: bool = False) -> bool:
    """True when the auto backend routes (sq, sk) to the flash kernel."""
    return select_attention_backend(sq, sk, masked)[0] == "flash"


# Grid layout: (batch*heads, q_blocks, k_blocks) for fwd/dq and
# (batch*heads, k_blocks, q_blocks) for dkv.  The innermost grid dimension
# iterates sequentially on-core, so only one (block, d) tile of each
# operand is VMEM-resident at a time (k/v stream from HBM block-by-block)
# while the running online-softmax state lives in VMEM scratch — this is
# what keeps the kernel O(block) in VMEM at arbitrary sequence length.
# m/l scratch is broadcast over 128 lanes to satisfy TPU tiling.

_LANES = 128


def _causal_offset(q_len, kv_len):
    """off such that q row i attends k positions <= i + off."""
    return kv_len - q_len


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_s, m_s, l_s, *,
                scale: float, causal: bool, q_len: int, kv_len: int):
    from jax.experimental import pallas as pl

    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    q_start = qi * block_q
    k_start = ki * block_k
    off = _causal_offset(q_len, kv_len)

    @pl.when(ki == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    live = True
    if causal:
        live = q_start + off + block_q - 1 >= k_start

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_start + off + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_s[:, 0]
        l_prev = l_s[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(s > _NEG_INF / 2, jnp.exp(s - m_new[:, None]), 0.0)
        m_s[...] = jnp.broadcast_to(m_new[:, None], m_s.shape)
        l_s[...] = jnp.broadcast_to(
            (l_prev * alpha + jnp.sum(p, axis=-1))[:, None], l_s.shape)
        acc_s[...] = acc_s[...] * alpha[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_s[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_s[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, :, 0] = m_s[:, 0] + jnp.log(l_safe)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_s, *, scale: float, causal: bool, q_len: int, kv_len: int):
    from jax.experimental import pallas as pl

    block_q, d = q_ref.shape[1], q_ref.shape[2]
    block_k = k_ref.shape[1]
    qi, ki = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)
    q_start = qi * block_q
    k_start = ki * block_k
    off = _causal_offset(q_len, kv_len)

    @pl.when(ki == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s)

    live = True
    if causal:
        live = q_start + off + block_q - 1 >= k_start

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, :, 0]
        delta = delta_ref[0, :, 0]
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_start + off + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.where(s > _NEG_INF / 2, jnp.exp(s - lse[:, None]), 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_s[...] = dq_s[...] + jnp.dot(
            ds, k_blk, preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_s[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_s, dv_s, *,
                scale: float, causal: bool, q_len: int, kv_len: int):
    from jax.experimental import pallas as pl

    block_k, d = k_ref.shape[1], k_ref.shape[2]
    block_q = q_ref.shape[1]
    ki, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)
    q_start = qi * block_q
    k_start = ki * block_k
    off = _causal_offset(q_len, kv_len)

    @pl.when(qi == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    live = True
    if causal:
        live = q_start + off + block_q - 1 >= k_start

    @pl.when(live)
    def _step():
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        q_blk = q_ref[0].astype(jnp.float32)
        do_blk = do_ref[0].astype(jnp.float32)
        lse_blk = lse_ref[0, :, 0]
        delta_blk = delta_ref[0, :, 0]
        s = jnp.dot(q_blk, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = q_start + off + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.where(s > _NEG_INF / 2, jnp.exp(s - lse_blk[:, None]), 0.0)
        dv_s[...] = dv_s[...] + jnp.dot(
            p.T, do_blk, preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk[:, None]) * scale
        dk_s[...] = dk_s[...] + jnp.dot(
            ds.T, q_blk, preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[...].astype(dv_ref.dtype)


def _pick_block(s: int, pref: int) -> int:
    if s <= pref:
        return s
    b = pref
    while s % b != 0:
        b //= 2
    return max(b, 1)


def _flash_fwd_impl(q, k, v, scale, causal, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)
    grid = (b * h, sq // bq, sk // bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               q_len=sq, kv_len=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


def _flash_bwd_impl(q, k, v, out, lse, do, scale, causal,
                    block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)
    dor = do.reshape(b * h, sq, d)
    lser = lse.reshape(b * h, sq, 1)
    deltar = delta.reshape(b * h, sq, 1)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          q_len=sq, kv_len=sk),
        grid=(b * h, sq // bq, sk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, deltar)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          q_len=sq, kv_len=sk),
        grid=(b * h, sk // bk, sq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, bq, d), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, ki, qi: (bh, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda bh, ki, qi: (bh, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr, dor, lser, deltar)
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd_impl(q, k, v, scale, causal, block_q, block_k,
                             interpret)
    return out


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd_impl(q, k, v, scale, causal, block_q, block_k,
                               interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    return _flash_bwd_impl(q, k, v, out, lse, do, scale, causal,
                           block_q, block_k, interpret)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Flash attention (Pallas TPU kernel).  [B, H, S, D] in/out.

    O(S) memory: softmax is computed online per q block over streamed k/v
    blocks; backward recomputes p from the saved logsumexp (no S x S
    materialization).  Off-TPU the kernels run in Pallas interpret mode so
    the identical code path is testable on the CPU mesh.

    Block sizes default to 1024/512 (clamped to the sequence):
    the round-5 hardware sweep (`tools/experiments/exp_flash_blocks.py`,
    BASELINE.md) measured seq-4096 training 3.5x FASTER at 1024/512 than
    at the old 128/128 default — small blocks underfill the MXU and pay
    the grid-iteration overhead per tiny tile, exactly the short-seq
    pathology the auto backend routes to dense.  ``BIGDL_FLASH_BLOCK_Q``
    / ``BIGDL_FLASH_BLOCK_K`` override process-wide so sweeps need no
    code change.
    """
    import os

    if block_q is None:
        block_q = int(os.environ.get("BIGDL_FLASH_BLOCK_Q", "1024"))
    if block_k is None:
        block_k = int(os.environ.get("BIGDL_FLASH_BLOCK_K", "512"))
    d = q.shape[-1]
    scale = (1.0 / math.sqrt(d)) if scale is None else scale
    if interpret is None:
        interpret = _use_interpret()
    sq, sk = q.shape[2], k.shape[2]
    bq, bk = _pick_block(sq, block_q), _pick_block(sk, block_k)
    if not interpret and ((bq % 8 and bq != sq) or (bk % 8 and bk != sk)):
        # shapes the Mosaic tiling can't express — dense fallback
        return dot_product_attention(q, k, v, causal=causal, scale=scale)
    return _flash(q, k, v, scale, causal, block_q, block_k, interpret)

