"""Fused LRN kernels (cross-map + within-channel) with exact VJPs.

Round-5 motivation (BENCH_banked_r5.json): inception sits at 0.25 MFU
and its LRN layers lower to multi-op HLO chains — square, window-sum,
scale, power, multiply — that XLA leaves as separate HBM-bound fusions
(the channel window additionally fights TPU tiling: C is non-minor in
NCHW activations).  Each op here is ONE Pallas pass per block: read x,
square, unrolled shift-accumulate window sum, powf epilogue, write
(y, denom) — and the backward is the hand-derived exact cotangent in a
second fused pass, replacing an autodiff chain that re-materialized
every intermediate.

Math (both ops share the shape ``y = x * s^-beta``):

- cross-map (``nn/SpatialCrossMapLRN.scala``):
  ``s_i = k + (a/n) * sum_{j in band(i)} x_j^2`` over a channel band of
  ``n = size`` (odd) channels;
  ``dx = g*s^-b - (2ab/n) * x * band^T(g*x*s^(-b-1))`` — for odd bands
  the transpose band IS the band.
- within-channel (``nn/SpatialWithinChannelLRN.scala``):
  ``s = 1 + (a/n^2) * win(x^2)`` over an ``n x n`` spatial window with
  Torch pads ``(lo, hi) = (half, n-1-half)``;
  ``dx = g*s^-b - (2ab/n^2) * x * win^T(g*x*s^(-b-1))`` where the
  transpose window uses the swapped pads ``(hi, lo)`` (exact also for
  even windows).

Both are registered as ``jax.custom_vjp`` with the backend (Pallas vs
an XLA reference built from the same formulas) chosen per leg by
``ops.dispatch`` — the VJP is exact on either leg, so the numeric-grad
suite holds no matter how the knob is set.  Off-TPU the Pallas leg runs
``interpret=True`` (same code path, pure jax ops — this is what the
parity tests pin).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.ops import dispatch as _dispatch
from bigdl_tpu.ops.pallas_util import (TPU_DTYPES as _TPU_DTYPES,
                                       VMEM_BUDGET as _VMEM_BUDGET,
                                       plane_call as _plane_call)

__all__ = ["cross_map_lrn", "cross_map_lrn_supported",
           "within_channel_lrn", "within_channel_lrn_supported"]


def _pow(s, p: float):
    """``s ** p`` for s > 0 via exp/log — one transcendental pair the
    VPU lowers directly (jnp.power would route negative-base checks)."""
    if p == -0.5:
        return lax.rsqrt(s)
    return jnp.exp(p * jnp.log(s))


def _on_tpu_compiled() -> bool:
    return not _dispatch.use_interpret()


# ---------------------------------------------------------------------------
# cross-map LRN: banded channel-window sum, layout [N, Cpad, HW-tile]
# ---------------------------------------------------------------------------

def cross_map_lrn_supported(x, size: int, layout: str = "NCHW") -> bool:
    """Structural gate for the Pallas leg: 4-D NCHW, odd band.  NHWC
    stays on the XLA leg, which runs the banded conv NATIVELY in that
    layout — repacking for the kernel would cost the exact full-tensor
    relayout class this library exists to remove.  On real TPU
    additionally require a Mosaic dtype and the block to fit VMEM."""
    if x.ndim != 4 or size % 2 != 1 or size < 1 or layout != "NCHW":
        return False
    if _on_tpu_compiled():
        if x.dtype not in _TPU_DTYPES:
            return False
        n, c, h, w = x.shape
        f_pad = -(-(h * w) // 128) * 128
        t = _pick_tile(f_pad, c + size - 1, jnp.dtype(x.dtype).itemsize)
        if t is None:
            return False
    return True


def _pick_tile(f_pad: int, cp: int, esz: int):
    """Largest HW-tile (divisor of f_pad) whose fwd/bwd block stack fits
    the VMEM budget; None when even the smallest tile does not fit."""
    t = f_pad
    while t > 0:
        # ~5 live [Cp, T] planes: x, sq, running band sum, den, y
        if 5 * cp * t * esz <= _VMEM_BUDGET:
            return t
        if t % 2:
            return None
        t //= 2
    return None


def _cml_fwd_kernel(xp_ref, y_ref, den_ref, *, c: int, size: int,
                    half: int, alpha: float, beta: float, k: float):
    xp = xp_ref[0]                      # [Cp, T]
    sq = xp * xp
    s = sq[0:c]
    for d in range(1, size):
        s = s + sq[d:d + c]
    den = k + s * (alpha / size)
    den_ref[0] = den
    y_ref[0] = xp[half:half + c] * _pow(den, -beta)


def _cml_bwd_kernel(xp_ref, gp_ref, denp_ref, dx_ref, *, c: int, size: int,
                    half: int, alpha: float, beta: float):
    xp = xp_ref[0]
    gp = gp_ref[0]
    denp = denp_ref[0]                  # halo channels carry 1.0
    t = gp * xp * _pow(denp, -beta - 1.0)
    ts = t[0:c]
    for d in range(1, size):            # odd band: transpose == forward
        ts = ts + t[d:d + c]
    g = gp[half:half + c]
    x = xp[half:half + c]
    den = denp[half:half + c]
    dx_ref[0] = g * _pow(den, -beta) \
        - (2.0 * alpha * beta / size) * x * ts


def _cml_pack(a, pad_val: float, half: int, f_pad: int):
    """[N, C, H, W] -> [N, C + 2*half, f_pad] with channel halo."""
    n, c, h, w = a.shape
    flat = a.reshape(n, c, h * w)
    return jnp.pad(flat, ((0, 0), (half, half), (0, f_pad - h * w)),
                   constant_values=pad_val)


def _cml_call(kernel, packed_inputs, out_shapes, n, f_pad, t):
    from jax.experimental import pallas as pl

    grid = (n, f_pad // t)
    cp = packed_inputs[0].shape[1]
    in_specs = [pl.BlockSpec((1, cp, t), lambda b, i: (b, 0, i))
                for _ in packed_inputs]
    out_specs = [pl.BlockSpec((1, s[1], t), lambda b, i: (b, 0, i))
                 for s in out_shapes]
    outs = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs,
        out_specs=out_specs if len(out_specs) > 1 else out_specs[0],
        out_shape=[jax.ShapeDtypeStruct((n, s[1], f_pad), s[2])
                   for s in out_shapes] if len(out_shapes) > 1
        else jax.ShapeDtypeStruct(
            (n, out_shapes[0][1], f_pad), out_shapes[0][2]),
        interpret=_dispatch.use_interpret(),
    )(*packed_inputs)
    return outs


def _cml_fwd_pallas(x, size, alpha, beta, k):
    n, c, h, w = x.shape
    half = (size - 1) // 2
    f = h * w
    f_pad = -(-f // 128) * 128
    t = _pick_tile(f_pad, c + 2 * half, jnp.dtype(x.dtype).itemsize) \
        or f_pad
    xp = _cml_pack(x, 0.0, half, f_pad)
    kern = functools.partial(_cml_fwd_kernel, c=c, size=size, half=half,
                             alpha=alpha, beta=beta, k=k)
    y, den = _cml_call(kern, [xp],
                       [(n, c, x.dtype), (n, c, x.dtype)], n, f_pad, t)
    return (y[:, :, :f].reshape(n, c, h, w),
            den[:, :, :f].reshape(n, c, h, w))


def _cml_bwd_pallas(x, den, g, size, alpha, beta):
    n, c, h, w = x.shape
    half = (size - 1) // 2
    f = h * w
    f_pad = -(-f // 128) * 128
    t = _pick_tile(f_pad, c + 2 * half, jnp.dtype(x.dtype).itemsize) \
        or f_pad
    xp = _cml_pack(x, 0.0, half, f_pad)
    gp = _cml_pack(g, 0.0, half, f_pad)
    denp = _cml_pack(den, 1.0, half, f_pad)  # 1.0: powf stays finite
    kern = functools.partial(_cml_bwd_kernel, c=c, size=size, half=half,
                             alpha=alpha, beta=beta)
    dx = _cml_call(kern, [xp, gp, denp], [(n, c, x.dtype)], n, f_pad, t)
    return dx[:, :, :f].reshape(n, c, h, w)


def _band_matrix(c: int, size: int, transpose: bool) -> np.ndarray:
    half = (size - 1) // 2
    hi = size - 1 - half
    d = np.arange(c)
    rel = d[None, :] - d[:, None]       # rel = j - i
    if transpose:
        band = (rel >= -hi) & (rel <= half)
    else:
        band = (rel >= -half) & (rel <= hi)
    return band.astype(np.float32)


def _band_apply(v, size: int, transpose: bool, layout: str):
    """Banded C x C matrix at every pixel as a 1x1 conv — it (and only
    it) runs the channel window on the MXU, NATIVELY in either layout;
    the XLA reference leg (see SpatialCrossMapLRN's original profile
    note: reduce_window over the non-minor channel dim was ~10x
    slower)."""
    c_ax = 3 if layout == "NHWC" else 1
    band = _band_matrix(v.shape[c_ax], size, transpose)
    if layout == "NHWC":
        w = jnp.asarray(band.T[None, None], v.dtype)  # HWIO
        dn = lax.conv_dimension_numbers(v.shape, w.shape,
                                        ("NHWC", "HWIO", "NHWC"))
    else:
        w = jnp.asarray(band[:, :, None, None], v.dtype)  # OIHW
        dn = lax.conv_dimension_numbers(v.shape, w.shape,
                                        ("NCHW", "OIHW", "NCHW"))
    return lax.conv_general_dilated(v, w, (1, 1), ((0, 0), (0, 0)),
                                    dimension_numbers=dn)


def _cml_fwd_xla(x, size, alpha, beta, k, layout="NCHW"):
    den = k + _band_apply(x * x, size, False, layout) * (alpha / size)
    return x * _pow(den, -beta), den


def _cml_bwd_xla(x, den, g, size, alpha, beta, layout="NCHW"):
    t = g * x * _pow(den, -beta - 1.0)
    return g * _pow(den, -beta) \
        - (2.0 * alpha * beta / size) * x \
        * _band_apply(t, size, True, layout)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def cross_map_lrn(x, size: int, alpha: float, beta: float, k: float,
                  layout: str = "NCHW"):
    """AlexNet-style cross-channel LRN over NCHW/NHWC with exact custom
    VJP; backend (fused Pallas kernel vs XLA banded-conv reference)
    chosen by ``ops.dispatch`` — the NHWC reference runs in its native
    layout (no relayout transposes)."""
    y, _ = _cml_fwd(x, size, alpha, beta, k, layout)
    return y


def _cml_fwd(x, size, alpha, beta, k, layout):
    if layout == "NHWC":  # elementwise VJP math is layout-agnostic
        return _cml_fwd_xla(x, size, alpha, beta, k, layout)
    return _dispatch.dispatch(
        "lrn_cross_map.fwd", _cml_fwd_pallas, _cml_fwd_xla,
        cross_map_lrn_supported(x, size, layout), x, size, alpha, beta,
        k)


def _cml_vjp_fwd(x, size, alpha, beta, k, layout):
    y, den = _cml_fwd(x, size, alpha, beta, k, layout)
    return y, (x, den)


def _cml_vjp_bwd(size, alpha, beta, k, layout, res, g):
    x, den = res
    if layout == "NHWC":
        return (_cml_bwd_xla(x, den, g, size, alpha, beta, layout),)
    dx = _dispatch.dispatch(
        "lrn_cross_map.bwd", _cml_bwd_pallas, _cml_bwd_xla,
        cross_map_lrn_supported(x, size, layout), x, den, g, size,
        alpha, beta)
    return (dx,)


cross_map_lrn.defvjp(_cml_vjp_fwd, _cml_vjp_bwd)


# ---------------------------------------------------------------------------
# within-channel LRN: spatial-window sum, layout [N*C, Hpad, Wpad]
# ---------------------------------------------------------------------------

def within_channel_lrn_supported(x, size: int) -> bool:
    if x.ndim != 4 or size < 1:
        return False
    if _on_tpu_compiled():
        if x.dtype not in _TPU_DTYPES:
            return False
        h, w = x.shape[2], x.shape[3]
        hp, wp = h + size - 1, w + size - 1
        # ~4 live [Hp, Wp] planes per block (x, sq, accumulator, out)
        if 4 * hp * wp * jnp.dtype(x.dtype).itemsize > _VMEM_BUDGET:
            return False
    return True


def _wcl_fwd_kernel(xp_ref, y_ref, sc_ref, *, h: int, w: int, size: int,
                    lo: int, alpha: float, beta: float):
    xp = xp_ref[0]                      # [Hp, Wp]
    sq = xp * xp
    ws = None
    for dh in range(size):
        for dw in range(size):
            tap = sq[dh:dh + h, dw:dw + w]
            ws = tap if ws is None else ws + tap
    scale = 1.0 + ws * (alpha / (size * size))
    sc_ref[0] = scale
    y_ref[0] = xp[lo:lo + h, lo:lo + w] * _pow(scale, -beta)


def _wcl_bwd_kernel(tp_ref, x_ref, g_ref, sc_ref, dx_ref, *, h: int,
                    w: int, size: int, alpha: float, beta: float):
    tp = tp_ref[0]                      # transpose-padded t
    ts = None
    for dh in range(size):
        for dw in range(size):
            tap = tp[dh:dh + h, dw:dw + w]
            ts = tap if ts is None else ts + tap
    g = g_ref[0]
    x = x_ref[0]
    scale = sc_ref[0]
    dx_ref[0] = g * _pow(scale, -beta) \
        - (2.0 * alpha * beta / (size * size)) * x * ts


def _wcl_fwd_pallas(x, size, alpha, beta):
    n, c, h, w = x.shape
    lo, hi = (size - 1) // 2, size - 1 - (size - 1) // 2
    planes = x.reshape(n * c, h, w)
    xp = jnp.pad(planes, ((0, 0), (lo, hi), (lo, hi)))
    kern = functools.partial(_wcl_fwd_kernel, h=h, w=w, size=size, lo=lo,
                             alpha=alpha, beta=beta)
    y, scale = _plane_call(kern, [xp],
                           [((h, w), x.dtype), ((h, w), x.dtype)], n * c,
                           _dispatch.use_interpret())
    return y.reshape(n, c, h, w), scale.reshape(n, c, h, w)


def _wcl_bwd_pallas(x, scale, g, size, alpha, beta):
    n, c, h, w = x.shape
    lo, hi = (size - 1) // 2, size - 1 - (size - 1) // 2
    t = (g * x * _pow(scale, -beta - 1.0)).reshape(n * c, h, w)
    # TRANSPOSE pads (hi, lo): position m gathers windows o with
    # m in [o-lo, o+hi]  <=>  o in [m-hi, m+lo]
    tp = jnp.pad(t, ((0, 0), (hi, lo), (hi, lo)))
    flat = lambda a: a.reshape(n * c, h, w)  # noqa: E731
    kern = functools.partial(_wcl_bwd_kernel, h=h, w=w, size=size,
                             alpha=alpha, beta=beta)
    dx = _plane_call(kern, [tp, flat(x), flat(g), flat(scale)],
                     [((h, w), x.dtype)], n * c,
                     _dispatch.use_interpret())
    return dx.reshape(n, c, h, w)


def _win_sum(v, size: int, pads: Tuple[int, int]):
    dims = (1, 1, size, size)
    p = ((0, 0), (0, 0), pads, pads)
    return lax.reduce_window(v, jnp.zeros((), v.dtype), lax.add, dims,
                             (1, 1, 1, 1), p)


def _wcl_fwd_xla(x, size, alpha, beta):
    lo, hi = (size - 1) // 2, size - 1 - (size - 1) // 2
    scale = 1.0 + _win_sum(x * x, size, (lo, hi)) * (alpha / (size * size))
    return x * _pow(scale, -beta), scale


def _wcl_bwd_xla(x, scale, g, size, alpha, beta):
    lo, hi = (size - 1) // 2, size - 1 - (size - 1) // 2
    t = g * x * _pow(scale, -beta - 1.0)
    ts = _win_sum(t, size, (hi, lo))
    return g * _pow(scale, -beta) \
        - (2.0 * alpha * beta / (size * size)) * x * ts


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def within_channel_lrn(x, size: int, alpha: float, beta: float):
    """Within-channel spatial LRN over NCHW with exact custom VJP."""
    y, _ = _wcl_fwd(x, size, alpha, beta)
    return y


def _wcl_fwd(x, size, alpha, beta):
    return _dispatch.dispatch(
        "lrn_within_channel.fwd", _wcl_fwd_pallas, _wcl_fwd_xla,
        within_channel_lrn_supported(x, size), x, size, alpha, beta)


def _wcl_vjp_fwd(x, size, alpha, beta):
    y, scale = _wcl_fwd(x, size, alpha, beta)
    return y, (x, scale)


def _wcl_vjp_bwd(size, alpha, beta, res, g):
    x, scale = res
    dx = _dispatch.dispatch(
        "lrn_within_channel.bwd", _wcl_bwd_pallas, _wcl_bwd_xla,
        within_channel_lrn_supported(x, size), x, scale, g, size, alpha,
        beta)
    return (dx,)


within_channel_lrn.defvjp(_wcl_vjp_fwd, _wcl_vjp_bwd)
