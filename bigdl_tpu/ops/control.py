"""Control-flow combinators: the TPU-native mapping of the reference's
ControlOps/Scheduler cycles (``nn/ops/ControlOps.scala``,
``nn/Scheduler.scala:41``).

The reference executes while-loops by re-enqueuing graph nodes in a
ready-queue scheduler.  Under XLA everything is traced once and compiled,
so loops/branches must be structured primitives: ``while_modules`` lowers
to ``jax.lax.while_loop`` and ``cond_modules`` to ``jax.lax.cond``.  The
nn-level ``While``/``Cond``/``Switch``/``Merge`` layers
(``bigdl_tpu.nn.ops``) wrap these.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["while_modules", "cond_modules"]


def _as_tuple(x):
    return tuple(x) if isinstance(x, (list, tuple)) else (x,)


def while_modules(cond_module, body_module, init_vars):
    """Run ``body_module`` on the loop-variable table while ``cond_module``
    returns true.  Both receive the loop vars (a single array or a tuple);
    cond must produce a scalar boolean."""
    init = _as_tuple(init_vars)
    multi = isinstance(init_vars, (list, tuple))

    def cond_fn(vs):
        out = cond_module.forward(vs if multi else vs[0])
        return jnp.reshape(jnp.asarray(out), ()).astype(bool)

    def body_fn(vs):
        out = body_module.forward(vs if multi else vs[0])
        return _as_tuple(out)

    final = lax.while_loop(cond_fn, body_fn, init)
    return final if multi else final[0]


def cond_modules(pred, true_module, false_module, operand):
    """``lax.cond`` over two modules sharing one operand."""
    p = jnp.reshape(jnp.asarray(pred), ()).astype(bool)
    return lax.cond(p, lambda x: true_module.forward(x),
                    lambda x: false_module.forward(x), operand)
