"""bigdl_tpu.ops — functional TPU ops: Pallas kernels and the attention
family.

The reference keeps its perf-critical inner kernels in
``nn/NNPrimitive.scala`` (im2col/col2im/pooling hot loops) + MKL gemm; the
TPU-native analogue is (a) XLA itself for conv/matmul/elementwise fusion and
(b) Pallas kernels for ops XLA cannot fuse well — attention being the big
one (SURVEY §5 "Long-context": absent in the reference, first-class here).
"""

from bigdl_tpu.ops.attention import (  # noqa: F401
    dot_product_attention,
    flash_attention,
    attention_partial,
    combine_partials,
)
from bigdl_tpu.ops.lrn_pallas import (  # noqa: F401
    cross_map_lrn,
    within_channel_lrn,
)
from bigdl_tpu.ops.norm_pallas import (  # noqa: F401
    contrastive_norm,
    divisive_norm,
    smooth2d,
    subtractive_norm,
)
from bigdl_tpu.ops.pool_pallas import (  # noqa: F401
    avg_pool,
    maxpool_tie_split,
)
