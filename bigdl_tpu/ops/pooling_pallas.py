"""Argmax-index max-pool: packed-u32 XLA forward + Pallas scatter backward.

Why this exists (round-5 TPU profile, Inception-v1 train step): XLA's
select-and-scatter backward — the best of the three maxpool gradients
measured so far (BASELINE.md round-3 table) — re-reads the full input
activation AND the pool output to locate each window's first argmax:
~21.5% of the step in select_and_scatter fusions plus ~7.1% in the
compare/select index path, all HBM-bound traffic over tensors like the
[256,64,112,112] first-pool activation.

Design (settled by hardware iteration — four Mosaic lowering classes and
one VMEM-economics dead end are documented in BASELINE.md):

- **Forward: one XLA ``reduce_window`` over packed u32.**  Each element
  packs ``monotonic(bf16 bits) << 16 | inverted low-8 (h, w) coords``;
  integer max then yields the window max AND its position in a single
  window pass: the monotonic map makes float order = unsigned order, the
  inverted coordinates break value ties toward the smallest (h, w) —
  the reference's first-argmax (``nn/NNPrimitive.scala:594-972``) — and
  a NaN's monotonic image is the largest u16, so NaN propagates exactly
  like ``lax.reduce_window(max)``.  The pack/unpack are elementwise and
  fuse into the reduce; no Pallas forward and no extra VPU argmax chain
  (a full Pallas forward measured ~2 ms of pure compare work on the
  first pool alone — more than the backward win it enabled).
- **Backward: a Pallas scatter kernel in channel-last layout.**
  ``(gy, idx) -> dx`` never touches x or y.  The layout is
  ``[rows, cols, N*C]``: rows land on the UNTILED leading dim (row
  phase-split/interleave are free reshapes), cols on the sublane dim
  (the one dim Mosaic reshape-splits natively), batch*channel on lanes
  (pure SIMD).  Every slice is static; halo rows come from a
  neighbor-block BlockSpec, not DMA code.

    select-and-scatter bwd traffic:  read x + read y + read gy + write dx
    argmax-index bwd traffic:        read gy + read idx(1/2 size) + write dx

Supported: 16-bit float dtypes (bf16/f16 — the bench path).  f32 would
need a u64 pack; it falls back to select-and-scatter.  Off-TPU the
backward runs in Pallas interpret mode so the CPU mesh exercises the
same code path.  ``BIGDL_POOL_KERNEL=off`` forces the fallback.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from bigdl_tpu.ops.attention import is_tpu_device

__all__ = ["maxpool_argmax", "pallas_pool_supported"]

_NEG = float("-inf")

#: windows larger than this are global-pool-sized; the unrolled shift
#: structure in the backward would bloat compile time
_MAX_TAPS = 64

#: per-block VMEM budget (bytes); conservative vs the 16 MB/core arena
_VMEM_BUDGET = 6 * 1024 * 1024

#: lane-chunk and row-tile defaults for the backward grid
_LANES = 512
_ROW_TILE = 8


def pallas_pool_supported(x, dims, strides, pads) -> bool:
    """True when (x, window) fits this path: 4-D NCHW input, window on
    the trailing two axes, 16-bit float dtype, window extents within the
    low-8-bit coordinate encoding, bounded tap count."""
    from bigdl_tpu.ops.dispatch import kernel_mode

    mode = os.environ.get("BIGDL_POOL_KERNEL", "auto")
    if mode == "off" or kernel_mode() == "xla":
        return False  # BIGDL_KERNELS=xla: process-wide Pallas kill switch
    if x.ndim != 4 or x.dtype not in (jnp.bfloat16, jnp.float16):
        return False  # f32 would need a u64 pack
    if dims[0] != 1 or dims[1] != 1 or strides[0] != 1 or strides[1] != 1:
        return False  # pooled axes must be the trailing (H, W) pair
    if pads[0] != (0, 0) or pads[1] != (0, 0):
        return False
    kh, kw = dims[2], dims[3]
    if kh * kw > _MAX_TAPS or kh < 1 or kw < 1:
        return False
    sh, sw = strides[2], strides[3]
    ho, wo, lh, lw = _geometry(x.shape[2], x.shape[3], kh, kw, sh, sw,
                               (pads[2], pads[3]))
    (lo_h, hi_h), (lo_w, hi_w) = pads[2], pads[3]
    if lo_h + x.shape[2] + hi_h > 256 or lo_w + x.shape[3] + hi_w > 256:
        # the low-8 coordinate code wraps at padded position 256, which
        # would invert first-argmax tie order across the wrap
        return False
    n = x.shape[0]
    if n * x.shape[1] % 8:
        return False  # lane chunking wants a multiple-of-8 batch extent
    # the backward block must fit the VMEM budget even at the minimum
    # (th=1, bl=8) tile — otherwise fall back instead of a Mosaic
    # VMEM-overflow compile error
    jw_max = -(-kw // sw) - 1
    cpad = -(-(jw_max + lw) // 8) * 8
    if _bwd_est(1, 8, cpad, kh * kw, jnp.dtype(x.dtype).itemsize) \
            > _VMEM_BUDGET:
        return False
    if mode == "auto":
        # OPT-IN until the scatter kernel A/Bs a win on hardware
        # (tools/experiments/exp_pool_kernel.py).  NB is_tpu_device(),
        # not jax.default_backend() == "tpu": proxied PJRT plugins
        # (axon) register under their own platform name — the round-4
        # flash-attention gating bug.
        return False
    return True  # "interpret" / "on": run everywhere (tests)


def _bwd_est(th: int, bl: int, cpad: int, taps: int, esz: int) -> int:
    """Scoped-VMEM stack estimate for one backward block — shared by the
    support gate and the launcher's block chooser so they can't drift.
    Calibrated on hardware: the Mosaic stack does not reuse slots across
    the unrolled shift chain (~3 live planes per tap) plus the i32
    index upcast and block inputs."""
    plane = th * cpad * bl
    return (3 * taps + 6) * plane * esz + 3 * plane * 4


def _use_interpret() -> bool:
    if os.environ.get("BIGDL_POOL_KERNEL") == "interpret":
        return True
    return not is_tpu_device()


def _geometry(h: int, w: int, kh: int, kw: int, sh: int, sw: int,
              pads: Tuple[Tuple[int, int], Tuple[int, int]]):
    """Output sizes and residue-class lengths on the padded grid."""
    (lo_h, hi_h), (lo_w, hi_w) = pads
    ph, pw = lo_h + h + hi_h, lo_w + w + hi_w
    ho, wo = (ph - kh) // sh + 1, (pw - kw) // sw + 1
    lh, lw = -(-ph // sh), -(-pw // sw)  # ceil
    return ho, wo, lh, lw


# ---------------------------------------------------------------------------
# forward: packed-u32 reduce_window (pure XLA)
# ---------------------------------------------------------------------------

def _monotonic_u16(x):
    """Map 16-bit float bits to u16 such that float order == unsigned
    order (negatives flip all bits, positives flip the sign bit).  NaN
    maps above +inf, so integer max propagates it like float max.
    -0.0 collapses onto +0.0's key: the floats compare EQUAL, so the
    tie must resolve by position (select-and-scatter routes it to the
    first element), not by sign bit."""
    u = lax.bitcast_convert_type(x, jnp.uint16).astype(jnp.uint32)
    sign = u >> 15
    mono = (u ^ (0x8000 + sign * 0x7FFF)) & 0xFFFF
    mono = jnp.where(u == 0x8000, jnp.uint32(0x8000), mono)
    # ALL NaNs (either sign bit) map to the top key: the sign-flip rule
    # alone would drop a negative NaN below -inf and silently hide a
    # diverged run
    return jnp.where(jnp.isnan(x), jnp.uint32(0xFFFF), mono)


def _unmonotonic(u16, dtype):
    sign = 1 - (u16 >> 15)  # monotonic image of a negative has top bit 0
    bits = (u16 ^ (0x8000 + sign * 0x7FFF)) & 0xFFFF
    return lax.bitcast_convert_type(bits.astype(jnp.uint16), dtype)


def _fwd_packed(x, dims, strides, pads):
    """(y, idx) from ONE u32 reduce_window.  idx = dh*kw + dw in int8,
    first-argmax tie order, computed per output window from the packed
    low-8 coordinates of the winning element."""
    n, c, h, w = x.shape
    kh, kw, sh, sw = dims[2], dims[3], strides[2], strides[3]
    (lo_h, _), (lo_w, _) = pads[2], pads[3]
    ho, wo, _, _ = _geometry(h, w, kh, kw, sh, sw, (pads[2], pads[3]))

    mono = _monotonic_u16(x)
    # inverted low-8 coordinates of the PADDED position: integer max
    # prefers the largest code, so inversion makes value ties resolve to
    # the smallest (h, w) — first argmax in the reference's scan order
    p_h = lax.broadcasted_iota(jnp.uint32, x.shape, 2) + lo_h
    p_w = lax.broadcasted_iota(jnp.uint32, x.shape, 3) + lo_w
    code = ((p_h & 0xFF) ^ 0xFF) << 8 | ((p_w & 0xFF) ^ 0xFF)
    packed = mono << 16 | code
    # init-0 invariant: mono >= 0x007F for every non-NaN input (the
    # minimum, at -inf, is 0x007F), so packed >= 0x7F0000 > 0 for every
    # real tap and the 0 init can never win a window that contains one.
    # A fully-padded window would decode init 0 to a NaN rather than
    # reduce_window's -inf, but pallas_pool_supported's pads-vs-window
    # geometry excludes that case.
    red = lax.reduce_window(packed, jnp.uint32(0), lax.max,
                            dims, strides, pads)

    y = _unmonotonic(red >> 16, x.dtype)
    win_h = (red >> 8) & 0xFF ^ 0xFF
    win_w = red & 0xFF ^ 0xFF
    o_h = lax.broadcasted_iota(jnp.uint32, red.shape, 2)
    o_w = lax.broadcasted_iota(jnp.uint32, red.shape, 3)
    dh = (win_h - sh * o_h) & 0xFF
    dw = (win_w - sw * o_w) & 0xFF
    idx = (dh * kw + dw).astype(jnp.int8)
    return y, idx


# ---------------------------------------------------------------------------
# backward: Pallas scatter kernel, channel-last layout
# ---------------------------------------------------------------------------

def _bwd_kernel(gy_ref, gy_next_ref, idx_ref, idx_next_ref, dx_ref, *,
                kh, kw, sh, sw, jh_max, jw_pad, th, w_out_cols, lo_w):
    """One (row-tile, lane-chunk) block.

    Row geometry: gy/idx arrive TOP-PADDED by jh_max rows (and tiled by
    th), so for output-grid row a in this tile and row shift jh the
    source row is ``a + jh_max - jh`` — always in [0, th + jh_max),
    covered by this block plus the first jh_max rows of the next block.
    Col geometry: gy/idx arrive LEFT-PADDED by jw_pad cols on the
    sublane dim, so col shifts are static slices too.  All shifts
    static, rows untiled (leading), cols sublane, lanes batch."""
    gy = jnp.concatenate([gy_ref[...], gy_next_ref[0:jh_max]], axis=0) \
        if jh_max else gy_ref[...]
    idx = jnp.concatenate([idx_ref[...], idx_next_ref[0:jh_max]], axis=0) \
        if jh_max else idx_ref[...]
    idx = idx.astype(jnp.int32)
    bl = gy.shape[2]

    # hoist the column shifts: a sublane-offset slice is a relayout
    # copy, so take each jw view ONCE (jw_max+1 of them) — the per-tap
    # row shifts below slice only the untiled leading dim (free views)
    n_jw = jw_pad + 1
    gy_w = [gy[:, jw_pad - jw:jw_pad - jw + w_out_cols] for jw in range(n_jw)]
    idx_w = [idx[:, jw_pad - jw:jw_pad - jw + w_out_cols]
             for jw in range(n_jw)]

    # residue-class accumulation: padded input row p = sh*a + rh
    # receives gy[a - jh] where the tap dh = rh + sh*jh won
    rows = []
    for rh in range(sh):
        cols = []
        for rw in range(sw):
            acc = jnp.zeros((th, w_out_cols, bl), gy.dtype)
            for jh in range(-(-(kh - rh) // sh)):
                dh = rh + sh * jh
                if dh >= kh:
                    continue
                for jw in range(-(-(kw - rw) // sw)):
                    dw = rw + sw * jw
                    if dw >= kw:
                        continue
                    t = dh * kw + dw
                    g = gy_w[jw][jh_max - jh:jh_max - jh + th]
                    m = idx_w[jw][jh_max - jh:jh_max - jh + th]
                    # mask-multiply, not where (Mosaic i1-select
                    # relayout); caveat: a non-finite gy element leaks
                    # NaN into sibling tap positions (0 * inf) — wider
                    # NaN spread on an already-diverged step, not hidden
                    acc = acc + (m == t).astype(g.dtype) * g
            cols.append(acc)
        # W-interleave on the SUBLANE dim: [th, L, bl] x sw ->
        # [th, L*sw, bl] with out[.., sw*b + rw, ..] = cols[rw][.., b, ..]
        if sw == 1:
            rows.append(cols[0])
        else:
            rows.append(jnp.stack(cols, axis=2).reshape(
                th, w_out_cols * sw, bl))
    # H-interleave on the UNTILED leading dim: free reshape
    if sh == 1:
        dxp = rows[0]
    else:
        dxp = jnp.stack(rows, axis=1).reshape(th * sh, rows[0].shape[1], bl)
    dx_ref[...] = dxp[:, lo_w:lo_w + dx_ref.shape[1], :]


def _bwd_impl(gy, idx, x_shape, x_dtype, dims, strides, pads):
    n, c, h, w = x_shape
    kh, kw, sh, sw = dims[2], dims[3], strides[2], strides[3]
    hw_pads = (pads[2], pads[3])
    (lo_h, _), (lo_w, _) = hw_pads
    ho, wo, lh, lw = _geometry(h, w, kh, kw, sh, sw, hw_pads)
    b = n * c
    jh_max = -(-kh // sh) - 1
    jw_max = -(-kw // sw) - 1

    # channel-last: [ho, wo, b] with b = (c, n), n MINOR.  XLA's TPU
    # layout for NCHW conv activations is {0,1,3,2} — memory order
    # H, W, C, N — so this exact transpose is a bitcast, not a data
    # movement; b built as (n, c) instead would force a real HBM
    # relayout at the pallas row-major operand boundary (measured:
    # the first A/B ran 2.2x SLOWER from exactly that).
    gyt = jnp.transpose(gy.astype(x_dtype).reshape(n, c, ho, wo),
                        (2, 3, 1, 0)).reshape(ho, wo, b)
    idxt = jnp.transpose(idx.reshape(n, c, ho, wo),
                         (2, 3, 1, 0)).reshape(ho, wo, b)

    # block chooser: the Mosaic scoped stack does not reuse slots
    # across the unrolled shift chain (measured 28.2 MB at th=8/bl=512
    # on the first pool), so budget ~3 live planes per tap plus the i32
    # index upcast and the block inputs, and shrink (th, bl) to fit
    taps = kh * kw
    cpad = -(-(jw_max + lw) // 8) * 8  # sublane-padded col extent
    esz = jnp.dtype(x_dtype).itemsize

    th, bl = _ROW_TILE, _LANES
    while b % bl:
        bl //= 2
    while _bwd_est(th, bl, cpad, taps, esz) > _VMEM_BUDGET and bl > 8 \
            and b % (bl // 2) == 0:
        bl //= 2
    while _bwd_est(th, bl, cpad, taps, esz) > _VMEM_BUDGET and th > 1:
        th //= 2

    # row tiling: pad top by jh_max (shift halo) + bottom so gyp holds
    # EXACTLY (n_tiles + 1) row blocks — the neighbor-block spec
    # (lambda i, l: (i + 1, ...)) reads block n_tiles for the last tile,
    # so it must exist in-array (round-5 advisor: sizing the bottom pad
    # off lh instead of gyt's true ho rows left the neighbor block out
    # of range when lh > ho + jh_max, silently relying on Mosaic's
    # block-index clamping); col padding: left jw_max, right to the
    # residue grid
    n_tiles = -(-lh // th)
    top, bot = jh_max, (n_tiles + 1) * th - jh_max - ho
    right = lw - wo
    gyp = jnp.pad(gyt, ((top, bot), (jw_max, right), (0, 0)))
    idxp = jnp.pad(idxt, ((top, bot), (jw_max, right), (0, 0)),
                   constant_values=-1)
    w_cols = lw  # output-grid cols available per row after left pad
    kern = functools.partial(
        _bwd_kernel, kh=kh, kw=kw, sh=sh, sw=sw, jh_max=jh_max,
        jw_pad=jw_max, th=th, w_out_cols=w_cols, lo_w=lo_w)
    cols_pad = gyp.shape[1]
    dxp = pl.pallas_call(
        kern,
        grid=(n_tiles, b // bl),
        in_specs=[
            pl.BlockSpec((th, cols_pad, bl), lambda i, l: (i, 0, l)),
            pl.BlockSpec((th, cols_pad, bl), lambda i, l: (i + 1, 0, l)),
            pl.BlockSpec((th, cols_pad, bl), lambda i, l: (i, 0, l)),
            pl.BlockSpec((th, cols_pad, bl), lambda i, l: (i + 1, 0, l)),
        ],
        out_specs=pl.BlockSpec((th * sh, lw * sw - lo_w, bl),
                               lambda i, l: (i, 0, l)),
        out_shape=jax.ShapeDtypeStruct(
            (n_tiles * th * sh, lw * sw - lo_w, b), x_dtype),
        interpret=_use_interpret(),
    )(gyp, gyp, idxp, idxp)
    # valid region: padded rows [lo_h, lo_h + h), cols already start at
    # lo_w in-kernel; back to NCHW — the row-major [h, w, c, n] result
    # transposed to NCHW is exactly the {0,1,3,2} physical layout the
    # conv-backward consumer wants, so this folds too
    dx = dxp[lo_h:lo_h + h, :w, :].reshape(h, w, c, n)
    return jnp.transpose(dx, (3, 2, 0, 1))


# ---------------------------------------------------------------------------
# custom-vjp wrapper
# ---------------------------------------------------------------------------

def maxpool_argmax(x, dims, strides, pads):
    """Max pooling over the trailing (H, W) axes of an NCHW tensor with
    first-argmax gradient routing via a saved int8 tap index.  Value-
    and tie-parity with ``lax.reduce_window(max)`` + select-and-scatter
    under the support predicate ``pallas_pool_supported``."""
    return _pool(x, dims, strides, tuple(pads), x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _pool(x, dims, strides, pads, xshape):
    # undifferentiated primal (inference/eval): plain reduce_window —
    # identical values, fully XLA-fusable, no index computation
    return lax.reduce_window(x, _NEG, lax.max, dims, strides, pads)


def _vjp_fwd(x, dims, strides, pads, xshape):
    y, idx = _fwd_packed(x, dims, strides, pads)
    return y, idx


def _vjp_bwd(dims, strides, pads, xshape, idx, gy):
    dx = _bwd_impl(gy, idx, xshape, gy.dtype, dims, strides, pads)
    return (dx,)


_pool.defvjp(_vjp_fwd, _vjp_bwd)
