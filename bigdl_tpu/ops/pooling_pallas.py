"""Pallas TPU max-pool with an argmax-index backward.

Why this kernel exists (round-5 TPU profile, Inception-v1 train step):
XLA's select-and-scatter backward — the best of the three maxpool
gradients measured so far (BASELINE.md round-3 table) — still re-reads
the full input activation AND the pool output to locate each window's
first argmax: ~21.5% of the step in select_and_scatter fusions plus
~7.1% in the compare/select index path, all of it HBM-bound traffic
over tensors like the [256,64,112,112] first-pool activation.

This kernel removes the re-read.  The forward computes the max and the
*winning tap index* (0..kh*kw-1, int8) in one pass over the input; the
backward then scatters gy straight from (gy, idx) — it never touches x
or y again:

    select-and-scatter bwd traffic:  read x + read y + read gy + write dx
    argmax-index bwd traffic:        read gy + read idx(+1/8 size) + write dx

Both passes run as one Pallas grid over N*C row-blocks with the whole
(H, W) plane resident in VMEM, so the residue-class interleave that made
the pure-XLA gather backward slow (an extra HBM relayout pass) happens
in-register instead.

Semantics: first-argmax tie-breaking in lexicographic (kh, kw) tap
order — bit-parity with the reference's CPU loop
(``nn/NNPrimitive.scala:594-972``, rows then cols) and with XLA's
select-and-scatter lowering, asserted in ``tests/test_pooling_pallas.py``.

Off-TPU the kernel runs in Pallas interpret mode so the CPU test mesh
exercises the identical code path.  ``BIGDL_POOL_KERNEL=off`` falls back
to select-and-scatter (the measured round-3 default).
"""

from __future__ import annotations

import functools
import os
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from bigdl_tpu.ops.attention import is_tpu_device

__all__ = ["maxpool_argmax", "pallas_pool_supported"]

_NEG = float("-inf")

#: unrolled taps beyond this would bloat compile time (same cap as the
#: tie-split VJP in nn/layers/pooling.py)
_MAX_TAPS = 64

#: per-block VMEM budget (bytes); conservative vs the 16 MB/core arena
_VMEM_BUDGET = 6 * 1024 * 1024


def pallas_pool_supported(x, dims, strides, pads) -> bool:
    """True when (x, window) fits this kernel: 4-D NCHW input, window on
    the trailing two axes only, float dtype, bounded tap count, and a
    single (H, W) plane that fits the per-block VMEM budget."""
    mode = os.environ.get("BIGDL_POOL_KERNEL", "auto")
    if mode == "off":
        return False
    if x.ndim != 4 or not jnp.issubdtype(x.dtype, jnp.floating):
        return False
    if dims[0] != 1 or dims[1] != 1 or strides[0] != 1 or strides[1] != 1:
        return False  # pooled axes must be the trailing (H, W) pair
    if pads[0] != (0, 0) or pads[1] != (0, 0):
        return False
    kh, kw = dims[2], dims[3]
    if kh * kw > _MAX_TAPS or kh < 1 or kw < 1:
        return False
    h, w = x.shape[2], x.shape[3]
    sh, sw = strides[2], strides[3]
    ho, wo, lh, lw = _geometry(h, w, kh, kw, sh, sw, (pads[2], pads[3]))
    esz = jnp.dtype(x.dtype).itemsize
    # the single-row footprint must fit the budget even at bb=1
    if _row_bytes(h, w, ho, wo, lh, lw, sh, sw, esz, kh, kw) > _VMEM_BUDGET:
        return False  # fall back to reduce_window / select-and-scatter
    if mode == "auto":
        # OPT-IN until the Mosaic lowering is proven on hardware: the
        # first on-chip compile (round 5) rejected the strided tap
        # extraction (vector.extract_strided_slice strides must be 1),
        # so "auto" currently means off; flip after the stride-free
        # formulation A/Bs a win (tools/experiments/exp_pool_kernel.py).
        # NB gate on is_tpu_device(), not jax.default_backend() ==
        # "tpu": proxied PJRT plugins (axon) register under their own
        # platform name — the round-4 flash-attention gating bug.
        return False
    return True  # "interpret" / "on": run everywhere (tests)


def _use_interpret() -> bool:
    if os.environ.get("BIGDL_POOL_KERNEL") == "interpret":
        return True
    return not is_tpu_device()


def _geometry(h: int, w: int, kh: int, kw: int, sh: int, sw: int,
              pads: Tuple[Tuple[int, int], Tuple[int, int]]):
    """Padded extents, residue-class lengths, output sizes."""
    (lo_h, hi_h), (lo_w, hi_w) = pads
    ph, pw = lo_h + h + hi_h, lo_w + w + hi_w
    ho, wo = (ph - kh) // sh + 1, (pw - kw) // sw + 1
    lh, lw = -(-ph // sh), -(-pw // sw)  # ceil
    return ho, wo, lh, lw


def _row_bytes(h, w, ho, wo, lh, lw, sh, sw, esz, kh, kw) -> int:
    """Upper-bound VMEM footprint per N*C row — shared by the support
    gate and both kernel launchers so they can never drift apart.

    Calibrated against the compiler's scoped-vmem stack report on
    hardware (round 5): the scoped stack does NOT reuse slots across the
    unrolled tap chain (35.8 MB at block 512 on the 28x28 pool = ~23
    co-live planes for 9 taps), so the forward budget is ~3 f32
    full-res planes per tap (v copy + mask + idx chain) plus xb, best,
    idx and the decimation transposes; the backward's per-shift
    temporaries are quarter-planes in the gradient dtype, ~3 per tap,
    plus the interleave stack at full plane size."""
    plane = (lh * sh) * (lw * sw)
    taps = kh * kw
    fwd = h * w * esz + (3 * taps + 5) * plane * 4 \
        + ho * wo * (esz + 1 + 4)
    bwd = (3 * taps // (sh * sw) + 4) * plane * esz + plane * 4 \
        + ho * wo * (esz + 1 + 4 + 4)
    return max(fwd, bwd)


def _pick_block(b: int, row_bytes: int) -> int:
    """Largest divisor of b keeping the block under the VMEM budget."""
    best = 1
    for cand in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        if b % cand == 0 and cand * row_bytes <= _VMEM_BUDGET:
            best = cand
            break
    return best


# ---------------------------------------------------------------------------
# Mosaic-supported decimation / interleave primitives.
#
# What the backend actually lowers (learned on hardware, round 5):
#   * strided vector slices: NO  (vector.extract_strided_slice stride=1)
#   * splitting/merging the SUBLANE (second-minor) dim via reshape +
#     scalar middle-axis index: YES
#   * splitting/merging the LANE (minor) dim via reshape: NO
#     (tpu.reshape [..,114] -> [..,57,2] rejected)
#   * last-two-axes transpose: YES
# So lane-axis decimation = transpose, sublane decimation, transpose.
# ---------------------------------------------------------------------------

def _decimate_rows(a, s: int, n_out: int):
    """[bb, s*n_out, M] -> [bb, n_out, M] keeping rows 0, s, 2s, ...
    The extent must be an exact multiple: an in-kernel pad here lowers
    to tpu.concatenate, which rejects operands whose accumulated layout
    offsets differ (seen on hardware: 'result/input offset mismatch on
    non-concat dimension')."""
    if s == 1:
        return a[:, :n_out, :]
    bb, r, m = a.shape
    assert r == s * n_out, (r, s, n_out)
    return a.reshape(bb, n_out, s, m)[:, :, 0, :]


def _decimate_cols(a, s: int, n_out: int):
    """[bb, R, M] -> [bb, R, n_out] keeping cols 0, s, 2s, ..."""
    if s == 1:
        return a[:, :, :n_out]
    at = jnp.swapaxes(a, 1, 2)
    return jnp.swapaxes(_decimate_rows(at, s, n_out), 1, 2)


def _interleave_rows(parts, s: int):
    """s arrays [bb, L, M] -> [bb, L*s, M], out[s*a + r] = parts[r][a]."""
    if s == 1:
        return parts[0]
    bb, l, m = parts[0].shape
    return jnp.stack(parts, axis=2).reshape(bb, l * s, m)


def _interleave_cols(parts, s: int):
    """s arrays [bb, L, M] -> [bb, L, M*s], out[.., s*b + r] = parts[r][.., b]."""
    if s == 1:
        return parts[0]
    at = _interleave_rows([jnp.swapaxes(p, 1, 2) for p in parts], s)
    return jnp.swapaxes(at, 1, 2)


# ---------------------------------------------------------------------------
# forward kernel: x -> (y, idx)
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, y_ref, idx_ref, *, kh, kw, sh, sw, pads, ho, wo,
                lh, lw):
    # compute in f32: Mosaic rejects arith.cmpf on packed-bf16 native
    # tiles (vector<8x128x2xbf16>), and the tap loop is comparison-heavy
    x = x_ref[...].astype(jnp.float32)
    (lo_h, hi_h), (lo_w, hi_w) = pads
    bb = x.shape[0]
    # windowed max + argmax at FULL (stride-1) resolution — every tap is
    # a stride-1 slice — then decimate rows/cols once at the end.  The
    # full-res extent is sh*ho (an exact stride multiple, so the
    # decimation reshape needs no pad): rows past the last valid window
    # start are junk computed over -inf padding and dropped by the
    # decimation
    rh_, rw_ = sh * ho, sw * wo
    eh = (kh - 1 + rh_) - (lo_h + x.shape[1] + hi_h)
    ew = (kw - 1 + rw_) - (lo_w + x.shape[2] + hi_w)
    xb = jnp.pad(x, ((0, 0), (lo_h, hi_h + max(eh, 0)),
                     (lo_w, hi_w + max(ew, 0))),
                 constant_values=_NEG)
    best = jnp.full((bb, rh_, rw_), _NEG, jnp.float32)
    idx = jnp.zeros((bb, rh_, rw_), jnp.int32)
    # unrolled taps: a rolled fori needs dynamic_slice on values, which
    # the Mosaic lowering does not implement.  The cost of unrolling is
    # VMEM: the compiler's scoped stack keeps every tap's temporaries
    # co-live (no slot reuse — measured 35.8 MB at block 512 on the
    # 28x28 pool), so _row_bytes budgets ~3 live planes per tap and
    # _pick_block shrinks the block accordingly.
    t = 0
    for dh in range(kh):
        for dw in range(kw):
            v = xb[:, dh:dh + rh_, dw:dw + rw_]
            # strict >: a later equal tap never steals -> first argmax.
            # NaN taps must still win (reduce_window propagates NaN; a
            # silent NaN->-inf would hide a diverged run).  Integer mask
            # arithmetic + NaN-propagating maximum instead of jnp.where:
            # Mosaic rejected the i1-mask select's relayout.
            take = ((v > best) | jnp.isnan(v)).astype(jnp.int32)
            idx = take * t + (1 - take) * idx
            best = jnp.maximum(best, v)
            t += 1
    y_ref[...] = _decimate_cols(_decimate_rows(best, sh, ho), sw, wo
                                ).astype(y_ref.dtype)
    idx_ref[...] = _decimate_cols(_decimate_rows(idx, sh, ho), sw, wo
                                  ).astype(idx_ref.dtype)


# ---------------------------------------------------------------------------
# backward kernel: (gy, idx) -> dx
# ---------------------------------------------------------------------------

def _bwd_kernel(gy_ref, idx_ref, dx_ref, *, kh, kw, sh, sw, pads, h, w,
                lh, lw):
    gy = gy_ref[...]
    idx = idx_ref[...].astype(jnp.int32)
    bb, ho, wo = gy.shape
    (lo_h, _), (lo_w, _) = pads

    # residue-class accumulation entirely in VMEM: padded position
    # p = s*a + r receives gy[a - j] from tap d = r + s*j
    parts = []
    for rh in range(sh):
        row = []
        for rw in range(sw):
            acc = jnp.zeros((bb, lh, lw), gy.dtype)
            for jh in range(-(-(kh - rh) // sh)):
                dh = rh + sh * jh
                if dh >= kh:
                    continue
                for jw in range(-(-(kw - rw) // sw)):
                    dw = rw + sw * jw
                    if dw >= kw:
                        continue
                    t = dh * kw + dw
                    # mask-multiply, not where: see the fwd kernel's
                    # i1-relayout note.  Caveat vs select-and-scatter:
                    # a non-finite gy element leaks NaN into the OTHER
                    # taps' positions too (0 * inf = NaN) — wider NaN
                    # spread on an already-diverged step, never hidden
                    g = (idx == t).astype(gy.dtype) * gy
                    nh, nw = min(ho, lh - jh), min(wo, lw - jw)
                    g = g[:, :nh, :nw]
                    # static pad to the residue grid (Mosaic-friendlier
                    # than an in-place strided update)
                    g = jnp.pad(g, ((0, 0), (jh, lh - jh - nh),
                                    (jw, lw - jw - nw)))
                    acc = acc + g
            row.append(acc)
        parts.append(row)

    # interleave the residue grids back to the padded input plane:
    # cols per row-phase (transpose-based lane interleave), then rows
    # (sublane interleave) — see the Mosaic support notes above
    rows = [_interleave_cols(row, sw) for row in parts]
    dxp = _interleave_rows(rows, sh)
    dx_ref[...] = lax.slice(dxp, (0, lo_h, lo_w),
                            (bb, lo_h + h, lo_w + w))


# ---------------------------------------------------------------------------
# custom-vjp wrapper
# ---------------------------------------------------------------------------

def maxpool_argmax(x, dims, strides, pads):
    """Max pooling over the trailing (H, W) axes of an NCHW tensor with
    first-argmax gradient routing via a saved int8 tap index.  Drop-in
    for ``lax.reduce_window(max)`` under the support predicate
    ``pallas_pool_supported``."""
    return _pool(x, dims, strides, tuple(pads), x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _pool(x, dims, strides, pads, xshape):
    # undifferentiated primal (inference/eval): plain reduce_window —
    # identical values, fully XLA-fusable, no wasted idx write.  The
    # Pallas (y, idx) forward runs only under differentiation (_vjp_fwd).
    return lax.reduce_window(x, _NEG, lax.max, dims, strides, pads)


def _fwd_impl(x, dims, strides, pads):
    n, c, h, w = x.shape
    kh, kw, sh, sw = dims[2], dims[3], strides[2], strides[3]
    hw_pads = (pads[2], pads[3])
    ho, wo, lh, lw = _geometry(h, w, kh, kw, sh, sw, hw_pads)
    b = n * c
    xr = x.reshape(b, h, w)
    esz = x.dtype.itemsize
    bb = _pick_block(b, _row_bytes(h, w, ho, wo, lh, lw, sh, sw, esz, kh, kw))
    kern = functools.partial(_fwd_kernel, kh=kh, kw=kw, sh=sh, sw=sw,
                             pads=hw_pads, ho=ho, wo=wo, lh=lh, lw=lw)
    y, idx = pl.pallas_call(
        kern,
        grid=(b // bb,),
        in_specs=[pl.BlockSpec((bb, h, w), lambda i: (i, 0, 0))],
        out_specs=[pl.BlockSpec((bb, ho, wo), lambda i: (i, 0, 0)),
                   pl.BlockSpec((bb, ho, wo), lambda i: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((b, ho, wo), x.dtype),
                   jax.ShapeDtypeStruct((b, ho, wo), jnp.int8)],
        interpret=_use_interpret(),
    )(xr)
    return y.reshape(n, c, ho, wo), idx


def _vjp_fwd(x, dims, strides, pads, xshape):
    y, idx = _fwd_impl(x, dims, strides, pads)
    return y, idx


def _vjp_bwd(dims, strides, pads, xshape, idx, gy):
    n, c, h, w = xshape
    x_dtype = gy.dtype
    kh, kw, sh, sw = dims[2], dims[3], strides[2], strides[3]
    hw_pads = (pads[2], pads[3])
    ho, wo, lh, lw = _geometry(h, w, kh, kw, sh, sw, hw_pads)
    b = n * c
    gyr = gy.reshape(b, ho, wo)
    esz = jnp.dtype(x_dtype).itemsize
    bb = _pick_block(b, _row_bytes(h, w, ho, wo, lh, lw, sh, sw, esz, kh, kw))
    kern = functools.partial(_bwd_kernel, kh=kh, kw=kw, sh=sh, sw=sw,
                             pads=hw_pads, h=h, w=w, lh=lh, lw=lw)
    dx = pl.pallas_call(
        kern,
        grid=(b // bb,),
        in_specs=[pl.BlockSpec((bb, ho, wo), lambda i: (i, 0, 0)),
                  pl.BlockSpec((bb, ho, wo), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((bb, h, w), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, w), x_dtype),
        interpret=_use_interpret(),
    )(gyr, idx)
    return (dx.reshape(n, c, h, w),)


_pool.defvjp(_vjp_fwd, _vjp_bwd)
