"""Pallas TPU max-pool with an argmax-index backward.

Why this kernel exists (round-5 TPU profile, Inception-v1 train step):
XLA's select-and-scatter backward — the best of the three maxpool
gradients measured so far (BASELINE.md round-3 table) — still re-reads
the full input activation AND the pool output to locate each window's
first argmax: ~21.5% of the step in select_and_scatter fusions plus
~7.1% in the compare/select index path, all of it HBM-bound traffic
over tensors like the [256,64,112,112] first-pool activation.

This kernel removes the re-read.  The forward computes the max and the
*winning tap index* (0..kh*kw-1, int8) in one pass over the input; the
backward then scatters gy straight from (gy, idx) — it never touches x
or y again:

    select-and-scatter bwd traffic:  read x + read y + read gy + write dx
    argmax-index bwd traffic:        read gy + read idx(+1/8 size) + write dx

Both passes run as one Pallas grid over N*C row-blocks with the whole
(H, W) plane resident in VMEM, so the residue-class interleave that made
the pure-XLA gather backward slow (an extra HBM relayout pass) happens
in-register instead.

Semantics: first-argmax tie-breaking in lexicographic (kh, kw) tap
order — bit-parity with the reference's CPU loop
(``nn/NNPrimitive.scala:594-972``, rows then cols) and with XLA's
select-and-scatter lowering, asserted in ``tests/test_pooling_pallas.py``.

Off-TPU the kernel runs in Pallas interpret mode so the CPU test mesh
exercises the identical code path.  ``BIGDL_POOL_KERNEL=off`` falls back
to select-and-scatter (the measured round-3 default).
"""

from __future__ import annotations

import functools
import os
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from bigdl_tpu.ops.attention import is_tpu_device

__all__ = ["maxpool_argmax", "pallas_pool_supported"]

_NEG = float("-inf")

#: unrolled taps beyond this would bloat compile time (same cap as the
#: tie-split VJP in nn/layers/pooling.py)
_MAX_TAPS = 64

#: per-block VMEM budget (bytes); conservative vs the 16 MB/core arena
_VMEM_BUDGET = 6 * 1024 * 1024


def pallas_pool_supported(x, dims, strides, pads) -> bool:
    """True when (x, window) fits this kernel: 4-D NCHW input, window on
    the trailing two axes only, float dtype, bounded tap count, and a
    single (H, W) plane that fits the per-block VMEM budget."""
    mode = os.environ.get("BIGDL_POOL_KERNEL", "auto")
    if mode == "off":
        return False
    if x.ndim != 4 or not jnp.issubdtype(x.dtype, jnp.floating):
        return False
    if dims[0] != 1 or dims[1] != 1 or strides[0] != 1 or strides[1] != 1:
        return False  # pooled axes must be the trailing (H, W) pair
    if pads[0] != (0, 0) or pads[1] != (0, 0):
        return False
    kh, kw = dims[2], dims[3]
    if kh * kw > _MAX_TAPS or kh < 1 or kw < 1:
        return False
    h, w = x.shape[2], x.shape[3]
    sh, sw = strides[2], strides[3]
    ho, wo, lh, lw = _geometry(h, w, kh, kw, sh, sw, (pads[2], pads[3]))
    esz = jnp.dtype(x.dtype).itemsize
    # the single-row footprint must fit the budget even at bb=1
    if _row_bytes(h, w, ho, wo, lh, lw, sh, sw, esz) > _VMEM_BUDGET:
        return False  # fall back to reduce_window / select-and-scatter
    if mode == "auto":
        # OPT-IN until the Mosaic lowering is proven on hardware: the
        # first on-chip compile (round 5) rejected the strided tap
        # extraction (vector.extract_strided_slice strides must be 1),
        # so "auto" currently means off; flip after the stride-free
        # formulation A/Bs a win (tools/experiments/exp_pool_kernel.py).
        # NB gate on is_tpu_device(), not jax.default_backend() ==
        # "tpu": proxied PJRT plugins (axon) register under their own
        # platform name — the round-4 flash-attention gating bug.
        return False
    return True  # "interpret" / "on": run everywhere (tests)


def _use_interpret() -> bool:
    if os.environ.get("BIGDL_POOL_KERNEL") == "interpret":
        return True
    return not is_tpu_device()


def _geometry(h: int, w: int, kh: int, kw: int, sh: int, sw: int,
              pads: Tuple[Tuple[int, int], Tuple[int, int]]):
    """Padded extents, residue-class lengths, output sizes."""
    (lo_h, hi_h), (lo_w, hi_w) = pads
    ph, pw = lo_h + h + hi_h, lo_w + w + hi_w
    ho, wo = (ph - kh) // sh + 1, (pw - kw) // sw + 1
    lh, lw = -(-ph // sh), -(-pw // sw)  # ceil
    return ho, wo, lh, lw


def _row_bytes(h, w, ho, wo, lh, lw, sh, sw, esz) -> int:
    """Upper-bound VMEM footprint per N*C row — shared by the support
    gate and both kernel launchers so they can never drift apart.  The
    2x padded-plane term covers the backward's residue parts + stacked
    interleave (the forward's xb + phase copies fit under the same
    bound)."""
    return (h * w + 2 * (lh * sh) * (lw * sw)) * esz \
        + ho * wo * (esz + 1 + 4)


def _pick_block(b: int, row_bytes: int) -> int:
    """Largest divisor of b keeping the block under the VMEM budget."""
    best = 1
    for cand in (512, 256, 128, 64, 32, 16, 8, 4, 2, 1):
        if b % cand == 0 and cand * row_bytes <= _VMEM_BUDGET:
            best = cand
            break
    return best


# ---------------------------------------------------------------------------
# forward kernel: x -> (y, idx)
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, y_ref, idx_ref, *, kh, kw, sh, sw, pads, ho, wo,
                lh, lw):
    x = x_ref[...]
    (lo_h, _), (lo_w, _) = pads
    hp, wp = lh * sh, lw * sw
    xb = jnp.pad(x, ((0, 0), (lo_h, hp - lo_h - x.shape[1]),
                     (lo_w, wp - lo_w - x.shape[2])),
                 constant_values=_NEG)
    bb = x.shape[0]
    # phase-split ONCE (Mosaic rejects strided slices — stride must be
    # 1 in vector.extract_strided_slice — so decimation happens via
    # reshape splits + scalar index, verified to lower): phase[rh][rw]
    # holds padded positions (sh*a + rh, sw*b + rw)
    phases = []
    r4 = xb.reshape(bb, lh, sh, wp)
    for rh in range(sh):
        row_plane = r4[:, :, rh, :].reshape(bb, lh, lw, sw)
        phases.append([row_plane[:, :, :, rw] for rw in range(sw)])

    best = jnp.full((bb, ho, wo), _NEG, x.dtype)
    idx = jnp.zeros((bb, ho, wo), jnp.int32)
    t = 0
    for dh in range(kh):
        rh, jh = dh % sh, dh // sh
        for dw in range(kw):
            rw, jw = dw % sw, dw // sw
            # tap (dh, dw) at output (o_h, o_w) reads padded position
            # (sh*(o_h + jh) + rh, ...): a stride-1 window of the phase
            v = phases[rh][rw][:, jh:jh + ho, jw:jw + wo]
            # strict >: a later equal tap never steals -> first argmax.
            # NaN taps must still win (reduce_window propagates NaN; a
            # silent NaN->-inf would hide a diverged run)
            take = (v > best) | jnp.isnan(v)
            best = jnp.where(take, v, best)
            idx = jnp.where(take, t, idx)
            t += 1
    y_ref[...] = best
    idx_ref[...] = idx.astype(idx_ref.dtype)


# ---------------------------------------------------------------------------
# backward kernel: (gy, idx) -> dx
# ---------------------------------------------------------------------------

def _bwd_kernel(gy_ref, idx_ref, dx_ref, *, kh, kw, sh, sw, pads, h, w,
                lh, lw):
    gy = gy_ref[...]
    idx = idx_ref[...].astype(jnp.int32)
    bb, ho, wo = gy.shape
    (lo_h, _), (lo_w, _) = pads

    # residue-class accumulation entirely in VMEM: padded position
    # p = s*a + r receives gy[a - j] from tap d = r + s*j
    parts = []
    for rh in range(sh):
        row = []
        for rw in range(sw):
            acc = jnp.zeros((bb, lh, lw), gy.dtype)
            for jh in range(-(-(kh - rh) // sh)):
                dh = rh + sh * jh
                if dh >= kh:
                    continue
                for jw in range(-(-(kw - rw) // sw)):
                    dw = rw + sw * jw
                    if dw >= kw:
                        continue
                    t = dh * kw + dw
                    g = jnp.where(idx == t, gy, jnp.zeros((), gy.dtype))
                    nh, nw = min(ho, lh - jh), min(wo, lw - jw)
                    g = g[:, :nh, :nw]
                    # static pad to the residue grid (Mosaic-friendlier
                    # than an in-place strided update)
                    g = jnp.pad(g, ((0, 0), (jh, lh - jh - nh),
                                    (jw, lw - jw - nw)))
                    acc = acc + g
            row.append(acc)
        parts.append(row)

    if sh == 1 and sw == 1:
        dxp = parts[0][0]
    else:
        # interleave the residue grids: [bb, lh, sh, lw, sw] -> [bb, lh*sh, lw*sw]
        stacked = jnp.stack([jnp.stack(row, axis=-1) for row in parts], axis=2)
        dxp = stacked.reshape(bb, lh * sh, lw * sw)
    dx_ref[...] = lax.slice(dxp, (0, lo_h, lo_w),
                            (bb, lo_h + h, lo_w + w))


# ---------------------------------------------------------------------------
# custom-vjp wrapper
# ---------------------------------------------------------------------------

def maxpool_argmax(x, dims, strides, pads):
    """Max pooling over the trailing (H, W) axes of an NCHW tensor with
    first-argmax gradient routing via a saved int8 tap index.  Drop-in
    for ``lax.reduce_window(max)`` under the support predicate
    ``pallas_pool_supported``."""
    return _pool(x, dims, strides, tuple(pads), x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _pool(x, dims, strides, pads, xshape):
    # undifferentiated primal (inference/eval): plain reduce_window —
    # identical values, fully XLA-fusable, no wasted idx write.  The
    # Pallas (y, idx) forward runs only under differentiation (_vjp_fwd).
    return lax.reduce_window(x, _NEG, lax.max, dims, strides, pads)


def _fwd_impl(x, dims, strides, pads):
    n, c, h, w = x.shape
    kh, kw, sh, sw = dims[2], dims[3], strides[2], strides[3]
    hw_pads = (pads[2], pads[3])
    ho, wo, lh, lw = _geometry(h, w, kh, kw, sh, sw, hw_pads)
    b = n * c
    xr = x.reshape(b, h, w)
    esz = x.dtype.itemsize
    bb = _pick_block(b, _row_bytes(h, w, ho, wo, lh, lw, sh, sw, esz))
    kern = functools.partial(_fwd_kernel, kh=kh, kw=kw, sh=sh, sw=sw,
                             pads=hw_pads, ho=ho, wo=wo, lh=lh, lw=lw)
    y, idx = pl.pallas_call(
        kern,
        grid=(b // bb,),
        in_specs=[pl.BlockSpec((bb, h, w), lambda i: (i, 0, 0))],
        out_specs=[pl.BlockSpec((bb, ho, wo), lambda i: (i, 0, 0)),
                   pl.BlockSpec((bb, ho, wo), lambda i: (i, 0, 0))],
        out_shape=[jax.ShapeDtypeStruct((b, ho, wo), x.dtype),
                   jax.ShapeDtypeStruct((b, ho, wo), jnp.int8)],
        interpret=_use_interpret(),
    )(xr)
    return y.reshape(n, c, ho, wo), idx


def _vjp_fwd(x, dims, strides, pads, xshape):
    y, idx = _fwd_impl(x, dims, strides, pads)
    return y, idx


def _vjp_bwd(dims, strides, pads, xshape, idx, gy):
    n, c, h, w = xshape
    x_dtype = gy.dtype
    kh, kw, sh, sw = dims[2], dims[3], strides[2], strides[3]
    hw_pads = (pads[2], pads[3])
    ho, wo, lh, lw = _geometry(h, w, kh, kw, sh, sw, hw_pads)
    b = n * c
    gyr = gy.reshape(b, ho, wo)
    esz = jnp.dtype(x_dtype).itemsize
    bb = _pick_block(b, _row_bytes(h, w, ho, wo, lh, lw, sh, sw, esz))
    kern = functools.partial(_bwd_kernel, kh=kh, kw=kw, sh=sh, sw=sw,
                             pads=hw_pads, h=h, w=w, lh=lh, lw=lw)
    dx = pl.pallas_call(
        kern,
        grid=(b // bb,),
        in_specs=[pl.BlockSpec((bb, ho, wo), lambda i: (i, 0, 0)),
                  pl.BlockSpec((bb, ho, wo), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((bb, h, w), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, w), x_dtype),
        interpret=_use_interpret(),
    )(gyr, idx)
    return (dx.reshape(n, c, h, w),)


_pool.defvjp(_vjp_fwd, _vjp_bwd)
