"""Shared plumbing for the Pallas kernel modules: the ONE home for the
VMEM budget, the Mosaic dtype set, and the per-plane launcher — so the
support predicates in lrn_pallas/norm_pallas/pool_pallas can never
drift apart (a budget tuned in one module but not another would route
the same shape to different backends per op)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["VMEM_BUDGET", "TPU_DTYPES", "mosaic_dtype", "plane_call"]

#: per-block VMEM budget (bytes) — conservative vs the 16 MB/core arena
VMEM_BUDGET = 4 * 1024 * 1024

#: dtypes Mosaic compiles; anything else (f64 in the numeric-grad
#: suite) is interpret/XLA-only
TPU_DTYPES = (jnp.float32, jnp.bfloat16, jnp.float16)


def mosaic_dtype(dtype) -> bool:
    return dtype in TPU_DTYPES


def plane_call(kernel, inputs, out_shapes, b, interpret: bool,
               bcast=()):
    """Launcher over [B, *, *] plane stacks: grid (B,), one full
    (padded) plane per block — spatial windows need no neighbor blocks
    this way, at plane sizes (<= ~224x224 f32 = 200 KB) far under the
    VMEM budget.

    ``inputs``: arrays whose leading dim is B, except indices listed in
    ``bcast`` which are shared by every block verbatim (divisor planes,
    smoothing kernels).  ``out_shapes``: [(per-plane shape, dtype), ...]
    — a single entry returns the bare array."""
    from jax.experimental import pallas as pl

    in_specs = []
    for idx, a in enumerate(inputs):
        if idx in bcast:
            in_specs.append(
                pl.BlockSpec(a.shape, lambda i, nd=a.ndim: (0,) * nd))
        else:
            in_specs.append(
                pl.BlockSpec((1,) + a.shape[1:],
                             lambda i, nd=a.ndim: (i,) + (0,) * (nd - 1)))
    multi = len(out_shapes) > 1
    out_specs = [pl.BlockSpec((1,) + s, lambda i, nd=len(s): (i,) + (0,) * nd)
                 for s, _ in out_shapes]
    out_shape = [jax.ShapeDtypeStruct((b,) + s, d) for s, d in out_shapes]
    return pl.pallas_call(
        kernel, grid=(b,), in_specs=in_specs,
        out_specs=out_specs if multi else out_specs[0],
        out_shape=out_shape if multi else out_shape[0],
        interpret=interpret,
    )(*inputs)
