"""Kernel dispatch: one knob, per-op fallback, observable decisions.

The ops library keeps TWO implementations of every fused op — a Pallas
kernel (Mosaic-compiled on TPU, ``interpret=True`` elsewhere so the CPU
tier-1 suite exercises the identical code path) and an XLA reference
built from the same math.  Both sit UNDER the op's ``jax.custom_vjp``,
so the analytically exact backward holds on either leg; this module
decides which leg runs.

Knob: ``BIGDL_KERNELS`` (read at trace time):

- ``auto`` (default) — Pallas on TPU hardware when the op's support
  predicate admits the shape/dtype; XLA everywhere else.  CPU runs keep
  their fused-XLA paths, so enabling telemetry or running the tier-1
  suite never silently drops onto the (slow) Pallas interpreter.
- ``pallas`` — Pallas whenever the shape is structurally supported;
  off-TPU this means interpret mode (the parity tests' setting).
- ``xla`` — the reference leg everywhere, a process-wide kill switch.

Every decision is emitted as a ``kernel/dispatch`` telemetry instant
(op, backend, reason) at TRACE time — one instant per compilation, not
per step — so PR 4's attribution can say which backend each module's
HLO actually contains.  A small in-process ring (:func:`decisions`)
records the same tuples for tests and the micro-bench harness.

Caveat: the knob is read when a function is traced.  A jit-cached
executable does not re-dispatch when the env changes; tests flip the
env with fresh shapes (or eagerly) for exactly this reason.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Callable, Deque, List, Tuple

from bigdl_tpu import telemetry

__all__ = ["kernel_mode", "choose_backend", "dispatch", "use_interpret",
           "decisions", "clear_decisions", "MODES"]

MODES = ("auto", "pallas", "xla")

#: last N (op, backend, reason) decisions, trace-time order
_DECISIONS: Deque[Tuple[str, str, str]] = deque(maxlen=256)


def kernel_mode() -> str:
    """The process-wide kernel mode from ``BIGDL_KERNELS``.

    Raises on an unknown value instead of silently defaulting — a typo'd
    sweep leg comparing ``pallas`` against ``palas`` must fail loudly,
    not bench two identical XLA runs (same policy as
    ``flash_min_seq``)."""
    raw = os.environ.get("BIGDL_KERNELS", "auto")
    if raw not in MODES:
        raise ValueError(
            f"BIGDL_KERNELS={raw!r} is not one of {'|'.join(MODES)}")
    return raw


def use_interpret() -> bool:
    """Pallas interpret mode off-TPU (device check, not backend name —
    the round-4 proxied-PJRT gating bug)."""
    from bigdl_tpu.ops.attention import is_tpu_device

    return not is_tpu_device()


def choose_backend(op: str, supported: bool) -> Tuple[str, str]:
    """(backend, reason) for one op instance; backend in {pallas, xla}."""
    mode = kernel_mode()
    if mode == "xla":
        return "xla", "forced:BIGDL_KERNELS=xla"
    if not supported:
        return "xla", "unsupported-shape"
    if mode == "pallas":
        return "pallas", "forced:BIGDL_KERNELS=pallas"
    from bigdl_tpu.ops.attention import is_tpu_device

    if is_tpu_device():
        return "pallas", "auto:tpu"
    return "xla", "auto:off-tpu"


def note(op: str, backend: str, reason: str) -> None:
    """Record + emit one dispatch decision (shared by :func:`dispatch`
    and call sites with bespoke selection logic, e.g. the argmax pool
    and the attention auto-backend)."""
    _DECISIONS.append((op, backend, reason))
    telemetry.instant("kernel/dispatch", op=op, backend=backend,
                      reason=reason)


def dispatch(op: str, pallas_fn: Callable, xla_fn: Callable,
             supported: bool, *args, **kwargs):
    """Run ``pallas_fn`` or ``xla_fn`` per :func:`choose_backend`,
    recording the decision.  Called at trace time inside the op's
    custom-vjp forward/backward rules."""
    backend, reason = choose_backend(op, supported)
    note(op, backend, reason)
    fn = pallas_fn if backend == "pallas" else xla_fn
    return fn(*args, **kwargs)


def decisions() -> List[Tuple[str, str, str]]:
    """Recent (op, backend, reason) tuples — test/bench introspection."""
    return list(_DECISIONS)


def clear_decisions() -> None:
    _DECISIONS.clear()
