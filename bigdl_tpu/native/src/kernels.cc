// CPU oracle kernels — the TPU-native counterpart of the reference's
// BigDL-core native layer (mkl-java/bigdl-native JNI, SURVEY §2.1:
// BLAS gemm/gemv/ger/axpy/dot/scal + VML Add/Sub/Mul/Div/Powx/Ln/Exp/
// Sqrt/Tanh/Log1p/Abs, consumed at tensor/TensorNumeric.scala:457-530).
// On TPU the hot path is XLA (MXU/VPU); these kernels are the host-side
// numeric oracle used by the test suite and as a CPU fallback runtime.
#include <cstdint>
#include <cstddef>
#include <cmath>
#include <cstring>
#include <algorithm>

extern "C" {

// ---------- BLAS (row-agnostic: column-major like Fortran/MKL) ----------
// C[m,n] = alpha * op(A) @ op(B) + beta * C ; lda/ldb/ldc leading dims.
void bigdl_sgemm(char transa, char transb, int m, int n, int k, float alpha,
                 const float* A, int lda, const float* B, int ldb, float beta,
                 float* C, int ldc) {
  const bool ta = (transa == 'T' || transa == 't');
  const bool tb = (transb == 'T' || transb == 't');
  for (int j = 0; j < n; ++j) {
    for (int i = 0; i < m; ++i) {
      double acc = 0.0;
      for (int p = 0; p < k; ++p) {
        const float a = ta ? A[i * lda + p] : A[p * lda + i];
        const float b = tb ? B[p * ldb + j] : B[j * ldb + p];
        acc += (double)a * b;
      }
      C[j * ldc + i] = alpha * (float)acc + beta * C[j * ldc + i];
    }
  }
}

void bigdl_dgemm(char transa, char transb, int m, int n, int k, double alpha,
                 const double* A, int lda, const double* B, int ldb,
                 double beta, double* C, int ldc) {
  const bool ta = (transa == 'T' || transa == 't');
  const bool tb = (transb == 'T' || transb == 't');
  for (int j = 0; j < n; ++j) {
    for (int i = 0; i < m; ++i) {
      double acc = 0.0;
      for (int p = 0; p < k; ++p) {
        const double a = ta ? A[i * lda + p] : A[p * lda + i];
        const double b = tb ? B[p * ldb + j] : B[j * ldb + p];
        acc += a * b;
      }
      C[j * ldc + i] = alpha * acc + beta * C[j * ldc + i];
    }
  }
}

void bigdl_sgemv(char trans, int m, int n, float alpha, const float* A,
                 int lda, const float* x, int incx, float beta, float* y,
                 int incy) {
  const bool t = (trans == 'T' || trans == 't');
  const int ylen = t ? n : m;
  const int xlen = t ? m : n;
  for (int i = 0; i < ylen; ++i) {
    double acc = 0.0;
    for (int j = 0; j < xlen; ++j) {
      const float a = t ? A[i * lda + j] : A[j * lda + i];
      acc += (double)a * x[j * incx];
    }
    y[i * incy] = alpha * (float)acc + beta * y[i * incy];
  }
}

void bigdl_sger(int m, int n, float alpha, const float* x, int incx,
                const float* y, int incy, float* A, int lda) {
  for (int j = 0; j < n; ++j)
    for (int i = 0; i < m; ++i)
      A[j * lda + i] += alpha * x[i * incx] * y[j * incy];
}

void bigdl_saxpy(int n, float a, const float* x, int incx, float* y,
                 int incy) {
  for (int i = 0; i < n; ++i) y[i * incy] += a * x[i * incx];
}

float bigdl_sdot(int n, const float* x, int incx, const float* y, int incy) {
  double acc = 0.0;
  for (int i = 0; i < n; ++i) acc += (double)x[i * incx] * y[i * incy];
  return (float)acc;
}

void bigdl_sscal(int n, float a, float* x, int incx) {
  for (int i = 0; i < n; ++i) x[i * incx] *= a;
}

// ---------- VML elementwise (float32) ----------
#define VML_BINOP(name, expr)                                            \
  void bigdl_vs##name(int n, const float* a, const float* b, float* y) { \
    for (int i = 0; i < n; ++i) y[i] = (expr);                           \
  }
VML_BINOP(Add, a[i] + b[i])
VML_BINOP(Sub, a[i] - b[i])
VML_BINOP(Mul, a[i] * b[i])
VML_BINOP(Div, a[i] / b[i])
#undef VML_BINOP

#define VML_UNOP(name, expr)                                  \
  void bigdl_vs##name(int n, const float* a, float* y) {      \
    for (int i = 0; i < n; ++i) y[i] = (expr);                \
  }
VML_UNOP(Ln, std::log(a[i]))
VML_UNOP(Exp, std::exp(a[i]))
VML_UNOP(Sqrt, std::sqrt(a[i]))
VML_UNOP(Tanh, std::tanh(a[i]))
VML_UNOP(Log1p, std::log1p(a[i]))
VML_UNOP(Abs, std::fabs(a[i]))
#undef VML_UNOP

void bigdl_vsPowx(int n, const float* a, float b, float* y) {
  for (int i = 0; i < n; ++i) y[i] = std::pow(a[i], b);
}

// ---------- NN primitives (reference nn/NNPrimitive.scala hot loops) ----
// im2col, NCHW. input [C,H,W] -> cols [C*kh*kw, outH*outW]
void bigdl_im2col(const float* img, int channels, int h, int w, int kh,
                  int kw, int sh, int sw, int ph, int pw, float* cols) {
  const int out_h = (h + 2 * ph - kh) / sh + 1;
  const int out_w = (w + 2 * pw - kw) / sw + 1;
  const int ck = channels * kh * kw;
  for (int c = 0; c < ck; ++c) {
    const int woff = c % kw;
    const int hoff = (c / kw) % kh;
    const int cim = c / (kh * kw);
    for (int oh = 0; oh < out_h; ++oh) {
      const int ih = oh * sh - ph + hoff;
      for (int ow = 0; ow < out_w; ++ow) {
        const int iw = ow * sw - pw + woff;
        cols[(c * out_h + oh) * out_w + ow] =
            (ih >= 0 && ih < h && iw >= 0 && iw < w)
                ? img[(cim * h + ih) * w + iw]
                : 0.0f;
      }
    }
  }
}

// col2im: scatter-add inverse of im2col
void bigdl_col2im(const float* cols, int channels, int h, int w, int kh,
                  int kw, int sh, int sw, int ph, int pw, float* img) {
  const int out_h = (h + 2 * ph - kh) / sh + 1;
  const int out_w = (w + 2 * pw - kw) / sw + 1;
  const int ck = channels * kh * kw;
  std::memset(img, 0, sizeof(float) * channels * h * w);
  for (int c = 0; c < ck; ++c) {
    const int woff = c % kw;
    const int hoff = (c / kw) % kh;
    const int cim = c / (kh * kw);
    for (int oh = 0; oh < out_h; ++oh) {
      const int ih = oh * sh - ph + hoff;
      if (ih < 0 || ih >= h) continue;
      for (int ow = 0; ow < out_w; ++ow) {
        const int iw = ow * sw - pw + woff;
        if (iw >= 0 && iw < w)
          img[(cim * h + ih) * w + iw] += cols[(c * out_h + oh) * out_w + ow];
      }
    }
  }
}

// max-pool forward with argmax indices. input [C,H,W]
void bigdl_maxpool_fwd(const float* in, int channels, int h, int w, int kh,
                       int kw, int sh, int sw, int ph, int pw, float* out,
                       int32_t* idx) {
  const int out_h = (h + 2 * ph - kh) / sh + 1;
  const int out_w = (w + 2 * pw - kw) / sw + 1;
  for (int c = 0; c < channels; ++c) {
    for (int oh = 0; oh < out_h; ++oh) {
      for (int ow = 0; ow < out_w; ++ow) {
        float best = -3.4e38f;
        int32_t best_i = -1;
        for (int i = 0; i < kh; ++i) {
          const int ih = oh * sh - ph + i;
          if (ih < 0 || ih >= h) continue;
          for (int j = 0; j < kw; ++j) {
            const int iw = ow * sw - pw + j;
            if (iw < 0 || iw >= w) continue;
            const float v = in[(c * h + ih) * w + iw];
            if (v > best) { best = v; best_i = ih * w + iw; }
          }
        }
        out[(c * out_h + oh) * out_w + ow] = best;
        idx[(c * out_h + oh) * out_w + ow] = best_i;
      }
    }
  }
}

void bigdl_maxpool_bwd(const float* grad_out, const int32_t* idx,
                       int channels, int h, int w, int out_h, int out_w,
                       float* grad_in) {
  std::memset(grad_in, 0, sizeof(float) * channels * h * w);
  for (int c = 0; c < channels; ++c)
    for (int o = 0; o < out_h * out_w; ++o) {
      const int32_t i = idx[c * out_h * out_w + o];
      if (i >= 0) grad_in[c * h * w + i] += grad_out[c * out_h * out_w + o];
    }
}

}  // extern "C"
