// Batch tf.Example proto parsing — native counterpart of the
// reference's generated-protobuf record decode on its SequenceFile
// ingest path (utils/tf/TFRecordIterator + ParseExample,
// ops/ParseExample.scala).  The Python wire walker
// (bigdl_tpu/dataset/tfrecord.py parse_example) is the semantic
// reference; this kernel parses a BATCH of serialized records into
// caller-allocated dense buffers, multi-threaded, so ImageNet-rate
// ingestion does not serialize on the interpreter.
//
// Wire subset handled (same as the Python walker):
//   Example  := features(field 1: message Features)
//   Features := repeated feature(field 1: map entry)
//   entry    := key(field 1: string) value(field 2: message Feature)
//   Feature  := bytes_list(1) | float_list(2) | int64_list(3)
//   BytesList:= repeated value(field 1: bytes)
//   FloatList:= packed (wt 2) or repeated (wt 5) field 1
//   Int64List:= packed (wt 2) or repeated (wt 0) field 1
#include <cstdint>
#include <cstddef>
#include <cstring>
#include <thread>
#include <vector>
#include <algorithm>

namespace {

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
};

// Returns false on malformed varint / overrun.
bool read_varint(Cursor& c, uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (c.p < c.end && shift < 64) {
    const uint8_t b = *c.p++;
    v |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

// One wire field: tag -> (field number, wire type, payload view).
struct Field {
  uint32_t num;
  uint32_t wt;
  const uint8_t* data;  // wt 2: payload; else unused
  uint64_t len;         // wt 2: payload length; wt 0: varint value
};

bool next_field(Cursor& c, Field* f) {
  uint64_t tag;
  if (!read_varint(c, &tag)) return false;
  f->num = (uint32_t)(tag >> 3);
  f->wt = (uint32_t)(tag & 7);
  switch (f->wt) {
    case 0:
      return read_varint(c, &f->len);
    case 1:
      if (c.end - c.p < 8) return false;
      std::memcpy(&f->len, c.p, 8);
      c.p += 8;
      return true;
    case 2: {
      uint64_t n;
      if (!read_varint(c, &n)) return false;
      if ((uint64_t)(c.end - c.p) < n) return false;
      f->data = c.p;
      f->len = n;
      c.p += n;
      return true;
    }
    case 5:
      if (c.end - c.p < 4) return false;
      f->len = 0;
      std::memcpy(&f->len, c.p, 4);
      c.p += 4;
      return true;
    default:
      return false;
  }
}

// kinds for the extraction spec
enum Kind { BYTES_FIXED = 0, INT64_FIXED = 1, FLOAT_FIXED = 2 };

struct Spec {
  const char* key;
  size_t key_len;
  int kind;
  int64_t count;     // elements per record (bytes: payload length)
  uint8_t* out;      // [n, count * elem_size]
};

// Parse the Feature message for one spec'd key into out-slot `row`.
bool parse_feature(const uint8_t* data, uint64_t len, const Spec& s,
                   int64_t row) {
  Cursor c{data, data + len};
  Field f;
  while (c.p < c.end) {
    if (!next_field(c, &f)) return false;
    if (f.num == 1 && f.wt == 2 && s.kind == BYTES_FIXED) {
      // BytesList { value: bytes } — exactly ONE value; extra values
      // fail the record so native availability never changes parse
      // semantics (the Python fallback rejects multi-value BytesLists)
      Cursor b{f.data, f.data + f.len};
      Field bf;
      if (!next_field(b, &bf) || bf.num != 1 || bf.wt != 2) return false;
      if ((int64_t)bf.len != s.count) return false;
      if (b.p < b.end) return false;  // a second value in the list
      std::memcpy(s.out + (size_t)row * s.count, bf.data, bf.len);
      return true;
    }
    if (f.num == 3 && f.wt == 2 && s.kind == INT64_FIXED) {
      Cursor b{f.data, f.data + f.len};
      Field bf;
      int64_t* dst = (int64_t*)(s.out + (size_t)row * s.count * 8);
      int64_t got = 0;
      while (b.p < b.end) {
        if (!next_field(b, &bf) || bf.num != 1) return false;
        if (bf.wt == 0) {
          if (got >= s.count) return false;
          dst[got++] = (int64_t)bf.len;
        } else if (bf.wt == 2) {  // packed
          Cursor pk{bf.data, bf.data + bf.len};
          uint64_t v;
          while (pk.p < pk.end) {
            if (!read_varint(pk, &v) || got >= s.count) return false;
            dst[got++] = (int64_t)v;
          }
        } else {
          return false;
        }
      }
      return got == s.count;
    }
    if (f.num == 2 && f.wt == 2 && s.kind == FLOAT_FIXED) {
      Cursor b{f.data, f.data + f.len};
      Field bf;
      float* dst = (float*)(s.out + (size_t)row * s.count * 4);
      int64_t got = 0;
      while (b.p < b.end) {
        if (!next_field(b, &bf) || bf.num != 1) return false;
        if (bf.wt == 5) {
          if (got >= s.count) return false;
          uint32_t raw = (uint32_t)bf.len;
          std::memcpy(&dst[got++], &raw, 4);
        } else if (bf.wt == 2) {  // packed
          if (bf.len % 4 || (int64_t)(bf.len / 4) + got > s.count)
            return false;
          std::memcpy(dst + got, bf.data, bf.len);
          got += bf.len / 4;
        } else {
          return false;
        }
      }
      return got == s.count;
    }
  }
  return false;  // wrong kind for this key
}

// One record: walk Example -> Features -> entries, fill every spec'd key.
bool parse_record(const uint8_t* rec, uint64_t len, const Spec* specs,
                  int nspec, int64_t row) {
  std::vector<bool> found(nspec, false);
  Cursor c{rec, rec + len};
  Field f;
  while (c.p < c.end) {
    if (!next_field(c, &f)) return false;
    if (f.num != 1 || f.wt != 2) continue;  // not Features
    Cursor fc{f.data, f.data + f.len};
    Field ff;
    while (fc.p < fc.end) {
      if (!next_field(fc, &ff)) return false;
      if (ff.num != 1 || ff.wt != 2) continue;  // not a map entry
      Cursor ec{ff.data, ff.data + ff.len};
      Field ef;
      const uint8_t* key = nullptr;
      uint64_t key_len = 0;
      const uint8_t* val = nullptr;
      uint64_t val_len = 0;
      while (ec.p < ec.end) {
        if (!next_field(ec, &ef)) return false;
        if (ef.num == 1 && ef.wt == 2) {
          key = ef.data;
          key_len = ef.len;
        } else if (ef.num == 2 && ef.wt == 2) {
          val = ef.data;
          val_len = ef.len;
        }
      }
      if (!key || !val) continue;
      for (int s = 0; s < nspec; ++s) {
        if (key_len == specs[s].key_len &&
            std::memcmp(key, specs[s].key, key_len) == 0) {
          if (!parse_feature(val, val_len, specs[s], row)) return false;
          found[s] = true;
        }
      }
    }
  }
  for (int s = 0; s < nspec; ++s)
    if (!found[s]) return false;
  return true;
}

}  // namespace

extern "C" {

// blob: concatenated serialized records; offsets: n+1 int64 boundaries.
// keys/kinds/counts/outs: nspec parallel arrays (outs are caller-
// allocated row-major buffers).  Returns 0 on success, or -(i+1) where
// i is the first failing record index.
int64_t bigdl_parse_examples(const uint8_t* blob, const int64_t* offsets,
                             int64_t n, const char** keys,
                             const int32_t* kinds, const int64_t* counts,
                             uint8_t** outs, int32_t nspec,
                             int32_t num_threads) {
  std::vector<Spec> specs((size_t)nspec);
  for (int s = 0; s < nspec; ++s)
    specs[s] = Spec{keys[s], std::strlen(keys[s]), kinds[s], counts[s],
                    outs[s]};
  if (num_threads <= 0)
    num_threads = (int)std::thread::hardware_concurrency();
  num_threads = std::max(1, std::min<int>(num_threads, (int)n));
  std::vector<int64_t> fail((size_t)num_threads, 0);
  auto work = [&](int t, int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      if (!parse_record(blob + offsets[i],
                        (uint64_t)(offsets[i + 1] - offsets[i]),
                        specs.data(), nspec, i)) {
        fail[t] = -(i + 1);
        return;
      }
    }
  };
  std::vector<std::thread> ts;
  const int64_t chunk = (n + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    const int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(work, t, lo, hi);
  }
  for (auto& t : ts) t.join();
  for (int t = 0; t < num_threads; ++t)
    if (fail[t] != 0) return fail[t];
  return 0;
}

}  // extern "C"
