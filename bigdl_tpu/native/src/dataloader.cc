// Multi-threaded batch assembly — native counterpart of the reference's
// MTLabeledBGRImgToBatch (dataset/image/MTLabeledBGRImgToBatch.scala):
// crop + flip + channel-normalize a stack of uint8 HWC images into one
// float32 NCHW batch, parallel over images with std::thread.
#include <cstdint>
#include <cstddef>
#include <thread>
#include <vector>
#include <algorithm>

extern "C" {

// imgs: N contiguous uint8 images [H, W, C]; out: [N, C, ch, cw] float32.
// crop offsets per image (oy[i], ox[i]); flip[i] != 0 => horizontal flip;
// mean/std per channel (length C).
void bigdl_batch_crop_normalize(const uint8_t* imgs, int n, int h, int w,
                                int c, int ch, int cw, const int32_t* oy,
                                const int32_t* ox, const uint8_t* flip,
                                const float* mean, const float* stdd,
                                float* out, int num_threads) {
  if (num_threads <= 0)
    num_threads = (int)std::thread::hardware_concurrency();
  num_threads = std::max(1, std::min(num_threads, n));
  auto work = [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      const uint8_t* img = imgs + (size_t)i * h * w * c;
      float* dst = out + (size_t)i * c * ch * cw;
      for (int y = 0; y < ch; ++y) {
        const int sy = oy[i] + y;
        for (int x = 0; x < cw; ++x) {
          const int sx = flip[i] ? (ox[i] + cw - 1 - x) : (ox[i] + x);
          const uint8_t* px = img + ((size_t)sy * w + sx) * c;
          for (int k = 0; k < c; ++k)
            dst[((size_t)k * ch + y) * cw + x] =
                ((float)px[k] - mean[k]) / stdd[k];
        }
      }
    }
  };
  std::vector<std::thread> ts;
  const int chunk = (n + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    const int lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(work, lo, hi);
  }
  for (auto& t : ts) t.join();
}

}  // extern "C"
