// CRC32C (Castagnoli) — slice-by-8 software implementation, plus the
// TFRecord "masked" variant. TPU-native counterpart of the reference's
// netty/Crc32c.java (consumed by visualization/tensorboard/RecordWriter.scala:30).
#include <cstdint>
#include <cstddef>

namespace {

constexpr uint32_t kPoly = 0x82F63B78u;  // reflected CRC32C polynomial

struct Tables {
  uint32_t t[8][256];
  Tables() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t crc = i;
      for (int k = 0; k < 8; ++k)
        crc = (crc >> 1) ^ ((crc & 1) ? kPoly : 0);
      t[0][i] = crc;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int s = 1; s < 8; ++s)
        t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xFF];
  }
};

const Tables kTables;

}  // namespace

extern "C" {

uint32_t bigdl_crc32c(const uint8_t* data, size_t n, uint32_t crc_in) {
  uint32_t crc = ~crc_in;
  const uint32_t (*t)[256] = kTables.t;
  while (n >= 8) {
    crc ^= (uint32_t)data[0] | ((uint32_t)data[1] << 8) |
           ((uint32_t)data[2] << 16) | ((uint32_t)data[3] << 24);
    uint32_t hi = (uint32_t)data[4] | ((uint32_t)data[5] << 8) |
                  ((uint32_t)data[6] << 16) | ((uint32_t)data[7] << 24);
    crc = t[7][crc & 0xFF] ^ t[6][(crc >> 8) & 0xFF] ^
          t[5][(crc >> 16) & 0xFF] ^ t[4][crc >> 24] ^
          t[3][hi & 0xFF] ^ t[2][(hi >> 8) & 0xFF] ^
          t[1][(hi >> 16) & 0xFF] ^ t[0][hi >> 24];
    data += 8;
    n -= 8;
  }
  while (n--) crc = t[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// TFRecord masking: ((crc >> 15) | (crc << 17)) + 0xa282ead8
uint32_t bigdl_masked_crc32c(const uint8_t* data, size_t n) {
  uint32_t crc = bigdl_crc32c(data, n, 0);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

}  // extern "C"
