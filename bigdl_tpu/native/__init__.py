"""Native C++ runtime components — the TPU-native counterpart of the
reference's BigDL-core JNI layer (SURVEY §2.1: ``mkl-java``/``bigdl-native``
consumed through ``com.intel.analytics.bigdl.mkl.MKL``, plus
``netty/Crc32c.java``).

On TPU the *device* hot path is XLA-compiled (MXU for gemm, VPU for
elementwise); what stays native here is exactly what stays native in the
reference's runtime:

- masked **CRC32C** for TFRecord/TensorBoard event framing
  (``visualization/tensorboard/RecordWriter.scala:30``),
- CPU **oracle kernels** (BLAS gemm/gemv/ger/axpy/dot/scal, VML
  elementwise, im2col/col2im, maxpool fwd/bwd — the reference's
  ``tensor/DenseTensorBLAS.scala`` + ``nn/NNPrimitive.scala`` hot loops)
  used as the host-side ground truth by the test suite,
- the **multi-threaded batch assembler** for the input pipeline
  (``dataset/image/MTLabeledBGRImgToBatch.scala``).

The shared library is compiled from ``src/*.cc`` with ``make`` on first use
and bound via ctypes; every entry point has a pure-NumPy fallback so the
package works without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libbigdl_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False
_disabled = False  # no_native seen -> short-circuit (hot paths)
_disabled_env: Optional[str] = None   # BIGDL_TPU_NO_NATIVE when latched
_disabled_cfg = None                  # installed config object when latched


def _try_load() -> Optional[ctypes.CDLL]:
    """Build (once) and load the native library; None if unavailable."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    global _disabled, _disabled_env, _disabled_cfg
    if _build_failed:
        return None
    from bigdl_tpu.utils import config as _cfgmod
    from bigdl_tpu.utils.config import get_config

    if _disabled:
        # latched while no_native was truthy; stay latched only while
        # BOTH knob sources (env var, installed config) are unchanged so
        # clearing either re-enables native like every other BIGDL_* knob
        if (os.environ.get("BIGDL_TPU_NO_NATIVE") == _disabled_env
                and _cfgmod._config is _disabled_cfg):
            return None
        _disabled = False
    if get_config().no_native:
        # cache the decision: _try_load sits on per-record hot paths
        # (crc32c framing), so don't re-resolve the config every call
        _disabled = True
        _disabled_env = os.environ.get("BIGDL_TPU_NO_NATIVE")
        _disabled_cfg = _cfgmod._config
        return None
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            try:
                subprocess.run(["make", "-s"], cwd=_DIR, check=True,
                               capture_output=True, timeout=120)
            except Exception:
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None
        # -- signatures --------------------------------------------------
        u8p = ctypes.POINTER(ctypes.c_uint8)
        f32p = ctypes.POINTER(ctypes.c_float)
        f64p = ctypes.POINTER(ctypes.c_double)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.bigdl_crc32c.restype = ctypes.c_uint32
        lib.bigdl_crc32c.argtypes = [u8p, ctypes.c_size_t, ctypes.c_uint32]
        lib.bigdl_masked_crc32c.restype = ctypes.c_uint32
        lib.bigdl_masked_crc32c.argtypes = [u8p, ctypes.c_size_t]
        lib.bigdl_sgemm.argtypes = [
            ctypes.c_char, ctypes.c_char, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_float, f32p, ctypes.c_int, f32p,
            ctypes.c_int, ctypes.c_float, f32p, ctypes.c_int]
        lib.bigdl_dgemm.argtypes = [
            ctypes.c_char, ctypes.c_char, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_double, f64p, ctypes.c_int, f64p,
            ctypes.c_int, ctypes.c_double, f64p, ctypes.c_int]
        lib.bigdl_sgemv.argtypes = [
            ctypes.c_char, ctypes.c_int, ctypes.c_int, ctypes.c_float,
            f32p, ctypes.c_int, f32p, ctypes.c_int, ctypes.c_float, f32p,
            ctypes.c_int]
        lib.bigdl_sger.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_float, f32p, ctypes.c_int,
            f32p, ctypes.c_int, f32p, ctypes.c_int]
        lib.bigdl_saxpy.argtypes = [ctypes.c_int, ctypes.c_float, f32p,
                                    ctypes.c_int, f32p, ctypes.c_int]
        lib.bigdl_sdot.restype = ctypes.c_float
        lib.bigdl_sdot.argtypes = [ctypes.c_int, f32p, ctypes.c_int, f32p,
                                   ctypes.c_int]
        lib.bigdl_sscal.argtypes = [ctypes.c_int, ctypes.c_float, f32p,
                                    ctypes.c_int]
        for nm in ("Add", "Sub", "Mul", "Div"):
            getattr(lib, f"bigdl_vs{nm}").argtypes = [ctypes.c_int, f32p,
                                                      f32p, f32p]
        for nm in ("Ln", "Exp", "Sqrt", "Tanh", "Log1p", "Abs"):
            getattr(lib, f"bigdl_vs{nm}").argtypes = [ctypes.c_int, f32p, f32p]
        lib.bigdl_vsPowx.argtypes = [ctypes.c_int, f32p, ctypes.c_float, f32p]
        lib.bigdl_im2col.argtypes = [f32p] + [ctypes.c_int] * 9 + [f32p]
        lib.bigdl_col2im.argtypes = [f32p] + [ctypes.c_int] * 9 + [f32p]
        lib.bigdl_maxpool_fwd.argtypes = \
            [f32p] + [ctypes.c_int] * 9 + [f32p, i32p]
        lib.bigdl_maxpool_bwd.argtypes = \
            [f32p, i32p] + [ctypes.c_int] * 5 + [f32p]
        lib.bigdl_batch_crop_normalize.argtypes = [
            u8p] + [ctypes.c_int] * 6 + [i32p, i32p, u8p, f32p, f32p, f32p,
                                         ctypes.c_int]
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.bigdl_parse_examples.restype = ctypes.c_int64
        lib.bigdl_parse_examples.argtypes = [
            u8p, i64p, ctypes.c_int64, ctypes.POINTER(ctypes.c_char_p),
            i32p, i64p, ctypes.POINTER(u8p), ctypes.c_int32,
            ctypes.c_int32]
        _lib = lib
        return _lib


def is_native_loaded() -> bool:
    """Analogue of the reference's ``MKL.isMKLLoaded`` guard."""
    return _try_load() is not None


def _u8(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _f32(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i32(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


# ---------------------------------------------------------------------------
# CRC32C
# ---------------------------------------------------------------------------
_CRC_TABLE = None


def _crc_table():
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        tbl = np.zeros(256, np.uint32)
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (poly if crc & 1 else 0)
            tbl[i] = crc
        _CRC_TABLE = tbl
    return _CRC_TABLE


def crc32c(data: bytes, crc: int = 0) -> int:
    lib = _try_load()
    buf = np.frombuffer(data, np.uint8)
    if lib is not None:
        return int(lib.bigdl_crc32c(_u8(buf), len(buf),
                                    ctypes.c_uint32(crc)))
    tbl = _crc_table()
    c = (~crc) & 0xFFFFFFFF
    for b in buf.tolist():
        c = int(tbl[(c ^ b) & 0xFF]) ^ (c >> 8)
    return (~c) & 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    """TFRecord masked CRC (``netty/Crc32c.java`` semantics)."""
    c = crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Oracle BLAS / VML (float32; column-major gemm like the MKL interface)
# ---------------------------------------------------------------------------
def gemm(transa: str, transb: str, alpha, A: np.ndarray, B: np.ndarray,
         beta, C: np.ndarray) -> np.ndarray:
    """Column-major gemm on 2-D float32/float64 arrays stored Fortran-order.
    Mirrors ``tensor/DenseTensorBLAS.scala:70-112``."""
    m, n = C.shape
    k = A.shape[1] if transa.upper() == "N" else A.shape[0]
    lib = _try_load()
    dt = A.dtype
    if lib is not None and dt == np.float32:
        Af = np.asfortranarray(A, np.float32)
        Bf = np.asfortranarray(B, np.float32)
        Cf = np.asfortranarray(C, np.float32)
        lib.bigdl_sgemm(transa.encode()[:1], transb.encode()[:1], m, n, k,
                        np.float32(alpha), _f32(Af), Af.shape[0], _f32(Bf),
                        Bf.shape[0], np.float32(beta), _f32(Cf), Cf.shape[0])
        return np.ascontiguousarray(Cf)
    Aop = A.T if transa.upper() == "T" else A
    Bop = B.T if transb.upper() == "T" else B
    return (alpha * (Aop @ Bop) + beta * C).astype(dt)


def vml(op: str, a: np.ndarray, b=None) -> np.ndarray:
    """Elementwise oracle: op in Add/Sub/Mul/Div/Ln/Exp/Sqrt/Tanh/Log1p/
    Abs/Powx (b = scalar exponent for Powx)."""
    lib = _try_load()
    a = np.ascontiguousarray(a, np.float32)
    if lib is not None:
        y = np.empty_like(a)
        n = a.size
        if op in ("Add", "Sub", "Mul", "Div"):
            bb = np.ascontiguousarray(b, np.float32)
            getattr(lib, f"bigdl_vs{op}")(n, _f32(a), _f32(bb), _f32(y))
        elif op == "Powx":
            lib.bigdl_vsPowx(n, _f32(a), np.float32(b), _f32(y))
        else:
            getattr(lib, f"bigdl_vs{op}")(n, _f32(a), _f32(y))
        return y
    fns = {"Add": np.add, "Sub": np.subtract, "Mul": np.multiply,
           "Div": np.divide, "Ln": np.log, "Exp": np.exp, "Sqrt": np.sqrt,
           "Tanh": np.tanh, "Log1p": np.log1p, "Abs": np.abs}
    if op == "Powx":
        return np.power(a, np.float32(b))
    return fns[op](a, b) if b is not None and op in ("Add", "Sub", "Mul",
                                                     "Div") else fns[op](a)


# ---------------------------------------------------------------------------
# NN primitives (oracle for conv/pool tests)
# ---------------------------------------------------------------------------
def im2col(img: np.ndarray, kh, kw, sh, sw, ph, pw) -> np.ndarray:
    c, h, w = img.shape
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    lib = _try_load()
    img = np.ascontiguousarray(img, np.float32)
    if lib is not None:
        cols = np.empty((c * kh * kw, oh * ow), np.float32)
        lib.bigdl_im2col(_f32(img), c, h, w, kh, kw, sh, sw, ph, pw,
                         _f32(cols))
        return cols
    padded = np.pad(img, ((0, 0), (ph, ph), (pw, pw)))
    cols = np.empty((c * kh * kw, oh * ow), np.float32)
    for idx in range(c * kh * kw):
        j, i, ci = idx % kw, (idx // kw) % kh, idx // (kh * kw)
        patch = padded[ci, i:i + oh * sh:sh, j:j + ow * sw:sw]
        cols[idx] = patch.reshape(-1)
    return cols


def maxpool_fwd(x: np.ndarray, kh, kw, sh, sw, ph, pw):
    c, h, w = x.shape
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    lib = _try_load()
    x = np.ascontiguousarray(x, np.float32)
    if lib is not None:
        out = np.empty((c, oh, ow), np.float32)
        idx = np.empty((c, oh, ow), np.int32)
        lib.bigdl_maxpool_fwd(_f32(x), c, h, w, kh, kw, sh, sw, ph, pw,
                              _f32(out), _i32(idx))
        return out, idx
    out = np.full((c, oh, ow), -np.inf, np.float32)
    idx = np.full((c, oh, ow), -1, np.int32)
    for ci in range(c):
        for y in range(oh):
            for xx in range(ow):
                for i in range(kh):
                    ih = y * sh - ph + i
                    if not 0 <= ih < h:
                        continue
                    for j in range(kw):
                        iw = xx * sw - pw + j
                        if 0 <= iw < w and x[ci, ih, iw] > out[ci, y, xx]:
                            out[ci, y, xx] = x[ci, ih, iw]
                            idx[ci, y, xx] = ih * w + iw
    return out, idx


# ---------------------------------------------------------------------------
# Multithreaded batch assembly (native data-loader hot loop)
# ---------------------------------------------------------------------------
def batch_crop_normalize(imgs: np.ndarray, crop_h: int, crop_w: int,
                         oy: np.ndarray, ox: np.ndarray, flip: np.ndarray,
                         mean, std, num_threads: int = 0) -> np.ndarray:
    """uint8 [N,H,W,C] -> float32 [N,C,crop_h,crop_w] with per-image crop
    offsets, horizontal flips, and channel normalization; multithreaded in
    C++ when available."""
    n, h, w, c = imgs.shape
    mean = np.ascontiguousarray(mean, np.float32)
    std = np.ascontiguousarray(std, np.float32)
    oy = np.ascontiguousarray(oy, np.int32)
    ox = np.ascontiguousarray(ox, np.int32)
    flip = np.ascontiguousarray(flip, np.uint8)
    lib = _try_load()
    if lib is not None and imgs.dtype == np.uint8:  # C++ kernel is uint8-only
        imgs = np.ascontiguousarray(imgs)
        out = np.empty((n, c, crop_h, crop_w), np.float32)
        lib.bigdl_batch_crop_normalize(
            _u8(imgs), n, h, w, c, crop_h, crop_w, _i32(oy), _i32(ox),
            _u8(flip), _f32(mean), _f32(std), _f32(out), num_threads)
        return out
    out = np.empty((n, c, crop_h, crop_w), np.float32)
    for i in range(n):
        patch = imgs[i, oy[i]:oy[i] + crop_h, ox[i]:ox[i] + crop_w, :]
        if flip[i]:
            patch = patch[:, ::-1, :]
        out[i] = ((patch.astype(np.float32) - mean) / std).transpose(2, 0, 1)
    return out


# ---------------------------------------------------------------------------
# Batch tf.Example parsing (native proto-wire walker)
# ---------------------------------------------------------------------------
def parse_examples_fixed(records, spec, num_threads: int = 0):
    """Parse serialized tf.Example records into dense arrays.

    ``spec``: list of ``(key, kind, count)`` where kind is ``"bytes"``
    (fixed-length raw payload -> uint8 [n, count]), ``"int64"``
    (-> int64 [n, count]) or ``"float"`` (-> float32 [n, count]).
    Returns one array per spec entry.  C++ multi-threaded when the
    native library is loaded; falls back to the Python wire walker
    (``dataset/tfrecord.parse_example``) otherwise.  Raises ValueError
    on a malformed record or a key/kind/size mismatch.
    """
    import ctypes

    kind_code = {"bytes": 0, "int64": 1, "float": 2}
    n = len(records)
    outs = []
    for key, kind, count in spec:
        if kind == "bytes":
            outs.append(np.empty((n, count), np.uint8))
        elif kind == "int64":
            outs.append(np.empty((n, count), np.int64))
        elif kind == "float":
            outs.append(np.empty((n, count), np.float32))
        else:
            raise ValueError(f"unknown kind {kind!r}")
    if n == 0:
        return outs

    lib = _try_load()
    if lib is not None:
        blob = b"".join(records)
        offsets = np.zeros(n + 1, np.int64)
        np.cumsum([len(r) for r in records], out=offsets[1:])
        blob_arr = np.frombuffer(blob, np.uint8)
        keys = (ctypes.c_char_p * len(spec))(
            *[k.encode() for k, _, _ in spec])
        kinds = np.asarray([kind_code[k] for _, k, _ in spec], np.int32)
        counts = np.asarray([c for _, _, c in spec], np.int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        out_ptrs = (u8p * len(spec))(
            *[o.ctypes.data_as(u8p) for o in outs])
        rc = lib.bigdl_parse_examples(
            _u8(blob_arr), offsets.ctypes.data_as(
                ctypes.POINTER(ctypes.c_int64)), n, keys, _i32(kinds),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            out_ptrs, len(spec), num_threads)
        if rc != 0:
            raise ValueError(
                f"record {-int(rc) - 1} failed to parse (missing key, "
                f"wrong kind, or size mismatch)")
        return outs

    # pure-Python fallback: the reference walker, one record at a time
    from bigdl_tpu.dataset.tfrecord import parse_example

    for i, rec in enumerate(records):
        feats = parse_example(bytes(rec))
        for (key, kind, count), out in zip(spec, outs):
            if key not in feats:
                raise ValueError(f"record {i} failed to parse (missing "
                                 f"key {key!r})")
            v = feats[key]
            if kind == "bytes":
                if not isinstance(v, list) or len(v) != 1 \
                        or len(v[0]) != count:
                    raise ValueError(f"record {i} failed to parse "
                                     f"(bytes size mismatch for {key!r})")
                out[i] = np.frombuffer(v[0], np.uint8)
            else:
                arr = np.asarray(v).reshape(-1)
                if isinstance(v, list) or arr.size != count:
                    raise ValueError(f"record {i} failed to parse "
                                     f"(size/kind mismatch for {key!r})")
                out[i] = arr
    return outs
