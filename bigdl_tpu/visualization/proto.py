"""Minimal protobuf wire-format codec for TensorBoard Event files.

The reference ships protoc-generated Java for the TF ``Event``/``Summary``
protos (``spark/dl/src/main/java/org/tensorflow/...``, SURVEY §2.1) and
writes them from ``visualization/tensorboard/*.scala``.  Here the three
messages we emit (Event, Summary, HistogramProto) are hand-encoded on the
wire format directly — no protobuf runtime dependency, byte-compatible
with TensorBoard's parser.

Wire layout used:
  Event        { double wall_time=1; int64 step=2; string file_version=3;
                 Summary summary=5; }
  Summary      { repeated Value value=1; }
  Value        { string tag=1; float simple_value=2; HistogramProto histo=5; }
  HistogramProto { double min=1,max=2,num=3,sum=4,sum_squares=5;
                 repeated double bucket_limit=6 [packed], bucket=7 [packed]; }
"""

from __future__ import annotations

import struct
from typing import Iterator, List, Optional, Tuple

__all__ = ["encode_event", "decode_event", "encode_histogram"]


def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _key(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _key(field, 2) + _varint(len(payload)) + payload


def _double(field: int, v: float) -> bytes:
    return _key(field, 1) + struct.pack("<d", v)


def _float(field: int, v: float) -> bytes:
    return _key(field, 5) + struct.pack("<f", v)


def _int64(field: int, v: int) -> bytes:
    return _key(field, 0) + _varint(v)


def _packed_doubles(field: int, vals) -> bytes:
    payload = b"".join(struct.pack("<d", float(v)) for v in vals)
    return _len_delim(field, payload)


def encode_histogram(mn: float, mx: float, num: float, total: float,
                     sum_squares: float, bucket_limits, buckets) -> bytes:
    out = _double(1, mn) + _double(2, mx) + _double(3, num) + \
        _double(4, total) + _double(5, sum_squares)
    out += _packed_doubles(6, bucket_limits)
    out += _packed_doubles(7, buckets)
    return out


def encode_event(wall_time: float, step: Optional[int] = None,
                 file_version: Optional[str] = None,
                 scalars: Optional[List[Tuple[str, float]]] = None,
                 histograms: Optional[List[Tuple[str, bytes]]] = None
                 ) -> bytes:
    """Serialize one Event proto."""
    out = _double(1, wall_time)
    if step is not None:
        out += _int64(2, step)
    if file_version is not None:
        out += _len_delim(3, file_version.encode())
    values = b""
    for tag, v in scalars or []:
        values += _len_delim(1, _len_delim(1, tag.encode()) + _float(2, v))
    for tag, histo in histograms or []:
        values += _len_delim(1, _len_delim(1, tag.encode()) +
                             _len_delim(5, histo))
    if values:
        out += _len_delim(5, values)
    return out


# ---------------------------------------------------------------------------
# decoding (for FileReader.read_scalar)
# ---------------------------------------------------------------------------
def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, bytes]]:
    i = 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
            yield field, wire, v
        elif wire == 1:
            yield field, wire, buf[i:i + 8]
            i += 8
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            yield field, wire, buf[i:i + ln]
            i += ln
        elif wire == 5:
            yield field, wire, buf[i:i + 4]
            i += 4
        else:  # pragma: no cover
            raise ValueError(f"unsupported wire type {wire}")


def decode_event(buf: bytes) -> dict:
    """Decode an Event into {wall_time, step, scalars: [(tag, value)]}."""
    ev = {"wall_time": 0.0, "step": 0, "scalars": []}
    for field, wire, val in _fields(buf):
        if field == 1 and wire == 1:
            ev["wall_time"] = struct.unpack("<d", val)[0]
        elif field == 2 and wire == 0:
            ev["step"] = val
        elif field == 5 and wire == 2:
            for f2, w2, v2 in _fields(val):
                if f2 == 1 and w2 == 2:
                    tag, sv = None, None
                    for f3, w3, v3 in _fields(v2):
                        if f3 == 1 and w3 == 2:
                            tag = v3.decode()
                        elif f3 == 2 and w3 == 5:
                            sv = struct.unpack("<f", v3)[0]
                    if tag is not None and sv is not None:
                        ev["scalars"].append((tag, sv))
    return ev
