"""Summary API: TrainSummary / ValidationSummary (SURVEY §2.10).

Mirrors ``visualization/Summary.scala`` (``addScalar :44``,
``addHistogram :61`` with TF-style exponential buckets ``:144-180``) and
``TrainSummary.scala:64-88`` (per-tag triggers: Loss/LearningRate/
Throughput written by default, Parameters histograms opt-in)."""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.visualization import proto
from bigdl_tpu.visualization.tensorboard import FileWriter, read_scalar

__all__ = ["Summary", "TrainSummary", "ValidationSummary",
           "histogram_proto"]


def _bucket_limits() -> List[float]:
    """TF's exponential histogram buckets (Summary.scala:144-180): positive
    limits 1e-12 * 1.1^k, mirrored negative, with 0-straddling edges."""
    pos = []
    v = 1e-12
    while v < 1e20:
        pos.append(v)
        v *= 1.1
    return [-x for x in reversed(pos)] + pos + [float("inf")]


_LIMITS = None
_LIMITS_LOCK = threading.Lock()


def _limits() -> np.ndarray:
    """The cached bucket-limit table, built once under a lock — histogram
    writers run on arbitrary threads (the Optimizer's Parameters trigger,
    FileWriter callers), and a double build could hand one of them a
    half-published array on weakly-ordered platforms."""
    global _LIMITS
    table = _LIMITS
    if table is None:
        with _LIMITS_LOCK:
            if _LIMITS is None:
                _LIMITS = np.asarray(_bucket_limits())
            table = _LIMITS
    return table


def histogram_proto(values) -> bytes:
    """Build a HistogramProto payload from an array of values.

    Degenerate inputs stay renderable: empty/all-NaN arrays histogram a
    single zero; constant arrays (all-zero included) get a padded
    min/max so the display range is never empty or inverted; non-finite
    values are dropped from bucketing (they have no finite bucket) but
    never corrupt min/max/sum."""
    limits = _limits()
    v = np.asarray(values, np.float64).reshape(-1)
    v = v[np.isfinite(v)]
    if v.size == 0:
        v = np.zeros(1)
    idx = np.searchsorted(limits, v, side="left")
    # values beyond the last finite limit land in the +inf bucket, never
    # past the table (a too-large idx would desync limits and counts)
    idx = np.minimum(idx, len(limits) - 1)
    counts = np.bincount(idx, minlength=len(limits)).astype(np.float64)
    # trim empty leading/trailing buckets (TF does the same to keep protos small)
    nz = np.nonzero(counts)[0]
    lo, hi = int(nz[0]), int(nz[-1]) + 1
    lo = max(lo - 1, 0)
    hi = min(hi + 1, len(limits))
    mn, mx = float(v.min()), float(v.max())
    if mn == mx:
        # constant input: pad the display range the way TF's histogram
        # does, so TensorBoard never sees an empty/inverted [min, max]
        pad = max(1.0, abs(mn)) * 0.5
        mn, mx = mn - pad, mx + pad
    return proto.encode_histogram(
        mn, mx, float(v.size), float(v.sum()),
        float((v * v).sum()), limits[lo:hi].tolist(),
        counts[lo:hi].tolist())


class Summary:
    """Base writer bound to <log_dir>/<app_name>/<folder>."""

    folder = ""

    def __init__(self, log_dir: str, app_name: str):
        self.log_dir = os.path.join(log_dir, app_name, self.folder)
        self._writer = FileWriter(self.log_dir)

    def add_scalar(self, tag: str, value: float, step: int) -> "Summary":
        self._writer.add_scalar(tag, value, step)
        return self

    def add_histogram(self, tag: str, values, step: int) -> "Summary":
        self._writer.add_histogram(tag, values, step)
        return self

    def read_scalar(self, tag: str) -> List[Tuple[int, float, float]]:
        self._writer.flush()
        return read_scalar(self.log_dir, tag)

    def close(self) -> None:
        self._writer.close()


class TrainSummary(Summary):
    """Training-side summary with per-tag trigger gating
    (``TrainSummary.scala:32-88``). Default tags Loss/LearningRate/
    Throughput are always written; 'Parameters' histograms are opt-in via
    ``set_summary_trigger("Parameters", Trigger.several_iteration(n))``."""

    folder = "train"

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name)
        self._triggers: Dict[str, object] = {}

    def set_summary_trigger(self, tag: str, trigger) -> "TrainSummary":
        self._triggers[tag] = trigger
        return self

    def trigger_for(self, tag: str):
        return self._triggers.get(tag)

    def should_write(self, tag: str, state: dict) -> bool:
        trig = self._triggers.get(tag)
        if trig is None:
            return tag != "Parameters"  # params opt-in, scalars default-on
        return bool(trig(state))


class ValidationSummary(Summary):
    """Validation-side scalars (one per ValidationMethod)."""

    folder = "validation"
