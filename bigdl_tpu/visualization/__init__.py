"""TensorBoard-compatible visualization (SURVEY §2.10): Summary API over
TFRecord event files with masked-CRC32C framing from the native layer."""

from bigdl_tpu.visualization.summary import (Summary, TrainSummary,
                                             ValidationSummary)
from bigdl_tpu.visualization.tensorboard import (EventWriter, FileWriter,
                                                 RecordWriter, read_scalar)

__all__ = ["Summary", "TrainSummary", "ValidationSummary", "FileWriter",
           "EventWriter", "RecordWriter", "read_scalar"]
