"""TensorBoard event-file IO: TFRecord framing + async writer + reader.

Mirrors the reference's ``visualization/tensorboard/`` stack:
``RecordWriter.scala:30`` (length + masked-CRC32C framing via
``netty/Crc32c.java`` — here the native C++ ``bigdl_masked_crc32c``),
``EventWriter.scala:31`` (dedicated writer thread, ``tfevents`` file
naming), ``FileWriter.scala:31`` (async queue facade), and
``FileReader.scala`` (scalar read-back)."""

from __future__ import annotations

import os
import queue
import socket
import struct
import threading
import time
from typing import List, Tuple

from bigdl_tpu import native
from bigdl_tpu.visualization import proto

__all__ = ["RecordWriter", "EventWriter", "FileWriter", "read_scalar"]


class RecordWriter:
    """TFRecord framing: <len u64><masked crc of len u32><data><masked crc
    of data u32> (``RecordWriter.scala:33-44``)."""

    def __init__(self, fileobj):
        self._f = fileobj

    def write(self, data: bytes) -> None:
        header = struct.pack("<Q", len(data))
        self._f.write(header)
        self._f.write(struct.pack("<I", native.masked_crc32c(header)))
        self._f.write(data)
        self._f.write(struct.pack("<I", native.masked_crc32c(data)))

    def flush(self) -> None:
        self._f.flush()


class EventWriter:
    """Writer thread draining an event queue into one tfevents file
    (``EventWriter.scala:31-76``)."""

    def __init__(self, log_dir: str, flush_secs: float = 10.0):
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.{socket.gethostname()}"
        self.path = os.path.join(log_dir, fname)
        self._file = open(self.path, "ab")
        self._rec = RecordWriter(self._file)
        self._q: "queue.Queue" = queue.Queue()
        self._flush_secs = flush_secs
        self._closed = threading.Event()
        # version header event, like EventWriter's first write
        self._rec.write(proto.encode_event(time.time(),
                                           file_version="brain.Event:2"))
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def add_event(self, event_bytes: bytes) -> None:
        self._q.put(event_bytes)

    def _run(self) -> None:
        last_flush = time.time()
        while not (self._closed.is_set() and self._q.empty()):
            try:
                ev = self._q.get(timeout=0.2)
            except queue.Empty:
                ev = None
            if ev is not None:
                self._rec.write(ev)
            if time.time() - last_flush > self._flush_secs:
                self._rec.flush()
                last_flush = time.time()
        self._rec.flush()

    def close(self) -> None:
        self._closed.set()
        self._thread.join(timeout=10)
        self._file.close()


class FileWriter:
    """User-facing async writer (``FileWriter.scala:31``)."""

    def __init__(self, log_dir: str, flush_secs: float = 10.0):
        self.log_dir = log_dir
        self._writer = EventWriter(log_dir, flush_secs)

    def add_scalar(self, tag: str, value: float, step: int) -> "FileWriter":
        self._writer.add_event(proto.encode_event(
            time.time(), step=step, scalars=[(tag, float(value))]))
        return self

    def add_histogram(self, tag: str, values, step: int) -> "FileWriter":
        from bigdl_tpu.visualization.summary import histogram_proto

        self._writer.add_event(proto.encode_event(
            time.time(), step=step,
            histograms=[(tag, histogram_proto(values))]))
        return self

    def flush(self) -> None:
        self._writer._rec.flush()

    def close(self) -> None:
        self._writer.close()


def _iter_records(path: str):
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return
            (length,) = struct.unpack("<Q", header)
            f.read(4)  # header crc
            data = f.read(length)
            f.read(4)  # data crc
            yield data


def read_scalar(log_dir: str, tag: str) -> List[Tuple[int, float, float]]:
    """Read back all (step, value, wall_time) triples for a scalar tag —
    the reference's ``FileReader.readScalar`` powering
    ``TrainSummary.readScalar``."""
    out = []
    if not os.path.isdir(log_dir):
        return out
    for fname in sorted(os.listdir(log_dir)):
        if "tfevents" not in fname:
            continue
        for rec in _iter_records(os.path.join(log_dir, fname)):
            ev = proto.decode_event(rec)
            for t, v in ev["scalars"]:
                if t == tag:
                    out.append((ev["step"], v, ev["wall_time"]))
    out.sort(key=lambda r: r[0])
    return out
