#!/usr/bin/env python
"""Open-loop serving load harness — the diff-gateable check on the
serving layer (docs/serving.md; ROADMAP item 2).

Starts a :class:`bigdl_tpu.serving.ModelServer` in-process on an
ephemeral port, AOT-warms every bucket, then drives it with an
**open-loop** arrival schedule: request send times are fixed up front
at the offered rate (``--qps``), independent of completions — the load
a server actually faces, where a slow dispatch makes the queue grow
instead of politely slowing the clients down (closed-loop harnesses
hide exactly the p99 failures this one exists to catch).

Request sizes cycle through ``--mix`` (rows per request), so the
steady-state traffic exercises MIXED bucket selection; the retrace
detector is armed for the whole timed window and any in-request-path
compile after warmup is counted separately (``steady_compiles``).

Emits one ``bench.py``-style JSON line with a per-config row::

    {"metric": "serving_lenet_qps", "value": 118.3, "unit": "qps",
     "configs": {"serve_lenet": {"qps": ..., "p50_ms": ..., "p99_ms":
     ..., "rejected": 0, "steady_compiles": 0,
     "retrace_diagnostics": 0, ...}}}

which ``python -m bigdl_tpu.telemetry diff A B`` and
``--diff-against BASELINE.json`` (exit 4 on regression, the bench.py
contract) compare: p50/p99 regress up, qps regresses down, and
``steady_compiles``/``retrace_diagnostics``/``rejected`` are
zero-slack counters — ONE production recompile fails the gate.

``--generate`` switches the harness to the LLM decode path
(docs/serving.md "Autoregressive generation"): the same open-loop
schedule drives ``POST /v1/generate`` with a mixed prompt-length cycle
(``--gen-mix``), reads each token off the chunked stream as it lands,
and banks the generation row — ``tokens_s`` (sustained emitted
tokens/s), ``ttft_p50_ms``/``ttft_p99_ms`` (time to first token — the
prefill + queue cost a user feels), and ``itl_p99_ms`` (p99 inter-token
latency — the decode-step tail).  All four are diff-gated:
``tokens_s`` regresses down, the latencies regress up, and the same
zero-slack ``steady_compiles``/``retrace_diagnostics`` counters hold —
a decode executable compiling mid-stream is a frozen token stream.

``--slo-p99-ms`` / ``--slo-ttft-ms`` declare latency budgets
(telemetry/request_trace.py SLOTracker): the server tracks its windowed
p99 (and TTFT p99) against them live, the worst 32 violators by
budget overshoot keep their trace ids (``VIOLATING_KEEP`` — worst-first,
not newest, so a sustained burn cannot evict its own catastrophic
evidence), and the harness **exits 4 when a budget is burned**
(observed p99 > budget) — the same exit code as ``--diff-against``, so
CI treats a blown SLO exactly like a regression.  The bench JSON row
carries the full SLO ledger (burn rates + the violating requests' trace
ids), so the failing artifact names its own evidence: feed any id to
``GET /v1/trace/<id>`` on a live server or ``python -m
bigdl_tpu.telemetry trace run.jsonl --id <id>`` offline.

Usage::

    python bench_serving.py --model lenet --qps 100 --duration 10
    python bench_serving.py --model lenet --diff-against BENCH_serving.json
    python bench_serving.py --model dlrm --qps 100 --duration 12 \
        --diff-against BENCH_SERVING_cpu_r15.json   # the recsys tenant
    python bench_serving.py --model lenet --qps 100 --slo-p99-ms 50
    python bench_serving.py --model transformer --generate --qps 5 \
        --duration 10 --gen-mix 8,24,64 --max-new-tokens 16
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import urllib.request

import numpy as np

__all__ = ["run_load", "main"]


def _pct(sorted_vals, p):
    """Nearest-rank percentile over a pre-sorted list; ``None`` when
    empty (client-side stats distinguish "no samples" from 0 ms)."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              int(round(p / 100.0 * (len(sorted_vals) - 1))))
    return round(sorted_vals[idx], 3)


def _synth_rows(spec, rng, rows: int, seq_len=None) -> np.ndarray:
    """Synthetic request payload: ``rows`` samples at the model's
    canonical feature shape (optionally a shorter seq for token
    models — the mixed-size part of the protocol)."""
    shape = (rows,) + tuple(spec.shape[1:])
    if seq_len is not None and len(shape) >= 2:
        shape = (rows, seq_len) + tuple(shape[2:])
    dt = np.dtype(spec.dtype)
    if np.issubdtype(dt, np.integer):
        return rng.integers(1, 200, shape).astype(dt)
    return rng.normal(size=shape).astype(dt)


def run_load(server, spec, qps: float, duration_s: float, mix,
             seq_mix=None, senders: int = 8, timeout_s: float = 30.0):
    """Drive ``server`` open-loop; returns client-side stats.

    ``mix`` cycles request row counts; ``seq_mix`` (token models)
    cycles sequence lengths.  Arrival times are scheduled before the
    first send and never adjusted — a stalled server meets the full
    backlog, exactly like production."""
    n = max(1, int(qps * duration_s))
    rng = np.random.default_rng(0)
    url = f"http://127.0.0.1:{server.port}/v1/predict"
    plan = []
    for i in range(n):
        rows = mix[i % len(mix)]
        seq = seq_mix[i % len(seq_mix)] if seq_mix else None
        body = json.dumps(
            {"inputs": _synth_rows(spec, rng, rows, seq).tolist()}
        ).encode("utf-8")
        plan.append((i / qps, rows, body))
    lat_ms, codes = [], []
    lock = threading.Lock()
    idx = [0]
    start = time.perf_counter()

    def sender():
        while True:
            with lock:
                if idx[0] >= len(plan):
                    return
                at, rows, body = plan[idx[0]]
                idx[0] += 1
            delay = start + at - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t0 = time.perf_counter()
            try:
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST")
                with urllib.request.urlopen(req, timeout=timeout_s) as r:
                    r.read()
                    code = r.status
            except urllib.error.HTTPError as e:
                code = e.code
            except Exception:  # noqa: BLE001 - connection-level failure
                code = -1
            with lock:
                codes.append(code)
                if code == 200:
                    lat_ms.append((time.perf_counter() - t0) * 1000.0)

    threads = [threading.Thread(target=sender, daemon=True)
               for _ in range(senders)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 3 * timeout_s)
    wall = time.perf_counter() - start
    lat = sorted(lat_ms)
    return {"offered_qps": round(qps, 2),
            "qps": round(len(lat) / wall, 2) if wall > 0 else None,
            "requests": len(codes), "ok": len(lat),
            "rejected": sum(1 for c in codes if c == 429),
            "failed": sum(1 for c in codes if c not in (200, 429)),
            "p50_ms": _pct(lat, 50), "p99_ms": _pct(lat, 99),
            "wall_s": round(wall, 3)}


def run_generate_load(server, qps: float, duration_s: float, gen_mix,
                      max_new_tokens: int, vocab: int, senders: int = 8,
                      temperature: float = 0.0,
                      timeout_s: float = 60.0):
    """Drive ``POST /v1/generate`` open-loop; returns client-side
    generation stats.  ``gen_mix`` cycles prompt lengths (mixed-length
    prefill is the scheduling case worth measuring); every request
    streams and the client clocks each token as its chunk lands —
    TTFT and inter-token latency are measured where the user sits,
    queue wait included."""
    n = max(1, int(qps * duration_s))
    rng = np.random.default_rng(0)
    url = f"http://127.0.0.1:{server.port}/v1/generate"
    plan = []
    for i in range(n):
        plen = gen_mix[i % len(gen_mix)]
        body = json.dumps(
            {"prompt": rng.integers(1, vocab, plen).tolist(),
             "max_new_tokens": max_new_tokens,
             "temperature": temperature, "seed": i}).encode("utf-8")
        plan.append((i / qps, body))
    ttft_ms, itl_ms, codes, tokens = [], [], [], [0]
    lock = threading.Lock()
    idx = [0]
    start = time.perf_counter()

    def sender():
        while True:
            with lock:
                if idx[0] >= len(plan):
                    return
                at, body = plan[idx[0]]
                idx[0] += 1
            delay = start + at - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t0 = time.perf_counter()
            got, stamps = 0, []
            try:
                req = urllib.request.Request(
                    url, data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST")
                with urllib.request.urlopen(req, timeout=timeout_s) as r:
                    code = r.status
                    for line in r:
                        if not line.strip():
                            continue
                        ev = json.loads(line)
                        if "token" in ev:
                            stamps.append(time.perf_counter())
                            got += 1
                        elif "error" in ev:
                            code = -2
            except urllib.error.HTTPError as e:
                code = e.code
            except Exception:  # noqa: BLE001 - connection-level failure
                code = -1
            with lock:
                codes.append(code)
                tokens[0] += got
                if code == 200 and stamps:
                    ttft_ms.append((stamps[0] - t0) * 1000.0)
                    itl_ms.extend((b - a) * 1000.0 for a, b in
                                  zip(stamps, stamps[1:]))

    threads = [threading.Thread(target=sender, daemon=True)
               for _ in range(senders)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + 3 * timeout_s)
    wall = time.perf_counter() - start
    ttft = sorted(ttft_ms)
    itl = sorted(itl_ms)
    return {"offered_qps": round(qps, 2),
            "requests": len(codes),
            "ok": sum(1 for c in codes if c == 200),
            "rejected": sum(1 for c in codes if c == 429),
            "failed": sum(1 for c in codes if c not in (200, 429)),
            "gen_tokens": tokens[0],
            "tokens_s": round(tokens[0] / wall, 2) if wall > 0 else None,
            "ttft_p50_ms": _pct(ttft, 50), "ttft_p99_ms": _pct(ttft, 99),
            "itl_p50_ms": _pct(itl, 50), "itl_p99_ms": _pct(itl, 99),
            "max_new_tokens": max_new_tokens,
            "wall_s": round(wall, 3)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--model", default="lenet")
    ap.add_argument("--num-classes", type=int, default=0)
    ap.add_argument("--qps", type=float, default=50.0,
                    help="offered (open-loop) request rate")
    ap.add_argument("--duration", type=float, default=10.0,
                    help="timed window seconds")
    ap.add_argument("--mix", default="1,1,2,4", metavar="R,R,...",
                    help="request row-count cycle (mixed sizes "
                         "exercise bucket selection)")
    ap.add_argument("--seq-mix", default=None, metavar="T,T,...",
                    help="token models: request sequence-length cycle")
    ap.add_argument("-b", "--max-batch", type=int, default=16)
    ap.add_argument("--buckets", default=None, metavar="N,N,...")
    ap.add_argument("--seq-buckets", default=None, metavar="T,T,...")
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--queue-limit", type=int, default=256)
    ap.add_argument("--senders", type=int, default=8)
    ap.add_argument("--int8", action="store_true",
                    help="serve quantized with calibrated static "
                         "activation scales")
    ap.add_argument("--generate", action="store_true",
                    help="bench the LLM decode path: POST /v1/generate "
                         "streamed token mix (tokens/s, TTFT, "
                         "inter-token p99)")
    ap.add_argument("--gen-mix", default="8,24,64", metavar="L,L,...",
                    help="--generate: prompt-length cycle (mixed "
                         "prefill shapes)")
    ap.add_argument("--max-new-tokens", type=int, default=16,
                    help="--generate: tokens emitted per request")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="--generate: 0 = greedy (default), >0 samples")
    ap.add_argument("--decode-buckets", default=None, metavar="B,B,...",
                    help="--generate: decode batch buckets (default "
                         "1,2,4,8)")
    ap.add_argument("--cache-buckets", default=None, metavar="C,C,...",
                    help="--generate: KV cache-length buckets")
    ap.add_argument("--vocab", type=int, default=0,
                    help="--generate: vocab size for synthetic prompts "
                         "(default: the model's)")
    ap.add_argument("--diff-against", default=None,
                    metavar="BASELINE.json",
                    help="compare against a prior bench_serving JSON "
                         "(telemetry diff); exit 4 on regression")
    ap.add_argument("--diff-threshold-pct", type=float, default=None)
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    metavar="MS",
                    help="declared request-latency p99 budget: exit 4 "
                         "when the observed p99 exceeds it; violating "
                         "requests' trace ids land in the bench JSON")
    ap.add_argument("--slo-ttft-ms", type=float, default=None,
                    metavar="MS",
                    help="--generate: declared time-to-first-token p99 "
                         "budget (same exit-4 gate)")
    args = ap.parse_args(argv)

    from bigdl_tpu import telemetry
    from bigdl_tpu.analysis.retrace import trace_retraces
    from bigdl_tpu.models import registry
    from bigdl_tpu.serving import serve_model

    if args.generate:
        # the shared build rule (unrolled transformer etc.) lives
        # beside the decode subsystem — same path as cli serve
        from bigdl_tpu.serving.generate import generation_model

        model = generation_model(args.model, args.num_classes)
    else:
        model = registry.build_model(args.model, args.num_classes)
    spec = registry.input_spec(args.model, 1)
    if args.int8:
        from bigdl_tpu.nn.quantized import calibrate, quantize

        model = quantize(model)
        calibrate(model, [_synth_rows(spec, np.random.default_rng(1),
                                      max(2, args.max_batch // 2))])

    def buckets(text):
        return [int(b) for b in text.split(",")] if text else None

    seq_buckets = buckets(args.seq_buckets)
    if args.generate and not seq_buckets:
        from bigdl_tpu.serving.generate import default_seq_buckets

        seq_buckets = default_seq_buckets(spec)
    with telemetry.maybe_run(meta={"cmd": "bench_serving",
                                   "model": args.model}) as owned_log:
        server = serve_model(
            model, spec, name=args.model, host="127.0.0.1", port=0,
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            queue_limit=args.queue_limit,
            batch_buckets=buckets(args.buckets),
            seq_buckets=seq_buckets,
            generate=args.generate,
            decode_buckets=buckets(args.decode_buckets),
            cache_buckets=buckets(args.cache_buckets),
            slo_p99_ms=args.slo_p99_ms, slo_ttft_ms=args.slo_ttft_ms)
        print(f"# serving {args.model} on :{server.port}, "
              f"{server.executor.compile_count} buckets warm "
              f"({server.executor.warmup_s:.1f}s)",
              file=sys.stderr, flush=True)
        warm_compiles = server.executor.compile_count
        mix = [int(r) for r in args.mix.split(",")]
        seq_mix = [int(t) for t in args.seq_mix.split(",")] \
            if args.seq_mix else None
        try:
            with telemetry.span("serve/load", qps=args.qps,
                                duration=args.duration):
                with trace_retraces() as mon:
                    if args.generate:
                        stats = run_generate_load(
                            server, args.qps, args.duration,
                            [int(p) for p in args.gen_mix.split(",")],
                            args.max_new_tokens,
                            vocab=args.vocab or args.num_classes or 256,
                            senders=args.senders,
                            temperature=args.temperature)
                    else:
                        stats = run_load(server, spec, args.qps,
                                         args.duration, mix,
                                         seq_mix=seq_mix,
                                         senders=args.senders)
            steady = server.executor.compile_count - warm_compiles
            row = dict(stats)
            try:
                # resident-executable HBM (weights + generated code +
                # largest bucket scratch): the serving-side
                # peak_hbm_bytes the diff gate compares, and the number
                # the KV-cache budgeting work subtracts from the device
                mem = server.executor.memory_summary()
                row["peak_hbm_bytes"] = mem["resident_bytes"]
                row["executable_memory"] = {
                    k: mem[k] for k in ("state_bytes", "code_bytes",
                                        "peak_temp_bytes")}
            except Exception:  # noqa: BLE001 - accounting only
                pass
            row.update(
                steady_compiles=steady,
                retrace_diagnostics=len(mon.report.diagnostics),
                warm_buckets=len(server.executor.warm_buckets()),
                warmup_s=round(server.executor.warmup_s, 3),
                max_batch=args.max_batch,
                max_wait_ms=args.max_wait_ms, int8=bool(args.int8),
                server=server.status())
            if server.slo.active():
                # the SLO ledger travels IN the bench artifact: burn
                # rates plus the worst violators' trace ids — the
                # failing JSON names its own evidence
                row["slo"] = server.slo.status()
                row["slo_violations"] = server.slo.violations
        finally:
            server.stop(drain=True)
        # read the live goodput ledger while the run is still open —
        # end_run (the `with` exit) detaches it
        gp = telemetry.goodput()
    if owned_log:
        print(f"# telemetry run log: {owned_log}", file=sys.stderr)

    if args.generate:
        name = f"generate_{args.model}"
        line = {"metric": f"serving_{args.model}_gen_tokens_s",
                "value": row.get("tokens_s"), "unit": "tokens/s",
                "vs_baseline": None, "configs": {name: row}}
    else:
        name = f"serve_{args.model}"
        line = {"metric": f"serving_{args.model}_qps",
                "value": row.get("qps"), "unit": "qps",
                "vs_baseline": None, "configs": {name: row}}
    if gp and gp.get("wall_s"):
        line["goodput_pct"] = gp["goodput_pct"]
        line["badput_s"] = gp["badput_s"]
    print(json.dumps(line))
    sys.stdout.flush()

    slo_burned = []
    if args.slo_p99_ms is not None or args.slo_ttft_ms is not None:
        burn = (row.get("slo") or {}).get("burn") or {}
        slo_burned = [
            which for which, b in sorted(burn.items())
            if (b or {}).get("burn") is not None and b["burn"] > 1.0]
        if slo_burned:
            violating = (row.get("slo") or {}).get("violating") or []
            ids = [v.get("trace_id") for v in violating]
            print(f"SLO VIOLATED ({', '.join(slo_burned)}): "
                  + "  ".join(
                      f"{w} {burn[w]['observed_ms']}ms observed vs "
                      f"{burn[w]['budget_ms']}ms budget "
                      f"(burn {burn[w]['burn']}x)" for w in slo_burned)
                  + f"; violating trace ids: {ids}", file=sys.stderr)

    if args.diff_against:
        from bigdl_tpu.telemetry import diff as tdiff

        base = tdiff.load_metrics(args.diff_against)
        cur = tdiff.bench_metrics(line, path="<this run>")
        kwargs = {}
        if args.diff_threshold_pct is not None:
            kwargs["threshold_pct"] = args.diff_threshold_pct
        rows = tdiff.diff_metrics(base, cur, **kwargs)
        print(tdiff.format_diff(rows, base, cur), file=sys.stderr)
        if not rows:
            print("error: --diff-against found nothing comparable",
                  file=sys.stderr)
            return 2
        if any(r["regressed"] for r in rows):
            return 4  # the sweep ran; it's just slower — bench.py's code
    if slo_burned:
        return 4  # the sweep ran; it blew its declared budget
    return 0


if __name__ == "__main__":
    sys.exit(main())
