#!/usr/bin/env python
"""Per-op kernel micro-benchmark: Pallas leg vs XLA reference leg.

Times every op in the kernel library (bigdl_tpu/ops/) forward and
forward+backward under ``BIGDL_KERNELS=pallas`` and ``=xla`` on
representative model geometries (inception LRN/pool planes, contrastive
front-end, transformer attention), and emits a BENCH_*-style JSON whose
``configs`` table is comparable by ``python -m bigdl_tpu.telemetry
diff`` / ``bench.py --diff-against`` (rows carry ``images_per_sec`` =
op executions per second on the preferred leg, so cross-round kernel
regressions gate exactly like model throughput).

On TPU the pallas column is the Mosaic-compiled kernel and the speedup
column is the number that justifies ``auto`` routing.  Off-TPU the
pallas leg runs the INTERPRETER — a correctness reference, not a perf
claim — and the JSON says so (``pallas_is_interpret: true``); use
``--skip-pallas`` to record an XLA-only baseline quickly.

Usage::

    python bench_kernels.py                       # all ops, default reps
    python bench_kernels.py --ops lrn_cross_map,pool_avg_ceil --repeat 20
    python bench_kernels.py --small               # CI-sized shapes
    python bench_kernels.py -o BENCH_KERNELS.json
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np

import jax
import jax.numpy as jnp


def _geoms(small: bool):
    """(op -> (builder, shape, static)) on bench geometries; --small
    shrinks planes so the CPU interpreter finishes in CI time."""
    if small:
        lrn = (2, 8, 8, 8)
        norm = (2, 3, 12, 12)
        pool = (2, 8, 9, 9)
        attn = (1, 2, 128, 32)
    else:
        # inception-v1's LRN sits on [N, 64, 56, 56]; the contrastive
        # front-end on 3-channel planes; pool3x3/s2 ceil everywhere
        lrn = (8, 64, 28, 28)
        norm = (8, 3, 56, 56)
        pool = (8, 64, 28, 28)
        attn = (2, 8, 512, 64)
    return {"lrn": lrn, "norm": norm, "pool": pool, "attn": attn}


def _build_cases(small: bool):
    from bigdl_tpu.nn.layers.normalization import _gaussian_kernel
    from bigdl_tpu.ops.lrn_pallas import cross_map_lrn, within_channel_lrn
    from bigdl_tpu.ops.norm_pallas import (contrastive_norm,
                                           divisive_norm,
                                           subtractive_norm)
    from bigdl_tpu.ops.pool_pallas import avg_pool, maxpool_tie_split
    from bigdl_tpu.ops.attention import (dot_product_attention,
                                         flash_attention)

    g = _geoms(small)
    gauss = jnp.asarray(_gaussian_kernel(9))
    pdims, pstr = (1, 1, 3, 3), (1, 1, 2, 2)
    ppads = ((0, 0), (0, 0), (1, 2), (1, 2))       # ceil-mode overflow
    pdecl = ((0, 0), (0, 0), (1, 1), (1, 1))

    cases = {
        "lrn_cross_map": (
            lambda x: cross_map_lrn(x, 5, 1e-4, 0.75, 1.0), g["lrn"]),
        "lrn_within_channel": (
            lambda x: within_channel_lrn(x, 5, 1e-4, 0.75), g["lrn"]),
        "norm_subtractive": (
            lambda x: subtractive_norm(x, gauss), g["norm"]),
        "norm_divisive": (
            lambda x: divisive_norm(x, gauss), g["norm"]),
        "norm_contrastive": (
            lambda x: contrastive_norm(x, gauss), g["norm"]),
        "pool_tie_split": (
            lambda x: maxpool_tie_split(x, pdims, pstr, ppads),
            g["pool"]),
        "pool_avg_ceil": (
            lambda x: avg_pool(x, pdims, pstr, ppads, pdecl, True, True),
            g["pool"]),
    }

    b, h, s, d = g["attn"]

    def _attn(kind):
        def run(qkv):
            q, k, v = qkv[0], qkv[1], qkv[2]
            if kind == "flash":
                return flash_attention(q, k, v, causal=True)
            return dot_product_attention(q, k, v, causal=True)
        return run

    # attention is special-cased: its two legs are distinct entry
    # points, not a dispatch inside one op
    cases["attention"] = ((_attn("dense"), _attn("flash")),
                          (3, b, h, s, d))
    return cases


def _time_one(fn, x, repeat: int, grad: bool):
    if grad:
        def loss(a):
            return jnp.sum(fn(a) ** 2)
        run = jax.jit(jax.grad(loss))
    else:
        run = jax.jit(fn)
    out = run(x)
    jax.block_until_ready(out)          # compile + warm
    t0 = time.perf_counter()
    for _ in range(repeat):
        out = run(x)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / repeat


def bench_op(name, case, repeat: int, skip_pallas: bool):
    fn, shape = case
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    row = {"shape": list(shape), "dtype": "float32", "repeat": repeat}
    legs = {}
    for leg in ("xla",) if skip_pallas else ("xla", "pallas"):
        if isinstance(fn, tuple):       # attention: explicit entry points
            leg_fn = fn[0] if leg == "xla" else fn[1]
            os.environ["BIGDL_KERNELS"] = "auto"
        else:
            leg_fn = fn
            os.environ["BIGDL_KERNELS"] = leg
        legs[leg] = {
            "fwd_ms": _time_one(leg_fn, x, repeat, grad=False) * 1e3,
            "fwdbwd_ms": _time_one(leg_fn, x, repeat, grad=True) * 1e3,
        }
    for leg, t in legs.items():
        row[f"{leg}_fwd_ms"] = round(t["fwd_ms"], 4)
        row[f"{leg}_fwdbwd_ms"] = round(t["fwdbwd_ms"], 4)
    if "pallas" in legs:
        row["speedup_fwd"] = round(
            legs["xla"]["fwd_ms"] / legs["pallas"]["fwd_ms"], 3)
        row["speedup_fwdbwd"] = round(
            legs["xla"]["fwdbwd_ms"] / legs["pallas"]["fwdbwd_ms"], 3)
    # comparable key for telemetry diff: executions/sec of the leg the
    # auto policy would run on THIS device (pallas on TPU, xla off-TPU)
    from bigdl_tpu.ops.attention import is_tpu_device

    pref = "pallas" if (is_tpu_device() and "pallas" in legs) else "xla"
    row["preferred_leg"] = pref
    row["images_per_sec"] = round(1e3 / legs[pref]["fwdbwd_ms"], 2)
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-op Pallas-vs-XLA kernel micro-benchmark")
    ap.add_argument("--ops", default=None,
                    help="comma-separated subset (default: all)")
    ap.add_argument("--repeat", type=int, default=10)
    ap.add_argument("--small", action="store_true",
                    help="CI-sized shapes (CPU interpreter budget)")
    ap.add_argument("--skip-pallas", action="store_true",
                    help="XLA-only baseline (skip the interpret leg)")
    ap.add_argument("-o", "--output", default=None, metavar="OUT.json")
    args = ap.parse_args(argv)

    from bigdl_tpu.ops.attention import is_tpu_device

    prev = os.environ.get("BIGDL_KERNELS")
    cases = _build_cases(args.small)
    if args.ops:
        wanted = [s.strip() for s in args.ops.split(",") if s.strip()]
        unknown = sorted(set(wanted) - set(cases))
        if unknown:
            ap.error(f"unknown ops: {', '.join(unknown)} "
                     f"(have: {', '.join(sorted(cases))})")
        cases = {k: cases[k] for k in wanted}

    dev = jax.devices()[0]
    configs = {}
    try:
        for name, case in cases.items():
            configs[name] = bench_op(name, case, args.repeat,
                                     args.skip_pallas)
            print(f"{name:22s} " + " ".join(
                f"{k}={v}" for k, v in configs[name].items()
                if k.endswith("_ms") or k.startswith("speedup")))
    finally:                            # never leak the knob
        if prev is None:
            os.environ.pop("BIGDL_KERNELS", None)
        else:
            os.environ["BIGDL_KERNELS"] = prev

    speed = [r["speedup_fwdbwd"] for r in configs.values()
             if "speedup_fwdbwd" in r]
    doc = {
        "metric": "kernel_microbench_speedup_geomean",
        "value": round(float(np.exp(np.mean(np.log(speed)))), 3)
        if speed else None,
        "unit": "x (xla_ms / pallas_ms, fwd+bwd)",
        "device": getattr(dev, "device_kind", str(dev)),
        "pallas_is_interpret": not is_tpu_device(),
        "configs": configs,
    }
    out = json.dumps(doc, indent=1)
    print(out)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as fh:
            fh.write(out + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
