#!/usr/bin/env python
"""Assemble banked per-config bench results into one artifact.

``bench.py`` flushes one stderr line per finished config (``# name:
{...}`` / ``# infer name: {...}``) precisely so a wedged tunnel can't
erase completed measurements; ``tools/run_legs_r5.sh`` banks those lines
across retries.  This script parses the banked stderr log, keeps the
BEST line per config (throughput ties broken by recency), and writes the
combined JSON in bench.py's one-line schema to ``BENCH_banked_r5.json``
(the replay-fallback artifact) and stdout.

Usage: python tools/assemble_legs.py [bench_legs_r5.err] [--out PATH]
"""

import ast
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# shared schema constants — the assembled line must not silently diverge
# from bench.py's own (bench_constants is dependency-free: this parser
# must work without jax and without bench's import side effects)
from bench_constants import HEADLINE, ROUND3_BEST  # noqa: E402

_CFG = re.compile(r"^# ([a-z0-9_]+): (\{.*\})\s*$")
_INFER = re.compile(r"^# infer ([a-z0-9_]+): (\{.*\})\s*$")
_ROUND = re.compile(r"^=== round \d+ commit=(\S+)")


def parse(path):
    configs, infer = {}, {}
    commit = None  # commit stamp of the current runner round's tree
    with open(path) as f:
        for raw in f:
            m = _ROUND.match(raw)
            if m:
                commit = m.group(1)
                continue
            m = _INFER.match(raw)
            if m:
                try:
                    row = ast.literal_eval(m.group(2))
                except (ValueError, SyntaxError):
                    continue
                if commit:
                    row["commit"] = commit
                infer[m.group(1)] = row
                continue
            m = _CFG.match(raw)
            if m:
                try:
                    row = ast.literal_eval(m.group(2))
                except (ValueError, SyntaxError):
                    continue
                if commit:
                    row["commit"] = commit
                name = m.group(1)
                old = configs.get(name)
                # keep the best throughput; an error row never displaces
                # a real measurement (later lines win ties = recency)
                if (old is None or "error" in old or
                        row.get("images_per_sec", -1)
                        >= old.get("images_per_sec", -1)):
                    if "error" not in row or old is None:
                        configs[name] = row
    return configs, infer


def main(argv):
    src = argv[1] if len(argv) > 1 and not argv[1].startswith("--") \
        else os.path.join(REPO, "bench_legs_r5.err")
    out_path = os.path.join(REPO, "BENCH_banked_r5.json")
    if "--out" in argv:
        out_path = argv[argv.index("--out") + 1]
    configs, infer = parse(src)
    if not configs:
        print(json.dumps({"error": f"no banked config lines in {src}"}))
        return 1
    # merge the committed banked artifact so the headline survives even
    # if this log predates it (same best-throughput-wins rule)
    try:
        with open(out_path) as f:
            prev = json.load(f)
        prev_commit = (prev.get("source") or {}).get("commit")
        for name, row in (prev.get("configs") or {}).items():
            # rows written before per-row stamping carry no "commit";
            # attribute them to the prior artifact's top-level stamp so
            # a merged best row never surfaces with null provenance
            if "commit" not in row and prev_commit:
                row["commit"] = prev_commit
            old = configs.get(name)
            if old is None or ("error" in old and "error" not in row) or \
                    (row.get("images_per_sec", -1)
                     > old.get("images_per_sec", -1)):
                configs[name] = row
        infer = {**(prev.get("infer_int8_vs_bf16") or {}), **infer}
    except (OSError, ValueError):
        pass
    head_name = HEADLINE if HEADLINE in configs else next(iter(configs))
    head = configs[head_name]
    line = {
        "metric": f"{head_name}_train_throughput",
        "value": head.get("images_per_sec"),
        "unit": "images/sec", "vs_baseline": None,
        "mfu": head.get("mfu"), "device": "TPU v5 lite",
        # rows may span trees: per-row "commit" fields (from the
        # runner's round stamps) are the authoritative attribution; the
        # headline's commit is surfaced here for the one-line readers
        "source": {"commit": head.get("commit"), "assembled": True,
                   "assembled_from": os.path.basename(src)},
        "vs_round3_best": (round(head["images_per_sec"] / ROUND3_BEST, 3)
                           if head_name == HEADLINE
                           and head.get("images_per_sec") else None),
        "configs": configs,
    }
    if infer:
        line["infer_int8_vs_bf16"] = infer
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(line, f)
    os.replace(tmp, out_path)
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
