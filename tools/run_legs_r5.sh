#!/bin/bash
# Round-5 TPU sweep with a progress watchdog.
#
# The axon tunnel wedges per-client and transiently: round-5 contact
# log shows probe OK -> headline leg OK -> next client wedged inside
# its first compile RPC (after probe_backend's bounded jax.devices()
# succeeded, so BENCH_BACKEND_TIMEOUT never fires).  bench.py flushes
# one stderr line per finished config, so the cheapest resilient
# protocol is: ONE process for all remaining configs (minimal client
# churn), watch stderr for progress, and on a stall kill + restart
# with the configs not yet banked.
set -u
cd /root/repo
OUT=bench_legs_r5.jsonl
ERR=bench_legs_r5.err
ALL=${LEGS:-"inception_v1_imagenet lenet_mnist vgg16_cifar10 lstm_text lstm_text_large resnet50_imagenet transformer_lm transformer_lm_long"}
STALL=${STALL:-420}          # s without a new stderr byte -> wedged
ROUNDS=${ROUNDS:-12}

remaining() {  # configs in $ALL with no REAL measurement in $ERR yet
  # (an '{'error': ...}' row is retryable — only an images_per_sec row
  # banks the config)
  local out=""
  for c in $ALL; do
    grep -q "^# $c: {'images_per_sec'" "$ERR" 2>/dev/null || out="$out,$c"
  done
  echo "${out#,}"
}

# a timeout on this wrapper must not orphan the measured child (it holds
# the device client + singleton flock) — and a TERM/INT must END the
# sweep, not let the loop respawn a fresh client
pid=""
trap '[ -n "$pid" ] && kill -9 "$pid" 2>/dev/null' EXIT
trap '[ -n "$pid" ] && kill -9 "$pid" 2>/dev/null; exit 143' TERM INT

touch "$ERR"
for round in $(seq 1 "$ROUNDS"); do
  rem=$(remaining)
  if [ -z "$rem" ]; then break; fi
  # the commit stamp lets the assembler attribute each banked row to the
  # tree that measured it (bench._source_state's lesson)
  echo "=== round $round commit=$(git rev-parse --short HEAD 2>/dev/null)$(git diff --quiet 2>/dev/null || echo -dirty) remaining=$rem $(date -u +%H:%M:%S)" >> "$ERR"
  # singleton wait bounded BELOW the stall watchdog: a held lock must
  # surface as bench's own conflict error line, not be misread as a
  # wedge when /tmp/TPU_BACK's 3700s harvest default kicks in
  BENCH_CONFIGS=$rem BENCH_INFER=1 BENCH_ITERS=24 BIGDL_SINGLETON_WAIT=210 \
    python bench.py >> "$OUT" 2>> "$ERR" &
  pid=$!
  # watchdog: kill on stall, reap on exit
  while kill -0 "$pid" 2>/dev/null; do
    sleep 20
    now=$(date +%s); mt=$(stat -c %Y "$ERR")
    if [ $((now - mt)) -ge "$STALL" ]; then
      echo "=== round $round STALL (no stderr for ${STALL}s), killing $pid" >> "$ERR"
      kill -9 "$pid" 2>/dev/null
      break
    fi
  done
  wait "$pid" 2>/dev/null
  echo "=== round $round child exit rc=$? $(date -u +%H:%M:%S)" >> "$ERR"
  rm -f /tmp/bigdl_tpu_u0_axon__p0.lock
  sleep 45
done
# the int8/bf16 inference table only prints inside the FINAL json line of
# a run that completes; if every train config is banked but no run ended
# cleanly, one more tiny run picks it up (lenet re-run, cheap)
echo "ALL_LEGS_DONE remaining='$(remaining)' $(date -u +%H:%M:%S)" >> "$ERR"
