#!/usr/bin/env python
"""Time-to-accuracy harness — the third leg of the BASELINE protocol
(images/sec, scaling efficiency, **time-to-accuracy**; BASELINE.md
"report ... plus time-to-accuracy for the five configs").

Trains a model-zoo config through the real Optimizer loop (validation
every epoch, ``Trigger.max_score`` early stop) and reports wall-clock
seconds and epochs to the target validation Top-1.  Real dataset folders
are used when given; otherwise the loaders synthesize class-dependent
data so the protocol runs anywhere (synthetic targets are reached in a
couple of epochs — the point offline is the protocol, the point on
hardware is the number).

    python tools/tta_bench.py --model lenet --target 0.95 [-f mnist/]
    python tools/tta_bench.py --model vgg_cifar --target 0.9 -b 128

Prints ONE JSON line: {"metric": "<model>_time_to_acc", ...}.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="lenet")
    ap.add_argument("-f", "--folder", default=None)
    ap.add_argument("-b", "--batch-size", type=int, default=64)
    ap.add_argument("--target", type=float, default=0.95,
                    help="validation Top-1 accuracy to stop at")
    ap.add_argument("--max-epoch", type=int, default=20)
    ap.add_argument("--learning-rate", type=float, default=0.05)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--num-classes", type=int, default=0)
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args(argv)

    from bigdl_tpu.utils.engine import honor_platform_request

    honor_platform_request()

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.cli import _build_model, _load_data
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(args.seed)
    x, y = _load_data(args.model, args.folder, "train", args.num_classes)
    xt, yt = _load_data(args.model, args.folder, "test", args.num_classes)
    if args.folder is None:
        # synthetic loaders draw disjoint class patterns per split; hold
        # validation out of the train split so accuracy is meaningful
        cut = max(len(x) // 4, 1)
        xt, yt = x[:cut], y[:cut]
        x, y = x[cut:], y[cut:]
    model = _build_model(args.model, args.num_classes)

    samples = [Sample(x[i], y[i]) for i in range(len(x))]
    val_samples = [Sample(xt[i], yt[i]) for i in range(len(xt))]

    o = optim.LocalOptimizer(
        model, samples, nn.ClassNLLCriterion(), batch_size=args.batch_size,
        end_trigger=optim.Trigger.or_(
            optim.Trigger.max_epoch(args.max_epoch),
            optim.Trigger.max_score(args.target)))
    o.set_optim_method(optim.SGD(learning_rate=args.learning_rate,
                                 momentum=args.momentum))
    o.set_validation(optim.Trigger.every_epoch(), val_samples,
                     [optim.Top1Accuracy()], args.batch_size)
    t0 = time.perf_counter()
    o.optimize()
    wall = time.perf_counter() - t0

    score = float(o.state.get("score", 0.0))
    result = {
        "metric": f"{args.model}_time_to_acc",
        "value": round(wall, 2),
        "unit": f"seconds to Top-1 >= {args.target}",
        "reached": bool(score >= args.target),
        "final_top1": round(score, 4),
        "epochs": int(o.state.get("epoch", 0)),
        "iterations": int(o.state.get("neval", 0)),
        "records": len(samples),
        "synthetic_data": args.folder is None,
    }
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
