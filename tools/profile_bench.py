#!/usr/bin/env python
"""Capture a jax.profiler trace of one bench config and print the per-op
time breakdown (parsed from the xplane proto via TF's profiler protos).

Usage: python tools/profile_bench.py [config] [batch] [iters]
"""

import glob
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def capture(config_name="inception_v1_imagenet", batch=None, iters=8,
            logdir="/tmp/jaxprof"):
    import bench
    from bigdl_tpu import telemetry

    # BIGDL_TELEMETRY: the capture's compile + device facts (emitted by
    # TrainStep.aot_scan) and the trace window land in the same JSONL
    # stream the Optimizer and bench.py write — one instrumented path
    with telemetry.maybe_run(meta={"cmd": "profile_bench",
                                   "config": config_name}) as owned_log:
        # SAME program bench times and hlo_dump prints (incl. graph passes)
        step, x, y = bench.make_step(config_name, batch)
        step.aot_scan(x, y, jax.random.key(0), iters)
        # warmup
        with telemetry.span("profile/warmup", iters=iters):
            step.run_scan(x, y, jax.random.key(1), iters)
            float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))

        os.system(f"rm -rf {logdir}")
        with telemetry.span("profile/trace", logdir=logdir):
            with jax.profiler.trace(logdir):
                step.run_scan(x, y, jax.random.key(2), iters)
                float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    if owned_log:
        print(f"# telemetry run log: {owned_log}", file=sys.stderr)
    return logdir


def parse_xplane(logdir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = glob.glob(f"{logdir}/**/*.xplane.pb", recursive=True)
    assert paths, f"no xplane under {logdir}"
    path = max(paths, key=os.path.getmtime)
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())

    for plane in xs.planes:
        if "TPU" not in plane.name and "/device:" not in plane.name:
            continue
        print(f"== plane: {plane.name}")
        ev_meta = plane.event_metadata
        by_op = defaultdict(float)
        total = 0.0
        for line in plane.lines:
            if "XLA Ops" not in line.name and "Steps" not in line.name \
                    and "XLA Modules" not in line.name:
                continue
            if "XLA Ops" not in line.name:
                continue
            for ev in line.events:
                name = ev_meta[ev.metadata_id].name
                dur = ev.duration_ps / 1e12
                by_op[name] += dur
                total += dur
        if not by_op:
            continue
        # async ops (copy-start/slice-start) and the outer scan `while`
        # OVERLAP compute — their durations span until -done. Split them out
        # and report the real compute ops (the while body) separately.
        def head(n):
            return n.lstrip("%").split(" ")[0].split(".")[0]

        ASYNC = ("copy-start", "slice-start", "copy-done", "slice-done",
                 "while", "async-start", "async-done")
        sync = {n: d for n, d in by_op.items() if head(n) not in ASYNC}
        stotal = sum(sync.values())
        print(f"total traced: {total*1e3:.1f} ms; compute (sync) ops: "
              f"{stotal*1e3:.1f} ms")
        fam = defaultdict(float)
        for name, dur in sync.items():
            fam[head(name)] += dur
        for name, dur in sorted(fam.items(), key=lambda kv: -kv[1])[:30]:
            print(f"  {name:60s} {dur*1e3:9.3f} ms  {100*dur/stotal:5.1f}%")
        print("-- top individual sync ops:")
        for name, dur in sorted(sync.items(), key=lambda kv: -kv[1])[:30]:
            print(f"  {name[:110]:110s} {dur*1e3:9.3f} ms  {100*dur/stotal:5.1f}%")


if __name__ == "__main__":
    # argv wins; BENCH_CONFIGS honored as fallback because the runbook
    # documents that form (a silent default-to-inception here once cost
    # a round-5 profiling window)
    env_cfg = os.environ.get("BENCH_CONFIGS", "").split(",")[0].strip()
    cfg = sys.argv[1] if len(sys.argv) > 1 \
        else (env_cfg or "inception_v1_imagenet")
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else None
    iters = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    logdir = capture(cfg, batch, iters)
    parse_xplane(logdir)
