"""A/B: per-block rematerialization on the transformer LM — throughput
cost vs activation-memory headroom.  Remat trades FLOPs for HBM; the
win case is a batch/sequence that OOMs (or spills) without it, so this
staged run measures both the same-shape slowdown and the largest batch
each variant sustains."""
import sys, time
sys.path.insert(0, '/root/repo')
import jax, jax.numpy as jnp, numpy as np
import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import models
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils.rng import RNG

ITERS = 8
SEQ = 1024
rng = np.random.default_rng(0)


def run(tag, remat, batch):
    RNG.set_seed(0)
    model = models.build_transformer_lm(
        32000, num_layers=6, embed_dim=512, num_heads=8, max_len=SEQ,
        remat=remat)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    step = TrainStep(model, crit, optim.SGD(learning_rate=0.01, momentum=0.9),
                     compute_dtype=jnp.bfloat16)
    x = jnp.asarray(rng.integers(0, 32000, (batch, SEQ), dtype=np.int32))
    y = jnp.asarray(rng.integers(0, 32000, (batch, SEQ), dtype=np.int32))
    step.aot_scan(x, y, jax.random.key(0), ITERS)
    losses = step.run_scan(x, y, jax.random.key(1), ITERS)
    assert bool(jnp.isfinite(losses).all())
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    t0 = time.perf_counter()
    step.run_scan(x, y, jax.random.key(2), ITERS)
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    wall = time.perf_counter() - t0
    print(f"{tag} b{batch}: {batch*SEQ*ITERS/wall:,.0f} tok/s "
          f"({wall/ITERS*1e3:.1f} ms/step)", flush=True)


for b in (8, 16, 32):
    for remat in (False, True):
        try:
            run("remat" if remat else "dense-act", remat, b)
        except Exception as e:  # OOM at some batch is the data point —
            # keep the message so RESOURCE_EXHAUSTED is distinguishable
            # from a compile/shape failure
            print(f"{'remat' if remat else 'dense-act'} b{b}: "
                  f"{type(e).__name__}: {e}", flush=True)
