"""Batch sweep for a bench config on the real chip (run when TPU is back):
times the committed train step at several batch sizes in one process."""
import sys, time
sys.path.insert(0, '/root/repo')
import jax, jax.numpy as jnp
import bench

ITERS = 16
config = sys.argv[1] if len(sys.argv) > 1 else "inception_v1_imagenet"
batches = [int(b) for b in (sys.argv[2].split(",") if len(sys.argv) > 2
                            else ["192", "256", "384", "512"])]

for b in batches:
    try:
        step, x, y = bench.make_step(config, b)
        step.aot_scan(x, y, jax.random.key(0), ITERS)
        losses = step.run_scan(x, y, jax.random.key(1), ITERS)
        assert bool(jnp.isfinite(losses).all())
        drain = bench.make_drain(step)
        drain()
        t0 = time.perf_counter()
        step.run_scan(x, y, jax.random.key(2), ITERS)
        drain()
        wall = time.perf_counter() - t0
        print(f"{config} b{b}: {b*ITERS/wall:,.0f} img/s "
              f"({wall/ITERS*1e3:.1f} ms/step)", flush=True)
    except Exception as e:
        print(f"{config} b{b}: FAILED {type(e).__name__}: {e}", flush=True)
