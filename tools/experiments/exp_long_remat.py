"""A/B: remat on/off for the long-context LM (seq 4096, batch 4).

The transformer_lm_long bench config bakes remat=True (per-block
rematerialization), but with flash attention the activation memory is
O(S) — if the no-remat variant fits HBM at this shape, the ~22%
recompute tax measured at seq 1024 (`exp_remat`) is pure loss here.
Run on the next tunnel contact; record the verdict in BASELINE.md and,
if no-remat wins AND fits, flip the config in bench.py.
"""
import sys, time, traceback
sys.path.insert(0, '/root/repo')
import jax, jax.numpy as jnp, numpy as np
import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import models
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils.rng import RNG

ITERS, SEQ, BATCH = 12, 4096, 4
rng = np.random.default_rng(0)


def run(tag, remat):
    RNG.set_seed(0)
    model = models.build_transformer_lm(
        32000, num_layers=6, embed_dim=512, num_heads=8, max_len=SEQ,
        remat=remat)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    step = TrainStep(model, crit,
                     optim.SGD(learning_rate=0.01, momentum=0.9),
                     compute_dtype=jnp.bfloat16)
    x = jnp.asarray(rng.integers(0, 32000, (BATCH, SEQ), dtype=np.int32))
    y = jnp.asarray(rng.integers(0, 32000, (BATCH, SEQ), dtype=np.int32))
    step.aot_scan(x, y, jax.random.key(0), ITERS)
    losses = step.run_scan(x, y, jax.random.key(1), ITERS)
    assert bool(jnp.isfinite(losses).all())
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    t0 = time.perf_counter()
    step.run_scan(x, y, jax.random.key(2), ITERS)
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    wall = time.perf_counter() - t0
    print(f"{tag}: {BATCH*ITERS/wall:,.1f} seq/s ({wall/ITERS*1e3:.1f} ms/step)",
          flush=True)


if __name__ == "__main__":
    run("remat", True)
    try:
        run("no-remat", False)
    except Exception:
        print("no-remat: FAILED (likely HBM OOM — remat stays)", flush=True)
        traceback.print_exc()
