"""Timing experiment: Inception-v1 train step, NCHW vs NHWC, batch 256/512."""
import sys, time
sys.path.insert(0, '/root/repo')
import jax, jax.numpy as jnp, numpy as np
import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.models.inception import build_inception_v1
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils.rng import RNG

ITERS = 16
rng = np.random.default_rng(0)

def run(fmt, batch):
    RNG.set_seed(0)
    model = build_inception_v1(1000, format=fmt)
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.01, momentum=0.9),
                     compute_dtype=jnp.bfloat16)
    shape = (batch, 3, 224, 224) if fmt == "NCHW" else (batch, 224, 224, 3)
    x = jnp.asarray(rng.normal(size=shape).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 1000, batch))
    cost = step.aot_scan(x, y, jax.random.key(0), ITERS)
    losses = step.run_scan(x, y, jax.random.key(1), ITERS)
    assert bool(jnp.isfinite(losses).all())
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    t0 = time.perf_counter()
    step.run_scan(x, y, jax.random.key(2), ITERS)
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    wall = time.perf_counter() - t0
    rate = batch * ITERS / wall
    print(f"{fmt} b{batch}: {rate:,.0f} img/s  ({wall/ITERS*1e3:.1f} ms/step)",
          flush=True)

for fmt in ("NCHW", "NHWC"):
    for batch in (256, 512):
        try:
            run(fmt, batch)
        except Exception as e:
            print(f"{fmt} b{batch}: FAILED {type(e).__name__}: {e}", flush=True)
