"""A/B: dense vs Pallas-flash attention on the transformer_lm train
step (seq 512) and, budget permitting, transformer_lm_long (seq 4096).

Round-5 TPU profile motivated the `flash_min_seq` gate: flash fwd+bwd
was 53% of the seq-512 step.  This experiment measures both backends
end-to-end so the threshold default is a recorded decision, not a
profile inference.  BIGDL_FLASH_MIN_SEQ=0 forces flash; a huge value
forces dense.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import jax
import jax.numpy as jnp

ITERS = int(os.environ.get("EXP_ITERS", "12"))
CONFIGS = os.environ.get("EXP_CONFIGS", "transformer_lm").split(",")

from bigdl_tpu.ops.attention import is_tpu_device  # noqa: E402

if not is_tpu_device():
    # off-TPU the auto gate always picks dense — both legs would measure
    # the same path and record a meaningless "decision"
    print("SKIP: not on TPU hardware; dense-vs-flash A/B needs the chip",
          flush=True)
    sys.exit(0)


def run(config, min_seq):
    os.environ["BIGDL_FLASH_MIN_SEQ"] = str(min_seq)
    import bench

    step, x, y = bench.make_step(config)
    step.aot_scan(x, y, jax.random.key(0), ITERS)
    drain = bench.make_drain(step)
    step.run_scan(x, y, jax.random.key(1), ITERS)
    drain()
    t0 = time.perf_counter()
    step.run_scan(x, y, jax.random.key(2), ITERS)
    drain()
    wall = time.perf_counter() - t0
    n = x.shape[0] * ITERS
    return n / wall


for config in CONFIGS:
    for tag, min_seq in (("dense", 10**9), ("flash", 0)):
        try:
            rate = run(config, min_seq)
            print(f"{config} {tag}: {rate:.1f} seq/s", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"{config} {tag}: ERROR {type(e).__name__}: {e}",
                  flush=True)
