import sys, time
sys.path.insert(0, '/root/repo')
import jax, jax.numpy as jnp, numpy as np
import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.nn.fuse import optimize_for_tpu
from bigdl_tpu.models.inception import build_inception_v1
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils.rng import RNG

ITERS = 16
rng = np.random.default_rng(0)

def run(tag, fused, batch=256):
    RNG.set_seed(0)
    model = build_inception_v1(1000)
    if fused:
        model = optimize_for_tpu(model)
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.01, momentum=0.9),
                     compute_dtype=jnp.bfloat16)
    x = jnp.asarray(rng.normal(size=(batch, 3, 224, 224)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 1000, batch))
    step.aot_scan(x, y, jax.random.key(0), ITERS)
    losses = step.run_scan(x, y, jax.random.key(1), ITERS)
    assert bool(jnp.isfinite(losses).all())
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    t0 = time.perf_counter()
    step.run_scan(x, y, jax.random.key(2), ITERS)
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    wall = time.perf_counter() - t0
    print(f"{tag}: {batch*ITERS/wall:,.0f} img/s  ({wall/ITERS*1e3:.1f} ms/step)", flush=True)

run("relu-outgrad only", False)
run("relu-outgrad + fused-1x1", True)
run("relu-outgrad + fused-1x1 b512", True, 512)
