"""A/B: Recurrent.remat_cell() on the large-LSTM bench config.

The round-5 TPU profile of lstm_text_large put ~21% of the step in
residual stacking (gate pre-activation buffer init broadcast 11.8% +
dynamic-update-slice writes 9.3%); rematerializing the cell trades that
HBM traffic for one extra fused-gate matmul per scan step in the
backward (~+33% of the matmul share).  Whether that nets out positive
is shape-dependent — measure, record the verdict in BASELINE.md, and
flip the bench config only if remat wins.
"""
import sys, time
sys.path.insert(0, '/root/repo')
import jax, jax.numpy as jnp, numpy as np
import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import models
from bigdl_tpu.nn.layers.rnn import Recurrent
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils.rng import RNG

ITERS, BATCH = 16, 512
rng = np.random.default_rng(0)


def run(tag, remat):
    RNG.set_seed(0)
    model = models.build_lstm_classifier(
        20000, embed_dim=512, hidden_size=1024, num_layers=2, class_num=20)
    if remat:
        n = 0
        for m in model.modules():
            if isinstance(m, Recurrent):
                m.remat_cell()
                n += 1
        assert n, "no Recurrent layers found to remat"
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.01, momentum=0.9),
                     compute_dtype=jnp.bfloat16)
    x = jnp.asarray(rng.integers(0, 20000, (BATCH, 200), dtype=np.int32))
    y = jnp.asarray(rng.integers(0, 20, BATCH))
    step.aot_scan(x, y, jax.random.key(0), ITERS)
    losses = step.run_scan(x, y, jax.random.key(1), ITERS)
    assert bool(jnp.isfinite(losses).all())
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    t0 = time.perf_counter()
    step.run_scan(x, y, jax.random.key(2), ITERS)
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    wall = time.perf_counter() - t0
    print(f"{tag}: {BATCH*ITERS/wall:,.0f} rec/s ({wall/ITERS*1e3:.1f} ms/step)",
          flush=True)


if __name__ == "__main__":
    run("saved-gates", False)
    run("remat-cell", True)
