"""Flash-attention block-size sweep on the transformer_lm bench config.

Block sizes trade VMEM residency against grid parallelism; the right
point is hardware-specific, so sweep on the chip:

    python tools/experiments/exp_flash_blocks.py

Uses the BIGDL_FLASH_BLOCK_Q/K env override (ops/attention.py) so every
run times the bench-identical step.
"""
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(HERE))

for bq, bk in [(128, 128), (256, 128), (128, 256), (256, 256),
               (512, 128), (64, 128)]:
    env = dict(os.environ, BIGDL_FLASH_BLOCK_Q=str(bq),
               BIGDL_FLASH_BLOCK_K=str(bk),
               BENCH_CONFIGS="transformer_lm", BENCH_ITERS="16")
    print(f"### block_q={bq} block_k={bk}", flush=True)
    subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                   env=env, cwd=REPO, check=False)
