"""Flash-attention block-size sweep on the transformer_lm_long config.

Block sizes trade VMEM residency against grid parallelism; the right
point is hardware-specific, so sweep on the chip:

    python tools/experiments/exp_flash_blocks.py

transformer_lm_long (seq 4096), NOT transformer_lm: block sizes matter
most where many k blocks stream per q block — at seq 512 there is at
most one 512-wide k block, so the long config is where this sweep has
signal.  (Seq 512 runs flash again since flash_min_seq dropped to 512;
its backend choice is measured by exp_attention_backend instead.)

Uses the BIGDL_FLASH_BLOCK_Q/K env override (ops/attention.py) so every
run times the bench-identical step.
"""
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(HERE))

for bq, bk in [(1024, 512),          # current default (measured 38.0 img/s)
               (1024, 1024), (2048, 512), (512, 1024), (2048, 1024),
               (4096, 512)]:
    env = dict(os.environ, BIGDL_FLASH_BLOCK_Q=str(bq),
               BIGDL_FLASH_BLOCK_K=str(bk),
               BENCH_CONFIGS="transformer_lm_long", BENCH_ITERS="12")
    print(f"### block_q={bq} block_k={bk}", flush=True)
    subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                   env=env, cwd=REPO, check=False)
