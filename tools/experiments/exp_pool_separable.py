"""A/B: separable (3x1 then 1x3) max pooling vs single 3x3 window, on the
Inception-v1 train step.  Separable halves the select-and-scatter window
size in the backward at the cost of an intermediate tensor in the forward.
"""
import sys, time
sys.path.insert(0, '/root/repo')
import jax, jax.numpy as jnp, numpy as np
from jax import lax
import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.nn.layers import pooling
from bigdl_tpu.nn.fuse import optimize_for_tpu
from bigdl_tpu.models.inception import build_inception_v1
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils.rng import RNG

ITERS = 16
rng = np.random.default_rng(0)

_orig_max = pooling._PoolBase._max

def separable_max(self, x):
    dims, strides, pads, _ = self._window(x)
    if not all(d == 1 or d > 1 for d in dims):
        return _orig_max(self, x)
    init = pooling._max_init(x.dtype)
    out = x
    for ax in range(x.ndim):
        if dims[ax] == 1 and strides[ax] == 1 and pads[ax] == (0, 0):
            continue
        d = [1] * x.ndim; d[ax] = dims[ax]
        s = [1] * x.ndim; s[ax] = strides[ax]
        p = [(0, 0)] * x.ndim; p[ax] = pads[ax]
        out = lax.reduce_window(out, init, lax.max, d, s, p)
    return out

def run(tag):
    RNG.set_seed(0)
    model = optimize_for_tpu(build_inception_v1(1000))
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.01, momentum=0.9),
                     compute_dtype=jnp.bfloat16)
    x = jnp.asarray(rng.normal(size=(256, 3, 224, 224)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 1000, 256))
    step.aot_scan(x, y, jax.random.key(0), ITERS)
    losses = step.run_scan(x, y, jax.random.key(1), ITERS)
    assert bool(jnp.isfinite(losses).all())
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    t0 = time.perf_counter()
    step.run_scan(x, y, jax.random.key(2), ITERS)
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    wall = time.perf_counter() - t0
    print(f"{tag}: {256*ITERS/wall:,.0f} img/s ({wall/ITERS*1e3:.1f} ms/step)",
          flush=True)

if __name__ == "__main__":
    run("single-window")
    pooling._PoolBase._max = separable_max
    run("separable")
