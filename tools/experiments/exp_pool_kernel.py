"""A/B: Pallas argmax-index maxpool kernel vs XLA select-and-scatter, on
the Inception-v1 train step (the kernel's target: pool backward was ~28%
of the round-5 TPU profile between select_and_scatter and the
compare/select index path).

Runs the full train step both ways and, if the kernel path fails to
Mosaic-compile, reports that instead of crashing the harvest.
"""
import os, sys, time, traceback
sys.path.insert(0, '/root/repo')
import jax, jax.numpy as jnp, numpy as np
import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.nn.fuse import optimize_for_tpu
from bigdl_tpu.models.inception import build_inception_v1
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils.rng import RNG

ITERS = 16
rng = np.random.default_rng(0)


def run(tag):
    RNG.set_seed(0)
    model = optimize_for_tpu(build_inception_v1(1000))
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.01, momentum=0.9),
                     compute_dtype=jnp.bfloat16)
    x = jnp.asarray(rng.normal(size=(256, 3, 224, 224)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 1000, 256))
    step.aot_scan(x, y, jax.random.key(0), ITERS)
    losses = step.run_scan(x, y, jax.random.key(1), ITERS)
    assert bool(jnp.isfinite(losses).all())
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    t0 = time.perf_counter()
    step.run_scan(x, y, jax.random.key(2), ITERS)
    float(jnp.sum(jax.tree_util.tree_leaves(step.params)[0]))
    wall = time.perf_counter() - t0
    print(f"{tag}: {256*ITERS/wall:,.0f} img/s ({wall/ITERS*1e3:.1f} ms/step)",
          flush=True)


if __name__ == "__main__":
    os.environ["BIGDL_POOL_KERNEL"] = "off"
    run("select-and-scatter")
    # "on", not "auto": auto maps to off until this very experiment
    # proves the kernel on hardware (pallas_pool_supported)
    os.environ["BIGDL_POOL_KERNEL"] = "on"
    try:
        run("pallas-argmax-idx")
    except Exception:
        print("pallas-argmax-idx: FAILED", flush=True)
        traceback.print_exc()
