"""Microbench: conv bias-grad reduce formulations at the profile's
hottest shape ([256,192,56,56] bf16 — the 3 ms/step backward fusion in
the round-5 Inception profile ran ~3.75x over its bandwidth bound).

Isolates the [C]-output reduce from the surrounding fusion so the
residual can be attributed: if (a) already hits the fused number, the
cost is the fusion's OTHER output; if (c) wins big, a custom bias-add
VJP routing the reduce through the MXU is worth landing.
"""
import sys, time
sys.path.insert(0, '/root/repo')
import jax, jax.numpy as jnp, numpy as np
from bigdl_tpu.utils.engine import enable_compile_cache
enable_compile_cache(implicit=True)

N, C, H, W = 256, 192, 56, 56
rng = np.random.default_rng(0)
gy = jnp.asarray(rng.normal(size=(N, C, H, W)).astype(np.float32),
                 dtype=jnp.bfloat16)


def timed(name, f):
    g = jax.jit(f)
    r = g(gy); jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(20):
        r = g(gy)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / 20
    gb = N * C * H * W * 2 / 1e9
    print(f"{name}: {dt*1e3:.3f} ms ({gb/dt:.0f} GB/s effective)",
          flush=True)


timed("a) bf16 sum((0,2,3))", lambda g: g.sum((0, 2, 3)))
timed("b) f32-accum sum", lambda g: g.astype(jnp.float32).sum((0, 2, 3))
      .astype(jnp.bfloat16))
timed("c) MXU ones-einsum", lambda g: jnp.einsum(
    "nchw,n->ch", g, jnp.ones((N,), jnp.bfloat16),
    preferred_element_type=jnp.float32).sum((1,)).astype(jnp.bfloat16))
timed("d) reshape 2d sum", lambda g: g.transpose(1, 0, 2, 3)
      .reshape(C, -1).sum(1))
