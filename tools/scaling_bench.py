#!/usr/bin/env python
"""Scale-out efficiency harness — the pod-scale half of the BASELINE
north star (images/sec at 8/32/128/256 chips; the reference's cluster
protocol is ``models/utils/DistriOptimizerPerf.scala:33-124`` run at
increasing executor counts).

Runs the SAME compiled train step (`parallel/train_step.py`) over data-
parallel meshes of increasing size with a FIXED per-chip batch (weak
scaling, the reference's per-node partition model) and reports images/sec
and efficiency vs linear extrapolation of the smallest mesh.

On real multi-chip hardware this measures ICI allreduce overlap; on this
single-chip dev box run it with the virtual CPU mesh to validate the
protocol end-to-end:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/scaling_bench.py --config lenet_mnist --sizes 1,2,4,8

Prints one JSON line per mesh size plus a summary line.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="inception_v1_imagenet")
    ap.add_argument("--sizes", default="",
                    help="comma list of mesh sizes (default: 1,2,4,..,n_devices)")
    ap.add_argument("--per-chip-batch", type=int, default=0,
                    help="per-chip batch (default: config batch / largest size)")
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--zero1", action="store_true",
                    help="use the ZeRO-1 sharded-optimizer layout")
    ap.add_argument("--sync", default=None,
                    choices=["allreduce", "sharded", "fsdp"],
                    help="parameter_sync mode (overrides --zero1; fsdp "
                         "= ZeRO-3 parameter sharding)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    import bench
    import bigdl_tpu.optim as optim
    from bigdl_tpu.parallel.train_step import TrainStep
    from bigdl_tpu.utils.rng import RNG

    from bigdl_tpu.utils.engine import Engine

    devices = Engine.probe_backend()  # owns the BENCH_BACKEND_TIMEOUT knob
    n = len(devices)
    nproc = jax.process_count()
    if args.sizes:
        sizes = [int(s) for s in args.sizes.split(",")]
        too_big = [s for s in sizes if s > n]
        if too_big:
            ap.error(f"requested mesh sizes {too_big} exceed the "
                     f"{n} available devices")
        if any(s % nproc for s in sizes):
            ap.error(f"mesh sizes must be multiples of the "
                     f"{nproc} participating processes")
    else:
        sizes = [s for s in (1, 2, 4, 8, 16, 32, 64, 128, 256)
                 if s <= n and s % nproc == 0]
    build_model, build_batch, criterion, batch = bench._configs()[args.config]
    per_chip = args.per_chip_batch or max(1, batch // max(sizes))

    results = []
    for size in sizes:
        RNG.set_seed(0)
        from bigdl_tpu.nn.fuse import optimize_for_tpu

        model = optimize_for_tpu(build_model())
        mesh = Mesh(np.array(devices[:size]), ("data",))
        step = TrainStep(model, criterion,
                         optim.SGD(learning_rate=0.01, momentum=0.9),
                         mesh=mesh,
                         parameter_sync=args.sync or (
                             "sharded" if args.zero1 else "allreduce"),
                         compute_dtype=jnp.bfloat16)
        # each process builds its LOCAL rows of the global batch
        # (TrainStep._shard_batch's multi-host contract)
        x, y = build_batch(per_chip * size // nproc)
        step.aot_scan(x, y, jax.random.key(0), args.iters)
        losses = step.run_scan(x, y, jax.random.key(1), args.iters)
        if not bool(jnp.isfinite(losses).all()):
            raise FloatingPointError("non-finite loss during warmup")
        drain = bench.make_drain(step)
        drain()
        # h2d stays OUTSIDE the timed window: it scales with global batch
        # and would otherwise bias efficiency_vs_linear downward
        xs, ys = step._shard_batch(x, y)
        t0 = time.perf_counter()
        step.run_scan_sharded(xs, ys, jax.random.key(2))
        drain()
        wall = time.perf_counter() - t0
        rate = per_chip * size * args.iters / wall
        results.append({"chips": size, "global_batch": per_chip * size,
                        "images_per_sec": round(rate, 2),
                        "per_chip_images_per_sec": round(rate / size, 2)})
        print(json.dumps(results[-1]), flush=True)

    base = min(results, key=lambda r: r["chips"])
    summary = {
        "metric": f"{args.config}_scaling_efficiency",
        "config": args.config,
        "per_chip_batch": per_chip,
        "parameter_sync": args.sync or (
            "sharded" if args.zero1 else "allreduce"),
        "efficiency_vs_linear": {
            str(r["chips"]): round(
                r["images_per_sec"] /
                (base["images_per_sec"] * r["chips"] / base["chips"]), 4)
            for r in results},
        "device": devices[0].device_kind,
    }
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
