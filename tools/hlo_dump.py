#!/usr/bin/env python
"""Dump the train-step HLO for a bench config — the compiler-side view
that pairs with ``tools/profile_bench.py``'s runtime view.

Prints either the unoptimized StableHLO/HLO (portable, default) or the
backend-optimized HLO (``--optimized``, shows fusions/layouts the device
actually runs), plus a quick op-kind histogram.  Used to chase where the
compiler spends the step (e.g. the round-3 finding that maxpool backward
lowered to 9 interior pads).

Usage: python tools/hlo_dump.py [config] [--optimized] [--batch N]
       [--grep PATTERN]
"""

import argparse
import os
import re
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("config", nargs="?", default="inception_v1_imagenet")
    ap.add_argument("--optimized", action="store_true",
                    help="backend-optimized HLO (after fusion/layout)")
    ap.add_argument("--batch", type=int, default=8,
                    help="batch for shape purposes (default 8: tracing "
                    "only, no execution)")
    ap.add_argument("--grep", default=None,
                    help="print only lines matching this regex")
    ap.add_argument("--out", default=None, help="write full text here")
    args = ap.parse_args()

    import jax

    import bench

    step, x, y = bench.make_step(args.config, args.batch)
    fn = jax.jit(step._step_fn())
    lowered = fn.lower(step.params, step.opt_state, step.buffers, x, y,
                       jax.random.key(0))
    if args.optimized:
        text = lowered.compile().as_text()
    else:
        text = lowered.as_text("hlo")

    kinds = Counter()
    for m in re.finditer(r"= \S+ (\w[\w-]*)\(", text):
        kinds[m.group(1)] += 1
    print(f"# {args.config}: {len(text.splitlines())} HLO lines; top ops:",
          file=sys.stderr)
    for k, n in kinds.most_common(15):
        print(f"#   {k:30s} {n}", file=sys.stderr)

    if args.grep:
        pat = re.compile(args.grep)
        text = "\n".join(l for l in text.splitlines() if pat.search(l))
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        print(f"# {'filtered' if args.grep else 'full'} text -> {args.out}",
              file=sys.stderr)
    else:
        print(text)


if __name__ == "__main__":
    main()
