#!/bin/bash
# TPU-tunnel watcher with a deterministic device-claim handoff.
#
# Round-4 postmortem (VERDICT.md Weak #2): the old watcher held the
# engine's advisory flock for up to 150s per probe, and the bench's
# fail-fast claim lost the round's only measurement window.  This
# version shrinks + bounds the probe claim and HARVESTS the chip on
# first contact:
#   * probe timeout 60s (the held-lock window) — a healthy tunnel
#     answers in <30s, a wedged one is declared wedged at 60s;
#   * the probe process exits immediately after the verdict, dropping
#     both the flock and the PJRT device client;
#   * a conflicting holder makes the probe SKIP (logged), not block;
#   * on a successful probe the watcher runs the full `python bench.py`
#     sweep (whose claim waits up to 210s for any bounded holder),
#     stamps the JSON to BENCH_watch.json, touches /tmp/TPU_BACK, and
#     exits — but a FAILED sweep (tunnel re-wedged mid-run) loops back
#     to probing instead of consuming the round's measurement window.
#
# Usage: nohup bash tools/tpu_watch.sh >/dev/null 2>&1 &
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
LOG=/tmp/tpu_watch.log
PIDFILE=/tmp/tpu_watch.pid
# single-instance + manageable by exact pid (pgrep -f patterns match the
# launching shell's own command line and have killed the wrong process)
if [ -f "$PIDFILE" ] && kill -0 "$(cat "$PIDFILE")" 2>/dev/null; then
  echo "$(date -u +%H:%M:%S) watcher already running (pid $(cat "$PIDFILE"))" >> "$LOG"
  exit 0
fi
echo $$ > "$PIDFILE"
cd "$REPO"

# Live-progress probe: when BIGDL_METRICS_PORT is set the benched
# process serves a JSON /status endpoint (telemetry/metrics_http.py) —
# poll THAT for step/loss/throughput instead of scraping its log files
# (the log-scrape stays as the fallback when no port is configured).
status_line() {
  [ -z "${BIGDL_METRICS_PORT:-}" ] && return 1
  python - "$BIGDL_METRICS_PORT" 2>/dev/null <<'PY'
import json, sys, urllib.request
port = sys.argv[1]
st = json.load(urllib.request.urlopen(
    f"http://127.0.0.1:{port}/status", timeout=2))
step = st.get("step") or {}
line = (f"status: step={step.get('step', '?')} loss={step.get('loss', '?')} "
        f"throughput={step.get('throughput', '?')} "
        f"nonfinite={st.get('nonfinite_steps', 0)} "
        f"compiles={st.get('compiles', 0)}")
# managed compile cache (docs/compile.md): cumulative compile seconds
# + persistent-cache hit/miss — a babysitter sees at a glance whether a
# restart's compile bill is being paid in cash or from the cache
if st.get("compile_s"):
    line += f" compile_s={st['compile_s']}"
cache = st.get("compile_cache") or {}
proc_cache = st.get("compile_cache_process") or {}
# fall back to the process-lifetime pair only as a PAIR — mixing one
# scope's hits with the other's misses prints a ratio belonging to
# neither run
if not (cache.get("hits") or cache.get("misses")):
    cache = proc_cache
hits, misses = cache.get("hits", 0), cache.get("misses", 0)
if hits or misses:
    line += f" cache={hits}h/{misses}m"
# goodput ledger (telemetry/ledger.py): live share of wall time spent
# training plus the dominant badput category — a babysitter sees "the
# job holds the slice but only 60% of it trains" without waiting for
# the post-run `telemetry goodput` fold
gp = st.get("goodput") or {}
if gp.get("wall_s"):
    line += f" goodput={gp.get('goodput_pct', 0):.0f}%"
    bad = gp.get("badput") or {}
    worst = max(bad.items(), key=lambda kv: kv[1], default=None)
    if worst and worst[1] > 0:
        line += f" badput={worst[0]}:{worst[1]:.0f}s"
# on-demand profiler + flight recorder (telemetry/profiler.py,
# telemetry/flight.py): show a capture in flight / the last artifacts so
# a sweep babysitter knows a POST /profile actually landed
prof = st.get("profiler") or {}
if prof.get("state", "idle") != "idle":
    line += (f" profiler={prof['state']}:{prof.get('steps_left', '?')}"
             f"->{prof.get('trace_dir', '?')}")
elif prof.get("last_trace_dir"):
    line += f" last_trace={prof['last_trace_dir']}"
flight = st.get("flight") or {}
if flight.get("last_dump_path"):
    line += f" flight_dump={flight['last_dump_path']}"
# fault tolerance (docs/fault_tolerance.md): checkpoint freshness and
# the last injected fault — a babysitter sees at a glance whether the
# run is checkpointing on cadence and whether a fault plan has fired
ckpt = st.get("checkpoint") or {}
if ckpt.get("saved_at"):
    line += f" ckpt=step{ckpt.get('step', '?')}@{ckpt.get('age_s', '?')}s"
fault = st.get("last_fault") or {}
if fault.get("fault"):
    line += f" last_fault={fault['fault']}@{fault.get('step', '?')}"
if st.get("quarantined_checkpoints"):
    line += f" quarantined={st['quarantined_checkpoints']}"
if st.get("preempted"):
    line += " PREEMPTED"
# inference serving (bigdl_tpu/serving/): live qps + latency
# percentiles + queue pressure — a babysitter sees a p99 spike or
# shed load (429s) without curling the serve port itself; STEADY-
# STATE compiles above the warm bucket count mean the server is
# recompiling in production (docs/serving.md runbook entry)
srv = st.get("serving") or {}
if srv:
    line += (f" serve[{srv.get('model', '?')}]:"
             f"qps={srv.get('qps', 0)}"
             f" p50={srv.get('p50_ms', '?')}ms"
             f" p99={srv.get('p99_ms', '?')}ms"
             f" q={srv.get('queue_depth', 0)}/{srv.get('queue_limit', '?')}"
             f" fill={srv.get('batch_fill', '?')}"
             f" compiles={srv.get('compiles', '?')}")
    if srv.get("rejected"):
        line += f" rejected={srv['rejected']}"
    # the LLM decode path (serving/generate/): live token rate, TTFT,
    # and decode-slot pressure — a babysitter sees a TTFT spike or a
    # full decode batch (admissions queueing behind max_active) without
    # curling /v1/generate (docs/serving.md runbook entry)
    gen = srv.get("generate") or {}
    if gen:
        line += (f" gen={gen.get('tokens_s', 0)}tok/s"
                 f" ttft={gen.get('ttft_p50_ms', '?')}ms"
                 f" active={gen.get('active_seqs', 0)}"
                 f"/{gen.get('max_active', '?')}"
                 f" cache={gen.get('cache_occupancy', 0)}")
    # SLO burn + tail evidence (telemetry/request_trace.py): burn is
    # observed windowed p99 / declared budget (1.0x = budget exactly
    # spent); the slowest retained trace id is the exemplar a
    # babysitter feeds to GET /v1/trace/<id> for the waterfall + blame
    slo = srv.get("slo") or {}
    burn = slo.get("burn") or {}
    cells = []
    for which in ("p99", "ttft"):
        b = (burn.get(which) or {}).get("burn")
        if b is not None:
            cells.append(f"{which} {b}x")
    if cells:
        line += " slo=" + "/".join(cells)
        if slo.get("violations"):
            line += f"!viol{slo['violations']}"
    slowest = []
    for ep, rows in ((srv.get("traces") or {}).get("slowest")
                     or {}).items():
        if rows:
            slowest.append((rows[0].get("ms", 0), rows[0], ep))
    if slowest:
        ms, row, ep = max(slowest, key=lambda t: t[0])
        line += f" slowest={row.get('trace_id', '?')}@{ms:.0f}ms"
        if (row.get("blame") or {}).get("cause"):
            line += f":{row['blame']['cause']}"
    if srv.get("draining"):
        line += " DRAINING"
# cluster fault tolerance (parallel/cluster.py): the per-peer heartbeat
# table — a babysitter sees which host stalled BEFORE the watchdog
# aborts the collective, and DEGRADED the instant a peer is presumed
# lost (the same signal /healthz turns 503 on)
cl = st.get("cluster") or {}
if cl:
    if cl.get("state") == "degraded":
        line += " cluster=DEGRADED"
    peers = cl.get("peers") or {}
    cells = []
    for name in sorted(peers):
        p = peers[name]
        cell = f"{name}:s{p.get('step', '?')}@{p.get('age_s', '?')}s"
        if p.get("lost"):
            cell += "!LOST"
        elif p.get("status") not in ("running", None):
            cell += f":{p['status']}"
        cells.append(cell)
    if cells:
        line += " peers=" + ",".join(cells)
# comms attribution (telemetry/comms.py): collective bytes per compiled
# step — a babysitter sees whether a sharding change blew up the
# all-reduce bill without waiting for the post-run diff
comms = st.get("comms") or {}
if comms.get("bytes"):
    line += (f" comms={comms['bytes'] / 1e6:.1f}MB/step"
             f"@{comms.get('count', '?')}coll")
# sparse embedding sync (train/sparse instant, docs/sparse.md): the
# bytes-per-step the row-sparse sync saves vs a dense table all-reduce
# — a babysitter sees whether the fast path is actually engaged
sp = st.get("sparse") or {}
if sp.get("saved_bytes"):
    line += (f" sparse={sp['saved_bytes'] / 1e6:.1f}MB-saved/step"
             f"@{sp.get('tables', '?')}tbl")
# memory attribution (telemetry/memory.py): live allocator vs limit +
# the compiled step's predicted per-device peak — the babysitter sees a
# run creeping toward RESOURCE_EXHAUSTED before it dies
mem = st.get("memory") or {}
if mem.get("peak_bytes"):
    g = 1 << 30
    live = mem.get("live_bytes")
    limit = mem.get("limit_bytes") or mem.get("hbm_limit_bytes")
    if live is not None and limit:
        line += (f" hbm={live / g:.1f}G/{limit / g:.1f}G"
                 f" peak={mem['peak_bytes'] / g:.1f}G")
        # 0.95 == telemetry.memory.PRESSURE_FRACTION (stdlib-only
        # snippet; limit_bytes here is already the allocator's own)
        if live >= 0.95 * limit:
            line += "!PRESSURE"
    else:
        line += f" hbm_peak={mem['peak_bytes'] / g:.2f}G"
# straggler-tolerant local SGD (parallel/local_sync.py): averaging
# period, worst peer lag vs the staleness bound, cumulative barrier
# wait, and any shed hosts — the babysitter sees "p1 is 2/3 rounds
# behind" before the shed verdict lands
ls_ = st.get("local_sync") or {}
if ls_.get("h"):
    line += f" sync=local H={ls_['h']} stale={ls_.get('lag', 0)}/{ls_.get('stale', '?')}"
    if ls_.get("waited_s"):
        line += f" held={ls_['waited_s']:.1f}s"
    if ls_.get("shed"):
        line += " shed=" + ",".join(f"p{p}" for p in ls_["shed"]) + "!"
# fleet watcher (telemetry/fleet.py, coordinator only): host count,
# completed-step lag, and the skew-blame verdict — "one host is slow,
# whose fault?" answered on one line
fl = st.get("fleet") or {}
if fl.get("hosts"):
    line += f" fleet={len(fl['hosts'])}h/lag{fl.get('lag_steps', 0)}"
    # elastic recovery (docs/fault_tolerance.md): current/declared
    # width when the cluster runs DEGRADED after a capacity-aware
    # reshard — the babysitter sees "2/4" instead of guessing why half
    # the hosts went quiet
    w = fl.get("width") or {}
    if w.get("current") and w.get("declared") \
            and w["current"] != w["declared"]:
        line += f" width={w['current']}/{w['declared']}!DEGRADED"
    bl = fl.get("blame") or {}
    if bl.get("cause"):
        line += (f" blame=p{bl.get('laggard', '?')}:{bl['cause']}"
                 f"+{bl.get('excess_s', 0) * 1e3:.0f}ms")
print(line)
PY
}

while true; do
  ts=$(date -u +%H:%M:%S)
  # success = exit status of the probe process, NOT output matching:
  # PJRT/absl teardown noise on stderr after the OK print must not
  # turn a healthy probe into a miss
  out=$(timeout 90 python -c "
from bigdl_tpu.utils.engine import Engine
devs = Engine.probe_backend(timeout_s=60, lock_wait_s=0)
print('OK', devs)
" 2>&1)
  rc=$?
  echo "$ts rc=$rc $(tail -1 <<<"$out")" >> "$LOG"
  if [ "$rc" -eq 0 ]; then
    echo "$ts TPU BACK — running banked leg sweep" >> "$LOG"
    touch /tmp/TPU_BACK
    # per-config banked sweep (tools/run_legs_r5.sh): bench.py flushes a
    # stderr line per finished config, the runner retries wedged clients
    # with a stall watchdog, and the assembler merges everything banked
    # so far — a mid-sweep wedge can no longer erase finished configs
    # (the round-5 failure mode: tunnel wedges per-client, transiently,
    # AFTER a successful probe, inside the first remote-compile RPC)
    # rotate the banked log so THIS contact re-measures every config
    # fresh (remaining() greps it; the assembler's merge of the prior
    # BENCH_banked artifact keeps older best-rows regardless)
    mkdir -p "$REPO/bench_watch"
    [ -s "$REPO/bench_legs_r5.err" ] && \
      mv "$REPO/bench_legs_r5.err" "$REPO/bench_watch/legs_$(date -u +%m%d_%H%M).err"
    # run the sweep in the background so the watcher can poll the live
    # status endpoint (BIGDL_METRICS_PORT) while it works
    timeout -k 30 14400 bash tools/run_legs_r5.sh >> "$LOG" 2>&1 &
    sweep_pid=$!
    while kill -0 "$sweep_pid" 2>/dev/null; do
      line=$(status_line) && echo "$(date -u +%H:%M:%S) $line" >> "$LOG"
      sleep 60 &
      wait $! 2>/dev/null
    done
    wait "$sweep_pid"
    # NB: grep -c prints 0 itself on no-match (exit 1) — no || echo,
    # which would yield the two-line string "0\n0"
    banked=$(grep -c "^# .*images_per_sec" "$REPO/bench_legs_r5.err" 2>/dev/null); banked=${banked:-0}
    python tools/assemble_legs.py > "$REPO/BENCH_watch.json" 2>> "$LOG"
    # proceed only on LIVE progress: >=1 newly banked row this cycle and
    # a clean assembly (top-level "error" only — a per-config error row
    # inside "configs" must not fail an otherwise good assembly)
    if [ "$banked" -ge 1 ] && python -c "import json,sys; d=json.load(open('$REPO/BENCH_watch.json')); sys.exit(1 if 'error' in d else 0)" 2>>"$LOG"; then
      echo "$(date -u +%H:%M:%S) banked sweep assembled -> BENCH_watch.json" >> "$LOG"
      # The full runbook harvest (profiles, realdata, A/B experiments,
      # TTA) completed earlier in round 5 (bench_watch/*.log, verdicts
      # in BASELINE.md) — on later contacts the watcher only refreshes
      # the per-config sweep so the banked artifact tracks current
      # HEAD, then resumes probing (set TPU_WATCH_ONCE=1 to exit after
      # the first refreshed sweep instead).
      echo "$(date -u +%H:%M:%S) sweep refreshed (harvest legs already done)" >> "$LOG"
      [ -n "${TPU_WATCH_ONCE:-}" ] && exit 0
      sleep 600
      continue  # success: skip the FAILED log line below
    fi
    echo "$(date -u +%H:%M:%S) bench sweep FAILED (see BENCH_watch.json); resuming probes" >> "$LOG"
  fi
  sleep 600
done
