#!/usr/bin/env python
"""CI gate: tracer-leak AST lint over the repo's Python sources.

Thin wrapper over ``bigdl_tpu.analysis.lint_sources`` (pass 4 of the
static analyzer) pinned to the repo's source roots; exits nonzero when
any error-severity finding fires, so CI fails on a freshly introduced
tracer leak.  The same check runs inside the tier-1 pytest run via
``tests/test_lint_clean.py``.

Usage::

    python tools/lint_graft.py                 # bigdl_tpu/ tools/ examples/
    python tools/lint_graft.py mypkg/ file.py  # explicit targets
    python tools/lint_graft.py --warnings-ok   # ignore warnings
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bigdl_tpu.analysis.ast_lint import DEFAULT_LINT_DIRS, lint_paths  # noqa: E402

#: modules the CI gate PINS: reliability-critical subsystems whose
#: accidental deletion/rename must fail the build, not pass it silently
#: (the default-dir lint would simply stop seeing a removed file)
PINNED_MODULES = [
    "bigdl_tpu/faults.py",
    "bigdl_tpu/utils/ckpt_digest.py",
    "bigdl_tpu/utils/sharded_ckpt.py",
    # elastic resharding (ISSUE 12): losing this silently reverts
    # checkpoints to same-shape-only restore — a shrunk slice can no
    # longer resume, and ZeRO restores onto the wrong width would
    # silently replicate every moment shard
    "bigdl_tpu/utils/ckpt_topology.py",
    # cluster fault tolerance (ISSUE 7): losing this silently reverts
    # peer loss to an indefinite collective hang and restores to
    # per-host (possibly mixed-step) discovery
    "bigdl_tpu/parallel/cluster.py",
    "bigdl_tpu/telemetry/schema.py",
    "bigdl_tpu/telemetry/flight.py",
    "bigdl_tpu/telemetry/metrics_http.py",
    # fleet-wide comms observability (ISSUE 10): losing comms.py blinds
    # the bytes-moved gate the ZeRO/pipeline work lands against; losing
    # fleet.py silently reverts cross-host visibility to after-the-fact
    # log merges with no skew blame
    "bigdl_tpu/telemetry/comms.py",
    "bigdl_tpu/telemetry/fleet.py",
    # request-level serving traces (ISSUE 14): losing this blinds the
    # per-request waterfalls, the slow-request blame verdict, and the
    # SLO burn gate — "one user's request was slow" reverts to an
    # unanswerable aggregate p99
    "bigdl_tpu/telemetry/request_trace.py",
    # memory observability (ISSUE 11): losing memory.py blinds the
    # peak_hbm_bytes gate (the ZeRO "optimizer HBM dropped" proof), the
    # fit estimator, and OOM forensics — device OOMs revert to a bare
    # RESOURCE_EXHAUSTED with no resident-buffer evidence
    "bigdl_tpu/telemetry/memory.py",
    # the kernel library (PR 6): losing any of these silently reverts
    # hot paths to unfused XLA chains and wrong-by-autodiff VJPs
    "bigdl_tpu/ops/dispatch.py",
    "bigdl_tpu/ops/lrn_pallas.py",
    "bigdl_tpu/ops/norm_pallas.py",
    "bigdl_tpu/ops/pool_pallas.py",
    "bigdl_tpu/ops/pooling_pallas.py",
    "bigdl_tpu/ops/attention.py",
    # the serving layer (ISSUE 8): losing any of these silently reverts
    # online inference to per-call EvalStep rebuilds (a compile per
    # predict) and drops the continuous-batching HTTP frontend
    "bigdl_tpu/serving/buckets.py",
    "bigdl_tpu/serving/executor.py",
    "bigdl_tpu/serving/batcher.py",
    "bigdl_tpu/serving/server.py",
    # the LLM decode subsystem (ISSUE 13): losing kv_cache.py breaks
    # the trace-order cache contract silently (decode would recompute
    # full context); losing decode.py/batcher.py drops /v1/generate and
    # reverts generation to one full forward per token
    "bigdl_tpu/serving/generate/kv_cache.py",
    "bigdl_tpu/serving/generate/decode.py",
    "bigdl_tpu/serving/generate/batcher.py",
    # compile-time war (ISSUE 9): losing scan.py silently reverts the
    # registry models to N-times-unrolled lowering; losing
    # compile_cache.py blinds the persistent cache (hits/misses/compile
    # budget become unmeasured again)
    "bigdl_tpu/nn/layers/scan.py",
    "bigdl_tpu/utils/compile_cache.py",
    # sparse embedding fast path (ISSUE 15): losing embedding.py
    # silently reverts every table gradient to the dense [vocab, dim]
    # all-reduce (and drops LookupTable/EmbeddingBag outright); losing
    # dlrm.py drops the recsys scenario both bench harnesses gate
    "bigdl_tpu/nn/layers/embedding.py",
    "bigdl_tpu/models/dlrm.py",
    # goodput ledger (ISSUE 18): losing ledger.py silently drops the
    # run-level wall-time accounting every surface folds (goodput
    # event, /status.goodput, fleet columns, diff/bench gates)
    "bigdl_tpu/telemetry/ledger.py",
    # straggler-tolerant local SGD (ISSUE 20): losing local_sync.py
    # silently drops the bounded-staleness barrier + shed protocol —
    # parameter_sync=local would average islands but never exchange
    # across processes, and a slow host would stall the fleet forever
    "bigdl_tpu/parallel/local_sync.py",
]


def check_pins(repo: str) -> list:
    """Missing pinned modules (empty = all present)."""
    return [m for m in PINNED_MODULES
            if not os.path.isfile(os.path.join(repo, m))]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="tracer-leak lint (python -m bigdl_tpu.analysis --lint)")
    p.add_argument("paths", nargs="*",
                   help=f"files/dirs to lint (default: "
                        f"{' '.join(DEFAULT_LINT_DIRS)})")
    p.add_argument("--suppress", action="append", default=[],
                   metavar="RULE")
    p.add_argument("--warnings-ok", action="store_true",
                   help="exit 0 even when warnings fire (errors still "
                        "fail)")
    args = p.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    missing = check_pins(repo)
    if missing:
        print(f"pinned modules missing: {', '.join(missing)}")
        return 1
    paths = args.paths or [os.path.join(repo, d) for d in DEFAULT_LINT_DIRS]
    report = lint_paths(paths, suppress=args.suppress)
    print(report.format())
    if report.errors:
        return 1
    if report.warnings and not args.warnings_ok:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
