#!/usr/bin/env python
"""Real-data input-pipeline benchmark — trains from TFRecord FILES through
the full host pipeline (record framing + Example proto decode + crop/
normalize batch assembly) with the Optimizer's async prefetch, and reports
whether input ever stalls the device (Metrics ``data time``).

This is the proof the framework's input path keeps a chip fed the way the
reference's SequenceFile + MTLabeledBGRImgToBatch pipeline feeds ImageNet
(``dataset/DataSet.scala:319`` SeqFileFolder,
``dataset/image/MTLabeledBGRImgToBatch.scala:31``); the synthetic
device-resident ``bench.py`` protocol deliberately excludes input, so this
tool is its real-data complement.

    # ImageNet shapes on the TPU (writes ~0.6 GB of records first):
    python tools/realdata_bench.py --config inception --iters 16

    # CPU smoke (tiny shapes):
    JAX_PLATFORMS=cpu python tools/realdata_bench.py --config tiny

Prints per-iteration throughput lines and ONE final JSON line with the
data-wait share.
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def write_dataset(path, n, h, w, classes, seed=0):
    """TFRecord files of raw uint8 HWC images + labels (the reference's
    SequenceFile-of-JPEG role, without a JPEG codec dependency)."""
    from bigdl_tpu.dataset.tfrecord import write_tfrecord
    from bigdl_tpu.utils.protowire import emit_bytes, emit_varint

    def feature_bytes(b):
        #  Feature{bytes_list: BytesList{value: b}}
        inner = emit_bytes(1, b)
        return emit_bytes(1, inner)

    def feature_int(v):
        inner = emit_varint(1, v)
        return emit_bytes(3, inner)

    def example(img, label):
        feats = b""
        for key, val in (("image", feature_bytes(img.tobytes())),
                         ("label", feature_int(int(label)))):
            entry = emit_bytes(1, key.encode()) + emit_bytes(2, val)
            feats += emit_bytes(1, entry)
        return emit_bytes(1, feats)

    rng = np.random.default_rng(seed)
    files = []
    per_file = max(n // 4, 1)
    base = rng.integers(0, 255, (classes, h, w, 3), np.uint8)
    idx = 0
    for f in range(4):
        recs = []
        for _ in range(per_file):
            label = idx % classes
            noise = rng.integers(-25, 25, (h, w, 3))
            img = np.clip(base[label].astype(np.int16) + noise,
                          0, 255).astype(np.uint8)
            recs.append(example(img, label))
            idx += 1
        fp = os.path.join(path, f"train-{f:05d}.tfrecord")
        write_tfrecord(fp, recs)
        files.append(fp)
    return files


def make_dataset(files, h, w, crop, batch, mean, std):
    """TFRecordIterator -> parse_example -> LabeledImage -> MTImageToBatch
    -> MiniBatch: the full host chain the Optimizer consumes."""
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.image import LabeledImage, MTImageToBatch
    from bigdl_tpu.dataset.minibatch import MiniBatch
    from bigdl_tpu.dataset.tfrecord import TFRecordIterator
    from bigdl_tpu.dataset.transformer import Transformer

    class DecodeExamples(Transformer):
        """Chunked batch decode through the native (C++ multithreaded)
        Example parser; Python wire walker as fallback.  Chunks of one
        minibatch keep the prefetcher's stream smooth instead of
        stalling a whole file's decode at file boundaries."""

        def apply(self, it):
            from bigdl_tpu import native

            def chunks():
                buf = []
                for path in it:
                    for rec in TFRecordIterator(path):
                        buf.append(rec)
                        if len(buf) == batch:
                            yield buf
                            buf = []
                if buf:
                    yield buf

            for recs in chunks():
                imgs, labels = native.parse_examples_fixed(
                    recs, [("image", "bytes", h * w * 3),
                           ("label", "int64", 1)])
                for i in range(len(recs)):
                    yield LabeledImage(imgs[i].reshape(h, w, 3),
                                       int(labels[i, 0]))

    class ToMiniBatch(Transformer):
        def apply(self, it):
            for feats, labels in it:
                yield MiniBatch(feats, labels)

    return DataSet.array(files) \
        .transform(DecodeExamples()) \
        .transform(MTImageToBatch(batch, crop, crop, mean, std)) \
        .transform(ToMiniBatch())


CONFIGS = {
    # name: (image hw, crop, batch, records, model builder)
    "inception": (256, 224, 64, 1024, "inception"),
    "tiny": (36, 32, 32, 256, "tiny"),
}


def build_model(kind, crop):
    import bigdl_tpu.nn as nn

    if kind == "inception":
        from bigdl_tpu import models
        from bigdl_tpu.nn.fuse import optimize_for_tpu

        return optimize_for_tpu(models.build_inception_v1(1000))
    return nn.Sequential(
        nn.SpatialConvolution(3, 16, 3, 3, 2, 2, 1, 1), nn.ReLU(True),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape((16 * (crop // 4) * (crop // 4),)),
        nn.Linear(16 * (crop // 4) * (crop // 4), 10), nn.LogSoftMax())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="tiny", choices=sorted(CONFIGS))
    ap.add_argument("--iters", type=int, default=12)
    ap.add_argument("--data-dir", default=None,
                    help="reuse/keep the TFRecord files here")
    args = ap.parse_args()

    from bigdl_tpu.utils.engine import honor_platform_request

    honor_platform_request()

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.utils.rng import RNG

    hw, crop, batch, records, kind = CONFIGS[args.config]
    data_dir = args.data_dir or tempfile.mkdtemp(prefix="bigdl_realdata_")
    os.makedirs(data_dir, exist_ok=True)
    if not any(f.endswith(".tfrecord") for f in os.listdir(data_dir)):
        t0 = time.perf_counter()
        write_dataset(data_dir, records, hw, hw, classes=10)
        print(f"# wrote {records} records ({hw}x{hw}) to {data_dir} "
              f"in {time.perf_counter() - t0:.1f}s", file=sys.stderr)
    files = sorted(os.path.join(data_dir, f) for f in os.listdir(data_dir)
                   if f.endswith(".tfrecord"))

    mean, std = (123.68, 116.78, 103.94), (58.4, 57.1, 57.4)
    ds = make_dataset(files, hw, hw, crop, batch, mean, std)
    RNG.set_seed(1)
    o = optim.LocalOptimizer(build_model(kind, crop), ds,
                             nn.ClassNLLCriterion(), batch_size=batch,
                             end_trigger=optim.Trigger.max_iteration(args.iters))
    o.set_optim_method(optim.SGD(learning_rate=0.01))
    t0 = time.perf_counter()
    o.optimize()
    wall = time.perf_counter() - t0

    m = o.metrics
    # exclude the compile iteration from the steady-state accounting
    steady_iters = max(m.count("computing time"), 1)
    data_wait = m.total("data time") - (m._scalars["data time"][0]
                                        if m.count("data time") else 0.0)
    compute = m.total("computing time")
    result = {
        "metric": f"realdata_{args.config}_img_s",
        "value": round(batch * steady_iters /
                       max(compute + max(data_wait, 0.0), 1e-9), 1),
        "unit": "img/s (steady-state)",
        "data_wait_mean_s": round(data_wait / steady_iters, 6),
        "data_wait_share": round(max(data_wait, 0.0) /
                                 max(compute + max(data_wait, 0.0), 1e-9), 4),
        "prefetch": int(os.environ.get("BIGDL_PREFETCH", "2") or 2),
        "iters": args.iters,
    }
    print(json.dumps(result))
    return result


if __name__ == "__main__":
    main()
