"""Dependency-free constants shared by bench.py and the harvest tooling
(tools/assemble_legs.py must stay importable without jax — it is a log
parser the watcher's live-progress gate depends on)."""

#: the north-star config (BASELINE.json)
HEADLINE = "inception_v1_imagenet"

#: best round-3 measured headline throughput (BASELINE.md) — the
#: progress denominator for ``vs_round3_best``
ROUND3_BEST = 4853.0
