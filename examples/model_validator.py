#!/usr/bin/env python
"""ModelValidator — the multi-format interop acceptance harness
(reference ``example/loadmodel/ModelValidator.scala:44``): load a model
saved as BigDL-TPU (BTPU), Caffe, Torch7 ``.t7``, or TensorFlow GraphDef
and report Top-1 / Top-5 accuracy over a validation folder.

The reference drives ImageNet through per-model preprocessors; here the
validation set is either

- a ``.npz`` file with arrays ``x`` (N, ...) and ``y`` (N,), or
- a folder of class subdirectories holding ``.npy`` feature arrays or
  images (decoded via PIL when installed), with an optional ``--meanFile``
  ``.npy`` subtracted from each record.

Run::

    python examples/model_validator.py -t bigdl  --modelPath m.btpu -f val/
    python examples/model_validator.py -t caffe  --modelPath m.caffemodel \
        --caffeDefPath m.prototxt -f val/
    python examples/model_validator.py -t torch  --modelPath m.t7 -f val/
    python examples/model_validator.py -t tf     --modelPath m.pb \
        --tfInput input --tfOutput logsoftmax_5 -f val.npz
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def load_model(model_type: str, model_path: str, caffe_def_path=None,
               tf_input="input", tf_output=None):
    """Dispatch on the four supported serialization formats
    (``ModelValidator.scala:105-131`` TorchModel/CaffeModel/BigDlModel)."""
    t = model_type.lower()
    if t == "bigdl":
        from bigdl_tpu.utils.serializer import load_module

        return load_module(model_path)
    if t == "caffe":
        from bigdl_tpu.utils.caffe import load_caffe

        if not caffe_def_path:
            raise SystemExit("caffe models need --caffeDefPath")
        return load_caffe(caffe_def_path, model_path)
    if t == "torch":
        from bigdl_tpu.utils.torch_file import load_torch

        return load_torch(model_path)
    if t == "tf":
        from bigdl_tpu.utils.tf_graph import load_graphdef

        if not tf_output:
            raise SystemExit("tf models need --tfOutput")
        return load_graphdef(model_path, [tf_input], [tf_output])
    raise SystemExit(f"unknown model type {model_type!r}; "
                     "use bigdl, caffe, torch, or tf")


def load_validation_samples(folder: str, mean_file=None):
    """(x, label) Samples from an ``.npz`` file or a class-subdir tree."""
    from bigdl_tpu.dataset.image import BytesToImage
    from bigdl_tpu.dataset.sample import Sample

    mean = np.load(mean_file) if mean_file else None

    def feat(arr):
        arr = np.asarray(arr, np.float32)
        return arr - mean if mean is not None else arr

    if os.path.isfile(folder):
        data = np.load(folder)
        return [Sample(feat(x), np.int64(y))
                for x, y in zip(data["x"], data["y"])]

    classes = sorted(d for d in os.listdir(folder)
                     if os.path.isdir(os.path.join(folder, d)))
    if not classes:
        raise SystemExit(f"no class subdirectories under {folder}")
    samples = []
    decode = BytesToImage()
    for label, cls in enumerate(classes):
        cdir = os.path.join(folder, cls)
        for name in sorted(os.listdir(cdir)):
            path = os.path.join(cdir, name)
            if name.endswith(".npy"):
                arr = np.load(path)
            else:
                with open(path, "rb") as f:
                    img = next(decode.apply(iter([(f.read(), label)])))
                arr = img.data.transpose(2, 0, 1)  # HWC -> CHW
            samples.append(Sample(feat(arr), np.int64(label)))
    return samples


def validate(model, samples, batch_size: int = 32):
    """Evaluate Top-1/Top-5 like the reference's ``model.evaluate`` call
    (``ModelValidator.scala:133-139``)."""
    import bigdl_tpu.optim as optim

    methods = [optim.Top1Accuracy(), optim.Top5Accuracy()]
    results = optim.Evaluator(model, batch_size=batch_size).evaluate(
        samples, methods)
    return {m.name: r.result()[0] for r, m in results}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-f", "--folder", default="./",
                   help="validation folder (class subdirs) or .npz file")
    p.add_argument("-t", "--modelType", required=True,
                   choices=["bigdl", "caffe", "torch", "tf"])
    p.add_argument("--modelPath", required=True)
    p.add_argument("--caffeDefPath", default=None)
    p.add_argument("--tfInput", default="input")
    p.add_argument("--tfOutput", default=None)
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("--meanFile", default=None)
    p.add_argument("--quantize", action="store_true",
                   help="evaluate the int8-quantized model (bigquant)")
    args = p.parse_args(argv)

    from bigdl_tpu.utils.engine import honor_platform_request

    honor_platform_request()

    model = load_model(args.modelType, args.modelPath, args.caffeDefPath,
                       args.tfInput, args.tfOutput)
    if args.quantize:
        from bigdl_tpu.nn.quantized import quantize

        model = quantize(model)
    samples = load_validation_samples(args.folder, args.meanFile)
    scores = validate(model, samples, args.batchSize)
    for name, value in scores.items():
        print(f"{args.modelType} {args.modelPath} {name}: {value:.4f}")
    return scores


if __name__ == "__main__":
    main()
