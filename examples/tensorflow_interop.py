#!/usr/bin/env python
"""TensorFlow interop example — both directions of the reference's
``example/tensorflow`` pair (``Load.scala``: run a TF-exported GraphDef
as a BigDL model; ``Save.scala``: export a BigDL model so TensorFlow
can read it).

Round trip shown here: build a small classifier, export it to a binary
GraphDef (``save_graphdef``), re-import it (``load_graphdef``), and
verify the imported graph computes identical outputs — then keep
training the IMPORTED graph (Consts were promoted to Variables).

Run: ``python examples/tensorflow_interop.py [--modelPath out.pb]``
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--modelPath", default=None,
                   help="where to write the GraphDef (tempfile default)")
    args = p.parse_args(argv)

    from bigdl_tpu.utils.engine import honor_platform_request

    honor_platform_request()

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.utils.rng import RNG
    from bigdl_tpu.utils.tf_graph import load_graphdef, save_graphdef

    RNG.set_seed(9)
    model = nn.Sequential(
        nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 3), nn.LogSoftMax(),
    ).evaluate()
    path = args.modelPath or os.path.join(
        tempfile.mkdtemp(prefix="bigdl_tf_"), "model.pb")

    # Save.scala direction: BigDL module tree -> binary GraphDef
    outputs = save_graphdef(model, path, input_name="input")
    print(f"saved GraphDef to {path} (outputs: {outputs})")

    # Load.scala direction: GraphDef -> trainable Graph (train_consts
    # promotes the exported Const weights to Variables)
    imported = load_graphdef(path, ["input"], outputs,
                             train_consts=True).evaluate()
    x = np.random.RandomState(0).randn(8, 6).astype(np.float32)
    a, b = np.asarray(model.forward(x)), np.asarray(imported.forward(x))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    print("imported graph matches the original forward (max "
          f"|diff| = {np.abs(a - b).max():.2e})")

    # the imported graph is TRAINABLE (Const weights became Variables)
    rng = np.random.RandomState(1)
    xs = rng.randn(96, 6).astype(np.float32)
    ys = np.argmax(xs[:, :3], axis=1)
    samples = [Sample(xs[i], np.int64(ys[i])) for i in range(96)]
    o = optim.LocalOptimizer(imported.training_mode(), samples,
                             nn.ClassNLLCriterion(), batch_size=16,
                             end_trigger=optim.Trigger.max_epoch(25))
    o.set_optim_method(optim.SGD(learning_rate=0.5, momentum=0.9))
    o.optimize()
    pred = np.asarray(imported.evaluate().forward(xs)).argmax(1)
    acc = float((pred == ys).mean())
    print(f"fine-tuned imported graph accuracy: {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
