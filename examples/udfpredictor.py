#!/usr/bin/env python
"""UDF serving example — register a trained text classifier as a
column-level predicate over tabular data (reference
``example/udfpredictor/DataframePredictor.scala``, SURVEY §2.13: a Spark
SQL UDF that classifies a text column so queries can filter on the
predicted class).

Without Spark, the same capability is a vectorized predict function over
columnar data: ``make_predict_udf`` closes over the trained model +
vocabulary and maps a text column to predicted classes; ``query`` applies
it to a list-of-dicts table, the DataFrame stand-in
(``DLClassifierModel.transform`` drives the batched forward).

Run: ``python examples/udfpredictor.py``
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_predict_udf(model, word_index, table, seq_len):
    """The UDF: list-of-texts -> predicted class ids (0-based), batched
    through DLClassifierModel like the reference routes its UDF through
    the broadcast predictor."""
    from bigdl_tpu.pipeline import DLClassifierModel

    from examples.textclassification import vectorize

    embed_dim = table.shape[1]
    dl = DLClassifierModel(model, (embed_dim, 1, seq_len))

    def udf(texts):
        feats = np.stack([vectorize(t, word_index, table, seq_len)
                          for t in texts])
        return dl.transform(feats).astype(int)

    return udf


def query(rows, text_col, udf, keep_classes):
    """SELECT * FROM rows WHERE udf(text_col) IN keep_classes."""
    preds = udf([r[text_col] for r in rows])
    return [dict(r, predicted=int(p)) for r, p in zip(rows, preds)
            if int(p) in keep_classes], preds


def main():
    from bigdl_tpu.utils.engine import honor_platform_request

    honor_platform_request()  # a user-pinned JAX_PLATFORMS must beat the plugin

    from examples.textclassification import main as train_main

    model, word_index, table, _ = train_main(
        ["--max-epoch", "4", "--seq-len", "150", "--synthetic-size", "250",
         "--batch-size", "16"])

    rows = [
        {"id": 1, "text": "the rocket launch reached orbit with the "
                          "satellite payload for nasa"},
        {"id": 2, "text": "the team scored a late goal to win the hockey "
                          "season opener"},
        {"id": 3, "text": "doctors recommend treatment for the patient's "
                          "health condition"},
    ]
    udf = make_predict_udf(model, word_index, table, 150)
    preds = udf([r["text"] for r in rows])
    # keep only rows the model assigns to the first predicted class —
    # the reference's "WHERE predict(text) = <class>" query shape
    kept, _ = query(rows, "text", udf, keep_classes={int(preds[0])})
    print(f"[udfpredictor] predictions: {preds.tolist()}; "
          f"{len(kept)}/{len(rows)} rows match class {int(preds[0])}")
    for r in kept:
        print(f"  id={r['id']} predicted={r['predicted']}")


if __name__ == "__main__":
    main()
