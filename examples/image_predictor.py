#!/usr/bin/env python
"""Image-classification predictor — load a trained model and predict
classes for a folder of images (reference
``example/imageclassification/ImagePredictor.scala:38``: DLClassifierModel
transform over an image DataFrame, printing (imageName, predict) rows).

The image path mirrors the reference's transformer chain
``BytesToBGRImg -> BGRImgCropper -> BGRImgNormalizer`` with the repo's
``BytesToImage -> CenterCropper -> ImageNormalizer``; ``.npy`` feature
files are accepted too so the example runs without PIL.

Run::

    python examples/image_predictor.py -t bigdl --modelPath m.btpu \
        -f images/ --imageSize 224
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# model loading is shared with the ModelValidator example (the reference
# pair shares MlUtils.loadModel the same way)
from examples.model_validator import load_model

# ImageNet eval normalization (``MlUtils.scala`` testMean/testStd)
TEST_MEAN = (0.485 * 255, 0.456 * 255, 0.406 * 255)
TEST_STD = (0.229 * 255, 0.224 * 255, 0.225 * 255)


def load_image_features(folder: str, image_size: int):
    """[(name, CHW float array)] via the crop+normalize chain."""
    from bigdl_tpu.dataset.image import (BytesToImage, CenterCropper,
                                         ImageNormalizer, LabeledImage)

    crop = CenterCropper(image_size, image_size)
    norm = ImageNormalizer(TEST_MEAN, TEST_STD)
    decode = BytesToImage()
    rows = []
    for name in sorted(os.listdir(folder)):
        path = os.path.join(folder, name)
        if not os.path.isfile(path):
            continue
        if name.endswith(".npy"):
            rows.append((name, np.load(path).astype(np.float32)))
            continue
        with open(path, "rb") as f:
            img = next(decode.apply(iter([(f.read(), 0)])))
        img = next(norm.apply(crop.apply(iter([img]))))
        rows.append((name, img.data.transpose(2, 0, 1)))  # HWC -> CHW
    if not rows:
        raise SystemExit(f"no image files under {folder}")
    return rows


def predict(model, rows, image_size: int, batch_size: int = 32):
    """(imageName, predict) pairs through DLClassifierModel.transform."""
    from bigdl_tpu.pipeline import DLClassifierModel

    trans = DLClassifierModel(model, (3, image_size, image_size)) \
        .set_batch_size(batch_size)
    feats = np.stack([r[1] for r in rows])
    classes = trans.transform(feats)
    return [(name, int(c)) for (name, _), c in zip(rows, classes)]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-f", "--folder", required=True)
    p.add_argument("-t", "--modelType", default="bigdl",
                   choices=["bigdl", "caffe", "torch", "tf"])
    p.add_argument("--modelPath", required=True)
    p.add_argument("--caffeDefPath", default=None)
    p.add_argument("--tfInput", default="input")
    p.add_argument("--tfOutput", default=None)
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("--imageSize", type=int, default=224)
    p.add_argument("--showNum", type=int, default=100)
    args = p.parse_args(argv)

    from bigdl_tpu.utils.engine import honor_platform_request

    honor_platform_request()

    model = load_model(args.modelType, args.modelPath, args.caffeDefPath,
                       args.tfInput, args.tfOutput)
    rows = load_image_features(args.folder, args.imageSize)
    results = predict(model, rows, args.imageSize, args.batchSize)
    for name, cls in results[:args.showNum]:
        print(f"{name} predict={cls}")
    return results


if __name__ == "__main__":
    main()
